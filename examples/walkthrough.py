"""Hyperspace-TPU worked walkthrough — the full index lifecycle on one dataset.

The long-form companion to `quickstart.py`, mirroring the reference's worked
example app + Hitchhiker's-Guide notebooks (`examples/scala/.../App.scala:23-103`,
`notebooks/python/`): every step prints what changed on the lake and in the plan,
and asserts the invariant it demonstrates, so it doubles as a CI smoke test.

  1.  Create dept/emp parquet sources.
  2.  Build a covering index on each side.
  3.  EXPLAIN: the join rewrite (shuffle-free bucketed join) with a plan diff.
  4.  Enable/disable round-trip: identical results either way.
  5.  Append source files -> index goes stale; Hybrid Scan unions the appended
      rows into the bucketed join on the fly.
  6.  refresh_index(mode="incremental"): only the appended rows are indexed.
  7.  optimizeIndex: compact the accumulated small files.
  8.  Delete -> restore -> vacuum lifecycle with the operation log on display.

Run:  python examples/walkthrough.py
"""

import glob
import json
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

from hyperspace_tpu import IndexConfig, IndexConstants
from hyperspace_tpu.engine import HyperspaceSession, col
from hyperspace_tpu.hyperspace import Hyperspace, disable_hyperspace, enable_hyperspace


def banner(step: str) -> None:
    print(f"\n=== {step} " + "=" * max(0, 70 - len(step)))


def log_states(system_path: str, name: str):
    entries = []
    for p in glob.glob(os.path.join(system_path, name, "_hyperspace_log", "*")):
        if os.path.basename(p).isdigit():
            with open(p) as f:
                entries.append((int(os.path.basename(p)), json.load(f).get("state")))
    return sorted(entries)


def main() -> None:
    base = tempfile.mkdtemp(prefix="hs_walkthrough_")
    sysdir = os.path.join(base, "indexes")
    try:
        s = HyperspaceSession(warehouse=base)
        s.conf.set(IndexConstants.INDEX_SYSTEM_PATH, sysdir)
        s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 8)
        s.conf.set(IndexConstants.INDEX_HYBRID_SCAN_ENABLED, True)
        hs = Hyperspace(s)

        banner("1. Source data: departments + employees")
        n = 2000
        rng = np.random.RandomState(0)
        s.write_parquet(
            {
                "deptId": np.arange(50, dtype=np.int64),
                "deptName": np.array([f"dept-{i:02d}" for i in range(50)]),
                "location": np.array(["NYC", "SEA", "SF", "ATX", "CHI"] * 10),
            },
            os.path.join(base, "departments"),
        )
        s.write_parquet(
            {
                "empId": np.arange(n, dtype=np.int64),
                "empDept": rng.randint(0, 50, n).astype(np.int64),
                "salary": (rng.rand(n) * 100000).round(2),
            },
            os.path.join(base, "employees"),
        )
        print(f"wrote {n} employees / 50 departments under {base}")

        def emp():
            return s.read.parquet(os.path.join(base, "employees"))

        def dept():
            return s.read.parquet(os.path.join(base, "departments"))

        def join_query():
            return (
                emp()
                .join(dept(), col("empDept") == col("deptId"))
                .select("empId", "salary", "deptName")
            )

        banner("2. Create covering indexes (bucketed by the join key)")
        hs.create_index(emp(), IndexConfig("empIdx", ["empDept"], ["empId", "salary"]))
        hs.create_index(dept(), IndexConfig("deptIdx", ["deptId"], ["deptName"]))
        for row in hs.indexes().rows():
            print("  ", row)
        print("log:", log_states(sysdir, "empIdx"))
        assert log_states(sysdir, "empIdx")[-1][1] == "ACTIVE"

        banner("3. EXPLAIN: the rewrite eliminates the shuffle")
        enable_hyperspace(s)
        captured = []
        hs.explain(join_query(), verbose=True, redirect=captured.append)
        explained = captured[0]
        print(explained)
        assert "empIdx" in explained and "deptIdx" in explained

        banner("4. Enable/disable round-trip: identical results")
        on_rows = join_query().sorted_rows()
        disable_hyperspace(s)
        off_rows = join_query().sorted_rows()
        assert on_rows == off_rows and len(on_rows) == n
        print(f"identical {len(on_rows)} rows with indexing on vs off")
        enable_hyperspace(s)

        banner("5. Append source data -> Hybrid Scan")
        from hyperspace_tpu.engine import io as eio
        from hyperspace_tpu.engine.table import Table

        eio.write_parquet(
            Table.from_pydict(
                {
                    "empId": np.arange(n, n + 100, dtype=np.int64),
                    "empDept": rng.randint(0, 50, 100).astype(np.int64),
                    "salary": (rng.rand(100) * 100000).round(2),
                }
            ),
            os.path.join(base, "employees", "part-00001.parquet"),
        )
        plan = join_query().explain_string()
        print(plan)
        assert "empIdx" in plan, "hybrid scan keeps using the index"
        assert join_query().count() == n + 100
        print(f"appended 100 rows; indexed join sees all {n + 100} without a rebuild")

        def latest_version_files() -> list:
            vdirs = glob.glob(os.path.join(sysdir, "empIdx", "v__=*"))
            latest = max(vdirs, key=lambda p: int(p.rsplit("=", 1)[1]))
            return glob.glob(os.path.join(latest, "part-*"))

        banner('6. refresh_index(mode="incremental")')
        hs.refresh_index("empIdx", mode="incremental")
        print("log:", log_states(sysdir, "empIdx"))
        print(f"{len(latest_version_files())} data files in the latest version")
        assert join_query().count() == n + 100

        banner("7. optimizeIndex: compact small files")
        before = len(latest_version_files())
        hs.optimize_index("empIdx")
        after = len(latest_version_files())
        print(f"{before} files -> {after} after compaction")
        assert after <= before
        assert join_query().count() == n + 100
        assert "empIdx" in join_query().explain_string()

        banner("8. Lifecycle: delete -> restore -> delete -> vacuum")
        hs.delete_index("empIdx")
        assert "empIdx" not in join_query().explain_string()
        hs.restore_index("empIdx")
        assert "empIdx" in join_query().explain_string()
        hs.delete_index("empIdx")
        hs.vacuum_index("empIdx")
        print("log:", log_states(sysdir, "empIdx"))
        remaining = glob.glob(os.path.join(sysdir, "empIdx", "v__=*", "part-*"))
        assert not remaining, "vacuum removed the data files"
        print("vacuumed: data files gone, tombstone log remains")

        print("\nWALKTHROUGH_OK")
    finally:
        shutil.rmtree(base, ignore_errors=True)


if __name__ == "__main__":
    main()
