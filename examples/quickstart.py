"""Hyperspace-TPU quickstart — the worked example from the reference's sample app
(`examples/scala/src/main/scala/App.scala:23-103`): departments/employees data,
index CRUD, a filter query and a join query accelerated by covering indexes, and
`explain` showing what the rewrite changed.

Run:  python examples/quickstart.py          (uses ./quickstart_data, cleaned up)
"""

import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Force CPU when no accelerator is reachable (the framework itself is
# backend-agnostic; on a TPU host just drop these two lines).
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
import jax

jax.config.update("jax_platforms", "cpu")

from hyperspace_tpu import IndexConfig, IndexConstants
from hyperspace_tpu.engine import HyperspaceSession, col
from hyperspace_tpu.hyperspace import Hyperspace, disable_hyperspace, enable_hyperspace


def main() -> None:
    base = tempfile.mkdtemp(prefix="hs_quickstart_")
    try:
        session = HyperspaceSession(warehouse=base)
        session.conf.set(IndexConstants.INDEX_SYSTEM_PATH, os.path.join(base, "indexes"))
        session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 8)

        # -- Sample data (the reference app's departments/employees) ----------
        departments = {
            "deptId": [10, 20, 30, 40],
            "deptName": ["Accounting", "Research", "Sales", "Operations"],
            "location": ["Seattle", "New York", "Chicago", "Boston"],
        }
        employees = {
            "empId": list(range(1, 9)),
            "empName": ["Clark", "Dave", "Ava", "Josh", "Kim", "Raj", "Lee", "Mia"],
            "empDeptId": [10, 20, 20, 30, 30, 30, 40, 10],
        }
        session.write_parquet(departments, os.path.join(base, "departments"))
        session.write_parquet(employees, os.path.join(base, "employees"))

        hs = Hyperspace(session)

        # -- Create covering indexes ------------------------------------------
        dept_df = session.read.parquet(os.path.join(base, "departments"))
        emp_df = session.read.parquet(os.path.join(base, "employees"))
        hs.create_index(dept_df, IndexConfig("deptIndex1", ["deptId"], ["deptName"]))
        hs.create_index(dept_df, IndexConfig("deptIndex2", ["location"], ["deptName"]))
        hs.create_index(emp_df, IndexConfig("empIndex", ["empDeptId"], ["empName"]))

        print("=== indexes ===")
        for row in hs.indexes().rows():
            print(row)

        # -- Filter query (FilterIndexRule) -----------------------------------
        def filter_query():
            return (
                session.read.parquet(os.path.join(base, "departments"))
                .filter(col("location") == "Seattle")
                .select("deptName", "location")
            )

        enable_hyperspace(session)
        print("\n=== filter query (indexed) ===")
        print(filter_query().collect().rows())
        print("\n=== explain ===")
        hs.explain(filter_query(), verbose=True)

        # -- Join query (JoinIndexRule: co-bucketed, shuffle-free) ------------
        def join_query():
            d = session.read.parquet(os.path.join(base, "departments"))
            e = session.read.parquet(os.path.join(base, "employees"))
            return (
                d.join(e, col("deptId") == col("empDeptId"))
                .select("deptName", "empName")
                .order_by("deptName", "empName")
            )

        print("\n=== join query (indexed, no exchange) ===")
        print(join_query().collect().rows())
        hs.explain(join_query())

        # -- Aggregation over the indexed join --------------------------------
        def agg_query():
            d = session.read.parquet(os.path.join(base, "departments"))
            e = session.read.parquet(os.path.join(base, "employees"))
            return (
                d.join(e, col("deptId") == col("empDeptId"))
                .group_by("deptName")
                .agg(headcount=("empName", "count"))
                .order_by(("headcount", False))
            )

        print("\n=== headcount by department ===")
        print(agg_query().collect().rows())

        # Oracle check: identical results with indexing off.
        indexed = join_query().collect().rows()
        disable_hyperspace(session)
        assert join_query().collect().rows() == indexed
        print("\nresults identical with indexing on/off — OK")

        # -- Lifecycle: delete / restore / vacuum -----------------------------
        hs.delete_index("deptIndex2")
        hs.restore_index("deptIndex2")
        hs.delete_index("deptIndex2")
        hs.vacuum_index("deptIndex2")
        print("after vacuum:", [r[0] for r in hs.indexes().rows()])
    finally:
        shutil.rmtree(base, ignore_errors=True)


if __name__ == "__main__":
    main()
