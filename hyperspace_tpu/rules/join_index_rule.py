"""JoinIndexRule: rewrite equi-joins to co-bucketed, shuffle-free index joins.

Parity: reference `index/rules/JoinIndexRule.scala:54-564`:
- `transformUp` on inner Join nodes (:59-87).
- Applicability: condition is equi-join CNF (`EqualTo`/`And` only, :188-194); both
  subplans linear with a single base relation (:219-220); every condition column maps
  L↔R in an exclusive one-to-one fashion (:287-326).
- Index selection per side (:407-418, :481-493): the index's indexed columns must be
  set-equal to that side's join columns, and every column of the side referenced in
  the plan must be covered by the index.
- Compatible pairs (:516-563): both indexes must list their indexed columns in the
  same order under the L→R mapping — this is what makes bucket b of the left index
  hold exactly the keys that bucket b of the right index holds.
- Ranking via JoinIndexRanker; rewrite substitutes each side's relation with its index
  scan WITH a BucketSpec so the sort-merge join runs with no shuffle (:137-162).
- Any exception → original plan; emits HyperspaceIndexUsageEvent on success.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

from ..engine.expr import Expr, extract_equi_join_keys
from ..engine.logical import JoinNode, LogicalPlan, ScanNode, find_single_relation
from ..index.log_entry import IndexLogEntry
from ..telemetry.event_logging import EventLoggerFactory
from ..telemetry.events import HyperspaceIndexUsageEvent
from ..util.resolver_utils import resolution_key
from .rule_utils import get_candidate_indexes, log_rule_failure, record_rule_decision


def _nkey(name: str, cs: bool) -> str:
    """Resolution key for one name under the session's case-sensitivity conf."""
    return resolution_key(name, cs)


def _norm(names, cs: bool) -> List[str]:
    return [_nkey(n, cs) for n in names]


def _collect_expr_refs(plan: LogicalPlan) -> List[str]:
    refs: List[str] = []
    from ..engine.logical import (
        AggregateNode,
        FilterNode,
        OrderByNode,
        ProjectNode,
        WithColumnNode,
    )

    for node in plan.collect_nodes():
        if isinstance(node, FilterNode):
            refs.extend(node.condition.references())
        elif isinstance(node, ProjectNode):
            refs.extend(node.column_names)
        elif isinstance(node, JoinNode):
            refs.extend(node.condition.references())
        elif isinstance(node, (AggregateNode, OrderByNode, WithColumnNode)):
            refs.extend(node.references())
    return refs


def _orient_pairs(
    pairs: List[Tuple[str, str]],
    lschema_names: List[str],
    rschema_names: List[str],
    cs: bool = False,
) -> Optional[List[Tuple[str, str]]]:
    """Orient each (a, b) pair as (left_col, right_col); None if any column is
    ambiguous or unresolvable (reference requires attrs to resolve to exactly one
    base relation, :287-326)."""
    lset, rset = set(_norm(lschema_names, cs)), set(_norm(rschema_names, cs))
    out = []
    for a, b in pairs:
        al, bl = _nkey(a, cs), _nkey(b, cs)
        a_in_l, a_in_r = al in lset, al in rset
        b_in_l, b_in_r = bl in lset, bl in rset
        if a_in_l and b_in_r and not (a_in_r or b_in_l):
            out.append((a, b))
        elif a_in_r and b_in_l and not (a_in_l or b_in_r):
            out.append((b, a))
        else:
            return None  # ambiguous or not from the two base relations
    return out


def _one_to_one(
    oriented: List[Tuple[str, str]], cs: bool = False
) -> Optional[Dict[str, str]]:
    """Exclusive one-to-one L→R column mapping; duplicates of the same pair are fine,
    conflicting mappings are not (reference :287-326)."""
    fwd: Dict[str, str] = {}
    bwd: Dict[str, str] = {}
    for l, r in oriented:
        ll, rl = _nkey(l, cs), _nkey(r, cs)
        if fwd.get(ll, rl) != rl or bwd.get(rl, ll) != ll:
            return None
        fwd[ll] = rl
        bwd[rl] = ll
    return fwd


def _usable_indexes(
    candidates, join_cols: List[str], required_cols: List[str], cs: bool = False
):
    """indexedCols set-equal to join cols AND all required ⊆ index cols
    (reference :481-493). Operates on CandidateIndex objects."""
    out = []
    jset = set(_norm(join_cols, cs))
    rset = set(_norm(required_cols, cs))
    for c in candidates:
        e = c.entry
        indexed = set(_norm(e.indexed_columns, cs))
        all_cols = set(_norm(e.indexed_columns + e.included_columns, cs))
        if indexed == jset and rset <= all_cols:
            out.append(c)
    return out


def _compatible_pairs(
    l_candidates, r_candidates, l_to_r: Dict[str, str], cs: bool = False
):
    """Pairs listing indexed columns in the same order under the mapping
    (reference :516-563). `l_to_r` maps and yields resolution keys."""
    out = []
    for lc in l_candidates:
        mapped = [l_to_r[c] for c in _norm(lc.entry.indexed_columns, cs)]
        for rc in r_candidates:
            if _norm(rc.entry.indexed_columns, cs) == [_nkey(m, cs) for m in mapped]:
                out.append((lc, rc))
    return out


def rank_join_pairs(pairs):
    """JoinIndexRanker: exact-match pairs beat hybrid ones, equal-bucket pairs first
    (zero shuffle), then higher bucket counts (more parallelism)
    (reference `rankers/JoinIndexRanker.scala:40-55`)."""

    def key(p):
        lc, rc = p
        li, ri = lc.entry, rc.entry
        equal = li.num_buckets == ri.num_buckets
        return (
            # Exact-match pairs first: ANY source-file drift (appended to merge
            # at query time, or deleted to lineage-prune at scan time) costs
            # per-query work an exact index avoids.
            len(lc.appended) + len(rc.appended) + len(lc.deleted) + len(rc.deleted),
            0 if equal else 1,
            -(li.num_buckets + ri.num_buckets),
        )

    return sorted(pairs, key=key)


#: Star recognition gate: ``HYPERSPACE_MULTIWAY=0`` keeps every star query
#: on the cascaded binary joins byte-for-byte (the wrapper is never emitted,
#: so the plan — and its fingerprint class — is exactly the pre-star one).
ENV_MULTIWAY = "HYPERSPACE_MULTIWAY"


def _rank_single(cands):
    """Single-side covering-index ranking for a star dimension: the
    JoinIndexRanker key restricted to one side — exact-match indexes first
    (no hybrid-append merge, no lineage prune), then higher bucket counts."""
    return sorted(
        cands,
        key=lambda c: (len(c.appended) + len(c.deleted), -c.entry.num_buckets),
    )


def _only_scan_filter(plan: LogicalPlan) -> bool:
    """True when a star side is just a relation under (optional) row
    filters: filters preserve per-row identity, so the side's table equals
    what the cascaded join would see. Projections/computed columns on a side
    are conservatively left to the cascade."""
    from ..engine.logical import FilterNode

    return all(
        isinstance(n, (FilterNode, ScanNode)) for n in plan.collect_nodes()
    )


_KIND = {"float32": "f", "float64": "f", "string": "s"}


def _key_kind(schema, name: str, cs: bool) -> Optional[str]:
    """Hash-kind of one join key ('i'/'f'/'s') — bucket assignment hashed
    each column in its OWN kind at build time, so a fact FK must hash in the
    dimension's kind to land in the dimension's buckets (the same guard as
    the physical planner's bucketed-path kinds check)."""
    for f in schema.fields:
        if _nkey(f.name, cs) == _nkey(name, cs):
            return _KIND.get(f.dtype, "i")
    return None


def _wrap_star(plan: LogicalPlan, root_refs, session, index_manager, cs: bool):
    """Recognize the star shape on the (possibly join-rewritten) plan and
    wrap its top join chain in a `StarJoinNode`. Any non-star shape returns
    the plan untouched — recognition is additive-only."""
    spine: List[LogicalPlan] = []
    node = plan
    while not isinstance(node, JoinNode):
        kids = node.children()
        if len(kids) != 1:
            return plan
        spine.append(node)
        node = kids[0]
    star = _try_star(node, root_refs, session, index_manager, cs)
    if star is None:
        return plan
    out: LogicalPlan = star
    for op in reversed(spine):
        out = op.with_children([out])
    return out


def _try_star(top: JoinNode, root_refs, session, index_manager, cs: bool):
    """Build a `StarJoinNode` over the left-deep inner equi-join chain
    rooted at `top`, or None when the shape/coverage rules don't hold:

    - >= 2 inner joins, left-deep, each with an equi-only condition;
    - the fact and every dimension side resolve to a single relation under
      only row filters;
    - every join's keys split exclusively fact-side vs THAT dimension (a
      name present on two sides would make cascaded resolution ambiguous);
    - key kinds match per pair (int/float/string — the bucket-hash space);
    - every dimension has a covering bucketed index on exactly its keys
      (the innermost dimension may already be index-substituted by the
      binary rewrite — reused as-is when it covers)."""
    from ..engine.logical import (
        FilterNode,
        HybridAppend,
        StarDimension,
        StarJoinNode,
    )
    from .filter_index_rule import _index_relation
    from .rule_utils import lineage_prune_condition

    chain: List[JoinNode] = []
    cur: LogicalPlan = top
    while isinstance(cur, JoinNode):
        if cur.how != "inner":
            return None
        chain.append(cur)
        cur = cur.left
    if len(chain) < 2:
        return None
    fact_plan = cur
    fact_scan = find_single_relation(fact_plan)
    if fact_scan is None or not _only_scan_filter(fact_plan):
        return None
    fact_names = fact_scan.output_schema.names
    fact_set = set(_norm(fact_names, cs))

    # Innermost join first — the cascade's fold order, which fixes the
    # star output's column naming (collision suffixes) and dim ordering.
    dims_raw = []
    for join in reversed(chain):
        dscan = find_single_relation(join.right)
        if dscan is None or not _only_scan_filter(join.right):
            return None
        dims_raw.append((join, dscan))
    dim_name_sets = [
        set(_norm(d.output_schema.names, cs)) for _, d in dims_raw
    ]

    hybrid = session.hs_conf.hybrid_scan_enabled
    dims: List[StarDimension] = []
    for i, (join, dscan) in enumerate(dims_raw):
        pairs = extract_equi_join_keys(join.condition)
        if not pairs:
            return None
        dnames = dscan.output_schema.names
        oriented = _orient_pairs(pairs, fact_names, dnames, cs)
        if oriented is None:
            return None
        f_to_d = _one_to_one(oriented, cs)
        if f_to_d is None:
            return None
        fkeys = list(dict.fromkeys(f for f, _ in oriented))
        dkeys = [f_to_d[_nkey(k, cs)] for k in fkeys]
        # Whole-star exclusivity: a fact key named in any dimension (or a
        # dim key named in the fact / another dimension) would resolve
        # differently — or collision-suffixed — in the cascade. Stay there.
        for k in fkeys:
            if any(_nkey(k, cs) in s for s in dim_name_sets):
                return None
        for k in dkeys:
            nk = _nkey(k, cs)
            if nk in fact_set or any(
                nk in s for j, s in enumerate(dim_name_sets) if j != i
            ):
                return None
        for fk, dk in zip(fkeys, dkeys):
            if _key_kind(fact_scan.output_schema, fk, cs) != _key_kind(
                dscan.output_schema, dk, cs
            ):
                return None
        dim_required = list(
            dict.fromkeys(
                [
                    n
                    for n in dnames
                    if _nkey(n, cs) in root_refs
                    or _nkey(n + "_r", cs) in root_refs
                ]
                + dkeys
            )
        )

        rel = dscan.relation
        if rel.index_name:
            # Already substituted by the binary rewrite (the innermost
            # join): reuse when it is bucketed on exactly this dimension's
            # keys and covers the required columns.
            spec = rel.bucket_spec
            if spec is None:
                return None
            if set(_norm(list(spec.bucket_columns), cs)) != set(
                _norm(dkeys, cs)
            ):
                return None
            if not set(_norm(dim_required, cs)) <= set(
                _norm(rel.schema.names, cs)
            ):
                return None
            dim_plan: LogicalPlan = join.right
            index_name, num_buckets = rel.index_name, spec.num_buckets
        else:
            cands = get_candidate_indexes(
                index_manager, dscan, hybrid, rule_name="JoinIndexRule"
            )
            usable = _usable_indexes(cands, dkeys, dim_required, cs)
            if not usable:
                return None
            cand = _rank_single(usable)[0]
            new_rel = _index_relation(cand.entry, with_bucket_spec=True)
            if cand.appended:
                new_rel.hybrid_append = HybridAppend(
                    files=cand.appended,
                    file_format=dscan.relation.file_format,
                    schema=dscan.relation.schema,
                    root_paths=list(dscan.relation.root_paths),
                    partition_spec=dscan.relation.partition_spec,
                )

            def replace(n, _scan=dscan, _rel=new_rel, _deleted=cand.deleted):
                if n is _scan or (
                    isinstance(n, ScanNode) and n.relation is _scan.relation
                ):
                    new_scan: LogicalPlan = ScanNode(_rel)
                    if _deleted:
                        new_scan = FilterNode(
                            lineage_prune_condition(_deleted), new_scan
                        )
                    return new_scan
                return n

            dim_plan = join.right.transform_up(replace)
            index_name, num_buckets = cand.entry.name, cand.entry.num_buckets

        dims.append(
            StarDimension(
                plan=dim_plan,
                fact_keys=fkeys,
                dim_keys=dkeys,
                dim_required=dim_required,
                index_name=index_name,
                num_buckets=num_buckets,
            )
        )

    fact_required = list(
        dict.fromkeys(
            [n for n in fact_names if _nkey(n, cs) in root_refs]
            + [k for d in dims for k in d.fact_keys]
        )
    )
    star = StarJoinNode(top, dims, fact_required)
    record_rule_decision(
        "JoinIndexRule",
        True,
        star_dims=len(dims),
        indexes=[d.index_name for d in dims],
        buckets=[d.num_buckets for d in dims],
    )
    EventLoggerFactory.get_logger(
        session.hs_conf.event_logger_class
    ).log_event(
        HyperspaceIndexUsageEvent(
            index_names=[d.index_name for d in dims],
            plan_before=top.tree_string(),
            plan_after=star.tree_string(),
            message="Multiway star-join recognized.",
        )
    )
    return star


class JoinIndexRule:
    """Rule protocol: apply(plan, session) -> plan."""

    def apply(self, plan: LogicalPlan, session) -> LogicalPlan:
        from .filter_index_rule import _index_relation
        from ..hyperspace import _index_manager_for

        try:
            index_manager = _index_manager_for(session)
            cs = session.hs_conf.case_sensitive

            def rewrite(node: LogicalPlan) -> LogicalPlan:
                # ANY join type with an equi-condition, like the reference's
                # wildcard matcher (`JoinIndexRule.scala:60` `Join(l, r, _,
                # Some(condition))`): the rewrite only swaps base relations
                # for covering index scans, which is row-set-preserving and
                # therefore sound for outer/semi/anti exactly as for inner.
                if not isinstance(node, JoinNode):
                    return node
                pairs = extract_equi_join_keys(node.condition)
                if not pairs:
                    return node
                l_scan = find_single_relation(node.left)
                r_scan = find_single_relation(node.right)
                if l_scan is None or r_scan is None:
                    return node
                if l_scan.relation.index_name or r_scan.relation.index_name:
                    return node  # already rewritten

                lnames = l_scan.output_schema.names
                rnames = r_scan.output_schema.names
                oriented = _orient_pairs(pairs, lnames, rnames, cs)
                if oriented is None:
                    record_rule_decision(
                        "JoinIndexRule", False, reason="unresolvable-join-columns"
                    )
                    return node
                l_to_r = _one_to_one(oriented, cs)
                if l_to_r is None:
                    record_rule_decision(
                        "JoinIndexRule", False, reason="not-one-to-one-keys"
                    )
                    return node

                lkeys = list(dict.fromkeys(l for l, _ in oriented))
                rkeys = [l_to_r[_nkey(k, cs)] for k in lkeys]

                # Required = every column of this side referenced anywhere in the
                # WHOLE query (expressions, other joins, the top-level output) +
                # this join's keys. The reference computes this against the
                # column-pruned plan Spark hands it (:407-418); this engine prunes
                # at physical planning, so the rule intersects full-plan references
                # with each side's schema instead — an unreferenced source column
                # must not disqualify an otherwise-covering index.
                root_refs = set(
                    _norm(plan.output_schema.names, cs)
                    + _norm(_collect_expr_refs(plan), cs)
                )
                l_required = list(
                    dict.fromkeys(
                        [n for n in lnames if _nkey(n, cs) in root_refs] + lkeys
                    )
                )
                r_required = list(
                    dict.fromkeys(
                        [n for n in rnames if _nkey(n, cs) in root_refs] + rkeys
                    )
                )

                hybrid = session.hs_conf.hybrid_scan_enabled
                l_candidates = get_candidate_indexes(
                    index_manager, l_scan, hybrid, rule_name="JoinIndexRule"
                )
                r_candidates = get_candidate_indexes(
                    index_manager, r_scan, hybrid, rule_name="JoinIndexRule"
                )
                l_usable = _usable_indexes(l_candidates, lkeys, l_required, cs)
                r_usable = _usable_indexes(r_candidates, rkeys, r_required, cs)
                compatible = _compatible_pairs(l_usable, r_usable, l_to_r, cs)
                if not compatible:
                    record_rule_decision(
                        "JoinIndexRule",
                        False,
                        reason=(
                            "no-candidate-index"
                            if not (l_candidates or r_candidates)
                            else "no-compatible-index-pair"
                        ),
                        left_usable=[c.entry.name for c in l_usable],
                        right_usable=[c.entry.name for c in r_usable],
                    )
                    return node
                lc, rc = rank_join_pairs(compatible)[0]
                li, ri = lc.entry, rc.entry

                def substitute(side: LogicalPlan, scan: ScanNode, cand):
                    from ..engine.logical import FilterNode, HybridAppend
                    from .rule_utils import lineage_prune_condition

                    new_rel = _index_relation(cand.entry, with_bucket_spec=True)
                    if cand.appended:
                        # Hybrid Scan: appended source rows are shuffle-unioned into
                        # the index's buckets at execution time.
                        new_rel.hybrid_append = HybridAppend(
                            files=cand.appended,
                            file_format=scan.relation.file_format,
                            schema=scan.relation.schema,
                            root_paths=list(scan.relation.root_paths),
                            partition_spec=scan.relation.partition_spec,
                        )

                    def replace(n: LogicalPlan) -> LogicalPlan:
                        if n is scan or (
                            isinstance(n, ScanNode) and n.relation is scan.relation
                        ):
                            new_scan: LogicalPlan = ScanNode(new_rel)
                            if cand.deleted:
                                # Delete tolerance: prune vanished files' rows by
                                # lineage. The filter preserves bucket membership
                                # and in-bucket order, so the co-bucketed
                                # no-shuffle join stays sound over it (the planner
                                # unwraps bucket-preserving filters).
                                new_scan = FilterNode(
                                    lineage_prune_condition(cand.deleted), new_scan
                                )
                            return new_scan
                        return n

                    return side.transform_up(replace)

                new_left = substitute(node.left, l_scan, lc)
                new_right = substitute(node.right, r_scan, rc)
                new_plan = JoinNode(new_left, new_right, node.condition, node.how)
                record_rule_decision(
                    "JoinIndexRule",
                    True,
                    indexes=[li.name, ri.name],
                    buckets=[li.num_buckets, ri.num_buckets],
                    hybrid_appended=len(lc.appended) + len(rc.appended),
                    lineage_pruned=len(lc.deleted) + len(rc.deleted),
                )
                EventLoggerFactory.get_logger(
                    session.hs_conf.event_logger_class
                ).log_event(
                    HyperspaceIndexUsageEvent(
                        index_names=[li.name, ri.name],
                        plan_before=node.tree_string(),
                        plan_after=new_plan.tree_string(),
                        message="Join index rule applied.",
                    )
                )
                return new_plan

            new_plan = plan.transform_up(rewrite)
            if os.environ.get(ENV_MULTIWAY, "") != "0":
                root_refs = set(
                    _norm(plan.output_schema.names, cs)
                    + _norm(_collect_expr_refs(plan), cs)
                )
                new_plan = _wrap_star(
                    new_plan, root_refs, session, index_manager, cs
                )
            return new_plan
        except Exception as e:
            log_rule_failure(session, "JoinIndexRule", e)
            return plan
