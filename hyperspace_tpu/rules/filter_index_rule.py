"""FilterIndexRule: rewrite Filter-over-relation plans to scan a covering index.

Parity: reference `index/rules/FilterIndexRule.scala:38-253`:
- Pattern: Project? > Filter > Relation (via `ExtractFilterNode`, :211-253).
- Applicability: the index covers all output + filter columns AND the filter references
  the head (first) indexed column (:183-195).
- Rewrite: replace the relation with a parquet scan over the index's files, with NO
  bucket spec — full scan parallelism is preferred for filters (:100-132).
- Ranking is first-candidate (reference TODO, :202-208).
- Any exception → return the original plan unchanged (:74-78).
- Emits HyperspaceIndexUsageEvent on success (:121-127).

Bucket pruning composes with the scan-layer row-group pushdown (PR 5): the
rewrite keeps the filter DIRECTLY over the substituted index scan, so the
planner threads the same condition into it (`ScanExec.pushdown`) — a point
lookup first drops every `part-<bucket>` file but the literal's hash bucket
(here), then decodes only the row groups of THAT file whose key-sorted zone
maps can contain the literal (`engine.pushdown`). The bucket-pruning decision
and the pushdown therefore act on one condition at two granularities.
"""

from __future__ import annotations

from typing import List, Optional

from ..engine.expr import Expr
from ..engine.logical import (
    FilterNode,
    LogicalPlan,
    ProjectNode,
    ScanNode,
    SourceRelation,
    UnionNode,
)
from ..index.log_entry import IndexLogEntry
from ..telemetry.event_logging import EventLoggerFactory
from ..telemetry.events import HyperspaceIndexUsageEvent
from ..util.resolver_utils import resolve, resolve_all
from .rule_utils import (
    get_candidate_indexes,
    index_files_as_statuses,
    log_rule_failure,
    record_rule_decision,
)


def _extract_filter_node(plan: LogicalPlan):
    """Match Project?>Filter>Scan; returns (project_or_none, filter, scan) or None."""
    if isinstance(plan, ProjectNode) and isinstance(plan.child, FilterNode):
        f = plan.child
        if isinstance(f.child, ScanNode):
            return plan, f, f.child
    if isinstance(plan, FilterNode) and isinstance(plan.child, ScanNode):
        return None, plan, plan.child
    return None


def index_covers_plan(
    output_columns: List[str],
    filter_columns: List[str],
    entry: IndexLogEntry,
    case_sensitive: bool = False,
) -> bool:
    """All referenced columns ⊆ index columns AND the filter references the head
    indexed column (reference :183-195)."""
    index_cols = entry.indexed_columns + entry.included_columns
    head = entry.indexed_columns[0]
    if resolve(head, filter_columns, case_sensitive) is None:
        return False
    return resolve_all(output_columns + filter_columns, index_cols, case_sensitive) is not None


class FilterIndexRule:
    """Rule protocol: apply(plan, session) -> plan."""

    def apply(self, plan: LogicalPlan, session) -> LogicalPlan:
        from ..hyperspace import _index_manager_for  # late import to avoid cycle

        try:
            index_manager = _index_manager_for(session)

            def rewrite(node: LogicalPlan) -> LogicalPlan:
                m = _extract_filter_node(node)
                if m is None:
                    return node
                project, filt, scan = m
                if scan.relation.index_name is not None:
                    return node  # already rewritten
                output_columns = (
                    project.column_names if project is not None else scan.output_schema.names
                )
                filter_columns = sorted(filt.condition.references())
                candidates = get_candidate_indexes(
                    index_manager,
                    scan,
                    hybrid_scan=session.hs_conf.hybrid_scan_enabled,
                    rule_name="FilterIndexRule",
                )
                if not candidates:
                    record_rule_decision(
                        "FilterIndexRule", False, reason="no-candidate-index"
                    )
                    return node
                usable = [
                    c
                    for c in candidates
                    if index_covers_plan(
                        list(output_columns),
                        filter_columns,
                        c.entry,
                        session.hs_conf.case_sensitive,
                    )
                ]
                if not usable:
                    record_rule_decision(
                        "FilterIndexRule",
                        False,
                        reason="not-covering",
                        candidates=[c.entry.name for c in candidates],
                    )
                    return node
                chosen = rank(usable)
                best = chosen.entry
                pruned_files = None
                if session.hs_conf.filter_bucket_pruning:
                    pruned_files = _bucket_pruned_files(
                        best, filt.condition, session.hs_conf.case_sensitive
                    )
                index_child: LogicalPlan = ScanNode(
                    _index_relation(best, files=pruned_files)
                )
                if chosen.deleted:
                    # Delete tolerance: prune rows of vanished source files by
                    # lineage BEFORE the output projection drops the column.
                    from .rule_utils import lineage_prune_condition

                    index_child = FilterNode(
                        lineage_prune_condition(chosen.deleted), index_child
                    )
                if chosen.appended:
                    # Hybrid Scan (extension): union the index data with the source
                    # files appended since the build, both projected to the needed
                    # columns so the union schemas line up.
                    needed = list(dict.fromkeys(list(output_columns) + filter_columns))
                    appended_rel = SourceRelation(
                        root_paths=list(scan.relation.root_paths),
                        file_format=scan.relation.file_format,
                        schema=scan.relation.schema,
                        files=chosen.appended,
                        options=dict(scan.relation.options),
                        partition_spec=scan.relation.partition_spec,
                    )
                    index_child = UnionNode(
                        [
                            ProjectNode(needed, index_child),
                            ProjectNode(needed, ScanNode(appended_rel)),
                        ]
                    )
                new_filter = FilterNode(filt.condition, index_child)
                # Always project: preserves the original output column order (the
                # index stores columns in indexed+included order, not source order).
                new_plan: LogicalPlan = ProjectNode(list(output_columns), new_filter)
                record_rule_decision(
                    "FilterIndexRule",
                    True,
                    indexes=[best.name],
                    bucket_pruned_files=(
                        None if pruned_files is None else len(pruned_files)
                    ),
                    hybrid_appended=len(chosen.appended),
                    lineage_pruned=len(chosen.deleted),
                )
                EventLoggerFactory.get_logger(
                    session.hs_conf.event_logger_class
                ).log_event(
                    HyperspaceIndexUsageEvent(
                        index_names=[best.name],
                        plan_before=node.tree_string(),
                        plan_after=new_plan.tree_string(),
                        message="Filter index rule applied.",
                    )
                )
                return new_plan

            return plan.transform_up(rewrite)
        except Exception as e:
            # Never break the user's query over an index problem (reference :74-78),
            # but record the swallowed failure (warning + telemetry event).
            log_rule_failure(session, "FilterIndexRule", e)
            return plan


def _head_equality_values(condition, head: str, case_sensitive: bool):
    """Literal values v such that `condition` implies head == v: a top-level
    conjunct of the form `head == lit` (either orientation) or
    `head IN [lits]`. None = no such conjunct (no pruning). Conservative by
    construction: only AND-descent, only plain literals."""
    from ..engine.expr import BinaryOp, Col, IsIn, Lit

    def is_head(e) -> bool:
        return isinstance(e, Col) and resolve(e.name, [head], case_sensitive) is not None

    stack = [condition]
    while stack:
        e = stack.pop()
        if isinstance(e, BinaryOp) and e.op == "and":
            stack += [e.left, e.right]
            continue
        if isinstance(e, BinaryOp) and e.op == "==":
            for a, b in ((e.left, e.right), (e.right, e.left)):
                if is_head(a) and isinstance(b, Lit):
                    return [b.value]
        if isinstance(e, IsIn) and is_head(e.child):
            return list(e.values)
    return None


def _bucket_of_literal(value, dtype: str, num_buckets: int):
    """The hash bucket a literal of the head column lands in at BUILD time, or
    None when the literal can't be put in the column's canonical hash space
    (then pruning must not apply). Uses the exact `ops.hashing.bucket_id`
    machinery the build uses, so build and prune can never disagree."""
    import numpy as np

    from ..engine.schema import BOOL, FLOAT64, INT32, INT64, STRING
    from ..engine.table import Column

    if isinstance(value, bool):
        arr = np.asarray([value]) if dtype == BOOL else None
    elif dtype == STRING:
        arr = np.asarray([value]) if isinstance(value, str) else None
    elif dtype in (INT32, INT64):
        # Integers hash from their int64 bit pattern; an integral float
        # literal equals the same int rows, a fractional or out-of-int64-range
        # one equals none (skip pruning rather than model the empty set).
        if isinstance(value, (int, np.integer)) or (
            isinstance(value, float) and float(value).is_integer()
        ):
            v = int(value)
            arr = (
                np.asarray([v], dtype=np.int64)
                if -(2**63) <= v < 2**63
                else None
            )
        else:
            arr = None
    elif dtype == FLOAT64:
        arr = (
            np.asarray([float(value)], dtype=np.float64)
            if isinstance(value, (int, float, np.integer, np.floating))
            else None
        )
    else:
        arr = None  # float32 storage widens before hashing; literal space differs
    if arr is None:
        return None
    import jax.numpy as jnp

    from ..ops.hashing import bucket_id

    col = Column.from_values(arr)
    return int(np.asarray(bucket_id([col], [jnp.asarray(col.data)], num_buckets))[0])


def _bucket_pruned_files(entry: IndexLogEntry, condition, case_sensitive: bool):
    """The subset of index data files a head-column point lookup can touch:
    rows with head == v live ONLY in v's hash bucket (the build's partitioning
    invariant), so every other `part-<bucket>` file is skippable. None = no
    pruning (no usable equality conjunct, unhashable literal, or an index
    file outside the `part-<bucket>` naming contract, e.g. after compaction)."""
    import os as _os
    import re

    from ..engine.schema import Schema

    values = _head_equality_values(condition, entry.indexed_columns[0], case_sensitive)
    if values is None:
        return None
    schema = Schema.from_json_string(entry.schema_json)
    head = resolve(entry.indexed_columns[0], schema.names, case_sensitive)
    if head is None:
        return None
    dtype = schema.field(head).dtype
    num_buckets = entry.num_buckets
    buckets = set()
    for v in values:
        b = _bucket_of_literal(v, dtype, num_buckets)
        if b is None:
            return None
        buckets.add(b)
    kept = []
    for f in index_files_as_statuses(entry):
        m = re.match(r"part-(\d+)\.parquet$", _os.path.basename(f.path))
        if m is None:
            return None  # unexpected layout: never prune what we can't place
        if int(m.group(1)) in buckets:
            kept.append(f)
    return kept


def rank(candidates):
    """FilterIndexRanker: exact-match candidates beat hybrid-scan ones (less
    source-file drift first), then first (reference ranking TODO at :202-208)."""
    return sorted(candidates, key=lambda c: len(c.appended) + len(c.deleted))[0]


def _index_relation(
    entry: IndexLogEntry, with_bucket_spec: bool = False, files=None
) -> SourceRelation:
    """Build the substituted relation over the index's own data files.

    No BucketSpec for filter scans (parallelism over all files, reference :100-132);
    the join rule passes with_bucket_spec=True. `files` restricts the scan to a
    subset (bucket pruning) — the relation is tagged so explain shows the prune."""
    from ..engine.logical import BucketSpec
    from ..engine.schema import Schema

    spec = None
    if with_bucket_spec:
        spec = BucketSpec(
            num_buckets=entry.num_buckets,
            bucket_columns=tuple(entry.indexed_columns),
            sort_columns=tuple(entry.indexed_columns),
        )
    all_files = index_files_as_statuses(entry)
    pruned_by = []
    if files is not None and len(files) < len(all_files):
        pruned_by = ["FilterIndexRule:bucket"]
    return SourceRelation(
        root_paths=[entry.index_location()],
        file_format="parquet",
        schema=Schema.from_json_string(entry.schema_json),
        files=all_files if files is None else files,
        bucket_spec=spec,
        index_name=entry.name,
        log_entry_id=entry.id,
        pruned_by=pruned_by,
    )
