"""Shared rule machinery.

Parity: reference `index/rules/RuleUtils.scala` — `getCandidateIndexes` fetches ACTIVE
indexes and keeps those whose recorded signature provider recomputes the same
signature on the query's relation node (memoized per provider name);
`getLogicalRelation` extracts the single relation of a linear plan.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..actions import states
from ..engine.logical import LogicalPlan, ScanNode, find_single_relation
from ..index.log_entry import FileInfo, IndexLogEntry
from ..index.signatures import create_provider
from ..storage.filesystem import FileStatus


def get_candidate_indexes(
    index_manager, plan: LogicalPlan, hybrid_scan: bool = False, kind: str = "CoveringIndex"
) -> List["CandidateIndex"]:
    """ACTIVE indexes applicable to `plan` (normally a relation node).

    Exact applicability = the recorded signature provider recomputes the same
    signature. With `hybrid_scan` (extension, BASELINE config 3), an index whose
    recorded source files are a strict SUBSET of the current files is also a
    candidate, carrying the appended files to merge at execution time."""
    signature_map: Dict[str, Optional[str]] = {}

    def signature_valid(entry: IndexLogEntry) -> bool:
        source_sig = entry.signature()
        if source_sig.provider not in signature_map:
            provider = create_provider(source_sig.provider)
            signature_map[source_sig.provider] = provider.signature(plan)
        computed = signature_map[source_sig.provider]
        return computed is not None and computed == source_sig.value

    def appended_files(entry: IndexLogEntry) -> Optional[List[FileStatus]]:
        """Current-files minus recorded; None unless recorded ⊊ current with no
        recorded file missing/changed."""
        if not isinstance(plan, ScanNode):
            return None
        recorded = {
            (f.name, f.size, f.modified_time)
            for r in entry.relations
            for f in r.data.file_infos()
        }
        current = plan.relation.files
        current_keys = {(f.path, f.size, f.modified_time) for f in current}
        if not recorded <= current_keys:
            return None  # a recorded file vanished or changed: not hybrid-scannable
        appended = [
            f for f in current if (f.path, f.size, f.modified_time) not in recorded
        ]
        return appended if appended else None

    out: List[CandidateIndex] = []
    for e in index_manager.get_indexes([states.ACTIVE]):
        if e.kind != kind or not e.created:
            continue
        if signature_valid(e):
            out.append(CandidateIndex(e, []))
        elif hybrid_scan:
            appended = appended_files(e)
            if appended is not None:
                out.append(CandidateIndex(e, appended))
    return out


class CandidateIndex:
    """An applicable index + the source files appended since it was built
    (empty for an exact signature match)."""

    def __init__(self, entry: IndexLogEntry, appended: List[FileStatus]):
        self.entry = entry
        self.appended = appended


def get_scan_node(plan: LogicalPlan) -> Optional[ScanNode]:
    return find_single_relation(plan)


def index_files_as_statuses(entry: IndexLogEntry) -> List[FileStatus]:
    """The index's data files as FileStatus (for building the substituted relation)."""
    return [
        FileStatus(path=f.name, size=f.size, modified_time=f.modified_time, is_dir=False)
        for f in entry.content.file_infos()
    ]


def log_rule_failure(session, rule_name: str, exc: BaseException) -> None:
    """Record a swallowed rule failure: stdlib warning + telemetry event.

    The non-fatal policy itself mirrors the reference
    (`FilterIndexRule.scala:74-78`); this makes the swallow observable so a
    programming error in a rule no longer vanishes without trace."""
    import logging

    logging.getLogger("hyperspace_tpu.rules").warning(
        "%s failed; query falls back to the original plan: %s: %s",
        rule_name,
        type(exc).__name__,
        exc,
    )
    try:
        from ..telemetry.event_logging import EventLoggerFactory
        from ..telemetry.events import HyperspaceRuleFailureEvent

        EventLoggerFactory.get_logger(session.hs_conf.event_logger_class).log_event(
            HyperspaceRuleFailureEvent(
                rule_name=rule_name,
                exception=f"{type(exc).__name__}: {exc}",
                message=f"{rule_name} failed; original plan returned.",
            )
        )
    except Exception:
        pass  # telemetry must never turn a swallowed failure into a raised one
