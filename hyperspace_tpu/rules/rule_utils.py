"""Shared rule machinery.

Parity: reference `index/rules/RuleUtils.scala` — `getCandidateIndexes` fetches ACTIVE
indexes and keeps those whose recorded signature provider recomputes the same
signature on the query's relation node (memoized per provider name);
`getLogicalRelation` extracts the single relation of a linear plan.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..actions import states
from ..engine.logical import LogicalPlan, ScanNode, find_single_relation
from ..index.log_entry import FileInfo, IndexLogEntry
from ..index.signatures import create_provider
from ..storage.filesystem import FileStatus


def get_candidate_indexes(
    index_manager,
    plan: LogicalPlan,
    hybrid_scan: bool = False,
    kind: str = "CoveringIndex",
    deletes_without_lineage_ok: bool = False,
    rule_name: Optional[str] = None,
) -> List["CandidateIndex"]:
    """ACTIVE indexes applicable to `plan` (normally a relation node).

    Exact applicability = the recorded signature provider recomputes the same
    signature. With `hybrid_scan` (extension, BASELINE config 3), an index
    whose recorded source inventory DRIFTED is also a candidate when the drift
    is recoverable: appended files are carried to merge at execution time, and
    files that vanished are tolerated iff the index records lineage — their
    rows are pruned at scan time (`hybrid_delta`). A file changed IN PLACE
    always disqualifies.

    Deletes an incremental refresh already FOLDED into the log entry
    (`entry.deleted_source_files()`, docs/reliability.md "Live tables") ride
    every candidate — exact matches included: the refreshed signature covers
    the post-delete source, but the index DATA still holds those rows until
    compaction physically removes them, so the scan-time lineage prune is
    mandatory on every path."""
    signature_map: Dict[str, Optional[str]] = {}

    def signature_valid(entry: IndexLogEntry) -> bool:
        source_sig = entry.signature()
        if source_sig.provider not in signature_map:
            provider = create_provider(source_sig.provider)
            signature_map[source_sig.provider] = provider.signature(plan)
        computed = signature_map[source_sig.provider]
        return computed is not None and computed == source_sig.value

    def hybrid_delta(entry: IndexLogEntry):
        """(appended_files, deleted_paths) between the recorded source inventory
        and the current one, or None when the index cannot Hybrid-Scan it:

        - a recorded file CHANGED in place (path present, size/mtime differ):
          its old rows are inseparable from new ones — never scannable;
        - a recorded file VANISHED: tolerable IFF the index carries lineage
          (`_data_file_name` per row) — its rows are pruned at scan time by a
          bucket-preserving filter. Without lineage, not scannable — except
          for index kinds whose data is PER SOURCE FILE
          (`deletes_without_lineage_ok`, e.g. data skipping: a vanished file
          simply vanishes from the scan; surviving files' sketches stay
          valid)."""
        if not isinstance(plan, ScanNode):
            return None
        recorded = {
            (f.name, f.size, f.modified_time)
            for r in entry.relations
            for f in r.data.file_infos()
        }
        current = plan.relation.files
        current_keys = {(f.path, f.size, f.modified_time) for f in current}
        current_paths = {f.path for f in current}
        deleted: List[str] = []
        for name, size, mtime in recorded:
            if (name, size, mtime) in current_keys:
                continue
            if name in current_paths:
                return None  # changed in place: rows not separable
            deleted.append(name)
        if deleted and not deletes_without_lineage_ok and not _has_lineage(entry):
            return None
        appended = [
            f for f in current if (f.path, f.size, f.modified_time) not in recorded
        ]
        if not appended and not deleted:
            return None
        return appended, sorted(deleted)

    from ..index import quarantine

    out: List[CandidateIndex] = []
    for e in index_manager.get_indexes([states.ACTIVE]):
        if e.kind != kind or not e.created:
            continue
        if quarantine.is_quarantined(e.name):
            # A corrupt data file condemned this index (`index/quarantine`):
            # it sits out until rebuilt, and the skip is attributed to the
            # asking rule so the fallback is visible in the metrics snapshot.
            from ..telemetry import metrics

            metrics.counter(
                f"rule.{rule_name}.quarantined" if rule_name else "rule.quarantined"
            ).inc()
            continue
        if not _hash_scheme_compatible(e):
            # Built under a different bucket/sketch hash scheme: bucket
            # co-location (and bloom probing) with the CURRENT scheme would
            # be silently wrong — the index must sit out until refreshed.
            continue
        folded = e.deleted_source_files()
        if signature_valid(e):
            out.append(CandidateIndex(e, [], folded))
            _update_staleness(e, [])
        elif hybrid_scan:
            delta = hybrid_delta(e)
            if delta is not None:
                out.append(
                    CandidateIndex(
                        e, delta[0], sorted(set(delta[1]) | set(folded))
                    )
                )
                _update_staleness(e, delta[0])
    return out


def _update_staleness(entry: IndexLogEntry, appended) -> None:
    """Refresh the per-index `index.staleness_s.<name>` gauge: now − the
    newest UNINDEXED source file's mtime (0 when the index covers the current
    source). Updated wherever the engine actually diffs an index against the
    live source — candidate selection here, and the refresh path
    (`index.collection_manager`)."""
    import time

    from ..telemetry import metrics

    if not appended:
        staleness = 0.0
    else:
        newest_ms = max(f.modified_time for f in appended)
        staleness = max(0.0, time.time() - newest_ms / 1000.0)
    metrics.gauge(f"index.staleness_s.{entry.name}").set(round(staleness, 3))


def _hash_scheme_compatible(entry: IndexLogEntry) -> bool:
    """Whether the index was bucketed/sketched under the CURRENT hash scheme
    (`IndexConstants.HASH_SCHEME_VERSION`). Entries with no recorded version
    predate the field and used scheme 1."""
    from ..config import IndexConstants

    props = getattr(entry.derived_dataset, "properties", None) or {}
    v = props.get(IndexConstants.HASH_SCHEME_KEY)
    return v in (None, IndexConstants.HASH_SCHEME_VERSION)


def _has_lineage(entry: IndexLogEntry) -> bool:
    """Whether the index data carries the per-row source-file lineage column."""
    return entry.has_lineage()


def lineage_prune_condition(deleted: List[str]):
    """The bucket-preserving scan-time filter that prunes rows of vanished
    source files: `NOT (_data_file_name IN deleted)`. Compaction keeps bucket
    membership and in-bucket order, so co-bucketed joins stay sound over the
    pruned table (same argument as side filters in `FilterExec.execute_concat`)."""
    from ..config import IndexConstants
    from ..engine.expr import Col, IsIn, Not

    return Not(IsIn(Col(IndexConstants.DATA_FILE_NAME_COLUMN), list(deleted)))


class CandidateIndex:
    """An applicable index + the source-file delta since it was built: files
    appended (merged at execution time) and files deleted (their rows pruned
    via lineage at scan time). Both empty for an exact signature match."""

    def __init__(
        self,
        entry: IndexLogEntry,
        appended: List[FileStatus],
        deleted: Optional[List[str]] = None,
    ):
        self.entry = entry
        self.appended = appended
        self.deleted = deleted or []


def get_scan_node(plan: LogicalPlan) -> Optional[ScanNode]:
    return find_single_relation(plan)


def index_files_as_statuses(entry: IndexLogEntry) -> List[FileStatus]:
    """The index's data files as FileStatus (for building the substituted relation)."""
    return [
        FileStatus(path=f.name, size=f.size, modified_time=f.modified_time, is_dir=False)
        for f in entry.content.file_infos()
    ]


def record_rule_decision(
    rule_name: str,
    applied: bool,
    reason: Optional[str] = None,
    indexes: Optional[List[str]] = None,
    **extra,
) -> None:
    """One optimizer-rule decision, recorded at the node where it was made:
    an `applied`/`skipped` counter in the metrics registry, and (while a
    query trace is active) a decision entry on the ambient rule span — so
    `explain(analyze=True)` and the JSONL export can say which rule rewrote
    the plan and why the others sat out. Recorded only at nodes that MATCHED
    a rule's pattern (a rule visiting an irrelevant node is not a decision)."""
    from ..telemetry import metrics, tracing

    verdict = "applied" if applied else "skipped"
    metrics.counter(f"rule.{rule_name}.{verdict}").inc()
    sp = tracing.current_span()
    if sp is not None:
        d = {"rule": rule_name, "applied": applied}
        if reason:
            d["reason"] = reason
        if indexes:
            d["indexes"] = list(indexes)
        if extra:
            d.update(extra)
        sp.append_attr("decisions", d)


def log_rule_failure(session, rule_name: str, exc: BaseException) -> None:
    """Record a swallowed rule failure: stdlib warning + telemetry event.

    The non-fatal policy itself mirrors the reference
    (`FilterIndexRule.scala:74-78`); this makes the swallow observable so a
    programming error in a rule no longer vanishes without trace."""
    import logging

    logging.getLogger("hyperspace_tpu.rules").warning(
        "%s failed; query falls back to the original plan: %s: %s",
        rule_name,
        type(exc).__name__,
        exc,
    )
    record_rule_decision(
        rule_name, False, reason=f"error: {type(exc).__name__}: {exc}"
    )
    try:
        from ..telemetry.event_logging import EventLoggerFactory
        from ..telemetry.events import HyperspaceRuleFailureEvent

        EventLoggerFactory.get_logger(session.hs_conf.event_logger_class).log_event(
            HyperspaceRuleFailureEvent(
                rule_name=rule_name,
                exception=f"{type(exc).__name__}: {exc}",
                message=f"{rule_name} failed; original plan returned.",
            )
        )
    except Exception:
        pass  # telemetry must never turn a swallowed failure into a raised one
