"""Shared rule machinery.

Parity: reference `index/rules/RuleUtils.scala` — `getCandidateIndexes` fetches ACTIVE
indexes and keeps those whose recorded signature provider recomputes the same
signature on the query's relation node (memoized per provider name);
`getLogicalRelation` extracts the single relation of a linear plan.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..actions import states
from ..engine.logical import LogicalPlan, ScanNode, find_single_relation
from ..index.log_entry import FileInfo, IndexLogEntry
from ..index.signatures import create_provider
from ..storage.filesystem import FileStatus


def get_candidate_indexes(index_manager, plan: LogicalPlan) -> List[IndexLogEntry]:
    """ACTIVE indexes whose signature matches `plan` (normally a relation node)."""
    signature_map: Dict[str, Optional[str]] = {}

    def signature_valid(entry: IndexLogEntry) -> bool:
        source_sig = entry.signature()
        if source_sig.provider not in signature_map:
            provider = create_provider(source_sig.provider)
            signature_map[source_sig.provider] = provider.signature(plan)
        computed = signature_map[source_sig.provider]
        return computed is not None and computed == source_sig.value

    all_indexes = index_manager.get_indexes([states.ACTIVE])
    return [e for e in all_indexes if e.created and signature_valid(e)]


def get_scan_node(plan: LogicalPlan) -> Optional[ScanNode]:
    return find_single_relation(plan)


def index_files_as_statuses(entry: IndexLogEntry) -> List[FileStatus]:
    """The index's data files as FileStatus (for building the substituted relation)."""
    return [
        FileStatus(path=f.name, size=f.size, modified_time=f.modified_time, is_dir=False)
        for f in entry.content.file_infos()
    ]
