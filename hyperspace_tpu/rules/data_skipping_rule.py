"""DataSkippingFilterRule: prune source files from scans using per-file sketches.

Extension rule (BASELINE.md config 4). Unlike the covering-index rules (which REPLACE
the relation), this rule keeps the source relation and shrinks its file list: for each
filter conjunct on a sketched column, files whose MinMax range excludes the literal or
whose BloomFilter rejects it are dropped. Runs after the covering rules, so it applies
to scans they left in place.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

from ..engine import io as engine_io
from ..engine.expr import Expr, split_conjuncts
from ..engine.logical import FilterNode, LogicalPlan, ScanNode, SourceRelation
from ..engine.pushdown import minmax_keeps, normalize_conjunct
from ..index.dataskipping import (
    DATA_SKIPPING_KIND,
    BloomFilterSketch,
    MinMaxSketch,
    bloom_probe,
    hex_to_bits,
    sketches_of,
)
from ..telemetry.event_logging import EventLoggerFactory
from ..telemetry.events import HyperspaceIndexUsageEvent
from ..util.resolver_utils import resolution_key
from .rule_utils import get_candidate_indexes, log_rule_failure, record_rule_decision


# Conjunct normalization and the [min, max]-zone decision are the SHARED
# zone-map evaluator (`engine.pushdown`) — one soundness contract for this
# rule's file/row-group sketches AND the scan layer's row-group pushdown.


def _zones_exclude(zones, op: str, value) -> bool:
    """True when EVERY recorded row-group zone of a file excludes
    `col op value` — the row-group MinMaxSketch's file-prune decision. A
    missing zone list ([]) or a stats-less zone (None) keeps the file."""
    if not zones:
        return False
    for z in zones:
        if z is None or minmax_keeps(op, value, z[0], z[1]):
            return False
    return True


class DataSkippingFilterRule:
    """Rule protocol: apply(plan, session) -> plan."""

    def __init__(self):
        # Sketch tables cached across queries, keyed by the entry's content file
        # list — a refresh/optimize writes new files, so the key changes and stale
        # sketches age out naturally.
        self._sketch_cache: Dict[tuple, dict] = {}

    def apply(self, plan: LogicalPlan, session) -> LogicalPlan:
        from ..hyperspace import _index_manager_for

        try:
            index_manager = _index_manager_for(session)
            cs = session.hs_conf.case_sensitive

            def nkey(n: str) -> str:
                return resolution_key(n, cs)

            def sketch_data(entry):
                key = (entry.name, tuple(entry.content.files()))
                if key not in self._sketch_cache:
                    t = engine_io.read_files(entry.content.files(), "parquet")
                    self._sketch_cache = {
                        k: v for k, v in self._sketch_cache.items() if k[0] != entry.name
                    }
                    self._sketch_cache[key] = t.to_pydict()
                return self._sketch_cache[key]

            def rewrite(node: LogicalPlan) -> LogicalPlan:
                if not (isinstance(node, FilterNode) and isinstance(node.child, ScanNode)):
                    return node
                scan = node.child
                if scan.relation.index_name is not None:
                    return node  # covering-index scans have no per-file sketches
                # Hybrid semantics are safe here: with appended-only changes the
                # recorded files are unchanged (sketches still valid) and appended
                # files are absent from the sketch, so they are always kept.
                candidates = get_candidate_indexes(
                    index_manager,
                    scan,
                    hybrid_scan=session.hs_conf.hybrid_scan_enabled,
                    # Sketches are PER SOURCE FILE: a vanished file vanishes
                    # from the scan itself, surviving files' sketches stay
                    # valid — deletes need no lineage here.
                    deletes_without_lineage_ok=True,
                    kind=DATA_SKIPPING_KIND,
                    rule_name="DataSkippingFilterRule",
                )
                if not candidates:
                    return node

                conjuncts = [normalize_conjunct(c) for c in split_conjuncts(node.condition)]
                conjuncts = [c for c in conjuncts if c is not None]
                if not conjuncts:
                    return node

                keep = {f.path: True for f in scan.relation.files}
                used_indexes: List[str] = []
                for cand in candidates:
                    entry = cand.entry
                    data = sketch_data(entry)
                    files_in_sketch = data.get("_file", [])
                    row_of = {p: i for i, p in enumerate(files_in_sketch)}
                    applied = False
                    for s in sketches_of(entry):
                        for op, col_name, value in conjuncts:
                            if nkey(col_name) != nkey(s.column):
                                continue
                            column_dtype = scan.relation.schema.field(col_name).dtype
                            for path in list(keep):
                                if not keep[path] or path not in row_of:
                                    continue  # unknown file (e.g. appended): keep
                                i = row_of[path]
                                if isinstance(s, MinMaxSketch) and op in (
                                    "==", "<", "<=", ">", ">=",
                                ):
                                    mn = data[f"min_{s.column}"][i]
                                    mx = data[f"max_{s.column}"][i]
                                    if not minmax_keeps(op, value, mn, mx):
                                        keep[path] = False
                                        applied = True
                                    elif s.granularity == "rowgroup":
                                        # File range straddles the literal:
                                        # the per-row-group zones may still
                                        # prove no single row group contains
                                        # it (clustered data).
                                        raw = data.get(f"rgzm_{s.column}")
                                        zones = (
                                            json.loads(raw[i]) if raw else []
                                        )
                                        if _zones_exclude(zones, op, value):
                                            keep[path] = False
                                            applied = True
                                elif isinstance(s, BloomFilterSketch) and op in ("==", "in"):
                                    bits = hex_to_bits(
                                        data[f"bloom_{s.column}"][i], s.num_bits
                                    )
                                    values = value if op == "in" else [value]
                                    if not any(
                                        bloom_probe(bits, v, column_dtype, s.num_hashes)
                                        for v in values
                                    ):
                                        keep[path] = False
                                        applied = True
                    if applied:
                        used_indexes.append(entry.name)

                kept_files = [f for f in scan.relation.files if keep[f.path]]
                if len(kept_files) == len(scan.relation.files):
                    record_rule_decision(
                        "DataSkippingFilterRule",
                        False,
                        reason="no-files-pruned",
                        candidates=[c.entry.name for c in candidates],
                    )
                    return node

                rel = scan.relation
                pruned = SourceRelation(
                    root_paths=list(rel.root_paths),
                    file_format=rel.file_format,
                    schema=rel.schema,
                    files=kept_files,
                    options=dict(rel.options),
                    pruned_by=sorted(set(used_indexes)),
                    partition_spec=rel.partition_spec,
                )
                new_node = FilterNode(node.condition, ScanNode(pruned))
                record_rule_decision(
                    "DataSkippingFilterRule",
                    True,
                    indexes=sorted(set(used_indexes)),
                    files_pruned=len(rel.files) - len(kept_files),
                    files_total=len(rel.files),
                )
                EventLoggerFactory.get_logger(
                    session.hs_conf.event_logger_class
                ).log_event(
                    HyperspaceIndexUsageEvent(
                        index_names=sorted(set(used_indexes)),
                        plan_before=node.tree_string(),
                        plan_after=new_node.tree_string(),
                        message="Data skipping index applied "
                        f"({len(rel.files) - len(kept_files)} of {len(rel.files)} files pruned).",
                    )
                )
                return new_node

            return plan.transform_up(rewrite)
        except Exception as e:
            log_rule_failure(session, "DataSkippingFilterRule", e)
            return plan
