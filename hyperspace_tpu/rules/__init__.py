from .data_skipping_rule import DataSkippingFilterRule  # noqa: F401
from .filter_index_rule import FilterIndexRule  # noqa: F401
from .join_index_rule import JoinIndexRule  # noqa: F401
