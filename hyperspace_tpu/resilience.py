"""Resilient execution: bounded retries with backoff, query deadlines, budgets.

The engine's availability story (PAPER.md: all metadata on the lake, optimistic
concurrency, no external catalog) assumed faults either never happen or kill
the query. This module is the middle ground, applied at every lake-touching
site (`engine/io.py` decode-pool reads and footer parses, bucket-file writes,
`index/log_manager.py` log writes):

- **`retry_io(point, fn)`** — retries `fn` on transient faults
  (`exceptions.is_transient`) up to ``HYPERSPACE_IO_RETRIES`` times with
  exponential backoff + jitter, ticking ``io.retries.*`` counters, the active
  query ledger (``io_retries``), and the ambient span (``io_retries`` attr, so
  `explain(analyze=True)` shows what was retried). Permanent faults and
  exhausted retries propagate unchanged.
- **`query_scope(name)`** — one per root query action (collect / count /
  create_index / refresh_index; nested scopes reuse the outer one). Carries
  the query DEADLINE (``HYPERSPACE_QUERY_TIMEOUT_S``) and the per-query RETRY
  BUDGET (``HYPERSPACE_QUERY_RETRY_BUDGET``) — a query whose sites each retry
  within bounds can still exceed its budget under systemic faults, which
  raises `RetryBudgetExceededError` instead of limping on.
- **`check_deadline(where)`** — the cooperative cancellation hook, called at
  chunk/pool boundaries in the streaming and decode paths. Past the deadline
  it raises a classified `QueryTimeoutError`; pools then drain through their
  existing try/finally shutdowns and the only-cache-on-success contract
  guarantees no partial cache/memo entries survive.
- **`use_scope(scope)`** — pool workers run in fresh contexts; the submitting
  code captures `current_scope()` and adopts it in the worker body, exactly
  like `accounting.use_ledger` / `tracing.span(parent=...)`.

Cost when idle: `check_deadline` is one contextvar read; `retry_io`'s happy
path is one function call around the operation.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import random
import threading
import time
from typing import Callable, Iterator, Optional, TypeVar

from .exceptions import (
    QueryTimeoutError,
    RetryBudgetExceededError,
    is_transient,
)
from .telemetry import accounting as _accounting
from .telemetry import metrics as _metrics
from .telemetry import tracing as _tracing

ENV_IO_RETRIES = "HYPERSPACE_IO_RETRIES"
ENV_RETRY_BACKOFF_S = "HYPERSPACE_RETRY_BACKOFF_S"
ENV_QUERY_RETRY_BUDGET = "HYPERSPACE_QUERY_RETRY_BUDGET"
ENV_QUERY_TIMEOUT_S = "HYPERSPACE_QUERY_TIMEOUT_S"

_DEFAULT_IO_RETRIES = 2  # retries per operation (attempts = retries + 1)
_DEFAULT_BACKOFF_S = 0.02
_DEFAULT_RETRY_BUDGET = 256
_BACKOFF_CAP_S = 2.0

_RETRY_ATTEMPTS = _metrics.counter("io.retries.attempts")
_RETRY_EXHAUSTED = _metrics.counter("io.retries.exhausted")
_TIMEOUTS = _metrics.counter("query.timeouts")


def max_retries() -> int:
    try:
        return max(0, int(os.environ.get(ENV_IO_RETRIES, "") or _DEFAULT_IO_RETRIES))
    except ValueError:
        return _DEFAULT_IO_RETRIES


def _backoff_base_s() -> float:
    try:
        return max(
            0.0, float(os.environ.get(ENV_RETRY_BACKOFF_S, "") or _DEFAULT_BACKOFF_S)
        )
    except ValueError:
        return _DEFAULT_BACKOFF_S


def retry_budget() -> int:
    try:
        return max(
            0,
            int(os.environ.get(ENV_QUERY_RETRY_BUDGET, "") or _DEFAULT_RETRY_BUDGET),
        )
    except ValueError:
        return _DEFAULT_RETRY_BUDGET


def query_timeout_s() -> Optional[float]:
    raw = os.environ.get(ENV_QUERY_TIMEOUT_S)
    if not raw:
        return None
    try:
        v = float(raw)
    except ValueError:
        return None
    return v if v > 0 else None


class QueryScope:
    """Deadline + retry-budget state of one root query action. `lane` is the
    serving-layer priority lane captured at open (None outside the serving
    layer) — pool workers adopt the scope, so the cooperative yield gate
    below sees the lane on every thread working for this query."""

    __slots__ = (
        "name",
        "start_mono",
        "deadline_mono",
        "timeout_s",
        "_lock",
        "retries",
        "lane",
        "plan_decisions",
        "stage_ledger",
    )

    def __init__(self, name: str, timeout_s: Optional[float]):
        self.name = name
        self.start_mono = time.monotonic()
        self.timeout_s = timeout_s
        self.deadline_mono = (
            None if timeout_s is None else self.start_mono + timeout_s
        )
        self._lock = threading.Lock()
        self.retries = 0
        self.lane = _lane.get()
        # The adaptive planner's PlanDecisions for the running query (None
        # until `planner.decisions_scope` stamps it). Rides the scope for the
        # same reason `lane` does: pool workers adopt the scope, so gates on
        # every thread working for this query see one decisions object.
        self.plan_decisions = None
        # Per-stage cost accumulator (telemetry/stage_ledger.py) — lazily
        # created on first stage bracket, rides the scope so every adopted
        # worker thread bills the same ledger. None until attribution stamps.
        self.stage_ledger = None

    def charge_retry(self) -> int:
        with self._lock:
            self.retries += 1
            return self.retries


_scope: "contextvars.ContextVar[Optional[QueryScope]]" = contextvars.ContextVar(
    "hyperspace_query_scope", default=None
)

#: Serving-layer priority lane of the CURRENT submission ("interactive" /
#: "batch"; None outside the serving layer). Captured onto each QueryScope
#: at open so pool workers inherit it through `use_scope`.
_lane: "contextvars.ContextVar[Optional[str]]" = contextvars.ContextVar(
    "hyperspace_serve_lane", default=None
)

#: Cooperative yield gate: the serving scheduler registers a hook that
#: BATCH-lane queries call at the same chunk/pool boundaries as the deadline
#: check, letting a 5 ms point lookup claim the core from a 500 ms cold scan
#: WITHOUT preemption (threads can't be preempted mid-GIL; boundaries can
#: pause). None (the default, and whenever serving is unused) costs one
#: predicate per check_deadline.
_yield_hook: Optional[Callable[[], None]] = None


@contextlib.contextmanager
def lane_scope(lane: Optional[str]) -> Iterator[None]:
    """Tag query scopes opened under this context with a serving lane."""
    if lane is None:
        yield
        return
    token = _lane.set(lane)
    try:
        yield
    finally:
        _lane.reset(token)


def current_lane() -> Optional[str]:
    """The ambient serving lane (None outside the serving layer) — what the
    query ledger captures at open so history records and the SLO reporter
    can slice by lane."""
    sc = _scope.get()
    if sc is not None and sc.lane is not None:
        return sc.lane
    return _lane.get()


def register_yield_hook(fn: Optional[Callable[[], None]]) -> None:
    """Install (or clear) the batch-lane cooperative yield hook — called by
    `serve.scheduler` when its first worker spawns."""
    global _yield_hook
    _yield_hook = fn


def current_scope() -> Optional[QueryScope]:
    return _scope.get()


@contextlib.contextmanager
def query_scope(name: str) -> Iterator[QueryScope]:
    """Open the resilience scope of one root action; nested under an existing
    scope it yields that scope unchanged (one deadline/budget per outermost
    action, matching the one-query_id-per-root rule)."""
    existing = _scope.get()
    if existing is not None:
        yield existing
        return
    sc = QueryScope(name, query_timeout_s())
    token = _scope.set(sc)
    try:
        yield sc
    finally:
        _scope.reset(token)


@contextlib.contextmanager
def use_scope(sc: Optional[QueryScope]) -> Iterator[None]:
    """Adopt `sc` on THIS thread (pool workers run in fresh contexts; the
    submitter captures `current_scope()` — the scope twin of `use_ledger`)."""
    if sc is None:
        yield
        return
    token = _scope.set(sc)
    try:
        yield
    finally:
        _scope.reset(token)


def check_deadline(where: str = "") -> None:
    """Cooperative cancellation: raise a classified `QueryTimeoutError` when
    the ambient query scope's deadline has passed. One contextvar read when no
    scope or no deadline is set."""
    sc = _scope.get()
    if sc is None:
        return
    if _yield_hook is not None and sc.lane == "batch":
        # Chunk/pool boundaries double as the serving layer's cooperative
        # yield points: a batch query pauses briefly here while interactive
        # work is in flight (bounded inside the hook — never starvation).
        _yield_hook()
    if sc.deadline_mono is None:
        return
    now = time.monotonic()
    if now < sc.deadline_mono:
        return
    _TIMEOUTS.inc()
    elapsed = now - sc.start_mono
    at = f" at {where}" if where else ""
    raise QueryTimeoutError(
        f"query '{sc.name}' exceeded HYPERSPACE_QUERY_TIMEOUT_S="
        f"{sc.timeout_s:g}s (elapsed {elapsed:.3f}s{at}); workers drained, "
        "no partial cache/memo entries were committed",
        elapsed_s=elapsed,
        timeout_s=sc.timeout_s or 0.0,
    )


def remaining_s() -> Optional[float]:
    """Seconds until the ambient deadline (None = no deadline)."""
    sc = _scope.get()
    if sc is None or sc.deadline_mono is None:
        return None
    return max(0.0, sc.deadline_mono - time.monotonic())


def reliability_rollup(snapshot: Optional[dict] = None) -> dict:
    """Compact reliability summary over a `metrics.snapshot()` — THE shared
    schema of `bench_detail.reliability` and the exporter frames'
    `reliability` key (one producer, so the gates/alerts reading either can
    never see drifted field sets)."""
    if snapshot is None:
        snapshot = _metrics.snapshot()
    c = snapshot.get("counters", {})
    try:
        from .index import quarantine as _quarantine

        quarantined = sorted(_quarantine.snapshot())
    except Exception:
        quarantined = []
    return {
        "faults_injected": c.get("faults.injected", 0),
        "io_retries": c.get("io.retries.attempts", 0),
        "retries_exhausted": c.get("io.retries.exhausted", 0),
        "query_timeouts": c.get("query.timeouts", 0),
        "quarantine_events": c.get("index.quarantine.events", 0),
        "staging_reclaimed": c.get("index.staging.reclaimed", 0),
        "quarantined": quarantined,
    }


T = TypeVar("T")


def retry_io(point: str, fn: Callable[[], T]) -> T:
    """Run `fn`, retrying transient failures with exponential backoff + jitter.

    `point` names the site for the ``io.retries.<point>`` counter (the fault
    points of `telemetry.faults` reuse their names here, so a chaos run's
    injections and retries line up by name). The retry sleep never outlives
    the ambient deadline — a query about to time out fails promptly rather
    than sleeping through its budget."""
    retries = max_retries()
    attempt = 0
    while True:
        check_deadline(point)
        try:
            return fn()
        except BaseException as e:
            transient = is_transient(e)
            if not transient or attempt >= retries:
                # "Exhausted" means precisely: a RETRYABLE fault hit the
                # attempt cap (including a cap of zero) — a permanent error
                # raised after some retries is a different outcome and must
                # not inflate the gated counter.
                if transient:
                    _RETRY_EXHAUSTED.inc()
                raise
            attempt += 1
            sc = _scope.get()
            if sc is not None and sc.charge_retry() > retry_budget():
                raise RetryBudgetExceededError(
                    f"query '{sc.name}' exceeded its retry budget "
                    f"({retry_budget()} retries; HYPERSPACE_QUERY_RETRY_BUDGET)"
                ) from e
            _RETRY_ATTEMPTS.inc()
            _metrics.counter(f"io.retries.{point}").inc()
            _accounting.add("io_retries", 1)
            sp = _tracing.current_span()
            if sp is not None:
                sp.inc_attr("io_retries", 1)
            delay = _backoff_base_s() * (2 ** (attempt - 1))
            delay = min(delay, _BACKOFF_CAP_S) * (0.5 + random.random())
            rem = remaining_s()
            if rem is not None:
                delay = min(delay, rem)
            if delay > 0:
                time.sleep(delay)
