"""Serving SLO monitor: per-lane latency objectives and burn rates.

The serving layer (PR 9) reports latency *distributions* but has no notion
of an *objective* — nothing says "interactive p99 must stay under 250 ms"
or tells an operator how fast the error budget is burning. This module is
that layer, in the standard SRE shape:

- **Objectives** ride env knobs: ``HYPERSPACE_SLO_INTERACTIVE_P99_MS``
  (default 250) / ``HYPERSPACE_SLO_BATCH_P99_MS`` (default 5000) — or
  ``HYPERSPACE_SLO_<LANE>_P99_MS`` for custom lanes — with one shared
  compliance target ``HYPERSPACE_SLO_TARGET`` (default 0.99: 99 % of a
  lane's queries must finish inside its objective).
- **Observation** happens at serve completion (`serve.scheduler` calls
  `observe(lane, wall_s, tenant)` for every executed submission, in both
  the concurrent and the ``HYPERSPACE_SERVING=0`` inline paths), so the
  measured latency is the CLIENT's submit→result experience, queue wait
  included — the only latency an SLO can honestly be about.
- **Burn rates** are computed over sliding windows (5 m and 1 h):
  ``burn = observed_error_rate / (1 - target)`` — burn 1.0 spends the
  budget exactly at sustainable rate; the classic fast-burn page threshold
  (burn ≥ 14.4 over 5 m, i.e. a 30-day budget gone in ~2 days) warns once
  per lane and ticks ``slo.fast_burn_alerts``.

Surfaces: ``slo.<lane>.total|violations`` counters in the registry,
the ``slo`` key of exporter frames, dedicated series in
`exporter.prometheus_text`, and ``bench_detail.serving.slo``.

Cost: one deque append + two integer bumps per served query; idle lanes
hold nothing. The monitor is process-wide (lanes are process-wide).
"""

from __future__ import annotations

import os
import threading
import time
import warnings
from collections import deque
from typing import Dict, Optional

from . import metrics as _metrics

ENV_TARGET = "HYPERSPACE_SLO_TARGET"
ENV_INTERACTIVE_P99_MS = "HYPERSPACE_SLO_INTERACTIVE_P99_MS"
ENV_BATCH_P99_MS = "HYPERSPACE_SLO_BATCH_P99_MS"

_DEFAULT_TARGET = 0.99
_DEFAULT_OBJECTIVE_MS = {"interactive": 250.0, "batch": 5000.0}
_FALLBACK_OBJECTIVE_MS = 5000.0

#: (window seconds, label) — multi-window burn rates, short to long.
WINDOWS = ((300.0, "5m"), (3600.0, "1h"))
#: Google-SRE fast-burn page threshold on the short window.
FAST_BURN_THRESHOLD = 14.4
#: Minimum events in the short window before a fast-burn alert can fire
#: (3 bad queries out of 5 is startup noise, not a burning budget).
FAST_BURN_MIN_EVENTS = 20

#: Per-lane sliding event window (ts, ok): 65536 events retain the FULL 5 m
#: window up to ~218 qps sustained (and the full 1 h up to ~18 qps) — far
#: above this engine's measured serving throughput (~66 qps, ~4 MB/lane at
#: this bound). Past that rate a window silently truncates to the retained
#: span; `summary()` reports the actual coverage via `window_<w>_covered_s`
#: so an operator never reads a truncated burn as a full-window figure.
_EVENTS_MAXLEN = 65536

#: Per-(lane) tenant compliance map bound, same rationale as the tenant
#: rollup cap in `accounting`.
TENANT_MAX = 256
TENANT_OVERFLOW = "<other>"

_FAST_BURN_ALERTS = _metrics.counter("slo.fast_burn_alerts")


def target() -> float:
    try:
        v = float(os.environ.get(ENV_TARGET, "") or _DEFAULT_TARGET)
    except ValueError:
        v = _DEFAULT_TARGET
    return min(max(v, 0.5), 0.99999)


def objective_ms(lane: str) -> float:
    env = os.environ.get(f"HYPERSPACE_SLO_{lane.upper()}_P99_MS")
    if env:
        try:
            v = float(env)
            if v > 0:
                return v
        except ValueError:
            pass
    return _DEFAULT_OBJECTIVE_MS.get(lane, _FALLBACK_OBJECTIVE_MS)


class SLOMonitor:
    """Process-wide SLO state: per-lane sliding windows + lifetime totals
    (+ a bounded per-tenant compliance map)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._events: Dict[str, deque] = {}
        self._totals: Dict[str, list] = {}  # lane -> [total, violations]
        self._tenants: Dict[str, Dict[str, list]] = {}  # lane -> tenant -> [t, v]
        self._fast_burn_warned: set = set()
        # Fast-burn check rate limit: lane -> [last_check_mono, events_since].
        # The window walk is O(events in 5m); running it on EVERY completion
        # would make the serving hot path quadratic in qps. One check per
        # second OR per FAST_BURN_MIN_EVENTS completions bounds the cost
        # without letting a burst slip past unexamined.
        self._fast_check: Dict[str, list] = {}

    def observe(
        self,
        lane: str,
        wall_s: float,
        tenant: Optional[str] = None,
        failed: bool = False,
    ) -> None:
        """`failed=True` marks the event a violation REGARDLESS of latency:
        an outage where every query errors out in 2 ms must burn the error
        budget, not read as 100% compliance (the SLI is "answered correctly
        within the objective", not "returned quickly")."""
        lane = lane or "batch"
        ok = (not failed) and (wall_s * 1000.0) <= objective_ms(lane)
        now = time.monotonic()
        with self._lock:
            ev = self._events.get(lane)
            if ev is None:
                ev = self._events[lane] = deque(maxlen=_EVENTS_MAXLEN)
            ev.append((now, ok))
            tot = self._totals.get(lane)
            if tot is None:
                tot = self._totals[lane] = [0, 0]
            tot[0] += 1
            if not ok:
                tot[1] += 1
            if tenant is not None:
                tmap = self._tenants.setdefault(lane, {})
                if tenant not in tmap and len(tmap) >= TENANT_MAX:
                    tenant = TENANT_OVERFLOW
                tt = tmap.setdefault(tenant, [0, 0])
                tt[0] += 1
                if not ok:
                    tt[1] += 1
            fc = self._fast_check.setdefault(lane, [0.0, 0])
            fc[1] += 1
            due = (now - fc[0] >= 1.0) or fc[1] >= FAST_BURN_MIN_EVENTS
            if due:
                fc[0], fc[1] = now, 0
        _metrics.counter(f"slo.{lane}.total").inc()
        if not ok:
            _metrics.counter(f"slo.{lane}.violations").inc()
        if due:
            self._maybe_fast_burn(lane)

    def _window_stats(self, lane: str, window_s: float, now: float):
        """(total, bad, covered_s) over the trailing window (lock held)."""
        ev = self._events.get(lane)
        if not ev:
            return 0, 0, 0.0
        cutoff = now - window_s
        total = bad = 0
        oldest = now
        for ts, ok in reversed(ev):
            if ts < cutoff:
                break
            total += 1
            oldest = ts
            if not ok:
                bad += 1
        return total, bad, (now - oldest if total else 0.0)

    def burn_rate(self, lane: str, window_s: float) -> Optional[float]:
        """``error_rate / error_budget`` over the trailing window; None
        before any event in the window. 1.0 = spending the budget exactly
        at the sustainable rate."""
        now = time.monotonic()
        with self._lock:
            total, bad, _cov = self._window_stats(lane, window_s, now)
        if total == 0:
            return None
        budget = 1.0 - target()
        return (bad / total) / budget if budget > 0 else float(bad)

    def _maybe_fast_burn(self, lane: str) -> None:
        now = time.monotonic()
        short_s = WINDOWS[0][0]
        with self._lock:
            total, bad, _cov = self._window_stats(lane, short_s, now)
        if total < FAST_BURN_MIN_EVENTS:
            return
        budget = 1.0 - target()
        burn = (bad / total) / budget if budget > 0 else float(bad)
        if burn < FAST_BURN_THRESHOLD:
            return
        _FAST_BURN_ALERTS.inc()
        # Fast-burn is the second profile-capture trigger (the first is the
        # history Nσ anomaly): grab one bounded trace window while the burn
        # is actually happening. Rate-limited/rotated inside; never raises
        # into the serving completion path.
        try:
            from . import device_observatory as _devobs

            _devobs.maybe_capture(
                "slo_fast_burn",
                {"lane": lane, "burn": round(burn, 2), "window": WINDOWS[0][1]},
            )
        except Exception:
            pass
        if lane in self._fast_burn_warned:
            return
        self._fast_burn_warned.add(lane)
        warnings.warn(
            f"hyperspace SLO: lane '{lane}' is fast-burning its error budget "
            f"(burn {burn:.1f}x over the last {WINDOWS[0][1]}; objective "
            f"{objective_ms(lane):g} ms at target {target():.2%}). Further "
            "alerts tick slo.fast_burn_alerts silently.",
            RuntimeWarning,
            stacklevel=3,
        )

    def summary(self) -> dict:
        """Per-lane SLO state: objective, target, lifetime compliance,
        multi-window burn rates, and per-tenant compliance. Empty dict when
        nothing was ever observed (schema-stable exporter frames)."""
        now = time.monotonic()
        with self._lock:
            lanes = list(self._totals)
            out = {}
            for lane in lanes:
                total, violations = self._totals[lane]
                entry = {
                    "objective_ms": objective_ms(lane),
                    "target": target(),
                    "total": total,
                    "violations": violations,
                    "compliance": round(1.0 - violations / total, 6) if total else None,
                }
                budget = 1.0 - target()
                for window_s, label in WINDOWS:
                    wt, wb, cov = self._window_stats(lane, window_s, now)
                    if wt:
                        burn = (wb / wt) / budget if budget > 0 else float(wb)
                        entry[f"burn_{label}"] = round(burn, 4)
                        entry[f"window_{label}_n"] = wt
                        # Honesty signal: when the event deque overflowed,
                        # the "1h" burn actually covers only this many
                        # seconds — an operator must not read a truncated
                        # window as a full-hour figure.
                        entry[f"window_{label}_covered_s"] = round(cov, 1)
                entry["fast_burn"] = lane in self._fast_burn_warned
                tmap = self._tenants.get(lane)
                if tmap:
                    entry["tenants"] = {
                        t: {
                            "total": tv[0],
                            "violations": tv[1],
                            "compliance": round(1.0 - tv[1] / tv[0], 6),
                        }
                        for t, tv in sorted(tmap.items())
                    }
                out[lane] = entry
            return out

    def reset(self) -> None:
        with self._lock:
            self._events.clear()
            self._totals.clear()
            self._tenants.clear()
            self._fast_burn_warned.clear()


_MONITOR = SLOMonitor()


def monitor() -> SLOMonitor:
    return _MONITOR


def observe(
    lane: str, wall_s: float, tenant: Optional[str] = None, failed: bool = False
) -> None:
    _MONITOR.observe(lane, wall_s, tenant, failed=failed)


def summary() -> dict:
    return _MONITOR.summary()


def reset() -> None:
    _MONITOR.reset()


def compliance_over(records, lane_key="lane", wall_key="wall_s") -> dict:
    """Offline SLO compliance over a HISTORY record stream (ledger dicts):
    what `tools/hsreport.py` renders for a stored workload — the same
    objective/target knobs as the live monitor, applied to recorded wall
    clocks AND recorded failures (``status: "error"`` ledgers violate
    regardless of latency, mirroring `observe(failed=True)`). Residual
    divergence from the live view is the queue wait: ledger wall starts at
    execution, the live SLI at admission."""
    lanes: Dict[str, list] = {}
    for led in records:
        lane = led.get(lane_key)
        wall = led.get(wall_key)
        if lane is None or not isinstance(wall, (int, float)):
            continue
        tot = lanes.setdefault(lane, [0, 0])
        tot[0] += 1
        if led.get("status") == "error" or wall * 1000.0 > objective_ms(lane):
            tot[1] += 1
    return {
        lane: {
            "objective_ms": objective_ms(lane),
            "target": target(),
            "total": t,
            "violations": v,
            "compliance": round(1.0 - v / t, 6) if t else None,
            "met": (1.0 - v / t) >= target() if t else None,
        }
        for lane, (t, v) in sorted(lanes.items())
    }
