"""Per-query stage ledger: cost vectors attributed to the pipeline stage
that spent them.

The four existing sinks each answer one question in isolation — stage spans
(PR 4) know WHEN a stage ran, the query ledger (PR 6) knows WHAT the query
spent, the device observatory (PR 14) knows what the DEVICE did, and the
planner outcome store (PR 17) knows WHICH arm ran. None of them joins cost
to stage, so a pushdown win can hide behind a cold decode and a packed-codes
regression behind a warm cache (whole-wall A/B misattribution — ROADMAP
item 2's named frontier). This module is the join key:

- **`stage_scope(name)`** — a contextvar marking the CURRENT stage. Every
  `StageTimings.timed(stage)` block composes it automatically, so the
  streamed executors' pad/probe/expand/verify/gather/eval/decode/filter/
  partial/merge brackets label themselves; dedicated sites label ``h2d``
  (device_cache uploads) and ``exchange`` (the mesh all-to-all). Exiting the
  scope banks the stage's wall seconds on the ambient `resilience.QueryScope`
  — busy time across workers, like `StageTimings` (stages overlap; walls are
  NOT a wall-clock partition).
- **Counter stamping** — `accounting.add` forwards every cost-vector counter
  (`_COUNTER_VECTOR`) through `stamp_counter`, billing it to the ambient
  stage (or the literal ``<unlabeled>`` bucket, so stage totals reconcile
  with the whole-query counters BY CONSTRUCTION). Pool workers inherit the
  submitting stage: the submit sites capture `worker_stage()` next to the
  existing `use_ledger`/`use_scope` adoption.
- **`close_stages()`** — the root-ledger-close join: per-stage cost vectors
  ``{wall_s, device_s, bytes_decoded, bytes_h2d, bytes_padded, xla_compiles,
  rows}`` attached as the ledger's ``stages`` key, from where history
  baselines, hsreport's stage-drift table, `explain(analyze=True)`'s
  Attribution section, and the exporter all read it.

The stage WALLS live on the `QueryScope` (not the `QueryLedger`): the
adaptive planner's stage-grain learning (`plananalysis/attribution.py`)
must work with every telemetry sink off, and the scope is the one object
every worker thread already adopts. Counter stamps ride the same ledger —
they only exist when a `QueryLedger` is live anyway.

Zero-cost-off: ``HYPERSPACE_STAGE_ATTRIBUTION=0`` makes `stage_scope` one
env read and the stamp sites one flag test (the bool is captured once per
ledger open); results are byte-identical in both states (this module only
observes). Pinned by tests/test_stage_attribution.py's counting oracle.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import threading
import time
from typing import Dict, Iterator, List, Optional

ENV_STAGE_ATTRIBUTION = "HYPERSPACE_STAGE_ATTRIBUTION"

#: The bucket counters land in when no stage is ambient — kept visible (not
#: dropped) so per-stage totals always sum to the whole-query counters.
UNLABELED = "<unlabeled>"

#: Query-ledger counter key -> stage cost-vector field. Counters outside
#: this map are whole-query-only (stage attribution does not claim them).
_COUNTER_VECTOR = {
    "device_time_s": "device_s",
    "bytes_decoded": "bytes_decoded",
    "device_upload_bytes": "bytes_h2d",
    "pad_bytes_padded": "bytes_padded",
    "xla_compiles": "xla_compiles",
}

#: Canonical cost-vector field order (rendering + docs).
VECTOR_FIELDS = (
    "wall_s",
    "device_s",
    "bytes_decoded",
    "bytes_h2d",
    "bytes_padded",
    "xla_compiles",
    "rows",
)

_stage: "contextvars.ContextVar[Optional[str]]" = contextvars.ContextVar(
    "hyperspace_stage", default=None
)


def enabled() -> bool:
    """Default ON; ``HYPERSPACE_STAGE_ATTRIBUTION=0`` restores the four
    disjoint sinks with one env read per stage bracket."""
    return os.environ.get(ENV_STAGE_ATTRIBUTION, "") != "0"


class StageLedger:
    """Thread-safe per-stage accumulator for one root query scope."""

    __slots__ = ("_lock", "_stages")

    def __init__(self):
        self._lock = threading.Lock()
        self._stages: Dict[str, Dict[str, float]] = {}

    def add(self, stage: str, field: str, n) -> None:
        with self._lock:
            vec = self._stages.get(stage)
            if vec is None:
                vec = self._stages[stage] = {}
            vec[field] = vec.get(field, 0) + n

    def wall_snapshot(self) -> Dict[str, float]:
        """Per-stage busy wall seconds (what the planner's stage-grain
        observe folds — available with every telemetry sink off)."""
        with self._lock:
            return {
                st: float(vec["wall_s"])
                for st, vec in self._stages.items()
                if vec.get("wall_s")
            }

    def snapshot(self) -> Dict[str, dict]:
        """Per-stage cost vectors, canonical field order, zeros dropped,
        floats rounded — the ledger's ``stages`` key at close."""
        with self._lock:
            out: Dict[str, dict] = {}
            for st in sorted(self._stages):
                vec = self._stages[st]
                row = {}
                for f in VECTOR_FIELDS:
                    v = vec.get(f)
                    if not v:
                        continue
                    row[f] = round(v, 6) if isinstance(v, float) else v
                if row:
                    out[st] = row
            return out


# Lazy resilience handle: resilience imports telemetry.accounting at module
# load and accounting imports this module, so the reverse edge must resolve
# at call time (by which point resilience is always fully imported — a scope
# only exists because resilience.query_scope opened it).
_resilience = None


def _scope_ledger(create: bool) -> Optional[StageLedger]:
    global _resilience
    if _resilience is None:
        from .. import resilience as _r

        _resilience = _r
    sc = _resilience.current_scope()
    if sc is None:
        return None
    sl = sc.stage_ledger
    if sl is None and create:
        with sc._lock:
            sl = sc.stage_ledger
            if sl is None:
                sl = sc.stage_ledger = StageLedger()
    return sl


def current_stage() -> Optional[str]:
    """The ambient stage name (None outside every stage bracket — and always
    None with attribution off, since only `stage_scope` sets it)."""
    return _stage.get()


def worker_stage(default: Optional[str] = None) -> Optional[str]:
    """The stage a pool submit site should bill its workers to: the ambient
    stage when one is set, else `default` (the pool's own stage — e.g. the
    decode pool IS the decode stage) when attribution is on, else None (the
    worker wrapper becomes a no-op)."""
    st = _stage.get()
    if st is not None:
        return st
    if default is not None and enabled():
        return default
    return None


@contextlib.contextmanager
def stage_scope(name: Optional[str]) -> Iterator[None]:
    """Mark the body as stage `name`: counters added inside bill the stage,
    and the body's wall seconds bank on the ambient QueryScope's stage
    ledger at exit. `None` (or attribution off) is a fast no-op. Nested
    scopes re-label (innermost wins) — each level still banks its own wall,
    so nesting the same name would double-bill; sites use distinct names."""
    if name is None or not enabled():
        yield
        return
    token = _stage.set(name)
    t0 = time.monotonic()
    try:
        yield
    finally:
        _stage.reset(token)
        sl = _scope_ledger(create=True)
        if sl is not None:
            sl.add(name, "wall_s", time.monotonic() - t0)


def stamp_counter(key: str, n) -> None:
    """Bill one query-ledger counter to the ambient stage (called by
    `accounting.add` only when the ledger opened with attribution on).
    Counters outside the cost vector return on the dict miss."""
    field = _COUNTER_VECTOR.get(key)
    if field is None:
        return
    sl = _scope_ledger(create=True)
    if sl is None:
        return
    sl.add(_stage.get() or UNLABELED, field, n)


def note_rows(n: int) -> None:
    """Stage-local row throughput (the `rows` vector component). Only stamps
    inside a stage bracket — one contextvar read otherwise."""
    st = _stage.get()
    if st is None or not n:
        return
    sl = _scope_ledger(create=True)
    if sl is not None:
        sl.add(st, "rows", int(n))


def query_stage_walls() -> Optional[Dict[str, float]]:
    """The ambient query's per-stage busy walls so far, or None (attribution
    off / no scope / nothing labeled yet). What the session passes to
    `planner.observe(stages=...)`."""
    if not enabled():
        return None
    sl = _scope_ledger(create=False)
    if sl is None:
        return None
    walls = sl.wall_snapshot()
    return walls or None


def close_stages(led) -> Optional[Dict[str, dict]]:
    """The ledger-close join: the ambient scope's per-stage cost vectors,
    or None when attribution was off for this ledger or nothing accumulated.
    Called by `accounting.ledger_scope` before the ledger snapshots."""
    if not getattr(led, "stage_attr", False):
        return None
    sl = _scope_ledger(create=False)
    if sl is None:
        return None
    snap = sl.snapshot()
    return snap or None


# ---------------------------------------------------------------------------
# Chrome-trace / Perfetto conversion (tools/hstimeline.py + the live
# HYPERSPACE_TIMELINE_DIR capture in tracing._finalize share this)
# ---------------------------------------------------------------------------

ENV_TIMELINE_DIR = "HYPERSPACE_TIMELINE_DIR"

#: Span-name prefixes whose spans are stage lanes: `record_*_stages`
#: synthesizes ``<kind>:<stage>`` children under each ``<kind>:stages``
#: summary span.
_STAGE_KINDS = ("build", "query", "join")


def _lane_of(span: dict) -> str:
    """Timeline lane for one exported span dict: the root query gets its own
    lane, synthesized stage spans get one lane PER STAGE (the causal
    timeline the issue asks for), operator spans share an ``ops`` lane, pool
    worker spans a ``workers`` lane, everything else groups by name family."""
    name = str(span.get("name", ""))
    if span.get("parent_id") is None:
        return "query"
    if ":" in name:
        kind, rest = name.split(":", 1)
        if kind in _STAGE_KINDS:
            if rest == "stages":
                return f"stages:{kind}"
            return f"stage:{rest}"
        if kind == "op":
            return "ops"
        if kind in ("worker", "pool", "decode"):
            return "workers"
        return kind
    return name


def chrome_trace(spans: List[dict]) -> dict:
    """Convert one query's exported span dicts (the `Span.to_json` schema:
    query_id/span_id/parent_id/name/start_s/duration_s/status/attrs) into
    Chrome-trace JSON (``chrome://tracing`` / Perfetto's legacy importer):
    one complete-event (``ph:"X"``) per span, one lane (tid) per stage /
    worker family / op class, thread-name metadata naming the lanes."""
    spans = [s for s in spans if isinstance(s, dict)]
    starts = [
        float(s["start_s"])
        for s in spans
        if isinstance(s.get("start_s"), (int, float))
    ]
    t0 = min(starts) if starts else 0.0
    lanes: Dict[str, int] = {}
    events: List[dict] = []
    for s in spans:
        start = s.get("start_s")
        if not isinstance(start, (int, float)):
            continue
        dur = s.get("duration_s")
        dur = float(dur) if isinstance(dur, (int, float)) else 0.0
        lane = _lane_of(s)
        tid = lanes.setdefault(lane, len(lanes) + 1)
        ev = {
            "name": str(s.get("name", "?")),
            "ph": "X",
            "ts": round((float(start) - t0) * 1e6, 1),
            "dur": round(max(0.0, dur) * 1e6, 1),
            "pid": 1,
            "tid": tid,
        }
        attrs = s.get("attrs")
        if isinstance(attrs, dict) and attrs:
            ev["args"] = attrs
        if s.get("status") not in (None, "ok"):
            ev.setdefault("args", {})["status"] = s["status"]
        events.append(ev)
    meta = [
        {
            "name": "thread_name",
            "ph": "M",
            "pid": 1,
            "tid": tid,
            "args": {"name": lane},
        }
        for lane, tid in lanes.items()
    ]
    qids = {s.get("query_id") for s in spans if s.get("query_id")}
    return {
        "traceEvents": meta + events,
        "displayTimeUnit": "ms",
        "otherData": {
            "query_id": sorted(qids)[0] if qids else None,
            "lanes": sorted(lanes),
        },
    }
