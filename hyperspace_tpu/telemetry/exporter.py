"""Continuous metrics export: periodic JSONL frames + Prometheus text dumps.

`metrics.snapshot()` and the per-query ledgers are point-in-time reads that
only bench.py and `explain(analyze=True)` ever consumed — a long-running
serving process had no way to ship its counters anywhere. This exporter is
the opt-in stream: a daemon thread that appends one JSON frame per interval
to ``HYPERSPACE_METRICS_FILE`` (every ``HYPERSPACE_METRICS_INTERVAL_S``
seconds, default 10), each frame carrying the full registry snapshot (now
with p50/p90/p99 on every histogram), the ledgers of queries closed since
the previous frame (`accounting.drain_pending`), the per-program compile
observatory (`compile_log.program_summary`), and a `jax.live_arrays()`
device-byte sample when jax is already imported.

Contracts:

- **Off by default, ≈zero cost.** No env var → no thread, no file, nothing
  on any hot path. The only standing cost with the exporter ON is the
  ledger/histogram accounting it turns on (integer adds) plus one snapshot
  per interval.
- **Clean shutdown.** `stop()` wakes the thread, writes one final frame
  (``"final": true``) and joins; an `atexit` hook stops a still-running
  exporter so a process exit never truncates mid-frame. Frames are written
  with a single `write` + flush per frame under a lock — concurrent stop()
  and tick never interleave lines.
- **Self-describing frames.** Every line is one JSON object:
  ``{"ts", "seq", "interval_s", "snapshot", "ledgers", "compile_programs",
  "device_live_bytes"?, "final"?}`` — parse failures in a consumer mean a
  torn file, not a schema guess (pinned by tests + the CI smoke leg).

`prometheus_text()` renders the registry in Prometheus text exposition
format on demand (counters, gauges, histograms with cumulative ``le``
buckets + ``_sum``/``_count``) for scrape-style integration without running
the file stream.
"""

from __future__ import annotations

import atexit
import json
import math
import os
import signal
import socket
import sys
import threading
import time
from typing import Optional

from . import metrics as _metrics

ENV_METRICS_FILE = "HYPERSPACE_METRICS_FILE"
ENV_METRICS_INTERVAL = "HYPERSPACE_METRICS_INTERVAL_S"
_DEFAULT_INTERVAL_S = 10.0

#: Exporter frame schema version (shared contract style with the history
#: segments' per-record version): bump only on changes a tolerant reader —
#: one that ignores unknown keys — could not absorb.
SCHEMA_VERSION = 1

# RLock: the SIGTERM/SIGINT handler runs stop() on the main thread, and a
# signal can land while the main thread itself holds this lock (an idempotent
# start()/stop() call) — a plain Lock would self-deadlock the handler.
_lock = threading.RLock()
_exporter: Optional["MetricsExporter"] = None


def _interval_from_env() -> float:
    try:
        v = float(os.environ.get(ENV_METRICS_INTERVAL, "") or _DEFAULT_INTERVAL_S)
    except ValueError:
        v = _DEFAULT_INTERVAL_S
    return max(0.01, v)


def _device_live_bytes():
    """(bytes, age_s) via the accounting module's shared rate-limited sampler
    — one `jax.live_arrays()` walk serves ledger closes AND frames, and the
    age rides the frame so a reused reading is never mistaken for live."""
    from . import accounting as _accounting

    return _accounting.device_live_bytes_sample()


class MetricsExporter:
    """One background export stream (the module-level `start`/`stop` manage
    the process singleton; direct construction is for tests)."""

    def __init__(self, path: str, interval_s: float):
        self.path = path
        self.interval_s = float(interval_s)
        self._stop = threading.Event()
        self._write_lock = threading.Lock()
        self._seq = 0
        self._thread = threading.Thread(
            target=self._run, name="hyperspace-metrics-exporter", daemon=True
        )

    def start(self) -> "MetricsExporter":
        self._thread.start()
        return self

    @property
    def running(self) -> bool:
        return self._thread.is_alive()

    def _frame(self, final: bool = False) -> dict:
        from . import accounting, compile_log

        out = {
            # Versioned frames: consumers tolerate unknown keys and gate
            # hard parsing changes on this (the forward-compat contract the
            # history segments share — see docs/observability.md).
            "schema_version": SCHEMA_VERSION,
            "ts": round(time.time(), 6),
            "seq": self._seq,
            "interval_s": self.interval_s,
            "snapshot": _metrics.snapshot(),
            "ledgers": accounting.drain_pending(),
            "compile_programs": compile_log.program_summary(),
        }
        # Fleet attribution (serve.replicas): which replica wrote this frame
        # — a dashboard tailing K replicas' streams splits by it. Stamped
        # unconditionally (consumers tolerate unknown keys by contract).
        try:
            from ..serve.replicas import replica_id as _rid

            out["replica_id"] = _rid()
        except Exception:
            pass
        # Persistent-compile-cache traffic: only when the knob is live or an
        # event fired, so pre-existing frame consumers see unchanged schemas.
        cache = compile_log.compile_cache_summary()
        if cache["dir"] or cache["events"]:
            out["compile_cache"] = cache
        # Compact reliability rollup (the raw counters also ride `snapshot`):
        # what a retry-storm alert or `tools/bench_compare.py` gate reads —
        # ONE schema shared with `bench_detail.reliability`.
        from .. import resilience as _resilience

        out["reliability"] = _resilience.reliability_rollup(out["snapshot"])
        # Per-tenant rollup (serving traffic): cumulative totals of every
        # labeled ledger — omitted entirely for unlabeled single-caller runs
        # so pre-serving frame consumers see byte-identical schemas.
        tenants = accounting.tenant_rollup()
        if tenants:
            out["tenants"] = tenants
        # Serving SLO state (per-lane objectives/burn rates) and workload-
        # history summary (records landed + drained anomalies): both omitted
        # when idle, so pre-existing frame consumers see unchanged schemas.
        from . import history as _history
        from . import slo as _slo

        slo_state = _slo.summary()
        if slo_state:
            out["slo"] = slo_state
        hist = _history.frame_summary()
        if hist:
            out["history"] = hist
        dev, age = _device_live_bytes()
        if dev is not None:
            out["device_live_bytes"] = dev
            if age is not None:
                out["device_live_bytes_age_s"] = round(age, 3)
            _metrics.gauge("device.live_bytes").set(dev)
        # Device cost observatory rollups (probed device time, H2D/D2H,
        # padding tax): omitted while empty so pre-existing frame consumers
        # see unchanged schemas.
        from . import device_observatory as _devobs

        dev_programs = _devobs.device_summary()
        pads = _devobs.pad_summary()
        transfers = _devobs.transfer_summary()
        if dev_programs or pads or any(
            t["count"] for t in transfers.values()
        ):
            out["device"] = {
                "programs": dev_programs,
                "pads": pads,
                "transfers": transfers,
            }
        # Adaptive-planner activity (decisions / explorations / measured
        # flips per knob): dashboards see planner behavior without reading
        # the outcome-store sidecar. Omitted while the planner never decided
        # so pre-planner frame consumers see unchanged schemas.
        try:
            from ..plananalysis import planner as _planner

            activity = _planner.activity_summary()
            if activity:
                out["planner"] = activity
        except Exception:
            pass
        if final:
            out["final"] = True
        return out

    def _write_frame(self, final: bool = False) -> None:
        try:
            from . import rotation as _rotation

            line = json.dumps(self._frame(final), default=str)
            with self._write_lock:
                self._seq += 1
                # Size-capped rotation (HYPERSPACE_METRICS_MAX_MB; off by
                # default). The final frame rides the same path: when it
                # itself trips the cap it lands in the fresh live file —
                # the stream's last line still carries "final": true.
                _rotation.append(
                    self.path, line + "\n", _rotation.ENV_METRICS_MAX_MB
                )
        except Exception:
            pass  # telemetry must never fail the process it observes

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self._write_frame()

    def stop(self, timeout: float = 5.0) -> None:
        """Wake the thread, join it, then append the final frame (so the last
        line of the stream always carries the end-state snapshot)."""
        self._stop.set()
        self._thread.join(timeout)
        self._write_frame(final=True)


def running() -> bool:
    e = _exporter
    return e is not None and e.running


_signals_installed = False


def _install_signal_handlers() -> None:
    """Chain SIGTERM/SIGINT so a KILLED (not just exited) serving process
    still flushes its ``final: true`` frame — atexit alone loses the last
    interval of frames on a signal death. The previous handler (or the
    default action) runs after the flush, so termination semantics are
    unchanged. Main-thread-only (the `signal` module's rule); non-main
    callers keep the atexit-only behavior."""
    global _signals_installed
    if _signals_installed:
        return
    if threading.current_thread() is not threading.main_thread():
        return
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            prev = signal.getsignal(sig)

            def _handler(signum, frame, _prev=prev):
                stop()
                if callable(_prev):
                    _prev(signum, frame)
                elif _prev == signal.SIG_DFL:
                    # Restore the default action and re-deliver, so the exit
                    # status still reports death-by-signal.
                    signal.signal(signum, signal.SIG_DFL)
                    os.kill(os.getpid(), signum)

            signal.signal(sig, _handler)
        except (ValueError, OSError):
            return  # not installable here (embedded interpreter, etc.)
    _signals_installed = True


def start(path: Optional[str] = None, interval_s: Optional[float] = None) -> bool:
    """Start the process exporter (idempotent: a live exporter wins). `path`
    defaults to ``HYPERSPACE_METRICS_FILE``; no path anywhere → False."""
    global _exporter
    with _lock:
        if _exporter is not None and _exporter.running:
            return True
        path = path or os.environ.get(ENV_METRICS_FILE)
        if not path:
            return False
        if interval_s is None:
            interval_s = _interval_from_env()
        try:
            _exporter = MetricsExporter(path, interval_s).start()
        except Exception:
            _exporter = None
            return False
    # Outside the module lock: a signal arriving the instant a handler is
    # installed runs stop() on this same (main) thread.
    _install_signal_handlers()
    return True


def stop(timeout: float = 5.0) -> None:
    """Stop the process exporter and write its final frame (no-op without
    one). Safe to call repeatedly and from `atexit`."""
    global _exporter
    with _lock:
        e = _exporter
        _exporter = None
    if e is not None and e.running:
        e.stop(timeout)


def maybe_start_from_env() -> bool:
    """The import-time hook (`telemetry/__init__`): start the stream iff
    ``HYPERSPACE_METRICS_FILE`` is set — the single opt-in switch."""
    if not os.environ.get(ENV_METRICS_FILE):
        return False
    return start()


atexit.register(stop)


# ---------------------------------------------------------------------------
# Prometheus text exposition (on demand; no server, no thread)
# ---------------------------------------------------------------------------


def _prom_name(name: str) -> str:
    out = "".join(c if (c.isalnum() or c == "_") else "_" for c in name)
    return out if not out[:1].isdigit() else "_" + out


def _prom_num(v) -> str:
    if isinstance(v, float) and math.isinf(v):
        return "+Inf"
    return repr(float(v)) if isinstance(v, float) else str(v)


def prometheus_text(prefix: str = "hyperspace") -> str:
    """The registry in Prometheus text exposition format: counters as
    `counter`, gauges as `gauge`, histograms as `histogram` with the
    log-spaced cumulative buckets (`Histogram.bucket_counts`), `_sum` and
    `_count`."""
    reg = _metrics.global_registry()
    with reg._lock:
        counters = list(reg._counters.values())
        gauges = list(reg._gauges.values())
        hists = list(reg._histograms.values())
    lines = []
    for c in counters:
        n = f"{prefix}_{_prom_name(c.name)}"
        lines.append(f"# TYPE {n} counter")
        lines.append(f"{n} {c.value}")
    for g in gauges:
        n = f"{prefix}_{_prom_name(g.name)}"
        lines.append(f"# TYPE {n} gauge")
        lines.append(f"{n} {_prom_num(g.value)}")
    for h in hists:
        n = f"{prefix}_{_prom_name(h.name)}"
        # One lock hold per histogram: _count must equal the +Inf bucket even
        # under concurrent observes (Prometheus consistency requirement).
        count, total, buckets = h.export_state()
        lines.append(f"# TYPE {n} histogram")
        for le, cum in buckets:
            lines.append(f'{n}_bucket{{le="{_prom_num(le)}"}} {cum}')
        lines.append(f"{n}_sum {_prom_num(round(total, 6))}")
        lines.append(f"{n}_count {count}")
    # Per-tenant series (serving traffic): the accounting rollup rendered as
    # labeled counters — `tenant` is the label dimension, one series per
    # rollup field. Absent tenants emit nothing (no dead zero series).
    from . import accounting as _accounting

    tenants = _accounting.tenant_rollup()
    if tenants:
        fields = sorted({f for t in tenants.values() for f in t})
        for field in fields:
            n = f"{prefix}_tenant_{_prom_name(field)}"
            lines.append(f"# TYPE {n} counter")
            for tenant in sorted(tenants):
                v = tenants[tenant].get(field)
                if v is None:
                    continue
                # Label-value escaping per the exposition format: backslash,
                # quote, AND newline (a raw \n would invalidate the whole
                # scrape payload, not just this series).
                esc = (
                    tenant.replace("\\", "\\\\")
                    .replace('"', '\\"')
                    .replace("\n", "\\n")
                )
                lines.append(f'{n}{{tenant="{esc}"}} {_prom_num(v)}')
    # Serving SLO series (lane-labeled): objective/compliance/burn gauges
    # from the live monitor — absent lanes emit nothing.
    from . import slo as _slo

    slo_state = _slo.summary()
    if slo_state:
        fields = (
            ("objective_ms", "gauge"),
            ("compliance", "gauge"),
            ("burn_5m", "gauge"),
            ("burn_1h", "gauge"),
            ("total", "counter"),
            ("violations", "counter"),
        )
        for field, mtype in fields:
            n = f"{prefix}_slo_{_prom_name(field)}"
            rendered_type = False
            for lane in sorted(slo_state):
                v = slo_state[lane].get(field)
                if v is None:
                    continue
                if not rendered_type:
                    lines.append(f"# TYPE {n} {mtype}")
                    rendered_type = True
                lines.append(f'{n}{{lane="{lane}"}} {_prom_num(v)}')
    # Replica identity as a Prometheus info series (the `build_info`
    # pattern): constant 1, identity in the labels — joins any other series
    # from this process to its replica on the fleet dashboard. Rendered
    # unconditionally (one process = one series); label values escaped like
    # the tenant series above.
    try:
        from ..serve.replicas import replica_id as _rid

        def _esc(v):
            return (
                str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
            )

        n = f"{prefix}_replica_info"
        lines.append(f"# TYPE {n} gauge")
        lines.append(
            f'{n}{{replica_id="{_esc(_rid())}",host="{_esc(socket.gethostname())}",'
            f'pid="{os.getpid()}"}} 1'
        )
    except Exception:
        pass
    return "\n".join(lines) + "\n"
