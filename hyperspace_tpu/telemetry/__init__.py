from . import (  # noqa: F401
    accounting,
    compile_log,
    exporter,
    faults,
    history,
    metrics,
    slo,
    tracing,
)
from .event_logging import (  # noqa: F401
    EventLogger,
    EventLoggerFactory,
    NoOpEventLogger,
    RecordingEventLogger,
)
from .events import (  # noqa: F401
    AppInfo,
    CancelActionEvent,
    CreateActionEvent,
    DeleteActionEvent,
    HyperspaceEvent,
    HyperspaceIndexCRUDEvent,
    HyperspaceIndexUsageEvent,
    OptimizeActionEvent,
    RefreshActionEvent,
    RestoreActionEvent,
    VacuumActionEvent,
)

# Opt-in continuous metrics stream: HYPERSPACE_METRICS_FILE set at import →
# the exporter daemon starts here (the engine imports telemetry before any
# query runs). Unset = no thread, nothing armed.
exporter.maybe_start_from_env()
