from . import metrics, tracing  # noqa: F401
from .event_logging import (  # noqa: F401
    EventLogger,
    EventLoggerFactory,
    NoOpEventLogger,
    RecordingEventLogger,
)
from .events import (  # noqa: F401
    AppInfo,
    CancelActionEvent,
    CreateActionEvent,
    DeleteActionEvent,
    HyperspaceEvent,
    HyperspaceIndexCRUDEvent,
    HyperspaceIndexUsageEvent,
    OptimizeActionEvent,
    RefreshActionEvent,
    RestoreActionEvent,
    VacuumActionEvent,
)
