"""XLA profiler hooks (SURVEY §5: the reference's only tracing surface is the
explain subsystem; the TPU-native framework additionally exposes the device-level
profiler so "where did the time go" is answerable below the plan level).

`trace(log_dir)` wraps a scope in `jax.profiler` start/stop — the output is an
xprof/TensorBoard trace directory with per-kernel device timelines. `annotate`
names a region so engine phases (probe, exchange, build) are findable in the
trace. Both degrade to no-ops when profiling is unavailable (e.g. a backend
without profiler support), so they are safe to leave in production paths.

The bench consumes this via `BENCH_PROFILE_DIR=/path python bench.py`, which
traces the device section; `block_until_ready` wall deltas in the bench JSON
remain the machine-readable summary (device_time_s / utilization)."""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional


@contextlib.contextmanager
def trace(log_dir: Optional[str], enabled: bool = True) -> Iterator[None]:
    """Profile a scope into `log_dir` (xprof format); no-op when disabled/unset."""
    if not enabled or not log_dir:
        yield
        return
    import jax

    try:
        jax.profiler.start_trace(log_dir)
    except Exception:
        yield  # profiler unavailable on this backend — scope still runs
        return
    try:
        yield
    finally:
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass


@contextlib.contextmanager
def annotate(name: str, enabled: bool = True) -> Iterator[None]:
    """Name a region in the device trace (`jax.profiler.TraceAnnotation`).

    The try covers only annotation SETUP — the body's own exceptions must
    propagate unmasked (a second yield in an except handler would swallow them
    into contextlib's 'generator didn't stop' RuntimeError)."""
    ann = None
    if enabled:
        try:
            import jax

            ann = jax.profiler.TraceAnnotation(name)
            ann.__enter__()
        except Exception:
            ann = None
    try:
        yield
    finally:
        if ann is not None:
            try:
                ann.__exit__(None, None, None)
            except Exception:
                pass
