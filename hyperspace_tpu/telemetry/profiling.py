"""XLA profiler hooks (SURVEY §5: the reference's only tracing surface is the
explain subsystem; the TPU-native framework additionally exposes the device-level
profiler so "where did the time go" is answerable below the plan level).

`trace(log_dir)` wraps a scope in `jax.profiler` start/stop — the output is an
xprof/TensorBoard trace directory with per-kernel device timelines. `annotate`
names a region so engine phases (probe, exchange, build) are findable in the
trace. Both degrade to no-ops when profiling is unavailable (e.g. a backend
without profiler support), so they are safe to leave in production paths.

The bench consumes this via `BENCH_PROFILE_DIR=/path python bench.py`, which
traces the device section; `block_until_ready` wall deltas in the bench JSON
remain the machine-readable summary (device_time_s / utilization).

`StageTimings` + `record_build_stages` are the HOST-side counterpart for the
pipelined index build: per-stage busy time (decode/hash/h2d/sort/take/write),
wall-clock, and the overlap ratio of each build, surfaced through bench.py's
`bench_detail.build_stages` (see docs/build-pipeline.md)."""

from __future__ import annotations

import contextlib
import threading
import time
from collections import deque
from typing import Dict, Iterator, Optional

from . import stage_ledger
from . import tracing


class StageTimings:
    """Thread-safe per-stage wall-clock accumulator for a pipelined operation.

    Stages run CONCURRENTLY (that is the point of the pipeline), so per-stage
    sums measure busy time across workers, not a wall-clock partition:
    `overlap_ratio = sum(stage_s) / wall_s` > 1 means stages genuinely ran on
    top of each other, ~1 means the pipeline degenerated to a serial chain."""

    def __init__(self, mode: str = ""):
        self._lock = threading.Lock()
        self._stages: Dict[str, float] = {}
        self._counts: Dict[str, int] = {}
        self.mode = mode
        self._t0 = time.monotonic()
        self._wall: Optional[float] = None
        # Pallas fallback counters at operation START: summary() attaches the
        # DELTA, so a fallback shows up in the summary of the operation where
        # it actually happened — not in every later operation's (the counters
        # themselves are session-cumulative).
        self._fallbacks0 = pallas_fallback_summary()

    def add(self, stage: str, seconds: float) -> None:
        with self._lock:
            self._stages[stage] = self._stages.get(stage, 0.0) + float(seconds)
            self._counts[stage] = self._counts.get(stage, 0) + 1

    @contextlib.contextmanager
    def timed(self, stage: str) -> Iterator[None]:
        # Every pipelined stage bracket doubles as a stage-attribution scope
        # (telemetry/stage_ledger.py): counters ticked inside bill this stage
        # and the busy wall banks on the ambient QueryScope. One env read
        # when HYPERSPACE_STAGE_ATTRIBUTION=0; StageTimings' own sums are
        # untouched either way.
        t0 = time.monotonic()
        try:
            with stage_ledger.stage_scope(stage):
                yield
        finally:
            self.add(stage, time.monotonic() - t0)

    def finish(self) -> None:
        if self._wall is None:
            self._wall = time.monotonic() - self._t0

    def summary(self) -> dict:
        self.finish()
        with self._lock:
            wall = self._wall or 0.0
            busy = sum(self._stages.values())
            out = {f"{k}_s": round(v, 4) for k, v in sorted(self._stages.items())}
            out["wall_s"] = round(wall, 4)
            out["overlap_ratio"] = round(busy / wall, 3) if wall > 0 else None
            out["mode"] = self.mode
            out["stage_counts"] = dict(sorted(self._counts.items()))
        delta = _fallback_delta(self._fallbacks0, pallas_fallback_summary())
        if delta:
            out["pallas_fallbacks"] = delta
        return out


# Most recent index-build / streaming-query / streamed-join stage summaries
# (newest last), consumed by bench.py's bench_detail. Bounded: telemetry must
# never grow with the number of builds/queries a long-lived session performs.
_BUILD_STAGES: "deque[dict]" = deque(maxlen=16)
_QUERY_STAGES: "deque[dict]" = deque(maxlen=16)
_JOIN_STAGES: "deque[dict]" = deque(maxlen=16)
_build_stages_lock = threading.Lock()


def _fallback_delta(before: dict, after: dict) -> dict:
    """Per-operation Pallas fallback delta between two `pallas_fallback_
    summary()` snapshots: only kinds whose diverted-dispatch count GREW, with
    the latched error strings carried along. Empty when nothing new fell
    back during the operation."""
    out: dict = {}
    for mod_key, a in after.items():
        bf = before.get(mod_key, {}).get("failures", {})
        grown = {
            k: v - bf.get(k, 0)
            for k, v in a.get("failures", {}).items()
            if v - bf.get(k, 0) > 0
        }
        if grown:
            ent = {"failures": grown}
            if a.get("errors"):
                ent["errors"] = dict(a["errors"])
            out[mod_key] = ent
    return out


def _observe_stage_histograms(kind: str, summary: dict) -> None:
    """Feed one stage summary into the registry's quantile histograms:
    ``latency.<kind>.wall`` for the operation wall clock and
    ``latency.stage.<kind>.<stage>`` per stage's busy seconds — so
    `metrics.snapshot()` carries p50/p90/p99 latency DISTRIBUTIONS across
    operations, not just each operation's last summary. Always on, like the
    counters: a handful of locked observes per operation."""
    from . import metrics as _metrics

    wall = summary.get("wall_s")
    if isinstance(wall, (int, float)):
        _metrics.histogram(f"latency.{kind}.wall").observe(wall)
    for key, val in summary.items():
        if key.endswith("_s") and key != "wall_s" and isinstance(val, (int, float)):
            _metrics.histogram(f"latency.stage.{kind}.{key[:-2]}").observe(val)


def record_build_stages(summary: dict) -> None:
    """Record one build's stage summary. Summaries come from `StageTimings.
    summary()`, which attaches the operation-scoped `pallas_fallbacks` DELTA
    — a silent host fallback during a build or a streaming scan is visible
    in THAT operation's summary, and only that one (it previously rode
    `record_join_stages` alone, as session-cumulative counters)."""
    d = dict(summary)
    with _build_stages_lock:
        _BUILD_STAGES.append(d)
    _observe_stage_histograms("build", d)
    tracing.record_stage_spans("build", d)


def last_build_stages() -> Optional[dict]:
    """The most recent build's stage summary (None if no build ran yet)."""
    with _build_stages_lock:
        return dict(_BUILD_STAGES[-1]) if _BUILD_STAGES else None


def build_stages_history() -> list:
    """Stage summaries of the last few builds, oldest first."""
    with _build_stages_lock:
        return [dict(d) for d in _BUILD_STAGES]


def record_query_stages(summary: dict) -> None:
    """Per-stage timings of one streaming query execution (decode/filter/
    partial/merge busy time + wall + overlap ratio) — the read-side twin of
    `record_build_stages`, surfaced through bench.py's
    ``bench_detail.query_stages``. Pallas fallback deltas ride the summary
    (see `record_build_stages`)."""
    d = dict(summary)
    with _build_stages_lock:
        _QUERY_STAGES.append(d)
    _observe_stage_histograms("query", d)
    tracing.record_stage_spans("query", d)


def last_query_stages() -> Optional[dict]:
    """The most recent streaming query's stage summary (None if none ran)."""
    with _build_stages_lock:
        return dict(_QUERY_STAGES[-1]) if _QUERY_STAGES else None


def query_stages_history() -> list:
    """Stage summaries of the last few streaming queries, oldest first."""
    with _build_stages_lock:
        return [dict(d) for d in _QUERY_STAGES]


def record_join_stages(summary: dict) -> None:
    """Per-stage timings of one streamed join→aggregate execution (pad/probe/
    expand/verify/gather/eval/partial busy time + wall + overlap ratio, plus
    class/outlier counts) — surfaced through bench.py's
    ``bench_detail.join_stages``. Pallas fallback deltas ride the summary so
    a silent host fallback is visible next to the timings it explains."""
    d = dict(summary)
    with _build_stages_lock:
        _JOIN_STAGES.append(d)
    _observe_stage_histograms("join", d)
    tracing.record_stage_spans("join", d)


def last_join_stages() -> Optional[dict]:
    """The most recent streamed join's stage summary (None if none ran)."""
    with _build_stages_lock:
        return dict(_JOIN_STAGES[-1]) if _JOIN_STAGES else None


def join_stages_history() -> list:
    """Stage summaries of the last few streamed joins, oldest first."""
    with _build_stages_lock:
        return [dict(d) for d in _JOIN_STAGES]


def pallas_fallback_summary() -> dict:
    """Session-level Pallas fallback counters (probe + sort kernels), empty
    when nothing fell back. Reads through sys.modules so it NEVER triggers
    the ~1 s `jax.experimental.pallas` import on paths that never wanted a
    kernel — a module that was never imported cannot have failed."""
    import sys

    out: dict = {}
    for name, key in (
        ("hyperspace_tpu.ops.pallas_probe", "probe"),
        ("hyperspace_tpu.ops.pallas_sort", "sort"),
    ):
        mod = sys.modules.get(name)
        stats = getattr(mod, "pallas_fallback_stats", None) if mod else None
        if stats is not None:
            s = stats()
            if s:
                out[key] = s
    return out


def io_pruning_summary() -> dict:
    """Session-cumulative scan-pushdown counters: row groups scanned vs
    skipped by zone-map pruning, the byte totals behind them, and footer-
    cache traffic. Consumed by ``bench_detail.io_pruning`` so the pruning win
    is measured, not modeled."""
    from . import metrics as _metrics

    return {
        "row_groups_scanned": _metrics.counter("io.pruning.row_groups_scanned").value,
        "row_groups_skipped": _metrics.counter("io.pruning.row_groups_skipped").value,
        "bytes_decoded": _metrics.counter("io.pruning.bytes_decoded").value,
        "bytes_skipped": _metrics.counter("io.pruning.bytes_skipped").value,
        # Encoded-execution byte split: kept-as-codes vs flattened-to-values
        # (engine/encoding.py) — distinguishes what `bytes_decoded` cannot,
        # so effective GB/s is computed over bytes actually moved.
        "bytes_encoded_kept": _metrics.counter("io.pruning.bytes_encoded_kept").value,
        "bytes_materialized": _metrics.counter("io.pruning.bytes_materialized").value,
        "columns_encoded": _metrics.counter("io.encoded.columns_encoded").value,
        "columns_flattened": _metrics.counter("io.encoded.columns_flattened").value,
        "footer_hits": _metrics.counter("io.footer.hits").value,
        "footer_misses": _metrics.counter("io.footer.misses").value,
    }


@contextlib.contextmanager
def trace(log_dir: Optional[str], enabled: bool = True) -> Iterator[None]:
    """Profile a scope into `log_dir` (xprof format); no-op when disabled/unset."""
    if not enabled or not log_dir:
        yield
        return
    import jax

    try:
        jax.profiler.start_trace(log_dir)
    except Exception:
        yield  # profiler unavailable on this backend — scope still runs
        return
    try:
        yield
    finally:
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass


@contextlib.contextmanager
def annotate(name: str, enabled: bool = True) -> Iterator[None]:
    """Name a region in the device trace (`jax.profiler.TraceAnnotation`).

    The try covers only annotation SETUP — the body's own exceptions must
    propagate unmasked (a second yield in an except handler would swallow them
    into contextlib's 'generator didn't stop' RuntimeError)."""
    ann = None
    if enabled:
        try:
            import jax

            ann = jax.profiler.TraceAnnotation(name)
            ann.__enter__()
        except Exception:
            ann = None
    try:
        yield
    finally:
        if ann is not None:
            try:
                ann.__exit__(None, None, None)
            except Exception:
                pass
