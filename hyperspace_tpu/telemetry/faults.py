"""Deterministic, seeded fault injection: named fault points wired into CI.

PRs 1/3/5 each pinned a per-subsystem fault contract (no partial index dir, no
partial memo, no partial cache entry) by monkeypatching internals from tests.
This module turns those ad-hoc patches into one system-wide discipline: the
engine's lake-touching sites declare NAMED fault points, and a seeded registry
decides per call whether to inject — so the chaos CI leg can run the full
oracle equivalence suites under ambient 5% transient decode faults and assert
byte-identical results.

Fault points (each site calls ``faults.check("<point>")`` right before the
real operation):

- ``io.decode``    — a data/index file decode (`engine.io._read_one` /
  `_read_row_groups_one`)
- ``io.footer``    — a parquet footer parse (`engine.io._parse_footer_meta`)
- ``storage.write``— a bucket/index/table file write (`engine.io.checked_write_table`)
- ``log.write``    — an operation-log entry write (`IndexLogManagerImpl.write_log`)
- ``pool.worker``  — a decode/build pool worker task body (worker-crash paths)
- ``device.compile``— an `observed_jit` program dispatch (`telemetry.compile_log`)
- ``serve.admit``  — a serving-layer admission decision
  (`serve.admission.AdmissionController.admit`; the chaos mixed-workload leg
  injects here to prove scheduling faults never change query results)
- ``refresh.merge``— the incremental-refresh merge window: after the delta
  version dir committed, before the merged log entry lands
  (`actions.refresh.RefreshIncrementalAction.op`; a ``hang`` here is the
  SIGKILL window between data commit and log commit)
- ``compact.commit``— the compaction commit window: after every compacted
  bucket file is staged, before the atomic rename publishes the version dir
  (`actions.optimize.OptimizeAction.op`; a ``hang`` here is the
  SIGKILL-mid-compaction window)

Configuration — ``HYPERSPACE_FAULTS`` (comma-separated specs) or the
programmatic API (`configure` / `inject`, which take precedence over the env):

    point:rate[:kind[:limit[:after]]]

- ``rate``  — injection probability per eligible call (1.0 = every call).
- ``kind``  — ``transient`` (default; raises `TransientError`, retry-eligible),
  ``permanent`` (raises `PermanentError`), or ``hang``/``hang<secs>`` (sleeps
  <secs> — default 30 — then proceeds; the window the SIGKILL crash tests aim at).
- ``limit`` — max injections for this spec (blank/0 = unlimited).
- ``after`` — skip the first N eligible calls (targets a specific call, e.g.
  ``log.write:1:hang300:1:1`` hangs the SECOND log write = an action's end()).

Determinism: decisions hash ``(seed, point, call_index)`` — the seed is
``HYPERSPACE_FAULTS_SEED`` (default 0), the call index is the per-point call
counter — so a serial run injects at exactly the same calls every time.
Every injection ticks ``faults.injected`` + ``faults.<point>.injected`` and is
charged to the active query ledger (``faults_injected``).

Cost when off: one `os.environ` lookup per `check` (the same budget as the
engine's other per-call env knobs).
"""

from __future__ import annotations

import contextlib
import hashlib
import os
import threading
import time
from typing import Dict, Iterator, List, Optional

from ..exceptions import PermanentError, TransientError
from . import accounting as _accounting
from . import metrics as _metrics

ENV_FAULTS = "HYPERSPACE_FAULTS"
ENV_FAULTS_SEED = "HYPERSPACE_FAULTS_SEED"

#: The named fault points the engine declares. `check` accepts only these —
#: a typo'd point name must fail loudly in tests, not silently never fire.
FAULT_POINTS = (
    "io.decode",
    "io.footer",
    "storage.write",
    "log.write",
    "pool.worker",
    "device.compile",
    "serve.admit",
    "refresh.merge",
    "compact.commit",
)

_INJECTED = _metrics.counter("faults.injected")

_lock = threading.Lock()
_programmatic: Optional[Dict[str, "FaultSpec"]] = None
_env_raw: Optional[str] = None
_env_parsed: Dict[str, "FaultSpec"] = {}
# Per-point call counters live OUTSIDE the specs: reconfiguring (or the env
# cache refreshing) must not reset call indices mid-run.
_calls: Dict[str, int] = {}
_injections: Dict[str, int] = {}


class FaultSpec:
    """One fault point's injection policy."""

    __slots__ = ("point", "rate", "kind", "limit", "after", "hang_s")

    def __init__(
        self,
        point: str,
        rate: float,
        kind: str = "transient",
        limit: Optional[int] = None,
        after: int = 0,
        hang_s: float = 30.0,
    ):
        if point not in FAULT_POINTS:
            raise ValueError(f"Unknown fault point '{point}'; known: {FAULT_POINTS}")
        if kind.startswith("hang"):
            suffix = kind[4:]
            hang_s = float(suffix) if suffix else hang_s
            kind = "hang"
        if kind not in ("transient", "permanent", "hang"):
            raise ValueError(f"Unknown fault kind '{kind}'")
        self.point = point
        self.rate = float(rate)
        self.kind = kind
        self.limit = limit if limit else None
        self.after = int(after)
        self.hang_s = hang_s


def _parse_specs(raw: str) -> Dict[str, FaultSpec]:
    out: Dict[str, FaultSpec] = {}
    for item in raw.split(","):
        item = item.strip()
        if not item:
            continue
        parts = item.split(":")
        if len(parts) < 2:
            raise ValueError(f"Bad fault spec '{item}' (need point:rate)")
        point, rate = parts[0], float(parts[1])
        kind = parts[2] if len(parts) > 2 and parts[2] else "transient"
        limit = int(parts[3]) if len(parts) > 3 and parts[3] else None
        after = int(parts[4]) if len(parts) > 4 and parts[4] else 0
        out[point] = FaultSpec(point, rate, kind, limit, after)
    return out


def _seed() -> str:
    return os.environ.get(ENV_FAULTS_SEED, "0") or "0"


def _active_specs() -> Optional[Dict[str, FaultSpec]]:
    """The effective spec map, or None when injection is fully off (the fast
    path: one env read). Programmatic config wins over the env; the parsed env
    value is cached against the raw string so repeated checks don't reparse."""
    global _env_raw, _env_parsed
    if _programmatic is not None:
        return _programmatic or None
    raw = os.environ.get(ENV_FAULTS)
    if not raw:
        return None
    if raw != _env_raw:
        with _lock:
            if raw != _env_raw:
                try:
                    _env_parsed = _parse_specs(raw)
                except ValueError as e:
                    # A malformed spec surfaces as a CLASSIFIED config error:
                    # a raw ValueError from here would be indistinguishable
                    # from a parquet parse failure at the decode-layer guards
                    # (and could bogusly quarantine a healthy index).
                    from ..exceptions import HyperspaceException

                    raise HyperspaceException(
                        f"Bad {ENV_FAULTS} spec {raw!r}: {e}"
                    ) from e
                _env_raw = raw
    return _env_parsed or None


def _decide(point: str, n: int, rate: float) -> bool:
    """Deterministic pseudo-uniform draw for call `n` of `point`."""
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    h = hashlib.sha256(f"{_seed()}|{point}|{n}".encode()).digest()
    return int.from_bytes(h[:8], "big") / float(1 << 64) < rate


def check(point: str) -> None:
    """The fault point hook: no-op unless a spec targets `point`, else count
    the call and (per the seeded decision) inject — raise `TransientError` /
    `PermanentError`, or sleep (``hang``) and proceed."""
    specs = _active_specs()
    if specs is None:
        return
    spec = specs.get(point)
    if spec is None:
        return
    with _lock:
        n = _calls.get(point, 0)
        _calls[point] = n + 1
        if n < spec.after:
            return
        if spec.limit is not None and _injections.get(point, 0) >= spec.limit:
            return
        fire = _decide(point, n, spec.rate)
        if fire:
            _injections[point] = _injections.get(point, 0) + 1
    if not fire:
        return
    _INJECTED.inc()
    _metrics.counter(f"faults.{point}.injected").inc()
    _accounting.add("faults_injected", 1)
    if spec.kind == "hang":
        time.sleep(spec.hang_s)
        return
    msg = f"injected {spec.kind} fault at {point} (call #{n})"
    if spec.kind == "permanent":
        raise PermanentError(msg)
    raise TransientError(msg)


def configure(specs) -> None:
    """Programmatic configuration (takes precedence over ``HYPERSPACE_FAULTS``):
    a spec string in the env grammar, a list of `FaultSpec`s, or a dict
    point → FaultSpec. Call counters are NOT reset (see `reset_counters`)."""
    global _programmatic
    if isinstance(specs, str):
        parsed = _parse_specs(specs)
    elif isinstance(specs, dict):
        parsed = dict(specs)
    else:
        parsed = {s.point: s for s in specs}
    with _lock:
        _programmatic = parsed


def clear() -> None:
    """Drop the programmatic configuration (the env, if set, applies again)."""
    global _programmatic
    with _lock:
        _programmatic = None


def reset_counters() -> None:
    """Zero the per-point call/injection counters (tests)."""
    with _lock:
        _calls.clear()
        _injections.clear()


def injected_count(point: Optional[str] = None) -> int:
    with _lock:
        if point is not None:
            return _injections.get(point, 0)
        return sum(_injections.values())


def call_count(point: str) -> int:
    with _lock:
        return _calls.get(point, 0)


@contextlib.contextmanager
def inject(
    point: str,
    rate: float = 1.0,
    kind: str = "transient",
    limit: Optional[int] = None,
    after: int = 0,
) -> Iterator[None]:
    """Test scope: inject at `point` for the duration, restoring the previous
    configuration (programmatic or env) on exit."""
    global _programmatic
    with _lock:
        prev = _programmatic
        merged = dict(prev or {})
        merged[point] = FaultSpec(point, rate, kind, limit, after)
        _programmatic = merged
    try:
        yield
    finally:
        with _lock:
            _programmatic = prev
