"""Size-capped rotation for the JSONL telemetry sinks.

``HYPERSPACE_TRACE_FILE`` and ``HYPERSPACE_METRICS_FILE`` previously grew
without bound — a long-lived serving process under tracing would fill its
disk with spans. This module is the shared append-with-rotation primitive:

- ``HYPERSPACE_TRACE_MAX_MB`` / ``HYPERSPACE_METRICS_MAX_MB`` cap the live
  file (0 / unset = unbounded, the pre-existing behavior — rotation is
  strictly opt-in).
- On cap, the live file shifts to ``<path>.1``, existing ``.1`` → ``.2`` …
  up to ``HYPERSPACE_SINK_KEEP`` rotated files (default 3); the oldest
  falls off. The shift happens BEFORE the new write, so one appended blob
  (a whole trace, a whole exporter frame) is never split across files —
  every file stays independently parseable.
- Each rotation ticks ``telemetry.sink.rotations``.

The exporter's ``final: true`` frame rides the same helper: a final frame
that itself triggers rotation still lands (in the fresh live file) — pinned
by tests.
"""

from __future__ import annotations

import os
from typing import Optional

from . import metrics as _metrics

ENV_TRACE_MAX_MB = "HYPERSPACE_TRACE_MAX_MB"
ENV_METRICS_MAX_MB = "HYPERSPACE_METRICS_MAX_MB"
ENV_SINK_KEEP = "HYPERSPACE_SINK_KEEP"
_DEFAULT_KEEP = 3

_ROTATIONS = _metrics.counter("telemetry.sink.rotations")


def _max_bytes(env_key: str) -> int:
    try:
        mb = float(os.environ.get(env_key, "") or 0.0)
    except ValueError:
        mb = 0.0
    return int(mb * 1_000_000) if mb > 0 else 0


def keep_files() -> int:
    try:
        return max(1, int(os.environ.get(ENV_SINK_KEEP, "") or _DEFAULT_KEEP))
    except ValueError:
        return _DEFAULT_KEEP


def rotate(path: str) -> None:
    """Shift `path` → `path.1` → … → `path.<keep>` (oldest dropped)."""
    keep = keep_files()
    try:
        os.unlink(f"{path}.{keep}")
    except OSError:
        pass
    for i in range(keep - 1, 0, -1):
        try:
            os.replace(f"{path}.{i}", f"{path}.{i + 1}")
        except OSError:
            continue  # that generation doesn't exist yet
    try:
        os.replace(path, f"{path}.1")
    except OSError:
        return  # nothing to rotate (vanished concurrently)
    _ROTATIONS.inc()


def rotate_dir(path: str, keep: Optional[int] = None) -> None:
    """Directory twin of `rotate`: shift `path/` → `path.1/` → … →
    `path.<keep>/` (oldest generation removed). Used by the profile-capture
    directories (``HYPERSPACE_PROFILE_DIR``), whose keep count rides its own
    knob — callers pass it explicitly; None falls back to the sink keep."""
    import shutil

    if keep is None:
        keep = keep_files()
    shutil.rmtree(f"{path}.{keep}", ignore_errors=True)
    for i in range(keep - 1, 0, -1):
        try:
            os.replace(f"{path}.{i}", f"{path}.{i + 1}")
        except OSError:
            continue  # that generation doesn't exist yet
    try:
        os.replace(path, f"{path}.1")
    except OSError:
        return  # nothing to rotate yet
    _ROTATIONS.inc()


def append(path: str, text: str, max_mb_env: Optional[str] = None) -> None:
    """Append `text` to `path`, rotating first when the configured cap
    (`max_mb_env`, e.g. ``HYPERSPACE_TRACE_MAX_MB``) would be crossed.
    A single blob larger than the whole cap still writes (into a fresh
    file) — rotation bounds growth, it never drops telemetry."""
    cap = _max_bytes(max_mb_env) if max_mb_env else 0
    if cap > 0:
        try:
            size = os.path.getsize(path)
        except OSError:
            size = 0
        if size > 0 and size + len(text) > cap:
            rotate(path)
    with open(path, "a") as f:
        f.write(text)
        f.flush()
