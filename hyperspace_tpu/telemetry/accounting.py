"""Per-query resource ledger: what each query actually SPENT.

The metrics registry answers "what did the process do" (cumulative counters);
the span tree answers "where did this query's time go". Neither attributes
RESOURCES — bytes decoded, cache bytes charged/evicted, decode-pool
task-seconds, device buffers — to the query that spent them, which is the
currency an admission controller needs (ROADMAP item 2) and the cost model
"Evaluating Learned Indexes for External-Memory Joins" argues for: bytes
moved, per consumer.

One `QueryLedger` rides each root query scope (the same boundary as the root
span — `tracing.query_span` opens both). Engine hooks call the module-level
`add(key, n)`, which resolves the ambient ledger through a contextvar; pool
workers inherit it via `use_ledger` (captured at submit time, exactly like
the explicit `parent=` contract for worker spans). With no sink active,
`add` is one contextvar read returning None — the standing off-by-default
≈zero-cost contract.

Ledger fields (all monotonic within one query):

- ``bytes_decoded`` / ``bytes_skipped`` — ticked at the SAME sites with the
  SAME values as the process-wide ``io.pruning.bytes_decoded|skipped``
  counters (`engine.io._record_decoded_bytes`), so per-query totals
  reconcile with the counters by construction.
- ``decode_files`` / ``decode_task_s`` — decode-pool work charged to the
  submitting query (task-seconds, not wall: concurrent decodes sum).
- ``rows_produced`` — root result rows (collect/count).
- ``cache_bytes_charged`` / ``cache_bytes_evicted`` — scan/concat-cache
  residency this query added or displaced.
- ``device_upload_bytes`` — host→device transfers this query caused.
- ``device_live_bytes`` — `jax.live_arrays()` byte total SAMPLED at close
  (only when jax is already imported; a point-in-time reading, not a sum).
- ``wall_s`` — the root scope's wall clock.

Closed ledgers land on the root span (`attrs["ledger"]`, so the JSONL trace
carries them), in a bounded history (`recent_ledgers`, what
`explain(analyze=True)` renders), in the exporter's drain queue, and in the
``accounting.*`` registry counters (process totals of the attributed work).
Latency histograms are fed here too: every closed scope observes
``latency.<root name>`` so `snapshot()` yields p50/p99 distributions even
when span tracing is off (exporter-only mode).
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import sys
import threading
import time
from collections import deque
from typing import Iterator, List, Optional

from . import metrics as _metrics
from . import stage_ledger as _stage_ledger

ENV_ACCOUNTING = "HYPERSPACE_ACCOUNTING"

#: Integer ledger fields mirrored into ``accounting.<field>`` registry
#: counters at close (process-wide totals of query-attributed work).
_COUNTER_FIELDS = (
    "bytes_decoded",
    "bytes_skipped",
    # Encoded-execution byte split (engine/encoding.py): bytes that entered
    # the engine still as codes + dictionary vs bytes flattened to raw
    # values — together the honest denominator of effective GB/s.
    "bytes_encoded_kept",
    "bytes_materialized",
    "decode_files",
    "rows_produced",
    "cache_bytes_charged",
    "cache_bytes_evicted",
    "device_upload_bytes",
    # Device cost observatory (telemetry/device_observatory.py): bytes pulled
    # device→host at materialization boundaries, and the pow2 staging split
    # (real payload vs padding) summed over every pad site the query hit.
    "d2h_bytes",
    "pad_bytes_payload",
    "pad_bytes_padded",
    # Device-resident encoded staging (engine/encoded_device.py): bytes the
    # flat path would have staged vs the narrow code bytes actually staged —
    # the encoded-vs-flat split of the transfer/pad ledgers.
    "device_code_bytes_flat",
    "device_code_bytes_staged",
    # Bit-packed sub-byte tier (engine/packed_codes.py): of the staged bytes,
    # the slice that crossed as packed uint32 words.
    "device_code_bytes_packed",
)

_current: "contextvars.ContextVar[Optional[QueryLedger]]" = contextvars.ContextVar(
    "hyperspace_query_ledger", default=None
)

#: Ambient tenant label (the serving layer's `QueryServer` sets it around
#: each executed query; direct single-caller use leaves it None). Read once
#: at ledger open — pool workers inherit it THROUGH the ledger, so no
#: separate propagation contract is needed.
_tenant: "contextvars.ContextVar[Optional[str]]" = contextvars.ContextVar(
    "hyperspace_tenant", default=None
)

# Per-tenant rollup of closed ledgers: the admission/billing view of the
# same numbers the per-query ledgers carry (exporter frames' `tenants` key
# and the `prometheus_text` tenant series read this — one producer).
_TENANT_FIELDS = (
    "wall_s",
    "bytes_decoded",
    "decode_files",
    "rows_produced",
    "cache_bytes_charged",
    "io_retries",
)
_tenant_totals: dict = {}
_tenant_lock = threading.Lock()
#: Rollup cardinality bound: tenant labels are arbitrary caller strings, and
#: the rollup is monotonic by design — without a cap, per-request labels
#: would grow every exporter frame and Prometheus scrape without bound.
#: Labels past the cap aggregate into one literal "<other>" bucket (totals
#: stay exact; only the attribution coarsens).
TENANT_ROLLUP_MAX = 256
TENANT_OVERFLOW = "<other>"

_RECENT: "deque[QueryLedger]" = deque(maxlen=32)
_recent_lock = threading.Lock()
# Exporter drain queue: bounded so an idle exporter (or none at all) can
# never grow memory with query count — oldest frames age out silently.
_PENDING: "deque[dict]" = deque(maxlen=256)


class QueryLedger:
    """Thread-safe resource accumulator for one root query scope."""

    __slots__ = (
        "query_id",
        "name",
        "tenant",
        "lane",
        "start_s",
        "wall_s",
        "_lock",
        "_counts",
        # Stage-attribution flag, captured ONCE at ledger open (one env read
        # per query) — the per-add stamp below gates on this bool, never on
        # the environment.
        "stage_attr",
    )

    def __init__(
        self,
        query_id: str,
        name: str,
        tenant: Optional[str] = None,
        lane: Optional[str] = None,
    ):
        self.query_id = query_id
        self.name = name
        self.tenant = tenant
        self.lane = lane
        self.start_s = time.time()
        self.wall_s: Optional[float] = None
        self._lock = threading.Lock()
        self._counts: dict = {}
        self.stage_attr = False

    def add(self, key: str, n) -> None:
        with self._lock:
            self._counts[key] = self._counts.get(key, 0) + n

    def set_value(self, key: str, n) -> None:
        with self._lock:
            self._counts[key] = n

    def get(self, key: str):
        with self._lock:
            return self._counts.get(key, 0)

    def to_dict(self) -> dict:
        with self._lock:
            out = {
                "query_id": self.query_id,
                "name": self.name,
                "start_s": round(self.start_s, 6),
            }
            if self.tenant is not None:
                out["tenant"] = self.tenant
            if self.lane is not None:
                out["lane"] = self.lane
            if self.wall_s is not None:
                out["wall_s"] = round(self.wall_s, 6)
            for k in sorted(self._counts):
                v = self._counts[k]
                out[k] = round(v, 6) if isinstance(v, float) else v
            return out


def enabled() -> bool:
    """Whether query scopes should carry a ledger: any tracing sink is active
    (a traced query always gets one), the continuous exporter is running,
    ``HYPERSPACE_ACCOUNTING=1`` forces it, the workload HISTORY store is on
    (``HYPERSPACE_HISTORY=1`` — closed ledgers are what the store lands, so
    enabling history enables the ledgers that feed it) — or the query
    carries a TENANT label (a served query is always accounted: per-tenant
    budgets/rollups are the serving layer's currency, and the label is the
    opt-in). One predicate on the root-scope path only — per-observation
    `add` calls gate on the ambient ledger, not on this."""
    if os.environ.get(ENV_ACCOUNTING) == "1":
        return True
    if os.environ.get("HYPERSPACE_HISTORY") == "1":
        return True
    if _tenant.get() is not None:
        return True
    from . import tracing

    if tracing.active():
        return True
    from . import exporter

    return exporter.running()


def current_ledger() -> Optional[QueryLedger]:
    return _current.get()


def add(key: str, n) -> None:
    """Charge `n` of `key` to the ambient query's ledger; no-op (one
    contextvar read) without one. Ledgers opened with stage attribution on
    additionally bill cost-vector counters to the ambient stage."""
    led = _current.get()
    if led is not None:
        led.add(key, n)
        if led.stage_attr:
            _stage_ledger.stamp_counter(key, n)


def set_value(key: str, n) -> None:
    """Last-write-wins field on the ambient ledger. Used for ROOT facts
    (`rows_produced`): a nested collect inside an outer query scope writes
    first, and the outer action's own write lands last — the ledger reports
    the root result, never an inner+outer sum."""
    led = _current.get()
    if led is not None:
        led.set_value(key, n)


@contextlib.contextmanager
def use_ledger(led: Optional[QueryLedger]) -> Iterator[None]:
    """Adopt `led` as the ambient ledger on THIS thread (pool workers run in
    a fresh context; the submitting code captures `current_ledger()` and
    wraps the worker body — the ledger twin of `span(parent=...)`)."""
    if led is None:
        yield
        return
    token = _current.set(led)
    try:
        yield
    finally:
        _current.reset(token)


@contextlib.contextmanager
def tenant_scope(tenant: Optional[str]) -> Iterator[None]:
    """Label every root query opened under this scope with `tenant`: the
    ledger carries it (`QueryLedger.tenant`, JSONL/exporter frames), the root
    span gets a ``tenant`` attr, and closed ledgers aggregate into the
    per-tenant rollup. The serving layer wraps each executed query; None
    passes through unchanged (direct single-caller use stays label-free)."""
    if tenant is None:
        yield
        return
    token = _tenant.set(str(tenant))
    try:
        yield
    finally:
        _tenant.reset(token)


def current_tenant() -> Optional[str]:
    return _tenant.get()


def _bank_tenant(led: "QueryLedger") -> None:
    """Fold one closed ledger into the per-tenant rollup (only labeled
    queries participate — unlabeled traffic stays out of tenant billing)."""
    if led.tenant is None:
        return
    with _tenant_lock:
        name = led.tenant
        if name not in _tenant_totals and len(_tenant_totals) >= TENANT_ROLLUP_MAX:
            name = TENANT_OVERFLOW
        t = _tenant_totals.setdefault(name, {"queries": 0})
        t["queries"] += 1
        for f in _TENANT_FIELDS:
            v = led.wall_s if f == "wall_s" else led.get(f)
            if v:
                t[f] = round(t.get(f, 0) + v, 6) if isinstance(v, float) else t.get(f, 0) + v


def tenant_rollup() -> dict:
    """Per-tenant totals over every labeled ledger closed so far:
    ``{tenant: {queries, wall_s, bytes_decoded, decode_files, rows_produced,
    cache_bytes_charged, io_retries}}`` — the exporter's `tenants` frame key
    and the `prometheus_text` tenant series render exactly this."""
    with _tenant_lock:
        return {k: dict(v) for k, v in _tenant_totals.items()}


def reset_tenant_rollup() -> None:
    """Zero the rollup (tests; the exporter never resets — tenant totals are
    monotonic like the cache stats)."""
    with _tenant_lock:
        _tenant_totals.clear()


#: Device-buffer sampling rate limit: `jax.live_arrays()` walks EVERY live
#: buffer, so a serving process with thousands of resident device arrays
#: must not pay that walk per sub-millisecond query. Ledgers closing inside
#: the window reuse the last sample — the value is a process-wide
#: point-in-time reading either way, not per-query attribution.
_DEVICE_SAMPLE_MIN_INTERVAL_S = 1.0
_device_sample_lock = threading.Lock()
# [claim mono ts, bytes, value mono ts] — the claim ts rate-limits the walk;
# the value ts is when the reading was actually taken (what age reports).
_device_sample: list = [-_DEVICE_SAMPLE_MIN_INTERVAL_S, None, None]


def device_live_bytes_sample() -> "tuple[Optional[int], Optional[float]]":
    """`jax.live_arrays()` byte total plus the sample's AGE in seconds, only
    when jax is ALREADY imported (accounting must never pay the import) and
    the probe succeeds; sampled at most once per
    `_DEVICE_SAMPLE_MIN_INTERVAL_S`. A reused reading comes back with its
    real age so consumers (ledger ``device_live_bytes_age_s``, exporter
    frames) can see the freshness instead of mistaking a stale 1 Hz sample
    for a live one. Shared by ledger close and the exporter — one walk serves
    both."""
    jax = sys.modules.get("jax")
    if jax is None:
        return None, None
    now = time.monotonic()
    with _device_sample_lock:
        if now - _device_sample[0] < _DEVICE_SAMPLE_MIN_INTERVAL_S:
            taken = _device_sample[2]
            age = (now - taken) if taken is not None else None
            return _device_sample[1], age
        _device_sample[0] = now  # claim the slot: concurrent closers reuse
    try:
        val = int(sum(int(a.nbytes) for a in jax.live_arrays()))
    except Exception:
        val = None
    with _device_sample_lock:
        _device_sample[1] = val
        _device_sample[2] = time.monotonic()
    if val is not None:
        _metrics.gauge("device.live_bytes.peak").set_max(val)
    return val, 0.0


def _device_live_bytes() -> Optional[int]:
    return device_live_bytes_sample()[0]


@contextlib.contextmanager
def ledger_scope(query_id: str, name: str, root=None) -> Iterator[QueryLedger]:
    """Open the ledger of one root query scope. Nested under an existing
    ledger it yields that ledger unchanged — one ledger per outermost action,
    matching the one-query_id-per-root-span rule. At close the ledger banks
    to the bounded history + exporter queue, mirrors into the
    ``accounting.*`` counters, observes the query-latency histogram, and
    lands on `root`'s attrs when a span is recording."""
    existing = _current.get()
    if existing is not None:
        yield existing
        return
    # The serving lane rides the ledger like the tenant does (history
    # records and the SLO reporter slice by it). Lazy import: resilience
    # imports accounting at module load, so the reverse edge must not.
    from .. import resilience as _resilience

    led = QueryLedger(
        query_id, name, tenant=_tenant.get(), lane=_resilience.current_lane()
    )
    led.stage_attr = _stage_ledger.enabled()
    token = _current.set(led)
    t0 = time.monotonic()
    try:
        yield led
    except BaseException:
        # The failure lands ON the ledger (status="error"), so the durable
        # history record carries it and the offline SLO view
        # (`slo.compliance_over`) judges an outage the way the live monitor
        # does — a fast failure is not compliance.
        led.set_value("status", "error")
        raise
    finally:
        _current.reset(token)
        wall = None
        if root is not None:
            wall = getattr(root, "duration_s", None)
        if wall is None:
            wall = time.monotonic() - t0
        led.wall_s = wall
        dev, age = device_live_bytes_sample()
        if dev is not None:
            led.add("device_live_bytes", dev)
            if age is not None:
                # Freshness signal: a reading reused from inside the 1 Hz
                # rate-limit window is honest only WITH its age attached.
                led.set_value("device_live_bytes_age_s", round(age, 3))
            _metrics.gauge("device.live_bytes").set(dev)
        # Device/host split (device_observatory probes): probed device time
        # accumulated on the ledger yields the host-side remainder — what
        # `explain(analyze=True)` renders as the device section.
        dev_s = led.get("device_time_s")
        if dev_s:
            led.set_value("host_time_s", round(max(0.0, wall - dev_s), 6))
        # Padding-tax ratio: fraction of this query's staged bytes that was
        # pow2 padding (0.0 = every staged byte was real payload).
        pad_payload = led.get("pad_bytes_payload")
        pad_padded = led.get("pad_bytes_padded")
        if pad_payload or pad_padded:
            led.set_value(
                "pad_ratio", round(pad_padded / (pad_payload + pad_padded), 4)
            )
        # Latency distribution: fed HERE (not at span end) so exporter-only
        # runs still get p50/p99 — and a traced run observes exactly once.
        _metrics.histogram(f"latency.{name.replace(':', '.')}").observe(wall)
        for field in _COUNTER_FIELDS:
            v = led.get(field)
            if v:
                _metrics.counter(f"accounting.{field}").inc(v)
        # Stage-attribution join: the scope's per-stage cost vectors land as
        # the ledger's ``stages`` key BEFORE annotate_close/to_dict, so the
        # planner's close annotation, history baselines, hsreport's drift
        # table, and explain's Attribution section all see one snapshot.
        if led.stage_attr:
            try:
                stages = _stage_ledger.close_stages(led)
                if stages:
                    led.set_value("stages", stages)
            except Exception:
                pass
        # Planner predicted-vs-actual join: runs only when the adaptive
        # planner recorded decisions on this ledger (a dict lookup when it
        # didn't), BEFORE to_dict snapshots — so history records, spans, and
        # hsreport all carry the annotated decisions.
        if led.get("planner"):
            try:
                from ..plananalysis import planner as _planner

                _planner.annotate_close(led, wall)
            except Exception:
                pass
        # Fleet attribution (serve.replicas): the process's stable replica
        # id rides every closed ledger — fleet on or off — so a shared
        # history dir written by K replicas splits per-replica afterwards
        # (tools/hsreport.py). Consumers tolerate unknown keys by contract.
        try:
            from ..serve.replicas import replica_id as _rid

            led.set_value("replica_id", _rid())
        except Exception:
            pass
        _bank_tenant(led)
        d = led.to_dict()
        if root is not None:
            try:
                root.set_attr("ledger", d)
            except Exception:
                pass
        # Durable workload history (telemetry/history.py): one env read when
        # off — and this close path itself only runs for accounted queries.
        if os.environ.get("HYPERSPACE_HISTORY") == "1":
            from . import history as _history

            _history.land(d, root)
        with _recent_lock:
            _RECENT.append(led)
            _PENDING.append(d)


def recent_ledgers() -> List[QueryLedger]:
    """Closed ledgers, oldest first (bounded history, newest last)."""
    with _recent_lock:
        return list(_RECENT)


def ledger_for(query_id: str) -> Optional[QueryLedger]:
    with _recent_lock:
        for led in reversed(_RECENT):
            if led.query_id == query_id:
                return led
    return None


def drain_pending() -> List[dict]:
    """Hand the exporter every ledger closed since the last drain (bounded
    queue: with no exporter attached old entries age out instead of
    growing)."""
    out: List[dict] = []
    with _recent_lock:
        while _PENDING:
            out.append(_PENDING.popleft())
    return out
