"""XLA compile observatory: who is compiling, what, how often, for how long.

The r05 TPU bench died inside a 2400 s compile of `ops/hashing.bucket_id`
with ZERO telemetry — no record of which program was compiling, how many
distinct shapes it had already compiled, or how long each took. This module
makes that failure mode diagnosable:

- A ``jax.monitoring`` duration listener (`install`) observes every backend
  compile and jaxpr trace the process performs, feeding the registry:
  ``xla.compiles.count`` / ``xla.compiles.seconds`` (a quantile histogram) /
  ``xla.compiles.traces``, plus ``xla.compile_cache.*`` counters from the
  persistent-cache events. Listener cost is zero between compiles — jax only
  calls it when a compile actually happens.
- Per-program attribution: the engine's jit entry points in ``ops/`` (and
  the fused device helpers) are declared through `observed_jit`, a drop-in
  `jax.jit` wrapper that pushes its label onto a thread-local stack for the
  duration of each call. Compiles are synchronous inside the call, so the
  listener reads the top of that stack — compile count, elapsed seconds, and
  distinct traced shapes per LABEL (`program_summary`), at the cost of one
  list push/pop per jit call.
- Operator-span deltas: while a span is recording, each backend compile also
  increments ``xla_compiles`` / ``xla_compile_s`` attrs on the ambient span,
  so `explain(analyze=True)` and the JSONL trace show compile time on the
  operator that triggered it.
- Recompile-storm warning: when one program label crosses
  ``HYPERSPACE_COMPILE_STORM_THRESHOLD`` distinct traced shapes (default 32,
  0 disables), a `warnings.warn` fires ONCE for that label and
  ``xla.compiles.storm_warnings`` ticks — the silent-hang precursor (a
  non-quantized shape stream) becomes a loud, attributed signal.
- Fallback: on a jax build without ``jax.monitoring``, `observed_jit`
  instead watches the jitted callable's compile-cache size around each call
  and charges the call's wall time to a detected compile — coarser, but the
  counters stay nonzero.

`install` never imports jax itself — it is called from `observed_jit`, whose
call sites have jax imported by definition.
"""

from __future__ import annotations

import functools
import os
import threading
import warnings
from typing import Dict, Optional

from ..exceptions import CompileTimeoutError
from . import device_observatory as _devobs
from . import faults as _faults
from . import metrics as _metrics
from . import tracing as _tracing

ENV_STORM_THRESHOLD = "HYPERSPACE_COMPILE_STORM_THRESHOLD"
_DEFAULT_STORM_THRESHOLD = 32

#: Compile/dispatch deadline per `observed_jit` call (seconds; unset/0 = off).
#: The r05 TPU bench hung 2400 s inside ONE `bucket_id` compile with no
#: deadline and no attribution; with this set, the call runs under a watchdog
#: and a runaway compile becomes a classified, program-labeled
#: `CompileTimeoutError` instead of a silent hang. Whether a given call WILL
#: compile is not knowable up front, so the watchdog wraps every call: the
#: ~0.1 ms thread handoff per dispatch prices this as a build/bench/first-
#: deploy supervision knob, not a hot-serving default (docs/configuration.md).
ENV_COMPILE_TIMEOUT_S = "HYPERSPACE_COMPILE_TIMEOUT_S"

_DEADLINE_EXCEEDED = _metrics.counter("xla.compiles.deadline_exceeded")


def compile_timeout_s() -> float:
    try:
        return max(0.0, float(os.environ.get(ENV_COMPILE_TIMEOUT_S, "") or 0.0))
    except ValueError:
        return 0.0

_EVENT_BACKEND_COMPILE = "/jax/core/compile/backend_compile_duration"
_EVENT_JAXPR_TRACE = "/jax/core/compile/jaxpr_trace_duration"
_CACHE_EVENT_PREFIX = "/jax/compilation_cache/"

_COMPILES = _metrics.counter("xla.compiles.count")
_COMPILE_SECONDS = _metrics.histogram("xla.compiles.seconds")
_TRACES = _metrics.counter("xla.compiles.traces")
_STORMS = _metrics.counter("xla.compiles.storm_warnings")

_UNLABELED = "<unlabeled>"

_local = threading.local()  # per-thread label stack (compiles are synchronous)
_lock = threading.Lock()
_programs: Dict[str, dict] = {}
_installed = False
_have_monitoring = False


def storm_threshold() -> int:
    """Distinct traced shapes per program before the storm warning (0 = off)."""
    try:
        return int(
            os.environ.get(ENV_STORM_THRESHOLD, _DEFAULT_STORM_THRESHOLD)
            or _DEFAULT_STORM_THRESHOLD
        )
    except ValueError:
        return _DEFAULT_STORM_THRESHOLD


def _current_label() -> str:
    stack = getattr(_local, "stack", None)
    return stack[-1][0] if stack else _UNLABELED


def _mark_traced() -> bool:
    """Flag the innermost in-flight observed_jit call as having traced; returns
    whether this is the FIRST trace event of that call. One call can emit many
    jaxpr-trace events (shard_map programs trace one inner jaxpr per collective
    region — 16+ for one exchange), so per-label `traces` counts CALLS that
    traced a new shape, which is the unit the storm heuristic reasons about."""
    stack = getattr(_local, "stack", None)
    if not stack:
        return True  # unlabeled: keep raw event counting
    cell = stack[-1]
    if cell[1]:
        return False
    cell[1] = True
    return True


def _program(label: str) -> dict:
    with _lock:
        p = _programs.get(label)
        if p is None:
            p = _programs[label] = {
                "compiles": 0,
                "compile_s": 0.0,
                "traces": 0,
                "storm_warned": False,
            }
        return p


def _check_storm(label: str, p: dict) -> None:
    if label == _UNLABELED:
        # The unlabeled bucket aggregates every jit program OUTSIDE the
        # engine's declared entry points (jax-internal helpers, eager
        # dispatch fragments) — many distinct programs, so "distinct shapes
        # of one program" is meaningless there and would warn on any long
        # session. Storm detection applies to labeled programs only.
        return
    threshold = storm_threshold()
    if threshold <= 0:
        return
    with _lock:
        if p["storm_warned"] or p["traces"] < threshold:
            return
        p["storm_warned"] = True
    _STORMS.inc()
    warnings.warn(
        f"hyperspace compile storm: program '{label}' has traced "
        f"{p['traces']} distinct shapes (threshold {threshold}, "
        f"{p['compile_s']:.1f}s in backend compiles so far) — a shape that "
        f"is not pow2-quantized is likely recompiling per call; see "
        f"docs/observability.md (compile observatory)",
        RuntimeWarning,
        stacklevel=2,
    )


def _on_event_duration(event: str, duration: float, **_kw) -> None:
    """jax.monitoring duration listener — called only when jax compiles."""
    if event == _EVENT_BACKEND_COMPILE:
        _COMPILES.inc()
        _COMPILE_SECONDS.observe(duration)
        label = _current_label()
        p = _program(label)
        with _lock:
            p["compiles"] += 1
            p["compile_s"] += float(duration)
        sp = _tracing.current_span()
        if sp is not None:
            sp.inc_attr("xla_compiles", 1)
            sp.inc_attr("xla_compile_s", round(float(duration), 6))
        # Per-query compile bill on the ledger too (the workload history
        # store's compile-storm hotspot axis); no-op without an open ledger.
        from . import accounting as _accounting

        _accounting.add("xla_compiles", 1)
        _accounting.add("xla_compile_s", round(float(duration), 6))
    elif event == _EVENT_JAXPR_TRACE:
        _TRACES.inc()
        if not _mark_traced():
            return  # later jaxpr of the SAME call: not a new program shape
        label = _current_label()
        p = _program(label)
        with _lock:
            p["traces"] += 1
        _check_storm(label, p)


_cache_events: Dict[str, int] = {}


def _on_event(event: str, **_kw) -> None:
    """Plain-event listener: persistent compile-cache traffic counters."""
    if event.startswith(_CACHE_EVENT_PREFIX):
        leaf = event[len(_CACHE_EVENT_PREFIX):].replace("/", ".")
        _metrics.counter(f"xla.compile_cache.{leaf}").inc()
        with _lock:
            _cache_events[leaf] = _cache_events.get(leaf, 0) + 1


def compile_cache_summary() -> dict:
    """Persistent-compilation-cache traffic: {"dir": configured cache dir or
    None, "events": {event leaf: count}}. A SECOND process (or a post-
    `jax.clear_caches()` re-dispatch) against a warm
    ``HYPERSPACE_COMPILE_CACHE_DIR`` shows `cache_hits` > 0 here — the
    observable proof it paid zero backend compiles. Consumed by the exporter
    frames and `bench_detail.mesh`."""
    import os as _os

    with _lock:
        events = dict(_cache_events)
    return {
        "dir": _os.environ.get("HYPERSPACE_COMPILE_CACHE_DIR") or None,
        "events": events,
    }


def install() -> bool:
    """Register the monitoring listeners once. Returns whether the
    ``jax.monitoring`` path is live (False = wrapper fallback mode). Callers
    have jax imported already; this never triggers the import."""
    global _installed, _have_monitoring
    with _lock:
        if _installed:
            return _have_monitoring
        _installed = True
    try:
        from jax import monitoring as _monitoring

        _monitoring.register_event_duration_secs_listener(_on_event_duration)
        _monitoring.register_event_listener(_on_event)
        _have_monitoring = True
    except Exception:
        _have_monitoring = False
    return _have_monitoring


def observed_jit(fun=None, *, label: Optional[str] = None, **jit_kwargs):
    """Drop-in `jax.jit` replacement that attributes compiles to a program
    label: ``observed_jit(f, static_argnums=(0,))`` or used as a decorator
    (optionally ``@observed_jit(label="hashing.bucket_id")``). The wrapper's
    per-call cost is one thread-local list push/pop — the compile accounting
    itself only runs inside actual compiles, via the `install` listener."""
    if fun is None:
        return lambda f: observed_jit(f, label=label, **jit_kwargs)
    import jax

    monitoring_live = install()
    lbl = label or f"{fun.__module__.rsplit('.', 1)[-1]}.{fun.__name__}"
    jitted = jax.jit(fun, **jit_kwargs)
    # Fallback compile detection when jax.monitoring is absent: the jitted
    # callable's cache growing across a call means that call compiled.
    cache_size = getattr(jitted, "_cache_size", None) if not monitoring_live else None

    from .. import resilience as _resilience

    @functools.wraps(fun)
    def wrapper(*args, **kwargs):
        # Reliability hooks BEFORE dispatch: the `device.compile` fault point,
        # and the ambient query deadline — a deadlined query must not start
        # another potentially-compiling program.
        _faults.check("device.compile")
        _resilience.check_deadline(lbl)
        stack = getattr(_local, "stack", None)
        if stack is None:
            stack = _local.stack = []
        cell = [lbl, False]  # [label, saw-a-trace-event-this-call]
        stack.append(cell)
        # Device-time probe (HYPERSPACE_DEVICE_TIMING): decided BEFORE
        # dispatch; one env read when off. See device_observatory.
        probe_t0 = _devobs.probe_start(lbl)
        if cache_size is not None:
            import time as _time

            before = cache_size()
            t0 = _time.monotonic()
        try:
            limit = compile_timeout_s()
            if limit > 0.0:
                out = _call_under_deadline(jitted, args, kwargs, lbl, limit, cell)
            else:
                out = jitted(*args, **kwargs)
            if probe_t0 is not None:
                _devobs.probe_finish(lbl, probe_t0, out, traced=cell[1])
            return out
        finally:
            stack.pop()
            if cache_size is not None and cache_size() > before:
                dur = _time.monotonic() - t0
                _COMPILES.inc()
                _TRACES.inc()
                _COMPILE_SECONDS.observe(dur)
                p = _program(lbl)
                with _lock:
                    p["compiles"] += 1
                    p["compile_s"] += dur
                    p["traces"] += 1
                _check_storm(lbl, p)

    wrapper._hyperspace_jitted = jitted  # the underlying jit object (tests)
    return wrapper


def _call_under_deadline(fn, args, kwargs, label: str, limit_s: float, cell=None):
    """Run one jitted call on a watchdog thread with a hard deadline. On
    timeout the caller gets a classified, program-attributed
    `CompileTimeoutError`; the abandoned daemon thread may finish its compile
    in the background (XLA compiles are not preemptible), but the query is no
    longer hostage to it. The worker pushes the CALLER's stack cell onto its
    own thread-local stack (so the trace flag lands where the caller's device
    probe reads it) and runs under a COPY of the caller's context, so the
    monitoring listener's span and ledger attribution — both contextvar
    reads — see the submitting query, not a blank worker context."""
    import contextvars as _contextvars

    result: list = []
    err: list = []
    ctx = _contextvars.copy_context()

    def run() -> None:
        stack = getattr(_local, "stack", None)
        if stack is None:
            stack = _local.stack = []
        stack.append(cell if cell is not None else [label, False])
        try:
            result.append(ctx.run(fn, *args, **kwargs))
        except BaseException as e:  # re-raised on the calling thread
            err.append(e)
        finally:
            stack.pop()

    t = threading.Thread(
        target=run, name=f"hyperspace-compile-watchdog:{label}", daemon=True
    )
    t.start()
    t.join(limit_s)
    if t.is_alive():
        _DEADLINE_EXCEEDED.inc()
        raise CompileTimeoutError(
            f"program '{label}' did not complete within "
            f"HYPERSPACE_COMPILE_TIMEOUT_S={limit_s:g}s — likely a runaway XLA "
            "compile (a shape stream that is not pow2-quantized recompiles per "
            "call); see docs/reliability.md",
            elapsed_s=limit_s,
            timeout_s=limit_s,
        )
    if err:
        raise err[0]
    return result[0]


def program_summary() -> dict:
    """Per-program compile stats: {label: {compiles, compile_s, traces}},
    labels sorted, JSON-serializable — consumed by the exporter frames and
    ``bench_detail.compile_observatory``."""
    with _lock:
        return {
            lbl: {
                "compiles": p["compiles"],
                "compile_s": round(p["compile_s"], 4),
                "traces": p["traces"],
            }
            for lbl, p in sorted(_programs.items())
        }


def reset_programs() -> None:
    """Zero the per-program stats IN PLACE (tests; the registry counters are
    reset separately via `metrics.reset`). Labels stay registered."""
    with _lock:
        for p in _programs.values():
            p.update(compiles=0, compile_s=0.0, traces=0, storm_warned=False)
