"""Telemetry event model.

Parity: reference `telemetry/HyperspaceEvent.scala:28-123` — `AppInfo`, a base event,
one event per lifecycle action, and `HyperspaceIndexUsageEvent` emitted when a rewrite
rule applies an index.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import List, Optional


@dataclass
class AppInfo:
    """Originating application info (reference `AppInfo`)."""

    sparkUser: str = ""
    appId: str = ""
    appName: str = ""


@dataclass
class HyperspaceEvent:
    app_info: AppInfo = field(default_factory=AppInfo)
    message: str = ""
    timestamp: float = field(default_factory=time.time)

    @property
    def name(self) -> str:
        return type(self).__name__


@dataclass
class HyperspaceIndexCRUDEvent(HyperspaceEvent):
    index_name: str = ""


class CreateActionEvent(HyperspaceIndexCRUDEvent):
    pass


class DeleteActionEvent(HyperspaceIndexCRUDEvent):
    pass


class RestoreActionEvent(HyperspaceIndexCRUDEvent):
    pass


class VacuumActionEvent(HyperspaceIndexCRUDEvent):
    pass


class RefreshActionEvent(HyperspaceIndexCRUDEvent):
    pass


class OptimizeActionEvent(HyperspaceIndexCRUDEvent):
    """Extension event: optimizeIndex compaction (north-star; no v0 reference analogue)."""


class CancelActionEvent(HyperspaceIndexCRUDEvent):
    pass


@dataclass
class HyperspaceIndexUsageEvent(HyperspaceEvent):
    """Emitted when a rewrite rule transforms a plan to use indexes
    (reference `HyperspaceIndexUsageEvent`)."""

    index_names: List[str] = field(default_factory=list)
    plan_before: str = ""
    plan_after: str = ""


@dataclass
class HyperspaceRuleFailureEvent(HyperspaceEvent):
    """Emitted when a rewrite rule raises and falls back to the original plan.

    The reference swallows rule failures so an index problem never breaks the
    user's query (`FilterIndexRule.scala:74-78`); this event keeps the failure
    observable instead of silent (r3 verdict weak item 7)."""

    rule_name: str = ""
    exception: str = ""
