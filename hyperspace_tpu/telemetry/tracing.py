"""Unified query-lifecycle tracing: a hierarchical span tree per query.

PRs 1–3 bolted three parallel ad-hoc stage recorders onto the engine
(`record_build_stages` / `record_query_stages` / `record_join_stages`) that
bench.py scrapes but no query can correlate end-to-end. This module is the
correlation layer: every user-facing action (`DataFrame.collect/count`,
`create_index`, `explain(analyze=True)`) opens a ROOT span carrying a stable
`query_id`; physical operators, the planner, the optimizer rules, and the
stage summaries of the pipelined executors attach child spans under it — one
tree answering "where did this query's time go and which caches/rules fired".

Design rules:

- **Off by default, zero device impact.** Spans record only while a sink is
  active: ``HYPERSPACE_TRACE_FILE`` set (JSONL export), ``HYPERSPACE_TRACING
  =1``, or a `capture()` scope (what `explain(analyze=True)` uses). When
  inactive every hook degrades to one predicate check and a shared no-op
  span — no allocation, no jax import, no new compilations.
- **Thread-safe child spans.** The ambient parent rides a `contextvars.
  ContextVar` (per-thread under plain threading); pool workers that outlive
  the submitting context pass `parent=` explicitly. Span/trace mutation is
  lock-guarded; a worker that raises inside a `span()` scope closes its span
  with ``status="error"`` before the exception propagates.
- **Bounded.** Finished traces land in a ``deque(maxlen=16)`` (same bound as
  the stage-summary histories); a long-lived session can never grow
  telemetry with query count. Per-trace span count is capped so a runaway
  loop inside one traced query cannot hold unbounded memory either.
- **Device correlation.** While recording, spans opened via `span()` also
  enter a `jax.profiler.TraceAnnotation` (only when jax is already imported
  — tracing must never pay the import), so host spans line up with device
  timelines in an xprof trace taken with `profiling.trace`.

JSONL export (``HYPERSPACE_TRACE_FILE``): one line per span, written when the
root span ends — `{"query_id", "span_id", "parent_id", "name", "start_s",
"duration_s", "status", "attrs"}`. Every span of a trace shares the root's
`query_id`; `parent_id` of the root is null and resolves within the file for
every other span (schema pinned by tests/test_tracing.py and the CI smoke
leg).
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import sys
import threading
import time
import uuid
from collections import deque
from typing import Dict, Iterator, List, Optional

ENV_TRACE_FILE = "HYPERSPACE_TRACE_FILE"
ENV_TRACING = "HYPERSPACE_TRACING"
#: Live Chrome-trace capture directory (`stage_ledger.ENV_TIMELINE_DIR`):
#: every finalized root trace writes one timeline-<query_id>.json here.
ENV_TIMELINE_DIR = "HYPERSPACE_TIMELINE_DIR"

#: Spans per trace hard cap (a traced query touching thousands of operators
#: keeps the tree; further spans are dropped, counted per trace, and surfaced
#: at finalize as the root's `spans_dropped` attr + the
#: `trace.spans.dropped` counter — no silent cap).
MAX_SPANS_PER_TRACE = 4096

_RECENT: "deque[QueryTrace]" = deque(maxlen=16)
_recent_lock = threading.Lock()
_export_lock = threading.Lock()

_current_span: "contextvars.ContextVar[Optional[Span]]" = contextvars.ContextVar(
    "hyperspace_current_span", default=None
)
_capture: "contextvars.ContextVar[Optional[Capture]]" = contextvars.ContextVar(
    "hyperspace_trace_capture", default=None
)


def new_query_id() -> str:
    return uuid.uuid4().hex[:16]


class QueryTrace:
    """All spans of one root query, in creation order (root first)."""

    def __init__(self, query_id: str):
        self.query_id = query_id
        self.spans: List[Span] = []
        self.dropped = 0
        self._lock = threading.Lock()
        self._next_id = 0

    def _register(self, span: "Span") -> bool:
        with self._lock:
            if len(self.spans) >= MAX_SPANS_PER_TRACE:
                self.dropped += 1
                return False
            span.span_id = self._next_id
            self._next_id += 1
            self.spans.append(span)
            return True

    @property
    def root(self) -> "Span":
        return self.spans[0]

    def spans_by_parent(self) -> Dict[Optional[int], List["Span"]]:
        out: Dict[Optional[int], List[Span]] = {}
        with self._lock:
            for s in self.spans:
                out.setdefault(s.parent_id, []).append(s)
        return out

    def find(self, name: str) -> List["Span"]:
        with self._lock:
            return [s for s in self.spans if s.name == name]


class Span:
    """One named, timed node of a query's span tree."""

    __slots__ = (
        "trace",
        "name",
        "span_id",
        "parent_id",
        "start_s",
        "_t0",
        "duration_s",
        "status",
        "attrs",
        "_lock",
        "_registered",
    )

    def __init__(self, trace: QueryTrace, name: str, parent_id: Optional[int], attrs=None):
        self.trace = trace
        self.name = name
        self.span_id = -1
        self.parent_id = parent_id
        self.start_s = time.time()
        self._t0 = time.monotonic()
        self.duration_s: Optional[float] = None
        self.status = "ok"
        self.attrs: dict = dict(attrs) if attrs else {}
        self._lock = threading.Lock()
        self._registered = trace._register(self)

    @property
    def query_id(self) -> str:
        return self.trace.query_id

    def set_attr(self, key: str, value) -> None:
        with self._lock:
            self.attrs[key] = value

    def add_attrs(self, **attrs) -> None:
        with self._lock:
            self.attrs.update(attrs)

    def append_attr(self, key: str, value) -> None:
        """Append to a list-valued attribute (rule decisions accumulate)."""
        with self._lock:
            self.attrs.setdefault(key, []).append(value)

    def inc_attr(self, key: str, delta) -> None:
        """Accumulate a numeric attribute atomically (compile-observatory
        deltas: several compiles may land on one operator span)."""
        with self._lock:
            self.attrs[key] = self.attrs.get(key, 0) + delta

    def end(self, status: Optional[str] = None, error: Optional[BaseException] = None) -> None:
        # Locked end-to-end: the exporter's end(status="unclosed") on a
        # worker span that outlived the root must not interleave with the
        # worker's own end(error=...) — the first end wins atomically.
        with self._lock:
            if self.duration_s is not None:
                return  # idempotent: the first end wins
            self.duration_s = max(0.0, time.monotonic() - self._t0)
            if error is not None:
                self.status = "error"
                self.attrs["error"] = f"{type(error).__name__}: {error}"
            elif status is not None:
                self.status = status

    def to_json(self) -> dict:
        # Attrs snapshot under the span lock: a still-running worker span
        # mutating attrs during export would otherwise raise mid-serialize
        # (and _finalize's swallow would drop the whole trace's lines).
        with self._lock:
            attrs = dict(self.attrs)
            duration = self.duration_s
            status = self.status
        return {
            "query_id": self.trace.query_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start_s": round(self.start_s, 6),
            "duration_s": None if duration is None else round(duration, 6),
            "status": status,
            "attrs": attrs,
        }


class _NoopSpan:
    """Shared do-nothing span: what every hook gets while tracing is off."""

    __slots__ = ()
    name = "<noop>"
    span_id = -1
    parent_id = None
    query_id = ""
    duration_s = 0.0
    status = "ok"
    attrs: dict = {}

    def set_attr(self, key, value):
        pass

    def add_attrs(self, **attrs):
        pass

    def append_attr(self, key, value):
        pass

    def inc_attr(self, key, delta):
        pass

    def end(self, status=None, error=None):
        pass


NOOP_SPAN = _NoopSpan()


class Capture:
    """In-memory sink for one traced execution (`explain(analyze=True)` and
    tests): the next root trace FINISHED on this context lands in `.trace`."""

    def __init__(self):
        self.trace: Optional[QueryTrace] = None


def active() -> bool:
    """Whether spans should record: any sink is attached. One env lookup on
    the hot path; everything heavier happens only when this is True."""
    if _capture.get() is not None:
        return True
    if os.environ.get(ENV_TRACE_FILE):
        return True
    if os.environ.get(ENV_TIMELINE_DIR):
        # Live timeline capture is a sink: spans must record for _finalize
        # to have a tree to convert.
        return True
    return os.environ.get(ENV_TRACING) == "1"


def current_span():
    return _current_span.get()


def set_attr(key: str, value) -> None:
    """Attribute on the ambient span; no-op without one (or tracing off)."""
    sp = _current_span.get()
    if sp is not None:
        sp.set_attr(key, value)


@contextlib.contextmanager
def capture() -> Iterator[Capture]:
    """Force-record the traces started under this scope and hand the first
    finished root trace to the caller (independent of the env sinks)."""
    cap = Capture()
    token = _capture.set(cap)
    try:
        yield cap
    finally:
        _capture.reset(token)


def _annotation(name: str):
    """`jax.profiler.TraceAnnotation` when jax is ALREADY imported (tracing
    must never trigger the import), else None. Setup failures are absorbed —
    the host span still records."""
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        ann = jax.profiler.TraceAnnotation(name)
        ann.__enter__()
        return ann
    except Exception:
        return None


@contextlib.contextmanager
def query_span(name: str, **attrs) -> Iterator:
    """Root span of one user-facing action (collect/count/build/explain).

    Nested under an already-active span (e.g. a scalar subquery's inner
    collect inside the outer query) it degrades to a plain child span — ONE
    query_id per outermost action. When no sink is active it yields the
    shared no-op span.

    The per-query resource ledger (`telemetry.accounting`) shares this exact
    boundary: a root span carries a ledger; with spans off but accounting on
    (the continuous exporter, or ``HYPERSPACE_ACCOUNTING=1``) a ledger-only
    scope opens around the no-op span, so resource attribution and latency
    histograms survive without paying for span trees."""
    from . import accounting as _accounting

    if not active():
        if not _accounting.enabled():
            yield NOOP_SPAN
            return
        with _accounting.ledger_scope(new_query_id(), name):
            yield NOOP_SPAN
        return
    parent = _current_span.get()
    if parent is not None:
        with span(name, **attrs) as sp:
            yield sp
        return
    trace = QueryTrace(new_query_id())
    root = Span(trace, name, None, attrs)
    # Tenant label end to end: the ambient tenant (the serving layer's
    # `tenant_scope`) rides the root span like it rides the ledger, so the
    # JSONL trace and explain(analyze) attribute the query to its tenant.
    tenant = _accounting.current_tenant()
    if tenant is not None:
        root.set_attr("tenant", tenant)
    token = _current_span.set(root)
    ann = _annotation(name)
    led = _accounting.ledger_scope(trace.query_id, name, root=root)
    led.__enter__()
    try:
        yield root
        root.end()
    except BaseException as e:
        root.end(error=e)
        raise
    finally:
        # Ledger closes AFTER root.end (it reads the root's duration and
        # writes the `ledger` attr) and BEFORE _finalize (the JSONL export
        # must carry the closed ledger).
        try:
            led.__exit__(None, None, None)
        except Exception:
            pass
        if ann is not None:
            try:
                ann.__exit__(None, None, None)
            except Exception:
                pass
        _current_span.reset(token)
        _finalize(trace)


@contextlib.contextmanager
def span(name: str, parent=None, **attrs) -> Iterator:
    """Child span under `parent` (default: the ambient span). Without an
    ambient root (or with tracing off) it is a no-op — stray spans outside a
    query never allocate a trace. Exceptions close the span with
    ``status="error"`` and propagate.

    An EXPLICIT real parent records regardless of this thread's `active()`
    view: pool workers run in a fresh contextvars context, so the submitting
    code passing `parent=` is the proof a sink is attached — without this, a
    worker's span would silently no-op (found by the pool hammer test)."""
    if parent is None:
        if not active():
            yield NOOP_SPAN
            return
        parent = _current_span.get()
    if parent is None or isinstance(parent, _NoopSpan):
        yield NOOP_SPAN
        return
    sp = Span(parent.trace, name, parent.span_id, attrs)
    token = _current_span.set(sp)
    ann = _annotation(name)
    try:
        yield sp
        sp.end()
    except BaseException as e:
        sp.end(error=e)
        raise
    finally:
        if ann is not None:
            try:
                ann.__exit__(None, None, None)
            except Exception:
                pass
        _current_span.reset(token)


def record_stage_spans(kind: str, summary: dict, parent=None) -> None:
    """Adapt one `StageTimings` summary into child spans of the ambient span:
    per stage a span named ``<kind>:<stage>`` whose duration is that stage's
    BUSY seconds (stages overlap — they are not a wall-clock partition, which
    is why `overlap_ratio` rides the summary span), plus one ``<kind>:stages``
    span carrying the whole summary verbatim. This is the bridge that keeps
    `bench_detail.*_stages` and the span tree telling the same story: the
    recorders in `telemetry.profiling` call it on every summary they keep."""
    if parent is None:
        if not active():
            return
        parent = _current_span.get()
    if parent is None or isinstance(parent, _NoopSpan):
        return
    meta = Span(parent.trace, f"{kind}:stages", parent.span_id)
    # These spans are SYNTHESIZED at summary-record time (the operation's
    # end): back-date them by the recorded wall so a timeline viewer places
    # them inside the operation, not after the root ended. Stage durations
    # are BUSY seconds summed across workers — concurrent by design, so they
    # all start at the operation start and legitimately overlap.
    wall = summary.get("wall_s")
    wall = float(wall) if isinstance(wall, (int, float)) else 0.0
    meta.start_s -= wall
    meta.duration_s = wall
    meta.set_attr("synthesized", True)
    counts = summary.get("stage_counts") or {}
    for key, val in summary.items():
        if not key.endswith("_s") or key == "wall_s" or not isinstance(val, (int, float)):
            continue
        stage = key[:-2]
        sp = Span(meta.trace, f"{kind}:{stage}", meta.span_id)
        sp.start_s = meta.start_s
        sp.set_attr("busy_s", float(val))
        cnt = counts.get(stage)
        if cnt is not None:
            sp.set_attr("count", cnt)
        sp.duration_s = max(0.0, float(val))
        sp.status = "ok"
    try:
        meta.attrs.update({k: v for k, v in summary.items() if _json_safe(v)})
    except Exception:
        pass


def _json_safe(v) -> bool:
    try:
        json.dumps(v)
        return True
    except (TypeError, ValueError):
        return False


def recent_traces() -> List[QueryTrace]:
    """Finished root traces, oldest first (bounded history, newest last)."""
    with _recent_lock:
        return list(_RECENT)


def last_trace() -> Optional[QueryTrace]:
    with _recent_lock:
        return _RECENT[-1] if _RECENT else None


def _finalize(trace: QueryTrace) -> None:
    """Root ended: bank the trace, hand it to a same-context capture, and
    export JSONL when the env sink is set. Export failures are swallowed —
    telemetry must never fail the query it observed."""
    if trace.dropped:
        # No silent caps: the span-cap overflow rides the root (JSONL +
        # explain consumers see it) and the process-wide counter.
        trace.root.set_attr("spans_dropped", trace.dropped)
        from . import metrics as _metrics

        _metrics.counter("trace.spans.dropped").inc(trace.dropped)
    with _recent_lock:
        _RECENT.append(trace)
    cap = _capture.get()
    if cap is not None and cap.trace is None:
        cap.trace = trace
    path = os.environ.get(ENV_TRACE_FILE)
    if path:
        try:
            lines = []
            for s in list(trace.spans):
                if s.duration_s is None:
                    # A worker span left open (its pool outlived the root):
                    # export it closed at the root's end with an explicit
                    # marker rather than an unparseable null duration.
                    s.end(status="unclosed")
                lines.append(json.dumps(s.to_json(), default=str))
            from . import rotation as _rotation

            with _export_lock:
                # Size-capped rotation (HYPERSPACE_TRACE_MAX_MB; off by
                # default): one whole trace per append, so rotated files each
                # stay independently parseable.
                _rotation.append(
                    path, "\n".join(lines) + "\n", _rotation.ENV_TRACE_MAX_MB
                )
        except Exception:
            pass
    # Live timeline capture: with HYPERSPACE_TIMELINE_DIR set, every root
    # trace also lands as one Chrome-trace/Perfetto JSON file (one lane per
    # stage / worker family / op class — `stage_ledger.chrome_trace`), so a
    # causal query timeline needs no post-hoc tool run. One env read off.
    tdir = os.environ.get(ENV_TIMELINE_DIR)
    if tdir:
        try:
            from . import stage_ledger as _stage_ledger

            spans = []
            for s in list(trace.spans):
                if s.duration_s is None:
                    s.end(status="unclosed")
                spans.append(s.to_json())
            doc = _stage_ledger.chrome_trace(spans)
            os.makedirs(tdir, exist_ok=True)
            out = os.path.join(tdir, f"timeline-{trace.query_id}.json")
            with open(out, "w") as fh:
                json.dump(doc, fh, default=str)
        except Exception:
            pass
