"""Pluggable event logging.

Parity: reference `telemetry/HyperspaceEventLogging.scala:30-68` — a mixin whose
singleton `EventLogger` is loaded reflectively from conf key
`spark.hyperspace.eventLoggerClass` (default no-op). Here the logger class is resolved
by dotted path from `hyperspace.eventLoggerClass`.
"""

from __future__ import annotations

import importlib
import threading
from typing import List, Optional

from .events import HyperspaceEvent


class EventLogger:
    def log_event(self, event: HyperspaceEvent) -> None:
        raise NotImplementedError


class NoOpEventLogger(EventLogger):
    def log_event(self, event: HyperspaceEvent) -> None:
        pass


class RecordingEventLogger(EventLogger):
    """Keeps events in memory — used by tests and the explain subsystem."""

    def __init__(self):
        self.events: List[HyperspaceEvent] = []

    def log_event(self, event: HyperspaceEvent) -> None:
        self.events.append(event)


class EventLoggerFactory:
    """Caches one logger instance per class name (reference's singleton wrapper)."""

    _lock = threading.Lock()
    _cache = {}

    @classmethod
    def get_logger(cls, class_name: Optional[str]) -> EventLogger:
        key = class_name or "noop"
        with cls._lock:
            if key not in cls._cache:
                if class_name is None:
                    cls._cache[key] = NoOpEventLogger()
                else:
                    # A bad dotted path (typo'd conf, missing module, class
                    # whose constructor raises) must not escape mid-query —
                    # and must not stay uncached, which would retry (and
                    # re-fail) the import on EVERY event. Fall back to the
                    # no-op logger, cached under the bad name, with one
                    # warning.
                    try:
                        module_name, _, attr = class_name.rpartition(".")
                        mod = importlib.import_module(module_name)
                        cls._cache[key] = getattr(mod, attr)()
                    except Exception as e:
                        import logging

                        logging.getLogger("hyperspace_tpu.telemetry").warning(
                            "Event logger class %r failed to load (%s: %s); "
                            "falling back to NoOpEventLogger",
                            class_name,
                            type(e).__name__,
                            e,
                        )
                        cls._cache[key] = NoOpEventLogger()
            return cls._cache[key]

    @classmethod
    def reset(cls) -> None:
        with cls._lock:
            cls._cache.clear()
