"""Workload history store: every closed query ledger, durable, on the lake.

PR 6 made the engine measure the true cost of every query (`QueryLedger`,
stage histograms, the compile observatory) — and then forget it at process
exit: the ledger history is a ``deque(32)`` and the exporter stream is
fire-and-forget. The adaptive cost model (ROADMAP item 4) needs the opposite:
durable, per-plan-class observed history in the "cost = bytes moved" framing
of *Evaluating Learned Indexes for External-Memory Joins*. This module is
that substrate, following the reference's own operation-log pattern — all
metadata lives ON THE LAKE, concurrency is optimistic, no external service.

Layout (``HYPERSPACE_HISTORY_DIR``, default ``<warehouse>/.hyperspace_history``):

- ``seg-<host>-<pid>-<uuid>.jsonl`` — one APPEND-ONLY segment per writer
  process generation. Writers never share a file, so concurrent processes
  are OCC-consistent by construction (the same ownership scheme as the
  PR-7 staging dirs: host+pid ride the name for liveness-checked reclaim).
  Each line is one self-describing record: ``{"schema_version", "kind":
  "ledger", "ts", "fingerprint", "ledger": {...}}``. Lines are written
  with a single write+flush, so a SIGKILL mid-append tears at most the
  LAST line — readers skip torn lines (``history.torn_lines``) and keep
  every committed record.
- ``compact-<host>-<pid>-<uuid>.jsonl`` — compaction output: per-
  fingerprint BASELINE CHECKPOINT records (``"kind": "baseline"``)
  summarizing raw ledgers via serialized `metrics.Histogram` bucket state
  (`dump_state`/`merge_state`), so baselines survive with bounded bytes.
- segments are bounded (``HYPERSPACE_HISTORY_SEGMENT_MB``, rotate-on-cap)
  and compacted opportunistically in the background of rotation/open: a
  segment whose writer is provably dead (same host, dead pid) or older
  than ``HYPERSPACE_HISTORY_TTL_S`` is CLAIMED by atomic rename (losers
  of the race skip — the `reclaim_orphans` arbitration), folded into
  checkpoints, committed via tmp + `os.replace`, then deleted.

On top of the store, per-fingerprint **rolling baselines** (p50/p99 wall,
bytes decoded/skipped, io retries, xla compiles) are maintained in memory —
rebuilt from segments at open, so history survives restart — and every
ledger landing is **anomaly-checked at close**: a query ≥ Nσ over its class
baseline (``HYPERSPACE_HISTORY_ANOMALY_SIGMA``, default 3) ticks
``history.anomalies``, lands a ``history_anomaly`` attr on the root span,
rides the exporter frame's ``history`` key, and warns once per fingerprint.

Cost when off (the default): `enabled()` is ONE env read, checked at ledger
close only — a query with no telemetry sink active never reaches it at all
(no ledger opens). Pinned by the zero-cost-off test like PR 6's.
"""

from __future__ import annotations

import contextlib
import json
import math
import os
import socket
import threading
import time
import uuid
import warnings
from collections import deque
from typing import Dict, Iterator, List, Optional, Tuple

from . import metrics as _metrics

SCHEMA_VERSION = 1

ENV_HISTORY = "HYPERSPACE_HISTORY"
ENV_HISTORY_DIR = "HYPERSPACE_HISTORY_DIR"
ENV_SEGMENT_MB = "HYPERSPACE_HISTORY_SEGMENT_MB"
ENV_TTL_S = "HYPERSPACE_HISTORY_TTL_S"
ENV_ANOMALY_SIGMA = "HYPERSPACE_HISTORY_ANOMALY_SIGMA"

_DEFAULT_SEGMENT_MB = 4.0
_DEFAULT_TTL_S = 24 * 3600.0
_DEFAULT_SIGMA = 3.0

#: A class baseline starts flagging anomalies only once it has seen this
#: many queries (a 2-sample "baseline" would flag ordinary warmup jitter).
ANOMALY_MIN_SAMPLES = 8
#: Sub-5ms queries never flag: at that scale scheduler jitter exceeds any
#: signal a cost model could act on.
ANOMALY_MIN_WALL_S = 0.005

SEGMENT_PREFIX = "seg-"
COMPACT_PREFIX = "compact-"
CLAIMED_PREFIX = ".claimed-"
_TMP_PREFIX = ".tmp-"

#: Ledger fields whose per-class totals/means the baseline tracks (beyond
#: wall): exactly the cost axes the ROADMAP-4 cost model reads.
TRACKED_FIELDS = (
    "bytes_decoded",
    "bytes_skipped",
    "decode_files",
    "io_retries",
    "xla_compiles",
    "rows_produced",
    # Device cost vectors (telemetry/device_observatory.py): per-class
    # device time, transfer bytes both ways, and the pow2 padding split —
    # the measured per-class costs the future planner prices against.
    "device_time_s",
    "device_upload_bytes",
    "d2h_bytes",
    "pad_bytes_payload",
    "pad_bytes_padded",
    # Encoded device staging split (engine/encoded_device.py), with the
    # bit-packed sub-byte tier (engine/packed_codes.py).
    "device_code_bytes_flat",
    "device_code_bytes_staged",
    "device_code_bytes_packed",
)

_RECORDS = _metrics.counter("history.records")
_ANOMALIES = _metrics.counter("history.anomalies")
_TORN = _metrics.counter("history.torn_lines")
_ROTATED = _metrics.counter("history.segments_rotated")
_COMPACTED = _metrics.counter("history.segments_compacted")

#: Anomalies drained into exporter frames (bounded like the ledger queue).
_PENDING_ANOMALIES: "deque[dict]" = deque(maxlen=64)
_warned_fingerprints: set = set()

_stores_lock = threading.Lock()
_stores: Dict[str, "HistoryStore"] = {}


def enabled() -> bool:
    """One env read: the history hot-path gate."""
    return os.environ.get(ENV_HISTORY) == "1"


def history_dir() -> str:
    """The store location: ``HYPERSPACE_HISTORY_DIR`` when set, else next to
    the active session's index logs (``<warehouse>/.hyperspace_history`` —
    the on-lake placement of the operation-log pattern), else the cwd."""
    env = os.environ.get(ENV_HISTORY_DIR)
    if env:
        return env
    try:
        from ..engine.session import HyperspaceSession

        sess = HyperspaceSession._active
        if sess is not None:
            return os.path.join(sess.warehouse, ".hyperspace_history")
    except Exception:
        pass
    return os.path.join(".", ".hyperspace_history")


def _segment_cap_bytes() -> int:
    try:
        mb = float(os.environ.get(ENV_SEGMENT_MB, "") or _DEFAULT_SEGMENT_MB)
    except ValueError:
        mb = _DEFAULT_SEGMENT_MB
    return max(4096, int(mb * 1_000_000))


def _ttl_s() -> float:
    try:
        return max(0.0, float(os.environ.get(ENV_TTL_S, "") or _DEFAULT_TTL_S))
    except ValueError:
        return _DEFAULT_TTL_S


def _sigma() -> float:
    try:
        return max(0.5, float(os.environ.get(ENV_ANOMALY_SIGMA, "") or _DEFAULT_SIGMA))
    except ValueError:
        return _DEFAULT_SIGMA


def _owner_of(name: str) -> Tuple[Optional[str], int]:
    """(host, pid) from a ``seg-<host>-<pid>-<uuid>.jsonl`` style name —
    hosts may contain '-', so parse from the RIGHT."""
    stem = name[: -len(".jsonl")] if name.endswith(".jsonl") else name
    parts = stem.split("-")
    try:
        return "-".join(parts[1:-2]) or None, int(parts[-2])
    except (IndexError, ValueError):
        return None, -1


def _pid_alive(pid: int) -> bool:
    from ..util.procs import pid_alive

    return pid_alive(pid)


def _claim_parts(name: str) -> Tuple[Optional[str], int, Optional[str]]:
    """(claimant host, claimant pid, claimed original name) from a
    ``.claimed-<host>~<pid>~<orig>`` name; (None, -1, None) if unparseable.
    The HOST rides the name because history dirs are shared across hosts
    (segment TTL reclaim exists for exactly that) — a pid number alone is
    meaningless on another machine."""
    rest = name[len(CLAIMED_PREFIX):]
    parts = rest.split("~", 2)
    if len(parts) != 3:
        return None, -1, None
    try:
        return parts[0], int(parts[1]), parts[2]
    except ValueError:
        return None, -1, None


def _claim_orphaned(name: str, path: str) -> bool:
    """Whether a claim's compactor is provably gone: same-host claimant →
    pid liveness; foreign/unparseable claimant → mtime age past the TTL
    (the exact liveness rules segments use)."""
    host, pid, _orig = _claim_parts(name)
    if host == socket.gethostname():
        return not _pid_alive(pid)
    try:
        ttl = _ttl_s()
        return ttl > 0 and time.time() - os.stat(path).st_mtime > ttl
    except OSError:
        return False


def _root_name(name: str) -> str:
    """The underlying segment name beneath any number of claim prefixes
    (a claim of an orphaned claim nests them)."""
    while name.startswith(CLAIMED_PREFIX):
        _h, _p, orig = _claim_parts(name)
        if not orig:
            break
        name = orig
    return name


def _folded_sources(dir_path: str) -> set:
    """Root segment names already folded into a committed checkpoint file
    (each ``compact-*.jsonl`` leads with a ``compact_manifest`` record
    listing its sources). A claim whose root appears here is GARBAGE from a
    compactor that died between checkpoint commit and claim unlink — its
    records are already counted, so readers skip it and the next compaction
    deletes it instead of double-folding."""
    out: set = set()
    try:
        names = os.listdir(dir_path)
    except OSError:
        return out
    for n in names:
        if n.startswith(COMPACT_PREFIX) and n.endswith(".jsonl"):
            # The manifest is pinned to the file's FIRST record — stop
            # there instead of JSON-parsing every checkpoint in the file
            # (this runs on the rotation path of a long-lived store).
            for rec in iter_file_records(os.path.join(dir_path, n)):
                if rec.get("kind") == "compact_manifest":
                    for s in rec.get("sources") or []:
                        out.add(str(s))
                break
    return out


# ---------------------------------------------------------------------------
# Per-fingerprint rolling baseline
# ---------------------------------------------------------------------------


class FingerprintBaseline:
    """Rolling cost baseline of one plan class: wall-clock distribution (a
    private `metrics.Histogram` for p50/p99 — its bucket state is what the
    compaction checkpoints serialize) plus sum/sum-of-squares for the Nσ
    anomaly bound, plus totals of the tracked cost fields."""

    __slots__ = ("fingerprint", "names", "hist", "wall_sumsq", "fields", "stages")

    def __init__(self, fingerprint: str):
        self.fingerprint = fingerprint
        self.names: set = set()
        self.hist = _metrics.Histogram(f"history.{fingerprint}")  # unregistered
        self.wall_sumsq = 0.0
        self.fields: Dict[str, float] = {}
        # Per-stage cost-vector totals (stage attribution, PR 19):
        # {stage: {"n": queries_that_labeled_it, <field>: total, ...}} —
        # folded from each ledger's ``stages`` key. Empty for classes whose
        # queries ran with HYPERSPACE_STAGE_ATTRIBUTION=0.
        self.stages: Dict[str, dict] = {}

    @property
    def count(self) -> int:
        return self.hist.count

    def mean_std(self) -> Tuple[float, float]:
        n = self.hist.count
        if n == 0:
            return 0.0, 0.0
        mean = self.hist.total / n
        var = max(0.0, self.wall_sumsq / n - mean * mean)
        return mean, math.sqrt(var)

    def check_anomaly(self, wall: float) -> Optional[dict]:
        """Nσ test against the CURRENT baseline (call before `observe` so a
        query is never compared against a baseline containing itself)."""
        if self.hist.count < ANOMALY_MIN_SAMPLES or wall < ANOMALY_MIN_WALL_S:
            return None
        mean, std = self.mean_std()
        # The σ bound with two floors: a near-zero-variance class (identical
        # warm lookups) must not flag 1.3x jitter, and the absolute floor
        # keeps microsecond classes quiet.
        threshold = max(mean + _sigma() * std, mean * 1.25, ANOMALY_MIN_WALL_S)
        if wall <= threshold:
            return None
        return {
            "fingerprint": self.fingerprint,
            "wall_s": round(wall, 6),
            "baseline_mean_s": round(mean, 6),
            "baseline_std_s": round(std, 6),
            "threshold_s": round(threshold, 6),
            "baseline_n": self.hist.count,
        }

    def observe(self, ledger: dict) -> None:
        wall = ledger.get("wall_s")
        if isinstance(wall, (int, float)):
            self.hist.observe(float(wall))
            self.wall_sumsq += float(wall) * float(wall)
        name = ledger.get("name")
        if name and len(self.names) < 8:
            self.names.add(str(name))
        for f in TRACKED_FIELDS:
            v = ledger.get(f)
            if isinstance(v, (int, float)) and v:
                self.fields[f] = self.fields.get(f, 0) + v
        stages = ledger.get("stages")
        if isinstance(stages, dict):
            # One ledger = one query: each stage it labeled counts n=1.
            self._fold_stages(stages, default_n=1)

    def _fold_stages(self, stages: dict, default_n: int) -> None:
        """Fold stage vectors into the per-stage totals. A ledger's vectors
        carry no "n" (each is one query → `default_n`); a checkpoint's
        accumulators carry their own folded "n"."""
        for st, vec in stages.items():
            if not isinstance(vec, dict):
                continue
            acc = self.stages.get(st)
            if acc is None:
                acc = self.stages[st] = {"n": 0}
            for k, v in vec.items():
                if k != "n" and isinstance(v, (int, float)):
                    acc[k] = acc.get(k, 0) + v
            n = vec.get("n", default_n)
            acc["n"] += n if isinstance(n, int) and n > 0 else default_n

    def to_checkpoint(self) -> dict:
        out = {
            "schema_version": SCHEMA_VERSION,
            "kind": "baseline",
            "fingerprint": self.fingerprint,
            "names": sorted(self.names),
            "wall": self.hist.dump_state(),
            "wall_sumsq": round(self.wall_sumsq, 9),
            "fields": {k: round(v, 6) if isinstance(v, float) else v
                       for k, v in sorted(self.fields.items())},
        }
        if self.stages:
            # New key on the v1 record: old readers ignore unknown keys (the
            # standing forward-compat contract), so no version bump needed.
            out["stages"] = {
                st: {k: round(v, 6) if isinstance(v, float) else v
                     for k, v in sorted(acc.items())}
                for st, acc in sorted(self.stages.items())
            }
        return out

    def merge_checkpoint(self, rec: dict) -> None:
        self.hist.merge_state(rec.get("wall") or {})
        try:
            self.wall_sumsq += float(rec.get("wall_sumsq") or 0.0)
        except (TypeError, ValueError):
            pass
        names = rec.get("names")
        if isinstance(names, (list, tuple)):
            for n in names:
                if len(self.names) < 8:
                    self.names.add(str(n))
        fields = rec.get("fields")
        if isinstance(fields, dict):
            for k, v in fields.items():
                if isinstance(v, (int, float)):
                    self.fields[k] = self.fields.get(k, 0) + v
        stages = rec.get("stages")
        if isinstance(stages, dict):
            self._fold_stages(stages, default_n=1)

    def summary(self) -> dict:
        mean, std = self.mean_std()
        s = self.hist.summary()
        out = {
            "n": s["count"],
            "names": sorted(self.names),
            "wall_total_s": round(s["total"], 6),
            "wall_mean_s": round(mean, 6),
            "wall_std_s": round(std, 6),
        }
        if s["count"]:
            out["wall_p50_s"] = s.get("p50")
            out["wall_p99_s"] = s.get("p99")
            out["wall_max_s"] = s.get("max")
        for k, v in sorted(self.fields.items()):
            out[k] = round(v, 6) if isinstance(v, float) else v
        if self.stages:
            out["stages"] = {
                st: {k: round(v, 6) if isinstance(v, float) else v
                     for k, v in sorted(acc.items())}
                for st, acc in sorted(self.stages.items())
            }
        return out


# ---------------------------------------------------------------------------
# Segment reader (tolerant: torn lines, unknown keys, future versions)
# ---------------------------------------------------------------------------


def iter_file_records(path: str, count_torn: bool = False) -> Iterator[dict]:
    """Parsed records of one segment. Torn/garbled lines are skipped — a
    SIGKILL mid-append tears at most the final line, and the committed
    prefix must stay readable. `count_torn` ticks ``history.torn_lines``
    per skipped line: only the store's OWN load pass sets it, so the
    counter measures tears encountered once — not re-reads of the same old
    tear by every reporting tool (which would false-alarm a monitor).
    Records from FUTURE schema versions parse too: the forward-compat
    contract is "tolerate unknown keys, skip unknown kinds", never
    "reject"."""
    try:
        f = open(path, "r")
    except OSError:
        return
    with f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                if count_torn:
                    _TORN.inc()
                continue
            if isinstance(rec, dict):
                yield rec


def _store_files(dir_path: str, include_claimed: bool = True) -> List[str]:
    try:
        names = os.listdir(dir_path)
    except OSError:
        return []
    out = []
    folded: Optional[set] = None  # computed lazily, only when a claim exists
    for n in sorted(names):
        if n.startswith((SEGMENT_PREFIX, COMPACT_PREFIX)) and n.endswith(".jsonl"):
            out.append(os.path.join(dir_path, n))
        elif include_claimed and n.startswith(CLAIMED_PREFIX) and n.endswith(".jsonl"):
            # A claim whose compactor died mid-fold: its records are intact
            # and must stay visible (the next compaction re-claims it). A
            # LIVE claimant's file is skipped — its content is about to be
            # re-committed as a checkpoint and must not double-count; and a
            # claim whose root is already in a committed manifest is garbage
            # (counted once already), skipped for the same reason.
            path = os.path.join(dir_path, n)
            if not _claim_orphaned(n, path):
                continue
            if folded is None:
                folded = _folded_sources(dir_path)
            if _root_name(n) in folded:
                continue
            out.append(path)
    return out


def iter_records(dir_path: str, count_torn: bool = False) -> Iterator[dict]:
    """Every record in a history dir (segments + compacted checkpoints +
    orphaned claims), torn-line tolerant. The reader `tools/hsreport.py`
    and `tools/bench_compare.py --history` share."""
    for path in _store_files(dir_path):
        yield from iter_file_records(path, count_torn=count_torn)


def fold_baselines(records: Iterator[dict]) -> Dict[str, FingerprintBaseline]:
    """Fold a record stream into per-fingerprint baselines: ledger records
    observe, baseline checkpoints merge, unknown kinds skip (forward
    compat). THE one folding implementation — store load, compaction, and
    the CLI tools all call it."""
    out: Dict[str, FingerprintBaseline] = {}
    for rec in records:
        kind = rec.get("kind")
        fp = rec.get("fingerprint")
        if not fp:
            continue
        if kind == "ledger":
            led = rec.get("ledger")
            if isinstance(led, dict):
                bl = out.get(fp)
                if bl is None:
                    bl = out[fp] = FingerprintBaseline(fp)
                bl.observe(led)
        elif kind == "baseline":
            bl = out.get(fp)
            if bl is None:
                bl = out[fp] = FingerprintBaseline(fp)
            bl.merge_checkpoint(rec)
        # any other kind: a future writer's record — tolerated, skipped.
    return out


def split_records(records) -> Tuple[Dict[str, list], Dict[str, list]]:
    """Partition a record stream into (raw ledger records, checkpoint
    records) keyed by fingerprint, ledgers time-ordered — the grouping both
    reporting tools start from."""
    raw: Dict[str, list] = {}
    checkpoints: Dict[str, list] = {}
    for rec in records:
        fp = rec.get("fingerprint")
        if not fp:
            continue
        if rec.get("kind") == "ledger" and isinstance(rec.get("ledger"), dict):
            raw.setdefault(fp, []).append(rec)
        elif rec.get("kind") == "baseline":
            checkpoints.setdefault(fp, []).append(rec)
    for recs in raw.values():
        recs.sort(key=lambda r: r.get("ts") or 0.0)
    return raw, checkpoints


def recent_vs_baseline(
    raw: Dict[str, list],
    checkpoints: Dict[str, list],
    recent_k: int,
    min_baseline: int = 1,
    require_full_window: bool = False,
) -> List[dict]:
    """Per plan class: the p50 wall of the newest `recent_k` raw ledgers vs
    the class BASELINE p50 (every older ledger + compacted checkpoints).
    THE one expected-vs-actual computation — `tools/hsreport.py`'s drift
    table and `tools/bench_compare.py --history`'s CI gate both call it
    (the gate passes ``min_baseline=ANOMALY_MIN_SAMPLES`` and
    ``require_full_window=True`` so it only judges credible classes; the
    report shows every class with any recent signal). Classes without a
    computable pair are omitted."""
    out = []
    for fp in sorted(set(raw) | set(checkpoints)):
        ledgers = raw.get(fp, [])
        recent = [
            r["ledger"]["wall_s"]
            for r in ledgers[-recent_k:]
            if isinstance(r["ledger"].get("wall_s"), (int, float))
        ]
        if not recent or (require_full_window and len(recent) < recent_k):
            continue
        baseline = FingerprintBaseline(fp)
        for rec in checkpoints.get(fp, ()):
            baseline.merge_checkpoint(rec)
        for rec in ledgers[:-recent_k]:
            baseline.observe(rec["ledger"])
        if baseline.count < min_baseline:
            continue
        expected = baseline.hist.quantile(0.5)
        if expected is None:
            continue
        actual = sorted(recent)[len(recent) // 2]
        out.append(
            {
                "fingerprint": fp,
                "names": sorted(baseline.names),
                "baseline_n": baseline.count,
                "recent_n": len(recent),
                "expected_p50_s": round(expected, 6),
                "actual_p50_s": round(actual, 6),
                "ratio": round(actual / expected, 3) if expected else None,
            }
        )
    return out


# ---------------------------------------------------------------------------
# The store
# ---------------------------------------------------------------------------


class HistoryStore:
    """One process's handle on a history directory: an append-only segment
    it owns exclusively, plus the folded baselines of everything on disk."""

    def __init__(self, dir_path: str, load: bool = True, compact_on_open: bool = True):
        self.dir = dir_path
        os.makedirs(dir_path, exist_ok=True)
        self._lock = threading.RLock()
        self._fh = None
        self._seg_path: Optional[str] = None
        self._seg_bytes = 0
        self.records_written = 0
        self._baselines: Dict[str, FingerprintBaseline] = (
            fold_baselines(iter_records(dir_path, count_torn=True)) if load else {}
        )
        if compact_on_open:
            try:
                self.compact()
            except Exception:
                pass  # compaction is an optimization, never a failure mode

    # -- segment ownership --------------------------------------------------

    def _new_segment_name(self) -> str:
        return (
            f"{SEGMENT_PREFIX}{socket.gethostname()}-{os.getpid()}"
            f"-{uuid.uuid4().hex[:8]}.jsonl"
        )

    def _open_segment_locked(self) -> None:
        self._seg_path = os.path.join(self.dir, self._new_segment_name())
        self._fh = open(self._seg_path, "a")
        self._seg_bytes = 0

    def _rotate_locked(self) -> None:
        if self._fh is not None:
            self._fh.close()
        self._open_segment_locked()
        _ROTATED.inc()

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    # -- landing ------------------------------------------------------------

    def record(self, fingerprint: str, ledger: dict) -> Optional[dict]:
        """Land one closed ledger: anomaly-check against the class baseline,
        append the record (one write+flush — the crash-safety unit), fold
        into the in-memory baseline. Returns the anomaly verdict, or None."""
        rec = {
            "schema_version": SCHEMA_VERSION,
            "kind": "ledger",
            "ts": round(time.time(), 6),
            "fingerprint": fingerprint,
            "ledger": ledger,
        }
        # Record-envelope replica stamp (serve.replicas): segments from K
        # replicas co-exist in one shared history dir, and the envelope
        # stamp attributes every record kind — not just ledgers — to its
        # writer (readers tolerate unknown keys by the segment contract).
        try:
            from ..serve.replicas import replica_id as _rid

            rec["replica_id"] = _rid()
        except Exception:
            pass
        # json.dumps defaults to ensure_ascii=True, so the line is pure
        # ASCII and len(line) == encoded bytes — the segment-cap arithmetic
        # below is exact without paying an encode.
        line = json.dumps(rec, default=str) + "\n"
        rotated = False
        with self._lock:
            bl = self._baselines.get(fingerprint)
            if bl is None:
                bl = self._baselines[fingerprint] = FingerprintBaseline(fingerprint)
            wall = ledger.get("wall_s")
            verdict = (
                bl.check_anomaly(float(wall))
                if isinstance(wall, (int, float))
                else None
            )
            bl.observe(ledger)
            if self._fh is None or self._seg_bytes + len(line) > _segment_cap_bytes():
                if self._fh is None:
                    self._open_segment_locked()
                else:
                    self._rotate_locked()
                    rotated = True
            wrote = False
            try:
                self._fh.write(line)
                self._fh.flush()
                self._seg_bytes += len(line)
                self.records_written += 1
                wrote = True
            except OSError:
                pass  # telemetry must never fail the query it observed
        if rotated:
            # Background compaction rides rotation — OUTSIDE the store lock
            # (folding dead segments does listdir + reads + fsync; other
            # threads' ledger closes must not stall behind it).
            try:
                self.compact()
            except Exception:
                pass
        if wrote:
            # Only records that actually reached the segment count — the
            # counter must reconcile with what a reader finds on disk.
            _RECORDS.inc()
        if verdict is not None:
            _ANOMALIES.inc()
            verdict["query_id"] = ledger.get("query_id")
            verdict["name"] = ledger.get("name")
            _PENDING_ANOMALIES.append(verdict)
            # Anomaly-triggered profile capture (HYPERSPACE_PROFILE_DIR):
            # one bounded trace window per rate-limit interval, keep-N
            # rotated. Never lets a capture failure reach the query path.
            try:
                from . import device_observatory as _devobs

                _devobs.maybe_capture("anomaly", dict(verdict))
            except Exception:
                pass
            if fingerprint not in _warned_fingerprints:
                _warned_fingerprints.add(fingerprint)
                warnings.warn(
                    f"hyperspace history: query class {fingerprint} "
                    f"({ledger.get('name')}) ran {verdict['wall_s']:.3f}s, "
                    f"over its baseline threshold {verdict['threshold_s']:.3f}s "
                    f"(mean {verdict['baseline_mean_s']:.3f}s over "
                    f"{verdict['baseline_n']} queries). Further anomalies in "
                    "this class tick history.anomalies silently.",
                    RuntimeWarning,
                    stacklevel=4,
                )
        return verdict

    # -- baselines ----------------------------------------------------------

    def baselines(self) -> Dict[str, dict]:
        with self._lock:
            return {fp: bl.summary() for fp, bl in self._baselines.items()}

    def baseline_for(self, fingerprint: str) -> Optional[FingerprintBaseline]:
        with self._lock:
            return self._baselines.get(fingerprint)

    # -- compaction ---------------------------------------------------------

    def _compactable(self) -> List[str]:
        """Segments/compacts safe to fold: not our own live segment, writer
        provably dead on this host, or older than the TTL (the
        `reclaim_orphans` liveness rules). Orphaned claims re-qualify."""
        out = []
        ttl = _ttl_s()
        now = time.time()
        try:
            names = os.listdir(self.dir)
        except OSError:
            return []
        for name in names:
            if not name.endswith(".jsonl"):
                continue
            path = os.path.join(self.dir, name)
            if path == self._seg_path:
                continue
            if name.startswith(CLAIMED_PREFIX):
                if _claim_orphaned(name, path):
                    out.append(path)
                continue
            if not name.startswith((SEGMENT_PREFIX, COMPACT_PREFIX)):
                continue
            host, pid = _owner_of(name)
            if host == socket.gethostname() and pid > 0:
                if not _pid_alive(pid):
                    out.append(path)
                # A LIVE same-host writer keeps ALL its segments — from the
                # outside its current segment is indistinguishable from its
                # rotated ones, and claiming the one it still appends to
                # would silently lose every record written after the rename
                # (the fh keeps flushing to an unlinked inode). Its history
                # compacts when the process exits (pid rule) — the same
                # lifecycle as the PR-7 staging dirs.
                continue
            try:
                if ttl > 0 and now - os.stat(path).st_mtime > ttl:
                    out.append(path)
            except OSError:
                continue
        return out

    def compact(self) -> int:
        """Fold every compactable file into one checkpoint-only compact
        segment. Concurrency-safe via claim-by-rename: only the process
        whose rename wins folds a given file (the loser's rename raises and
        it skips), so records are never double-counted across compactors.
        The committed checkpoint file LEADS with a ``compact_manifest``
        record naming its source segments — if this process dies between
        checkpoint commit and claim unlink, the orphaned claims' roots are
        in the manifest and later readers/compactors treat them as garbage
        instead of folding their records a second time. Runs WITHOUT the
        store lock (only the claim renames arbitrate), so a rotation-
        triggered compaction never stalls other threads' ledger closes."""
        candidates = self._compactable()
        if not candidates:
            return 0
        already_folded = _folded_sources(self.dir)
        claimed: List[str] = []
        garbage: List[str] = []
        me = f"{CLAIMED_PREFIX}{socket.gethostname()}~{os.getpid()}~"
        for path in candidates:
            claim = os.path.join(
                os.path.dirname(path), me + os.path.basename(path)
            )
            try:
                os.rename(path, claim)
            except OSError:
                continue  # another compactor won this file
            # Restart the TTL clock on the claim: rename PRESERVES mtime, so
            # a TTL-aged segment's fresh claim would otherwise be judged
            # orphaned instantly by a concurrent foreign compactor, which
            # would re-claim and double-fold the same records.
            with contextlib.suppress(OSError):
                os.utime(claim, None)
            if _root_name(os.path.basename(path)) in already_folded:
                garbage.append(claim)  # counted by a committed checkpoint
            else:
                claimed.append(claim)
        for p in garbage:
            with contextlib.suppress(OSError):
                os.unlink(p)
        if not claimed:
            return len(garbage)
        folded: Dict[str, FingerprintBaseline] = fold_baselines(
            rec for p in claimed for rec in iter_file_records(p)
        )
        tmp = os.path.join(self.dir, f"{_TMP_PREFIX}{uuid.uuid4().hex[:8]}.jsonl")
        out = os.path.join(
            self.dir,
            f"{COMPACT_PREFIX}{socket.gethostname()}-{os.getpid()}"
            f"-{uuid.uuid4().hex[:8]}.jsonl",
        )
        manifest = {
            "schema_version": SCHEMA_VERSION,
            "kind": "compact_manifest",
            "sources": sorted(_root_name(os.path.basename(p)) for p in claimed),
        }
        try:
            with open(tmp, "w") as f:
                f.write(json.dumps(manifest) + "\n")
                for fp in sorted(folded):
                    f.write(json.dumps(folded[fp].to_checkpoint(), default=str) + "\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, out)
        except OSError:
            # Commit failed (e.g. disk full): RELEASE the claims by renaming
            # them back to their original names — a claim held by a live pid
            # is invisible to readers, so leaving it claimed would hide
            # those records for this process's whole lifetime.
            with contextlib.suppress(OSError):
                os.unlink(tmp)
            for p in claimed:
                _h, _p, orig = _claim_parts(os.path.basename(p))
                if orig:
                    with contextlib.suppress(OSError):
                        os.rename(p, os.path.join(os.path.dirname(p), orig))
            return len(garbage)
        for p in claimed:
            with contextlib.suppress(OSError):
                os.unlink(p)
        _COMPACTED.inc(len(claimed))
        return len(claimed) + len(garbage)


# ---------------------------------------------------------------------------
# Module-level wiring (what accounting / exporter / tools call)
# ---------------------------------------------------------------------------


def get_store(dir_path: Optional[str] = None) -> HistoryStore:
    """The process's store for `dir_path` (default: the ambient history
    dir). One store per directory; creation folds the on-disk history."""
    d = os.path.abspath(dir_path or history_dir())
    with _stores_lock:
        st = _stores.get(d)
        if st is None:
            st = _stores[d] = HistoryStore(d)
        return st


def reset_stores() -> None:
    """Drop every cached store handle (tests): segments stay on disk; the
    next `get_store` re-folds them — which is exactly the restart-survival
    contract the tests pin."""
    with _stores_lock:
        for st in _stores.values():
            st.close()
        _stores.clear()
    _PENDING_ANOMALIES.clear()
    _warned_fingerprints.clear()


def land(ledger_dict: dict, root=None) -> Optional[dict]:
    """Land one closed ledger in the ambient store (called by
    `accounting.ledger_scope` at close, gated on `enabled()`). The ledger's
    ``plan_fingerprint`` keys it; ledgers without one (index builds, counts
    planned before fingerprinting existed) fall back to a name class."""
    try:
        st = get_store()
        fp = ledger_dict.get("plan_fingerprint") or f"name:{ledger_dict.get('name')}"
        verdict = st.record(fp, ledger_dict)
    except Exception:
        return None  # history must never fail the query it records
    if verdict is not None and root is not None:
        try:
            root.set_attr("history_anomaly", verdict)
        except Exception:
            pass
    return verdict


def drain_anomalies() -> List[dict]:
    out: List[dict] = []
    while _PENDING_ANOMALIES:
        try:
            out.append(_PENDING_ANOMALIES.popleft())
        except IndexError:
            break
    return out


def frame_summary() -> Optional[dict]:
    """The exporter frame's ``history`` key: present only once a store has
    landed records in this process (schema-stable for history-less runs)."""
    with _stores_lock:
        stores = list(_stores.values())
    if not stores:
        return None
    out = {
        "dirs": [st.dir for st in stores],
        "records_written": sum(st.records_written for st in stores),
        "fingerprints": sum(len(st._baselines) for st in stores),
        "anomalies_total": _ANOMALIES.value,
    }
    anomalies = drain_anomalies()
    if anomalies:
        out["anomalies"] = anomalies
    return out
