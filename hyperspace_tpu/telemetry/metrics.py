"""Process-wide metrics registry: named counters, gauges, and histograms.

The engine's caches and memos each kept private hit/miss integers that only
bench.py knew how to scrape, and only for the caches it knew about. This
registry is the one place every component reports to — `scan_cache`,
`device_cache`, the device memos in `engine/physical`, the decode pool in
`engine/io`, the optimizer rules, and the Pallas kernel fallbacks — so a
query's cache behavior is answerable from one `snapshot()` (consumed by
`bench_detail.metrics_snapshot` and `explain(analyze=True)`).

Contracts:
- Metric objects are cheap, lock-guarded, and process-wide singletons per
  name: `counter("cache.scan.hits").inc()` from any thread never loses an
  update (pinned by tests/test_tracing.py's pool hammer).
- `snapshot()` is a point-in-time copy (plain dicts, JSON-serializable) and
  includes derived `rates` for every `<base>.hits`/`<base>.misses` counter
  pair, so hit RATES ride the bench artifact without consumer arithmetic.
- Metrics are always on (integer adds; no env gate): unlike spans they cannot
  trigger device work or allocation growth — the registry holds one object
  per metric NAME, never per observation.
"""

from __future__ import annotations

import bisect
import math
import threading
from typing import Dict, List, Optional, Tuple


class Counter:
    """Monotonic counter. `inc` is atomic under the metric's own lock."""

    __slots__ = ("name", "_lock", "_value")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += int(n)

    def reset(self) -> None:
        with self._lock:
            self._value = 0

    @property
    def value(self) -> int:
        with self._lock:
            return self._value


class Gauge:
    """Last-write-wins scalar (e.g. bytes currently pinned). `add` supports
    up/down accounting (e.g. the decode pool's in-flight depth), which `set`
    alone cannot do race-free from concurrent workers."""

    __slots__ = ("name", "_lock", "_value")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v) -> None:
        with self._lock:
            self._value = v

    def add(self, n) -> None:
        with self._lock:
            self._value += n

    def inc(self, n=1) -> None:
        self.add(n)

    def dec(self, n=1) -> None:
        self.add(-n)

    def set_max(self, v) -> None:
        """High-water mark: keep the larger of the current and new value."""
        with self._lock:
            if v > self._value:
                self._value = v

    def reset(self) -> None:
        with self._lock:
            self._value = 0.0

    @property
    def value(self):
        with self._lock:
            return self._value


#: Shared log-spaced bucket upper bounds: 4 per decade over 1e-6 … 1e10
#: (microseconds → device-byte counts), Prometheus-style cumulative-`le`
#: semantics, ONE fixed 66-slot array per histogram regardless of observation
#: count. Quantile error is bounded by the bucket width (≤ 10^0.25 ≈ 1.78×
#: relative), which is what a latency p99 needs — the exact extremes still
#: ride `min`/`max`.
BUCKET_BOUNDS: Tuple[float, ...] = tuple(10.0 ** (k / 4.0) for k in range(-24, 41))
_N_BUCKETS = len(BUCKET_BOUNDS) + 1  # + overflow (+Inf)


class Histogram:
    """Quantile histogram: count / total / min / max PLUS bounded log-spaced
    buckets (`BUCKET_BOUNDS`), so `summary()` carries p50/p90/p99. The four
    summary fields keep their exact pre-bucket semantics — every existing
    `bench_detail` consumer reads them unchanged; the quantile keys are
    additive. Fixed memory per metric name, lock-guarded like the counters."""

    __slots__ = ("name", "_lock", "count", "total", "min", "max", "_buckets")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self.count = 0
        self.total = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self._buckets = [0] * _N_BUCKETS

    def observe(self, v) -> None:
        v = float(v)
        # Non-positive observations (0.0 durations exist) land in the first
        # bucket; bisect_left puts an exact boundary value in its own bucket.
        idx = bisect.bisect_left(BUCKET_BOUNDS, v) if v > 0.0 else 0
        with self._lock:
            self.count += 1
            self.total += v
            self.min = v if self.min is None or v < self.min else self.min
            self.max = v if self.max is None or v > self.max else self.max
            self._buckets[idx] += 1

    def reset(self) -> None:
        with self._lock:
            self.count = 0
            self.total = 0.0
            self.min = None
            self.max = None
            self._buckets = [0] * _N_BUCKETS

    def _quantile_locked(self, q: float) -> Optional[float]:
        if self.count == 0:
            return None
        rank = max(1, math.ceil(q * self.count))
        cum = 0
        for i, n in enumerate(self._buckets):
            if n == 0:
                continue
            if cum + n >= rank:
                lo = BUCKET_BOUNDS[i - 1] if i > 0 else 0.0
                hi = (
                    BUCKET_BOUNDS[i]
                    if i < len(BUCKET_BOUNDS)
                    else (self.max if self.max is not None else lo)
                )
                est = lo + (hi - lo) * ((rank - cum) / n)
                # Clamp to the observed range: an estimate can never claim a
                # latency outside what was actually seen. min/max can be
                # absent with count>0 after merging a checkpoint that
                # carried buckets but no extrema (forward-compat tolerates
                # that) — clamp only on the bounds we have.
                if self.min is not None:
                    est = max(est, self.min)
                if self.max is not None:
                    est = min(est, self.max)
                return est
            cum += n
        return self.max

    def quantile(self, q: float) -> Optional[float]:
        """Estimated q-quantile (0 < q ≤ 1) from the log buckets, clamped to
        the observed [min, max]. None before any observation."""
        with self._lock:
            return self._quantile_locked(q)

    def bucket_counts(self) -> List[Tuple[float, int]]:
        """Cumulative (upper_bound, count≤bound) pairs for the non-empty
        bucket range plus the +Inf total — the Prometheus text-exposition
        shape (`exporter.prometheus_text`). Empty list before any
        observation (no 66-pair noise for untouched metrics)."""
        return self.export_state()[2]

    def summary(self) -> dict:
        with self._lock:
            out = {
                "count": self.count,
                "total": round(self.total, 6),
                "min": self.min,
                "max": self.max,
            }
            if self.count:
                for key, q in (("p50", 0.5), ("p90", 0.9), ("p99", 0.99)):
                    v = self._quantile_locked(q)
                    out[key] = None if v is None else round(v, 6)
            return out

    def dump_state(self) -> dict:
        """JSON-serializable bucket state (sparse: only non-empty slots) —
        what the workload history store checkpoints into its segments so a
        baseline survives process restart and segment compaction without
        keeping every raw observation."""
        with self._lock:
            out = {"count": self.count, "total": round(self.total, 9)}
            if self.min is not None:
                out["min"] = self.min
                out["max"] = self.max
            buckets = {str(i): n for i, n in enumerate(self._buckets) if n}
            if buckets:
                out["buckets"] = buckets
            return out

    def merge_state(self, state: dict) -> None:
        """Fold a `dump_state` payload INTO this histogram (additive: counts
        and bucket slots sum, min/max extend). Unknown keys are ignored and
        malformed fields are skipped — the forward-compat contract of the
        history segment reader. Everything is PARSED before anything is
        mutated: one corrupt checkpoint record must neither raise nor leave
        a half-merged histogram (count without bucket mass)."""
        if not isinstance(state, dict):
            return
        try:
            count = int(state.get("count", 0))
            total = float(state.get("total", 0.0))
        except (TypeError, ValueError):
            return
        def _num(v):
            return float(v) if isinstance(v, (int, float)) and not isinstance(v, bool) else None
        mn, mx = _num(state.get("min")), _num(state.get("max"))
        buckets = []
        raw = state.get("buckets")
        if isinstance(raw, dict):
            for key, n in raw.items():
                try:
                    i, cnt = int(key), int(n)
                except (TypeError, ValueError):
                    continue
                if 0 <= i < _N_BUCKETS:
                    buckets.append((i, cnt))
        with self._lock:
            self.count += count
            self.total += total
            if mn is not None:
                self.min = mn if self.min is None else min(self.min, mn)
            if mx is not None:
                self.max = mx if self.max is None else max(self.max, mx)
            for i, cnt in buckets:
                self._buckets[i] += cnt

    def export_state(self) -> Tuple[int, float, List[Tuple[float, int]]]:
        """(count, total, cumulative buckets) read under ONE lock hold — the
        Prometheus exposition needs `_count` to equal the +Inf bucket, which
        separate `summary()`/`bucket_counts()` reads cannot guarantee under
        concurrent observes."""
        with self._lock:
            out: List[Tuple[float, int]] = []
            if self.count:
                cum = 0
                for i, n in enumerate(self._buckets):
                    cum += n
                    if n and i < len(BUCKET_BOUNDS):
                        out.append((BUCKET_BOUNDS[i], cum))
                out.append((math.inf, self.count))
            return self.count, self.total, out


class MetricsRegistry:
    """Name → metric map. Creation is get-or-create under one registry lock;
    reads/writes of individual metrics take only that metric's lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        with self._lock:
            m = self._counters.get(name)
            if m is None:
                m = self._counters[name] = Counter(name)
            return m

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            m = self._gauges.get(name)
            if m is None:
                m = self._gauges[name] = Gauge(name)
            return m

    def histogram(self, name: str) -> Histogram:
        with self._lock:
            m = self._histograms.get(name)
            if m is None:
                m = self._histograms[name] = Histogram(name)
            return m

    def snapshot(self) -> dict:
        """Point-in-time copy of every metric, JSON-serializable. Derived
        `rates` pair up `<base>.hits` / `<base>.misses` counters."""
        with self._lock:
            counters = {n: c.value for n, c in self._counters.items()}
            gauges = {n: g.value for n, g in self._gauges.items()}
            hists = {n: h.summary() for n, h in self._histograms.items()}
        rates = {}
        for name, hits in counters.items():
            # Exact last-segment match: "memo.pairs.peek_hits" must not pair
            # (it has no miss twin — a bogus 1.0 rate would ride the bench).
            base, _, leaf = name.rpartition(".")
            if leaf != "hits" or not base:
                continue
            total = hits + counters.get(base + ".misses", 0)
            if total:
                rates[base] = round(hits / total, 4)
        out = {"counters": counters}
        if gauges:
            out["gauges"] = gauges
        if hists:
            out["histograms"] = hists
        if rates:
            out["rates"] = rates
        return out

    def reset(self) -> None:
        """Zero every metric IN PLACE (tests; the bench never resets —
        lifetime accounting stays monotonic like the cache stats). Metric
        objects stay registered: hot paths bind them once at import
        (`device_cache._HITS`, `physical._MEMO_*`, …), so clearing the maps
        would silently orphan them — their increments would never reach
        `snapshot()` again."""
        with self._lock:
            metrics = (
                list(self._counters.values())
                + list(self._gauges.values())
                + list(self._histograms.values())
            )
        for m in metrics:
            m.reset()


_REGISTRY = MetricsRegistry()


def global_registry() -> MetricsRegistry:
    return _REGISTRY


def counter(name: str) -> Counter:
    return _REGISTRY.counter(name)


def gauge(name: str) -> Gauge:
    return _REGISTRY.gauge(name)


def histogram(name: str) -> Histogram:
    return _REGISTRY.histogram(name)


def snapshot() -> dict:
    return _REGISTRY.snapshot()


def counters_delta(before: dict, after: dict) -> dict:
    """Counter names whose value changed between two `snapshot()`s — the
    per-query attribution `explain(analyze=True)` prints."""
    b = before.get("counters", {})
    out = {}
    for name, v in after.get("counters", {}).items():
        d = v - b.get(name, 0)
        if d:
            out[name] = d
    return out
