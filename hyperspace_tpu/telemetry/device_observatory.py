"""Device cost observatory: where device time, transfer bytes, and padding go.

The host side of the engine is measured exhaustively (per-query ledgers,
compile observatory, workload history) but the device side was nearly blind:
``xla_compiles`` and ``device_upload_bytes`` existed, yet nothing said where
device *time* goes per program, what the pow2 padding tax costs outside the
mesh exchange, or how close any path runs to memory-bandwidth peak. ROADMAP
items 4 (measured cost model) and 5 (device-resident encoded execution) are
gated on exactly these numbers. This module is the measurement substrate:

- **Per-program device time** — `probe_start`/`probe_finish` wrap each
  `observed_jit` dispatch: under ``HYPERSPACE_DEVICE_TIMING`` a sampled (or,
  with ``=all``, every) call is followed by ``jax.block_until_ready``, and
  the dispatch→ready wall feeds ``latency.device.<label>`` histograms, a
  per-label `device_summary`, and the ambient ledger's ``device_time_s``.
  Calls that TRACED are skipped — compile time is billed separately by the
  compile observatory, and folding it in here would poison the steady-state
  execute distribution. Off (the default), the probe is one env read per
  jit call (the standing one-env-check contract); the sampled mode bounds
  the synchronization tax to one forced sync per label per interval.
- **Transfer ledgers** — `record_h2d`/`record_d2h` count bytes and events at
  the device-cache upload and host-materialization boundaries
  (``transfer.h2d.*`` / ``transfer.d2h.*``); transfer *seconds* are only
  timed under ``HYPERSPACE_DEVICE_TIMING`` (timing a transfer forces a
  sync). `to_host` is the D2H chokepoint: every deliberate device→host
  materialization funnels through it.
- **Padding ledgers** — `record_pad(site, payload, padded)` generalizes the
  mesh-only ``bytes_payload`` vs ``bytes_moved`` honesty split to EVERY pow2
  staging site (hash quantize, classed join matrices, streaming partials,
  eager masks): ``pad.bytes_payload|bytes_padded`` globally and per site,
  mirrored onto the ledger so each query carries its own ``pad_ratio``.
  These are unconditional integer adds, same always-on philosophy as the
  registry counters they feed.
- **Profile capture** — `maybe_capture(reason)` writes ONE bounded profile
  window into ``HYPERSPACE_PROFILE_DIR`` when an Nσ anomaly or SLO
  fast-burn fires: a synchronously-written, always-parseable
  ``capture.json`` manifest (reason, program/device/pad summaries, recent
  ledgers) plus a ``jax.profiler`` trace collected on a daemon thread for
  ``HYPERSPACE_PROFILE_WINDOW_S`` seconds where the profiler is available.
  Rate-limited (``HYPERSPACE_PROFILE_MIN_INTERVAL_S``) and keep-N rotated
  (``HYPERSPACE_PROFILE_KEEP`` capture directories) so a flapping alert can
  never fill a disk.

Everything here is import-light: jax is only touched from call sites that
have it imported by definition (`observed_jit` probes) or inside the
capture thread.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Optional

from . import metrics as _metrics

ENV_DEVICE_TIMING = "HYPERSPACE_DEVICE_TIMING"
#: Per-label probe interval in sampled mode ("1"); "all" probes every call.
ENV_TIMING_INTERVAL_S = "HYPERSPACE_DEVICE_TIMING_INTERVAL_S"
_DEFAULT_TIMING_INTERVAL_S = 0.25

ENV_PROFILE_DIR = "HYPERSPACE_PROFILE_DIR"
ENV_PROFILE_KEEP = "HYPERSPACE_PROFILE_KEEP"
ENV_PROFILE_WINDOW_S = "HYPERSPACE_PROFILE_WINDOW_S"
ENV_PROFILE_MIN_INTERVAL_S = "HYPERSPACE_PROFILE_MIN_INTERVAL_S"
_DEFAULT_PROFILE_KEEP = 3
_DEFAULT_PROFILE_WINDOW_S = 2.0
_DEFAULT_PROFILE_MIN_INTERVAL_S = 60.0

# Bound once: these ride warm paths (every upload miss / pad staging).
_H2D_BYTES = _metrics.counter("transfer.h2d.bytes")
_H2D_COUNT = _metrics.counter("transfer.h2d.count")
_D2H_BYTES = _metrics.counter("transfer.d2h.bytes")
_D2H_COUNT = _metrics.counter("transfer.d2h.count")
_PAD_PAYLOAD = _metrics.counter("pad.bytes_payload")
_PAD_PADDED = _metrics.counter("pad.bytes_padded")
# Encoded device staging (engine/encoded_device.py): bytes the flat path
# would have staged vs the narrow code bytes actually staged.
_ENC_FLAT = _metrics.counter("device.encoded.bytes_flat")
_ENC_STAGED = _metrics.counter("device.encoded.bytes_staged")
# Bit-packed tier (engine/packed_codes.py): of the staged bytes, how many
# crossed as packed sub-byte words — the below-int8 slice of the split.
_ENC_PACKED = _metrics.counter("device.encoded.bytes_packed")
_CAPTURES = _metrics.counter("profiler.captures")
_CAPTURES_SUPPRESSED = _metrics.counter("profiler.captures_suppressed")

_lock = threading.Lock()
#: label -> last probe monotonic ts (sampled mode rate limit).
_last_probe: Dict[str, float] = {}
#: label -> {"calls": probed calls, "device_s": summed dispatch→ready wall}.
_device_programs: Dict[str, dict] = {}
#: site -> [payload_bytes, padded_bytes] (mirrors the per-site counters).
_pad_sites: Dict[str, list] = {}
#: site -> [flat_bytes, staged_bytes, count] — encoded-vs-flat staging split.
_encoded_sites: Dict[str, list] = {}
#: direction -> [bytes, count, seconds] (seconds only when timing is on).
_transfers: Dict[str, list] = {"h2d": [0, 0, 0.0], "d2h": [0, 0, 0.0]}
#: [last capture monotonic ts] — profile-capture rate limit.
_last_capture: list = [-1e18]
_capture_seq = 0
#: Only one jax.profiler window may ever be in flight: overlapping
#: start_trace calls crash some builds outright (observed segfault on the
#: XLA-CPU profiler), so a second capture inside a live window writes its
#: manifest but skips the trace.
_trace_in_flight = threading.Event()
#: The live trace thread, drained (bounded join) at interpreter exit: the
#: runtime tears the profiler down underneath a still-running daemon thread
#: and segfaults if we just let the process die mid-window.
_trace_thread: list = [None]


def _drain_trace_thread() -> None:
    t = _trace_thread[0]
    if t is not None and t.is_alive():
        t.join(timeout=_profile_window_s() + 15.0)


def timing_mode() -> str:
    """'' = off (the default), '1' = sampled probes, 'all' = every call.
    ONE env read — this is the whole hot-path cost when off."""
    return os.environ.get(ENV_DEVICE_TIMING, "") or ""


def _timing_interval_s() -> float:
    try:
        return max(
            0.0,
            float(
                os.environ.get(ENV_TIMING_INTERVAL_S, "")
                or _DEFAULT_TIMING_INTERVAL_S
            ),
        )
    except ValueError:
        return _DEFAULT_TIMING_INTERVAL_S


def probe_start(label: str) -> Optional[float]:
    """Decide BEFORE dispatch whether this `observed_jit` call gets a device
    probe; returns the probe's t0 or None. Off = one env read. Sampled mode
    admits one probe per label per interval, so the forced sync a probe
    implies stays bounded regardless of call rate."""
    mode = timing_mode()
    if not mode:
        return None
    now = time.monotonic()
    if mode != "all":
        interval = _timing_interval_s()
        with _lock:
            if now - _last_probe.get(label, -1e18) < interval:
                return None
            _last_probe[label] = now
    return now


def probe_finish(label: str, t0: float, out, traced: bool) -> None:
    """Block until `out` is device-ready and bill dispatch→ready wall to
    `label` — unless the call traced (its wall is compile, already billed by
    the compile observatory; recording it here would poison the execute
    distribution)."""
    import jax

    try:
        jax.block_until_ready(out)
    except Exception:
        return
    if traced:
        return
    dt = time.monotonic() - t0
    _metrics.histogram(f"latency.device.{label}").observe(dt)
    with _lock:
        p = _device_programs.get(label)
        if p is None:
            p = _device_programs[label] = {"calls": 0, "device_s": 0.0}
        p["calls"] += 1
        p["device_s"] += dt
    from . import accounting as _accounting

    _accounting.add("device_time_s", dt)


def device_summary() -> dict:
    """Per-program probed device time: {label: {calls, device_s}}, labels
    sorted — the device twin of `compile_log.program_summary` (exporter
    frames, ``bench_detail.device_observatory``). Empty when timing never
    ran."""
    with _lock:
        return {
            lbl: {"calls": p["calls"], "device_s": round(p["device_s"], 6)}
            for lbl, p in sorted(_device_programs.items())
        }


def record_h2d(nbytes: int, seconds: Optional[float] = None) -> None:
    """One host→device transfer of `nbytes` (device-cache upload miss,
    explicit `device_put` staging). Seconds only arrive when the caller
    timed the transfer under ``HYPERSPACE_DEVICE_TIMING``."""
    _H2D_BYTES.inc(int(nbytes))
    _H2D_COUNT.inc()
    with _lock:
        t = _transfers["h2d"]
        t[0] += int(nbytes)
        t[1] += 1
        if seconds is not None:
            t[2] += seconds
    if seconds is not None:
        _metrics.histogram("transfer.h2d.seconds").observe(seconds)


def record_d2h(nbytes: int, seconds: Optional[float] = None) -> None:
    """One device→host materialization of `nbytes` (see `to_host`)."""
    _D2H_BYTES.inc(int(nbytes))
    _D2H_COUNT.inc()
    from . import accounting as _accounting

    _accounting.add("d2h_bytes", int(nbytes))
    with _lock:
        t = _transfers["d2h"]
        t[0] += int(nbytes)
        t[1] += 1
        if seconds is not None:
            t[2] += seconds
    if seconds is not None:
        _metrics.histogram("transfer.d2h.seconds").observe(seconds)


def to_host(arr):
    """THE device→host chokepoint: materialize a device array to numpy,
    recording bytes+count always and seconds under the timing flag. Host
    numpy passes through untouched (zero cost beyond the isinstance)."""
    import numpy as np

    if isinstance(arr, np.ndarray):
        return arr
    nbytes = int(getattr(arr, "nbytes", 0) or 0)
    if timing_mode():
        t0 = time.monotonic()
        host = np.asarray(arr)
        record_d2h(nbytes, time.monotonic() - t0)
    else:
        host = np.asarray(arr)
        record_d2h(nbytes)
    return host


def record_pad(site: str, payload_bytes: int, padded_bytes: int) -> None:
    """One pow2 staging event at `site`: `payload_bytes` of real data were
    staged inside `payload+padded` bytes of device buffer. The mesh
    exchange's payload-vs-moved honesty split, generalized: every site that
    pads to a shape class reports its tax here. Unconditional integer adds
    (the always-on registry philosophy); the ambient ledger — when one is
    open — carries the per-query split and derives ``pad_ratio`` at close."""
    payload_bytes = int(payload_bytes)
    padded_bytes = int(padded_bytes)
    if padded_bytes < 0:
        padded_bytes = 0
    _PAD_PAYLOAD.inc(payload_bytes)
    _PAD_PADDED.inc(padded_bytes)
    _metrics.counter(f"pad.{site}.bytes_payload").inc(payload_bytes)
    _metrics.counter(f"pad.{site}.bytes_padded").inc(padded_bytes)
    with _lock:
        s = _pad_sites.get(site)
        if s is None:
            s = _pad_sites[site] = [0, 0]
        s[0] += payload_bytes
        s[1] += padded_bytes
    from . import accounting as _accounting

    _accounting.add("pad_bytes_payload", payload_bytes)
    _accounting.add("pad_bytes_padded", padded_bytes)


def record_encoded_stage(
    site: str, flat_bytes: int, staged_bytes: int, packed_bytes=None
) -> None:
    """One encoded (code-space) device staging event at `site`: the flat path
    would have moved `flat_bytes` across the boundary; the narrow code lane
    actually moved `staged_bytes`. The gap is the decoded-bytes tax the
    device half no longer pays — the encoded-vs-flat split `tools/hsreport.py`
    reports next to the pad tax. `packed_bytes` marks the slice of the staged
    bytes that crossed as BIT-PACKED sub-byte words
    (`engine/packed_codes.py`) — the below-int8 tier of the split."""
    flat_bytes = int(flat_bytes)
    staged_bytes = int(staged_bytes)
    _ENC_FLAT.inc(flat_bytes)
    _ENC_STAGED.inc(staged_bytes)
    _metrics.counter(f"device.encoded.{site}.bytes_flat").inc(flat_bytes)
    _metrics.counter(f"device.encoded.{site}.bytes_staged").inc(staged_bytes)
    if packed_bytes is not None:
        packed_bytes = int(packed_bytes)
        _ENC_PACKED.inc(packed_bytes)
        _metrics.counter(f"device.encoded.{site}.bytes_packed").inc(packed_bytes)
    with _lock:
        s = _encoded_sites.get(site)
        if s is None:
            s = _encoded_sites[site] = [0, 0, 0, 0]
        s[0] += flat_bytes
        s[1] += staged_bytes
        s[2] += 1
        if packed_bytes is not None:
            s[3] += packed_bytes
    from . import accounting as _accounting

    _accounting.add("device_code_bytes_flat", flat_bytes)
    _accounting.add("device_code_bytes_staged", staged_bytes)
    if packed_bytes is not None:
        _accounting.add("device_code_bytes_packed", packed_bytes)


def encoded_stage_summary() -> dict:
    """Per-site encoded-vs-flat staging split: {site: {bytes_flat,
    bytes_staged, count, saved_ratio[, bytes_packed]}} — saved_ratio is the
    fraction of the flat bytes that never crossed the boundary (0.0 = no
    saving); bytes_packed appears when any of the staged bytes crossed as
    bit-packed sub-byte words."""
    with _lock:
        out = {}
        for site, (flat, staged, count, packed) in sorted(_encoded_sites.items()):
            e = {
                "bytes_flat": flat,
                "bytes_staged": staged,
                "count": count,
                "saved_ratio": round((flat - staged) / flat, 4) if flat else 0.0,
            }
            if packed:
                e["bytes_packed"] = packed
            out[site] = e
        return out


def pad_summary() -> dict:
    """Per-site padding tax: {site: {bytes_payload, bytes_padded,
    pad_ratio}} — pad_ratio is the fraction of staged bytes that is padding
    (0.0 = every staged byte was real data)."""
    with _lock:
        out = {}
        for site, (payload, padded) in sorted(_pad_sites.items()):
            total = payload + padded
            out[site] = {
                "bytes_payload": payload,
                "bytes_padded": padded,
                "pad_ratio": round(padded / total, 4) if total else 0.0,
            }
        return out


def transfer_summary() -> dict:
    """H2D/D2H rollup: {direction: {bytes, count[, seconds, gb_per_s]}} —
    seconds (and the derived effective GB/s) only appear once something was
    timed under ``HYPERSPACE_DEVICE_TIMING``."""
    with _lock:
        out = {}
        for d, (nbytes, count, seconds) in sorted(_transfers.items()):
            e = {"bytes": nbytes, "count": count}
            if seconds > 0:
                e["seconds"] = round(seconds, 6)
                e["gb_per_s"] = round(nbytes / seconds / 1e9, 3)
            out[d] = e
        return out


def reset() -> None:
    """Zero the module-local summaries (tests/bench; the registry counters
    reset separately via `metrics.reset`). Probe rate-limit state clears too
    so a fresh bench section probes immediately."""
    with _lock:
        _device_programs.clear()
        _pad_sites.clear()
        _encoded_sites.clear()
        _last_probe.clear()
        for t in _transfers.values():
            t[0] = t[1] = 0
            t[2] = 0.0
        _last_capture[0] = -1e18


# ---------------------------------------------------------------------------
# Anomaly-triggered profile capture
# ---------------------------------------------------------------------------


def profile_keep() -> int:
    try:
        return max(
            1, int(os.environ.get(ENV_PROFILE_KEEP, "") or _DEFAULT_PROFILE_KEEP)
        )
    except ValueError:
        return _DEFAULT_PROFILE_KEEP


def _profile_window_s() -> float:
    try:
        return max(
            0.05,
            float(
                os.environ.get(ENV_PROFILE_WINDOW_S, "")
                or _DEFAULT_PROFILE_WINDOW_S
            ),
        )
    except ValueError:
        return _DEFAULT_PROFILE_WINDOW_S


def _profile_min_interval_s() -> float:
    try:
        return max(
            0.0,
            float(
                os.environ.get(ENV_PROFILE_MIN_INTERVAL_S, "")
                or _DEFAULT_PROFILE_MIN_INTERVAL_S
            ),
        )
    except ValueError:
        return _DEFAULT_PROFILE_MIN_INTERVAL_S


def maybe_capture(reason: str, detail: Optional[dict] = None) -> Optional[str]:
    """Capture one bounded profile window into ``HYPERSPACE_PROFILE_DIR``
    (returns the capture directory, or None when disabled/suppressed).

    Called from the anomaly (history Nσ) and SLO fast-burn paths, both of
    which can flap — so captures are rate-limited to one per
    ``HYPERSPACE_PROFILE_MIN_INTERVAL_S`` and the directory is keep-N
    rotated (``capture/`` → ``capture.1/`` → …, `profile_keep` generations).
    The manifest (``capture.json``) writes SYNCHRONOUSLY so the capture is
    parseable the moment this returns; the ``jax.profiler`` trace — where
    the profiler works at all — collects on a daemon thread for the bounded
    window and marks completion in ``trace.json``. Never raises: a broken
    profiler must not take the query path down with it."""
    base_dir = os.environ.get(ENV_PROFILE_DIR)
    if not base_dir:
        return None
    global _capture_seq
    now = time.monotonic()
    with _lock:
        if now - _last_capture[0] < _profile_min_interval_s():
            _CAPTURES_SUPPRESSED.inc()
            return None
        _last_capture[0] = now
        _capture_seq += 1
        seq = _capture_seq
    try:
        from . import compile_log as _compile_log
        from . import rotation as _rotation

        cap_dir = os.path.join(base_dir, "capture")
        os.makedirs(base_dir, exist_ok=True)
        _rotation.rotate_dir(cap_dir, keep=profile_keep())
        os.makedirs(cap_dir, exist_ok=True)
        window_s = _profile_window_s()
        from . import accounting as _accounting

        manifest = {
            "schema_version": 1,
            "reason": reason,
            "seq": seq,
            "ts": time.time(),
            "window_s": window_s,
            "detail": detail or {},
            "programs": _compile_log.program_summary(),
            "device": device_summary(),
            "pads": pad_summary(),
            "transfers": transfer_summary(),
            "recent_ledgers": [
                led.to_dict() for led in _accounting.recent_ledgers()[-8:]
            ],
        }
        with open(os.path.join(cap_dir, "capture.json"), "w") as f:
            json.dump(manifest, f, indent=1, sort_keys=True)
        _CAPTURES.inc()
        with _lock:
            start_trace = not _trace_in_flight.is_set()
            if start_trace:
                _trace_in_flight.set()
        if start_trace:
            import atexit

            t = threading.Thread(
                target=_trace_window,
                args=(cap_dir, window_s),
                name="hyperspace-profile-capture",
                daemon=True,
            )
            if _trace_thread[0] is None:
                atexit.register(_drain_trace_thread)
            _trace_thread[0] = t
            t.start()
        else:
            # A previous window is still collecting; overlapping profiler
            # sessions are unsafe, so this capture is manifest-only.
            with open(os.path.join(cap_dir, "trace.json"), "w") as f:
                json.dump(
                    {"window_s": window_s, "trace": False,
                     "error": "skipped: trace already in flight"},
                    f,
                )
        return cap_dir
    except Exception:
        return None


def _trace_window(cap_dir: str, window_s: float) -> None:
    """Bounded jax.profiler trace into `cap_dir` (daemon thread). Status —
    including 'unavailable' on builds/backends without a working profiler —
    lands in ``trace.json`` so the capture is self-describing either way."""
    status = {"window_s": window_s, "trace": False}
    import sys as _sys

    jax = _sys.modules.get("jax")
    started = False
    try:
        if jax is not None:
            jax.profiler.start_trace(cap_dir)
            started = True
        time.sleep(window_s)
    except Exception as e:
        status["error"] = f"{type(e).__name__}: {e}"
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
                status["trace"] = True
            except Exception as e:
                status["error"] = f"{type(e).__name__}: {e}"
        if jax is None:
            status["error"] = "jax not imported"
        _trace_in_flight.clear()
        try:
            with open(os.path.join(cap_dir, "trace.json"), "w") as f:
                json.dump(status, f)
        except OSError:
            pass
