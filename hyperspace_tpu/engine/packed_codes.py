"""Bit-packed sub-byte code lanes: the width layer BELOW `encoded_device.py`.

PR 15 narrowed dictionary codes to {int8, int16, int32} and stalled at one
byte per code. This module extends the width policy downward with bit-packed
classes — 1/2/4-bit lanes packed into uint32 words — so a boolean-like or
low-cardinality string key crosses the host→device boundary (and the mesh
exchange — `parallel/distributed.py`) at its true information width.

Layout contract (the compute-on-packed soundness lemma):

- Lanes are stored BIASED: lane value = code + 1, so the null code -1 folds
  into the code space as the RESERVED lane value 0 — no separate mask lane
  rides the wire. A dictionary of `card` entries therefore needs lane values
  [0, card], i.e. `card + 1 <= 2**bits` (`bits_for_cardinality`).
- Lanes pack BIG-ENDIAN within each uint32 word: lane j of a word occupies
  bits [32 - bits*(j+1), 32 - bits*j). Consequence: comparing two packed
  words as UNSIGNED integers compares their lane tuples lexicographically —
  which is what lets the probe/sort kernels (`ops/pallas_probe.py`,
  `ops/pallas_sort.py`) compare packed words directly and unpack only
  survivors. `tests/test_packed_codes.py` pins both bijectivity and this
  order lemma property-style.
- The probe/sort compute path additionally reserves the TOP lane value
  `2**bits - 1` as the pad slot (pads must sort LAST), so it requires
  `card + 2 <= 2**bits` (`probe_bits_for_cardinality`).

Compile-class boundedness (the PR 15 trick, continued): `bits` comes from the
BOUNDED class set {1, 2, 4} (plus the 16-bit wire class the mesh exchange
uses for row ids). The H2D buffer itself is word-granular EXACT (the wire
moves only real bits — like the narrow path's exact-byte uploads); the word
array zero-pads to pow2 on the device side before the jitted unpack runs, so
the programs compile once per (bits, pow2-size) class — never per
cardinality. Asserted in tests via the compile observatory.

Gate: `HYPERSPACE_PACKED_CODES` — unset = auto (rides
`HYPERSPACE_ENCODED_DEVICE`: packing is a refinement of encoded staging),
`1` = force, `0` = byte-identical narrow/flat fallback in the standing
oracle style (index files and query results sha256-identical across flag
states — pinned by tests/test_packed_codes.py).
"""

from __future__ import annotations

import os
import weakref

import numpy as np

ENV_PACKED_CODES = "HYPERSPACE_PACKED_CODES"

_WORD_BITS = 32
#: The bounded sub-byte width-class set. 8/16-bit lanes already travel at
#: their true width through the PR 15 narrow layer; 16 additionally serves as
#: a WIRE class for mesh-exchange row ids (`parallel/table_ops.py`).
PACKED_BITS = (1, 2, 4)


def packed_codes_mode() -> str:
    """"off" | "force" | "auto" (the unset default)."""
    raw = os.environ.get(ENV_PACKED_CODES)
    if raw is None or raw == "":
        return "auto"
    if raw == "0":
        return "off"
    return "force"


def packed_codes_enabled() -> bool:
    """Is the bit-packed lane layer on? Auto rides the encoded-device switch:
    packing refines narrow staging, so it inherits that path's gate."""
    mode = packed_codes_mode()
    if mode == "off":
        return False
    if mode == "force":
        return True
    from ..plananalysis.planner import decided_value

    decided = decided_value("packed_codes")
    if decided is not None:
        return bool(decided)
    from .encoded_device import encoded_device_enabled

    return encoded_device_enabled()


def lanes_per_word(bits: int) -> int:
    return _WORD_BITS // bits


def bits_for_cardinality(card: int):
    """Smallest packed width whose lane space holds biased codes [0, card]
    (code + 1; the reserved 0 is the folded null). None = sub-byte packing
    does not apply — the dictionary rides the narrow {int8,int16} classes."""
    for bits in PACKED_BITS:
        if card + 1 <= (1 << bits):
            return bits
    return None


def probe_bits_for_cardinality(card: int):
    """Packed width for the COMPUTE path (probe/sort on packed words): the
    top lane value `2**bits - 1` is additionally reserved as the pad slot
    (pads must sort last), so the class bound tightens by one."""
    for bits in PACKED_BITS:
        if card + 2 <= (1 << bits):
            return bits
    return None


#: Mesh WIRE classes: the sub-byte set plus 8/16 — an int32 row-id lane packs
#: at 16 bits whenever the padded global row count fits, which is where the
#: exchange's bytes_moved win actually lives (row ids dominate the coded wire).
WIRE_BITS = (1, 2, 4, 8, 16)


def wire_bits_for_range(n_values: int):
    """Smallest mesh-wire class holding unsigned field values [0, n_values);
    None when even 16 bits is too narrow (the lane ships unpacked)."""
    for bits in WIRE_BITS:
        if n_values <= (1 << bits):
            return bits
    return None


def _pow2(n: int) -> int:
    return 1 << max(int(n) - 1, 0).bit_length() if n > 1 else 1


def packed_lane_count(n: int, bits: int) -> int:
    """EXACT word-granular lane count a length-`n` code array packs to: the
    H2D upload moves only real bits (at most one word of tail padding — the
    narrow int8 path uploads exact bytes too, so the packed-vs-narrow byte
    ratio stays the intrinsic `8/bits`). Pow2 quantization happens on the
    DEVICE side (`unpack_codes_device` zero-pads the word array before the
    jitted unpack), so the compile grid stays bounded without taxing the
    wire."""
    lpw = lanes_per_word(bits)
    return -(-max(int(n), 1) // lpw) * lpw


def packed_word_count(n: int, bits: int) -> int:
    return packed_lane_count(n, bits) // lanes_per_word(bits)


def pack_codes_host(codes: np.ndarray, bits: int) -> np.ndarray:
    """Pack a host code array (values >= -1) into big-endian biased uint32
    words. Lanes past `len(codes)` (the sub-word tail) hold the reserved 0 —
    they unpack to the null code -1 and are sliced off by the consumer."""
    n = int(len(codes))
    lpw = lanes_per_word(bits)
    n_lanes = packed_lane_count(n, bits)
    biased = np.zeros(n_lanes, np.uint32)
    biased[:n] = (codes.astype(np.int64) + 1).astype(np.uint32)
    lanes = biased.reshape(-1, lpw)
    shifts = (_WORD_BITS - bits * (np.arange(lpw) + 1)).astype(np.uint32)
    return (lanes << shifts[None, :]).sum(axis=1, dtype=np.uint32)


def unpack_codes_host(words: np.ndarray, n: int, bits: int) -> np.ndarray:
    """Host inverse of `pack_codes_host`: the first `n` lanes, un-biased back
    to codes (reserved 0 -> the null code -1). The round trip is bijective
    for every dictionary within the class bound (pinned property-style)."""
    lpw = lanes_per_word(bits)
    mask = np.uint32((1 << bits) - 1)
    shifts = (_WORD_BITS - bits * (np.arange(lpw) + 1)).astype(np.uint32)
    lanes = (words[:, None] >> shifts[None, :]) & mask
    return lanes.reshape(-1)[:n].astype(np.int64).astype(np.int32) - 1


# --- traced row-matrix pack/unpack: the shared word-layout primitives the
# mesh exchange (`parallel/distributed.py`) and the compute-on-packed kernels
# (`ops/pallas_probe.py`, `ops/pallas_sort.py`, `ops/bucket_join.py`) build
# on. Operands are 2-D [rows, lanes] matrices of NON-NEGATIVE (already
# biased) field values; the lane axis must divide into whole words. ----------


def pack_rows_traced(mat, bits: int):
    """[R, C] non-negative field values -> [R, C/lanes_per_word] uint32 words,
    big-endian lane layout. Traced (jit-safe); fields are disjoint, so the
    lane-axis sum IS the bitwise-or."""
    import jax.numpy as jnp

    lpw = lanes_per_word(bits)
    lanes = mat.astype(jnp.uint32).reshape(mat.shape[0], -1, lpw)
    shifts = (
        _WORD_BITS - bits * (jnp.arange(lpw, dtype=jnp.uint32) + 1)
    ).astype(jnp.uint32)
    return (lanes << shifts[None, None, :]).sum(axis=2, dtype=jnp.uint32)


def unpack_rows_traced(words, bits: int):
    """Traced inverse of `pack_rows_traced`: [R, W] uint32 -> [R, W*lpw]
    int32 field values."""
    import jax.numpy as jnp

    lpw = lanes_per_word(bits)
    shifts = (
        _WORD_BITS - bits * (jnp.arange(lpw, dtype=jnp.uint32) + 1)
    ).astype(jnp.uint32)
    mask = jnp.uint32((1 << bits) - 1)
    lanes = (words[:, :, None] >> shifts[None, None, :]) & mask
    return lanes.reshape(words.shape[0], -1).astype(jnp.int32)


# --- device unpack: shift/mask gather, one compiled program per bounded
# (bits, pow2-lane-count) class ------------------------------------------------


def _unpack_program(bits: int, n_lanes: int):
    import jax.numpy as jnp

    from ..telemetry.compile_log import observed_jit as _observed_jit

    lpw = lanes_per_word(bits)
    mask = np.uint32((1 << bits) - 1)
    shifts = (_WORD_BITS - bits * (np.arange(lpw) + 1)).astype(np.uint32)

    @_observed_jit(label="packed.unpack")
    def unpack(words):
        lanes = (words[:, None] >> jnp.asarray(shifts)[None, :]) & jnp.uint32(mask)
        # Biased lanes -> codes: int8 keeps the device working set (and every
        # downstream compile class) IDENTICAL to the PR 15 narrow path.
        return (lanes.reshape(-1).astype(jnp.int32) - 1).astype(jnp.int8)

    return unpack


_unpack_programs: dict = {}


def unpack_codes_device(words_dev, bits: int):
    """Jitted shift/mask unpack of a device word array -> the pow2 lane array
    as int8 codes (biased 0 back to -1). The exact-size upload is zero-padded
    to the pow2 word count ON DEVICE first (zero words are all-reserved-null
    lanes — the same eager pad-to-pow2 the hash layer applies to its narrow
    lanes), so the program cache stays keyed by the bounded (bits, pow2)
    class while the H2D transfer moved only real words."""
    import jax.numpy as jnp

    n_words = int(words_dev.shape[0])
    n_words_pow2 = _pow2(n_words)
    if n_words_pow2 != n_words:
        words_dev = jnp.pad(words_dev, (0, n_words_pow2 - n_words))
    n_lanes = n_words_pow2 * lanes_per_word(bits)
    key = (bits, n_lanes)
    fn = _unpack_programs.get(key)
    if fn is None:
        fn = _unpack_programs[key] = _unpack_program(bits, n_lanes)
    return fn(words_dev)


# --- column staging: the packed tier of `encoded_device.stage_codes` ---------

#: id(packed host words) -> (weakref, unpacked int8 device lane). The eager
#: slice to the column's true length runs ONCE per column here; steady-state
#: queries reuse the sliced device lane with zero dispatches.
_unpacked_memo: dict = {}


def packable_bits(col):
    """Packed width for a column's code lane, or None when the packed layer
    is off / the column doesn't qualify for encoded staging / the dictionary
    exceeds every sub-byte class."""
    if not packed_codes_enabled():
        return None
    from .encoded_device import column_qualifies

    if not column_qualifies(col):
        return None
    if col.data.dtype != np.int32:
        return None
    return bits_for_cardinality(len(col.dictionary))


def packed_host_codes(col, bits: int) -> np.ndarray:
    """Packed uint32 words of a column's code lane, memoized on the Column so
    the identity-keyed upload cache keeps hitting across queries."""
    cached = getattr(col, "_packed_codes", None)
    if cached is not None and cached[0] == bits and cached[1] == len(col.data):
        return cached[2]
    words = pack_codes_host(col.data, bits)
    try:
        col._packed_codes = (bits, len(col.data), words)
    except Exception:
        pass  # slotted/frozen column subclass: lose the memo, not the packing
    return words


def _charged_packed_bytes(col, words: np.ndarray) -> int:
    """TRUE packed footprint: packed words + dictionary + validity (the same
    accounting `encoded_device._charged_bytes` applies to narrow lanes)."""
    total = int(words.nbytes)
    if col.dictionary is not None:
        total += int(col.dictionary.nbytes)
    if col.validity is not None:
        total += int(col.validity.nbytes)
    return total


def stage_packed_codes(col, site: str, bits: int):
    """Device-stage a column's code lane through the PACKED tier: upload the
    uint32 words (H2D moves `bits` bits per code — charged as true packed
    bytes in the `packed` tier of the encoded-staging ledger), then widen on
    device with the jitted shift/mask unpack. The returned lane is int8 with
    the exact values of `encoded_device.narrow_codes` — every consumer
    downstream of the boundary sees the PR 15 narrow path, bit for bit."""
    from .device_cache import device_array

    words = packed_host_codes(col, bits)
    n = len(col.data)
    key = id(words)
    ent = _unpacked_memo.get(key)
    if ent is not None and ent[0]() is words:
        return ent[1]
    dev_words = device_array(
        words,
        site=site,
        flat_bytes=int(col.data.nbytes),
        charged_bytes=_charged_packed_bytes(col, words),
        packed=True,
    )
    lane = unpack_codes_device(dev_words, bits)[:n]
    try:
        ref = weakref.ref(words, lambda _wr, k=key: _unpacked_memo.pop(k, None))
    except TypeError:
        return lane
    _unpacked_memo[key] = (ref, lane)
    return lane


def clear_packed_memos() -> None:
    """Drop the unpack memo (tests/bench cold-path measurements)."""
    _unpacked_memo.clear()
