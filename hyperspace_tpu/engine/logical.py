"""Logical plan IR.

The engine analogue of Catalyst logical plans, with just the node set the reference's
rules pattern-match: relation scans, Filter, Project, and (equi-)Join
(`FilterIndexRule.scala:211-253` matches Project?>Filter>Relation; `JoinIndexRule`
transforms Join nodes whose subplans are linear).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from ..storage.filesystem import FileStatus
from .expr import Expr
from .schema import Field, Schema


@dataclass
class SourceRelation:
    """A file-backed source: root paths + resolved file inventory + schema + format.

    The analogue of `HadoopFsRelation` + `PartitioningAwareFileIndex`: the file list is
    resolved eagerly at read time (like InMemoryFileIndex) and is what signature
    providers fingerprint (`FileBasedSignatureProvider.scala:39-79`)."""

    root_paths: List[str]
    file_format: str
    schema: Schema
    files: List[FileStatus] = field(default_factory=list)
    options: Dict[str, str] = field(default_factory=dict)
    # Set when this relation is an index scan substituted by a rewrite rule:
    bucket_spec: Optional["BucketSpec"] = None
    index_name: Optional[str] = None
    # The substituting index's LOG ENTRY id: advances on every refresh/vacuum/
    # optimize, so engine memos keyed on it (the join pair caches) can never
    # serve results computed against a superseded index generation. Excluded
    # from value equality (serde round-trips don't carry it).
    log_entry_id: Optional[int] = field(default=None, compare=False)
    # Hybrid Scan: source files appended after the index was built, merged in at
    # execution time (shuffle-union into buckets for the join path):
    hybrid_append: Optional["HybridAppend"] = None
    # Data-skipping: names of indexes whose sketches pruned this scan's file list:
    pruned_by: List[str] = field(default_factory=list)
    # Hive-partitioned source: layout of `key=value` path segments whose values
    # materialize as columns at read time (`engine.partitioning`); the partition
    # fields are appended to `schema`.
    partition_spec: Optional[object] = None

    def __repr__(self):
        tag = f" index={self.index_name}" if self.index_name else ""
        if self.hybrid_append is not None:
            tag += f" (+{len(self.hybrid_append.files)} appended)"
        if self.pruned_by:
            tag += f" (files pruned by {','.join(self.pruned_by)})"
        return f"Relation[{self.file_format}]({','.join(self.root_paths)}{tag})"


@dataclass
class HybridAppend:
    """Appended source files + how to read them (their format/schema/partition
    layout are the SOURCE's, not the index's)."""

    files: List[FileStatus]
    file_format: str
    schema: Schema
    root_paths: List[str] = field(default_factory=list)
    partition_spec: Optional[object] = None


@dataclass(frozen=True)
class BucketSpec:
    """Bucketing contract of written data (the analogue of Spark's BucketSpec,
    `DataFrameWriterExtensions.scala:60-64`): hash-partitioned into `num_buckets` by
    `bucket_columns`, sorted within each bucket by `sort_columns`."""

    num_buckets: int
    bucket_columns: tuple
    sort_columns: tuple


class LogicalPlan:
    def children(self) -> Sequence["LogicalPlan"]:
        return ()

    @property
    def output_schema(self) -> Schema:
        raise NotImplementedError

    def transform_up(self, fn) -> "LogicalPlan":
        """Bottom-up plan rewrite (Catalyst `transformUp` analogue)."""
        new_children = [c.transform_up(fn) for c in self.children()]
        node = self.with_children(new_children) if new_children else self
        return fn(node)

    def with_children(self, children: Sequence["LogicalPlan"]) -> "LogicalPlan":
        raise NotImplementedError

    def simple_string(self) -> str:
        return type(self).__name__

    def tree_string(self, indent: int = 0) -> str:
        lines = ["  " * indent + ("+- " if indent else "") + self.simple_string()]
        for c in self.children():
            lines.append(c.tree_string(indent + 1))
        return "\n".join(lines)

    def collect_nodes(self) -> List["LogicalPlan"]:
        out: List[LogicalPlan] = [self]
        for c in self.children():
            out.extend(c.collect_nodes())
        return out

    def is_linear(self) -> bool:
        """True if every node has at most one child (reference `JoinIndexRule.scala:219-220`
        requires both join subplans linear)."""
        kids = self.children()
        if len(kids) > 1:
            return False
        return all(c.is_linear() for c in kids)


def internal_column(name: str) -> bool:
    """The reserved index-internal column names (currently the lineage column
    `_data_file_name`): hidden from logical schemas, physically read only on
    explicit request (the delete-prune filter), stripped once consumed. The
    ONE home of the rule — logical hiding, scan defaults, hybrid merges and
    the filter strip all route through it."""
    from ..config import IndexConstants

    return name.lower() == IndexConstants.DATA_FILE_NAME_COLUMN


class ScanNode(LogicalPlan):
    def __init__(self, relation: SourceRelation):
        self.relation = relation

    @property
    def output_schema(self) -> Schema:
        if self.relation.index_name:
            # An INDEX relation's lineage column is internal bookkeeping
            # (`_data_file_name` — reference `IndexConstants.scala:54-56`):
            # rewrites must be output-schema-preserving, so the logical
            # schema hides it. The physical layer still reads it when the
            # delete-tolerance prune filter asks (its condition references
            # the column) and strips it once the filter has evaluated.
            fields = [
                f for f in self.relation.schema.fields
                if not internal_column(f.name)
            ]
            if len(fields) != len(self.relation.schema.fields):
                return Schema(fields)
        return self.relation.schema

    def with_children(self, children):
        return self

    def simple_string(self):
        return f"Scan {self.relation!r}"


class FilterNode(LogicalPlan):
    def __init__(self, condition: Expr, child: LogicalPlan):
        self.condition = condition
        self.child = child

    def children(self):
        return (self.child,)

    @property
    def output_schema(self) -> Schema:
        return self.child.output_schema

    def with_children(self, children):
        return FilterNode(self.condition, children[0])

    def simple_string(self):
        return f"Filter {self.condition!r}"


class ProjectNode(LogicalPlan):
    def __init__(self, column_names: Sequence[str], child: LogicalPlan):
        self.column_names = list(column_names)
        self.child = child

    def children(self):
        return (self.child,)

    @property
    def output_schema(self) -> Schema:
        return self.child.output_schema.select(self.column_names)

    def with_children(self, children):
        return ProjectNode(self.column_names, children[0])

    def simple_string(self):
        return f"Project [{', '.join(self.column_names)}]"


_NUMERIC_DTYPES = frozenset({"int32", "int64", "float32", "float64", "bool"})


def _check_schema_compatible(op: str, a: "Schema", b: "Schema") -> None:
    """Multi-child operator schema contract (Union/Intersect/Except): names
    resolve case-insensitively positionally; numeric widths may differ
    (execution promotes), but string-vs-numeric fails HERE, not as an obscure
    runtime error later."""
    if [n.lower() for n in a.names] != [n.lower() for n in b.names]:
        raise ValueError(f"{op} children schemas differ: {a.names} vs {b.names}")
    for fa, fb in zip(a.fields, b.fields):
        if fa.dtype != fb.dtype and not (
            fa.dtype in _NUMERIC_DTYPES and fb.dtype in _NUMERIC_DTYPES
        ):
            raise ValueError(
                f"{op} column {fa.name!r} type mismatch: {fa.dtype} vs {fb.dtype}"
            )


class UnionNode(LogicalPlan):
    """Row-union of same-schema children (the Hybrid Scan merge shape: index data ∪
    appended source files)."""

    def __init__(self, children: Sequence[LogicalPlan]):
        import numpy as _np

        from .schema import Field, Schema, dtype_from_numpy

        self._children = list(children)
        first = self._children[0].output_schema
        dtypes = [f.dtype for f in first.fields]
        for c in self._children[1:]:
            sch = c.output_schema
            _check_schema_compatible("Union", first, sch)
            for i, fb in enumerate(sch.fields):
                if dtypes[i] != fb.dtype:
                    # Numeric widths may differ (concat promotes — the
                    # declared schema promotes with them).
                    dtypes[i] = dtype_from_numpy(
                        _np.promote_types(
                            _np.dtype(dtypes[i]), _np.dtype(fb.dtype)
                        )
                    )
        self._schema = Schema(
            [Field(f.name, d) for f, d in zip(first.fields, dtypes)]
        )

    def children(self):
        return tuple(self._children)

    @property
    def output_schema(self) -> Schema:
        # Numeric widths promote across children (concat promotes the data, so
        # the declared schema must agree).
        return self._schema

    def with_children(self, children):
        return UnionNode(children)

    def simple_string(self):
        return f"Union ({len(self._children)} children)"


class SetOpNode(LogicalPlan):
    """Base of the DISTINCT set operations INTERSECT / EXCEPT (SQL semantics:
    output rows are deduplicated; NULLs compare equal to each other — the same
    null-aware row equality the aggregate's key records implement). Schema
    compatibility follows UnionNode's contract (names resolve case-insensitively
    positionally; string-vs-numeric is a schema error here, not a late runtime
    one). Reference: Catalyst `Intersect`/`Except`, serde-wrapped at
    `index/serde/package.scala:59-186`."""

    op = ""

    def __init__(self, left: LogicalPlan, right: LogicalPlan):
        self.left = left
        self.right = right
        _check_schema_compatible(self.op, left.output_schema, right.output_schema)

    def children(self):
        return (self.left, self.right)

    @property
    def output_schema(self) -> Schema:
        return self.left.output_schema

    def with_children(self, children):
        return type(self)(children[0], children[1])

    def simple_string(self):
        return self.op


class IntersectNode(SetOpNode):
    """Rows present in BOTH children (distinct)."""

    op = "Intersect"


class ExceptNode(SetOpNode):
    """Rows of the left child absent from the right (distinct)."""

    op = "Except"


_JOIN_TYPES = {
    "inner": "inner",
    "cross": "inner",
    "left": "left",
    "leftouter": "left",
    "left_outer": "left",
    "right": "right",
    "rightouter": "right",
    "right_outer": "right",
    "full": "full",
    "outer": "full",
    "fullouter": "full",
    "full_outer": "full",
    "semi": "left_semi",
    "leftsemi": "left_semi",
    "left_semi": "left_semi",
    "anti": "left_anti",
    "leftanti": "left_anti",
    "left_anti": "left_anti",
}


def normalize_join_type(how: str) -> str:
    """Spark-compatible join-type spellings → canonical
    {inner, left, right, full, left_semi, left_anti}."""
    key = how.strip().lower().replace(" ", "")
    if key not in _JOIN_TYPES:
        from ..exceptions import HyperspaceException

        raise HyperspaceException(f"Unsupported join type: {how}")
    return _JOIN_TYPES[key]


class JoinNode(LogicalPlan):
    def __init__(self, left: LogicalPlan, right: LogicalPlan, condition: Expr, how: str = "inner"):
        self.left = left
        self.right = right
        self.condition = condition
        self.how = normalize_join_type(how)

    def children(self):
        return (self.left, self.right)

    @property
    def output_schema(self) -> Schema:
        if self.how in ("left_semi", "left_anti"):
            return self.left.output_schema
        fields = list(self.left.output_schema.fields) + list(self.right.output_schema.fields)
        return Schema(fields)

    def with_children(self, children):
        return JoinNode(children[0], children[1], self.condition, self.how)

    def simple_string(self):
        return f"Join {self.how} on {self.condition!r}"


@dataclass
class StarDimension:
    """One dimension of a recognized star join: a self-contained covering-
    index subplan (`plan` — an index ScanNode, possibly under a lineage
    delete-prune FilterNode, built exactly like `JoinIndexRule.substitute`'s
    output) plus the oriented key mapping fact→dimension and the column sets
    the query needs from each side. `plan` is intentionally NOT a child of
    the StarJoinNode: later rules must not rewrite it, and the cascade
    fallback never executes it."""

    plan: "LogicalPlan"
    fact_keys: List[str]
    dim_keys: List[str]
    dim_required: List[str]
    index_name: Optional[str]
    num_buckets: int


class StarJoinNode(LogicalPlan):
    """N-way star join (one fact, 2+ dimensions, all inner equi-joins on
    fact FKs) recognized by `JoinIndexRule` over a left-deep cascade of
    binary joins. `cascade` is the UNTOUCHED cascaded plan — it is the only
    child, so later rules (filter index, data skipping) keep rewriting it
    exactly as they would without the wrapper, and it stays the byte-
    identical fallback for every non-streamed consumer. The physical planner
    re-derives the fact subplan by walking the (possibly rule-rewritten)
    cascade's left spine; `dims` (innermost join first — the cascade's fold
    order) carries each dimension's covering-index subplan. Output schema
    and row semantics are exactly the cascade's."""

    def __init__(
        self,
        cascade: LogicalPlan,
        dims: Sequence[StarDimension],
        fact_required: Sequence[str],
    ):
        self.cascade = cascade
        self.dims = list(dims)
        self.fact_required = list(fact_required)

    def children(self):
        return (self.cascade,)

    @property
    def output_schema(self) -> Schema:
        return self.cascade.output_schema

    def with_children(self, children):
        return StarJoinNode(children[0], self.dims, self.fact_required)

    def simple_string(self):
        names = ", ".join(d.index_name or "?" for d in self.dims)
        return f"StarJoin ({len(self.dims)} dims: {names})"


def infer_expr_dtype(e: Expr, schema: Schema) -> str:
    """Static result type of an expression against a schema (comparisons/boolean/
    null-tests → bool; '/' → float64; +,-,* promote numerically; bare columns and
    literals keep their own types)."""
    from ..exceptions import HyperspaceException
    from .expr import BinaryOp, Col, IsIn, IsNull, Lit, Not

    if isinstance(e, Col):
        return schema.field(e.name).dtype
    if isinstance(e, Lit):
        v = e.value
        if isinstance(v, bool):
            return "bool"
        if isinstance(v, int):
            return "int64"
        if isinstance(v, float):
            return "float64"
        if isinstance(v, str):
            return "string"
        raise HyperspaceException(f"Cannot type literal: {v!r}")
    if isinstance(e, (Not, IsNull, IsIn)):
        return "bool"
    from .expr import Udf

    if isinstance(e, Udf):
        return e.dtype  # declared by udf(fn, dtype)
    if isinstance(e, BinaryOp):
        if e.op in BinaryOp.COMPARISONS or e.op in BinaryOp.BOOLEAN:
            return "bool"
        lt = infer_expr_dtype(e.left, schema)
        rt = infer_expr_dtype(e.right, schema)
        if "string" in (lt, rt) or "bool" in (lt, rt):
            raise HyperspaceException(f"Arithmetic on {lt}/{rt}: {e!r}")
        if e.op == "/":
            # True division: floating result; float32-only operands stay float32.
            return "float32" if lt == rt == "float32" else "float64"
        import numpy as _np

        return str(_np.result_type(_np.dtype(lt), _np.dtype(rt)))
    raise HyperspaceException(f"Cannot type expression: {e!r}")


class WithColumnNode(LogicalPlan):
    """Computed column: `name` = `expr` evaluated per row (the Spark `withColumn`
    analogue — what lets aggregation run over derived measures like TPC-H's
    `price * (1 - discount)`). Replaces an existing column of the same name in
    place, else appends."""

    def __init__(self, name: str, expr: Expr, child: LogicalPlan):
        self.name = name
        self.expr = expr
        self.child = child
        dtype = infer_expr_dtype(expr, child.output_schema)
        fields = []
        replaced = False
        for f in child.output_schema.fields:
            if f.name.lower() == name.lower():
                fields.append(Field(f.name, dtype))
                replaced = True
            else:
                fields.append(f)
        if not replaced:
            fields.append(Field(name, dtype))
        self._schema = Schema(fields)

    def children(self):
        return (self.child,)

    @property
    def output_schema(self) -> Schema:
        return self._schema

    def with_children(self, children):
        return WithColumnNode(self.name, self.expr, children[0])

    def references(self) -> List[str]:
        return sorted(self.expr.references())

    def simple_string(self):
        return f"WithColumn {self.name} = {self.expr!r}"


class AggregateNode(LogicalPlan):
    """GROUP BY + aggregates (sum/count/min/max/avg). The reference gets this from
    Spark SQL for free (`docs/_docs/13-toh-overview.md:33-36` — index scans
    accelerate whatever query encloses them); here it is an IR node so rewrite
    rules fire underneath aggregation-bearing queries (the TPC-H/DS shapes in
    BASELINE.md). `aggs` = [(out_name, fn, column|None)]; column None = count(*)."""

    def __init__(self, group_keys: Sequence[str], aggs: Sequence[tuple], child: LogicalPlan):
        from ..ops.aggregate import result_dtype  # validates fn names/dtypes

        self.group_keys = list(group_keys)
        self.aggs = [tuple(a) for a in aggs]
        self.child = child
        schema = child.output_schema
        fields = [schema.field(k) for k in self.group_keys]
        seen = {f.name.lower() for f in fields}
        for out_name, fn, col in self.aggs:
            if out_name.lower() in seen:
                from ..exceptions import HyperspaceException

                raise HyperspaceException(
                    f"Duplicate aggregate output name: {out_name!r}"
                )
            seen.add(out_name.lower())
            in_dtype = schema.field(col).dtype if col is not None else None
            fields.append(Field(out_name, result_dtype(fn, in_dtype)))
        self._schema = Schema(fields)

    def children(self):
        return (self.child,)

    @property
    def output_schema(self) -> Schema:
        return self._schema

    def with_children(self, children):
        return AggregateNode(self.group_keys, self.aggs, children[0])

    def references(self) -> List[str]:
        return self.group_keys + [c for _, _, c in self.aggs if c is not None]

    def simple_string(self):
        aggs = ", ".join(
            f"{o}={fn}({c if c is not None else '*'})" for o, fn, c in self.aggs
        )
        keys = ", ".join(self.group_keys)
        return f"Aggregate [{keys}] [{aggs}]"


class OrderByNode(LogicalPlan):
    """ORDER BY: `keys` = [(column, ascending)]. Null ordering follows Spark's
    default (nulls first ascending, last descending)."""

    def __init__(self, keys: Sequence[tuple], child: LogicalPlan):
        self.keys = [(k, bool(asc)) for k, asc in keys]
        self.child = child
        for k, _ in self.keys:
            child.output_schema.field(k)  # resolve-or-raise

    def children(self):
        return (self.child,)

    @property
    def output_schema(self) -> Schema:
        return self.child.output_schema

    def with_children(self, children):
        return OrderByNode(self.keys, children[0])

    def references(self) -> List[str]:
        return [k for k, _ in self.keys]

    def simple_string(self):
        keys = ", ".join(f"{k} {'ASC' if a else 'DESC'}" for k, a in self.keys)
        return f"OrderBy [{keys}]"


class LimitNode(LogicalPlan):
    def __init__(self, n: int, child: LogicalPlan):
        if n < 0:
            raise ValueError(f"limit must be non-negative: {n}")
        self.n = int(n)
        self.child = child

    def children(self):
        return (self.child,)

    @property
    def output_schema(self) -> Schema:
        return self.child.output_schema

    def with_children(self, children):
        return LimitNode(self.n, children[0])

    def simple_string(self):
        return f"Limit {self.n}"


def push_filters_below_computed(plan: LogicalPlan) -> LogicalPlan:
    """Predicate pushdown through computed columns: `Filter > WithColumn` becomes
    `WithColumn > Filter` whenever the predicate doesn't reference the computed
    column. Filters earlier = less per-row work, and more importantly the
    rewrite rules pattern-match `Filter > Scan` — without this a
    `.with_column(...).filter(...)` query could never use a filter index
    (Spark's optimizer does the same pushdown before the Hyperspace rules run).
    The sink recurses through stacks of computed columns AND intervening filters
    (row-wise predicates commute) in one pass — `.with_column(r, ...)
    .filter(r > 10).filter(src == 1)` still lands the source predicate on the
    scan. A filter only moves when an eligible computed column actually sits
    beneath it (no gratuitous reordering of plain filter stacks)."""

    def sinkable(refs, child: LogicalPlan) -> bool:
        while isinstance(child, FilterNode):
            child = child.child
        return isinstance(child, WithColumnNode) and child.name.lower() not in refs

    def sink(cond: Expr, refs, child: LogicalPlan) -> LogicalPlan:
        if isinstance(child, WithColumnNode) and child.name.lower() not in refs:
            return WithColumnNode(child.name, child.expr, sink(cond, refs, child.child))
        if isinstance(child, FilterNode) and sinkable(refs, child.child):
            return FilterNode(child.condition, sink(cond, refs, child.child))
        return FilterNode(cond, child)

    def swap(node: LogicalPlan) -> LogicalPlan:
        if isinstance(node, FilterNode):
            refs = {r.lower() for r in node.condition.references()}
            if sinkable(refs, node.child):
                return sink(node.condition, refs, node.child)
        return node

    return plan.transform_up(swap)


def find_single_relation(plan: LogicalPlan) -> Optional[ScanNode]:
    """Extract the single ScanNode of a linear plan (reference
    `RuleUtils.getLogicalRelation`, `RuleUtils.scala:67-74`); None if not linear or
    not exactly one relation."""
    if not plan.is_linear():
        return None
    scans = [n for n in plan.collect_nodes() if isinstance(n, ScanNode)]
    return scans[0] if len(scans) == 1 else None
