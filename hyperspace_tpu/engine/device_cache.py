"""Host→device transfer cache.

A query executes many device ops over the same cached host columns; re-uploading
them per query would dominate on a real TPU (HBM transfers over PCIe/tunnel). Device
arrays are cached per host-array identity (weakref-keyed, so entries die with their
host arrays — which are themselves owned by the scan cache).

Encoded staging (`engine/encoded_device.py`) uploads NARROW code lanes through
the same memo: callers pass `site`/`flat_bytes`/`charged_bytes` so the entry is
charged its TRUE encoded footprint (codes + dictionary + validity — the PR-8
ScanCache accounting, applied to device memory), warm hits tick
`cache.device_upload.encoded_hits`, and the miss records the flat-vs-staged
byte split in the encoded-staging ledger."""

from __future__ import annotations

import os
import threading
import weakref

import jax.numpy as jnp
import numpy as np

from ..telemetry import accounting as _accounting
from ..telemetry import device_observatory as _devobs
from ..telemetry import metrics as _metrics
from ..telemetry import stage_ledger as _stage_ledger

# Bound once: device_array is the hottest instrumented path (every device op
# over cached host columns) — per-call cost is one locked int add.
_HITS = _metrics.counter("cache.device_upload.hits")
_MISSES = _metrics.counter("cache.device_upload.misses")
# Warm hits served from CODE-SPACE entries: how much of the steady state rides
# encoded staging (the device mirror of `cache.scan.encoded_hits`).
_ENCODED_HITS = _metrics.counter("cache.device_upload.encoded_hits")
# Footprint watermarks (exporter frames / prometheus): live bytes pinned by
# the upload memo, and the high-water mark across the process lifetime.
_CACHE_BYTES = _metrics.gauge("cache.device_upload.bytes")
_CACHE_BYTES_PEAK = _metrics.gauge("cache.device_upload.bytes_peak")

# id(host) -> (weakref, device_array, charged_bytes, encoded); insertion
# order = LRU. `charged_bytes` is what the budget accounting carries for the
# entry — the device array's own bytes for flat stages, the TRUE encoded
# footprint for code-space stages.
_cache: dict = {}
# Device copies are pinned until their host arrays die (the scan cache bounds
# hosts at 4 GiB); this byte budget additionally bounds DEVICE memory so the
# memo can never approach HBM capacity on its own.
_BUDGET = int(os.environ.get("HYPERSPACE_UPLOAD_CACHE_BUDGET", 4 << 30))
_bytes = 0
# Concurrent queries (thread-local active sessions) interleave on this memo;
# RLock because weakref eviction callbacks can fire inside guarded sections.
_lock = threading.RLock()


def _note_bytes() -> None:
    """Publish the live footprint + high-water mark (called with `_lock`
    held, after any `_bytes` mutation)."""
    _CACHE_BYTES.set(_bytes)
    _CACHE_BYTES_PEAK.set_max(_bytes)


def _evict_over_budget(protect_key) -> None:
    global _bytes
    while _bytes > _BUDGET:
        victim = next((k for k in _cache if k != protect_key), None)
        if victim is None:
            return
        dropped = _cache.pop(victim, None)
        if dropped is not None:
            _bytes -= int(dropped[2])
            _note_bytes()


def device_array(
    host: np.ndarray, *, site=None, flat_bytes=None, charged_bytes=None, packed=False
):
    """jnp view of a host numpy array, cached by identity.

    `flat_bytes`/`charged_bytes`/`site` mark an ENCODED stage (narrow code
    lane): the entry is charged `charged_bytes` against the byte budget, the
    upload miss records `flat_bytes` vs the actual narrow bytes in the
    encoded-staging ledger, and warm hits tick the encoded-hit counter.
    `packed=True` additionally marks the stage as a BIT-PACKED lane
    (`engine/packed_codes.py`): the upload's true word bytes land in the
    `packed` tier of the encoded-staging ledger."""
    global _bytes
    if not isinstance(host, np.ndarray):
        return jnp.asarray(host)
    encoded = flat_bytes is not None
    key = id(host)
    with _lock:
        hit = _cache.get(key)
        if hit is not None and hit[0]() is host:
            _cache[key] = _cache.pop(key)  # LRU refresh
            _HITS.inc()
            if hit[3]:
                _ENCODED_HITS.inc()
            return hit[1]

    _MISSES.inc()
    # Upload-miss = a real host→device transfer this query caused. Timing it
    # requires forcing the (async) transfer to completion, so seconds only
    # arrive under HYPERSPACE_DEVICE_TIMING — bytes and count always. The
    # whole miss region is the ``h2d`` stage for attribution: upload bytes
    # bill to a dedicated lane even when the miss fires inside another
    # stage's bracket (innermost label wins).
    with _stage_ledger.stage_scope("h2d"):
        if _devobs.timing_mode():
            import time as _time

            t0 = _time.monotonic()
            dev = jnp.asarray(host)
            dev.block_until_ready()
            upload_s = _time.monotonic() - t0
        else:
            dev = jnp.asarray(host)
            upload_s = None
        _accounting.add("device_upload_bytes", int(dev.nbytes))
        _devobs.record_h2d(int(dev.nbytes), upload_s)
        if encoded:
            _devobs.record_encoded_stage(
                site or "?",
                int(flat_bytes),
                int(dev.nbytes),
                packed_bytes=int(dev.nbytes) if packed else None,
            )
    charged = int(charged_bytes) if charged_bytes is not None else int(dev.nbytes)

    def _evict(wr, key=key):
        # Only drop the entry this weakref installed: a dead array's id can be
        # reused by a new array before the deferred callback runs.
        global _bytes
        with _lock:
            ent_now = _cache.get(key)
            if ent_now is not None and ent_now[0] is wr:
                _cache.pop(key, None)
                _bytes -= int(ent_now[2])
                _note_bytes()

    try:
        ref = weakref.ref(host, _evict)
    except TypeError:
        return dev  # non-weakref-able subclass: skip caching
    with _lock:
        hit = _cache.get(key)  # re-read: another thread may have inserted
        if hit is not None:
            if hit[0]() is host:
                return hit[1]  # raced: reuse the first upload, drop ours
            _bytes -= int(hit[2])  # displaced stale entry leaves accounting
        _cache[key] = (ref, dev, charged, encoded)
        _bytes += charged
        _note_bytes()
        _evict_over_budget(key)
    return dev
