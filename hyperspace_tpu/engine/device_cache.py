"""Host→device transfer cache.

A query executes many device ops over the same cached host columns; re-uploading
them per query would dominate on a real TPU (HBM transfers over PCIe/tunnel). Device
arrays are cached per host-array identity (weakref-keyed, so entries die with their
host arrays — which are themselves owned by the scan cache)."""

from __future__ import annotations

import weakref

import jax.numpy as jnp
import numpy as np

_cache: dict = {}


def device_array(host: np.ndarray):
    """jnp view of a host numpy array, cached by identity."""
    if not isinstance(host, np.ndarray):
        return jnp.asarray(host)
    key = id(host)
    hit = _cache.get(key)
    if hit is not None and hit[0]() is host:
        return hit[1]

    dev = jnp.asarray(host)

    def _evict(wr, key=key):
        # Only drop the entry this weakref installed: a dead array's id can be
        # reused by a new array before the deferred callback runs.
        ent_now = _cache.get(key)
        if ent_now is not None and ent_now[0] is wr:
            _cache.pop(key, None)

    try:
        ref = weakref.ref(host, _evict)
    except TypeError:
        return dev  # non-weakref-able subclass: skip caching
    _cache[key] = (ref, dev)
    return dev
