"""Expression IR for filters, projections, and join conditions.

The engine analogue of Catalyst expressions — just enough surface for the reference's
rule semantics: column refs, literals, comparisons, boolean algebra, arithmetic. The
join rule needs to pattern-match equi-join CNF (`EqualTo`/`And` only,
`JoinIndexRule.scala:188-194`), so the tree shape is kept explicit.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Set


class Expr:
    def references(self) -> Set[str]:
        """All column names referenced by this expression."""
        out: Set[str] = set()
        self._collect_refs(out)
        return out

    def _collect_refs(self, out: Set[str]) -> None:
        for c in self.children():
            c._collect_refs(out)

    def children(self) -> Sequence["Expr"]:
        return ()

    # -- operator sugar -----------------------------------------------------

    def __eq__(self, other):  # type: ignore[override]
        return BinaryOp("==", self, _lit(other))

    def __ne__(self, other):  # type: ignore[override]
        return BinaryOp("!=", self, _lit(other))

    def __lt__(self, other):
        return BinaryOp("<", self, _lit(other))

    def __le__(self, other):
        return BinaryOp("<=", self, _lit(other))

    def __gt__(self, other):
        return BinaryOp(">", self, _lit(other))

    def __ge__(self, other):
        return BinaryOp(">=", self, _lit(other))

    def __and__(self, other):
        return BinaryOp("and", self, _lit(other))

    def __or__(self, other):
        return BinaryOp("or", self, _lit(other))

    def __invert__(self):
        return Not(self)

    def __add__(self, other):
        return BinaryOp("+", self, _lit(other))

    def __radd__(self, other):
        return BinaryOp("+", _lit(other), self)

    def __sub__(self, other):
        return BinaryOp("-", self, _lit(other))

    def __rsub__(self, other):
        return BinaryOp("-", _lit(other), self)

    def __mul__(self, other):
        return BinaryOp("*", self, _lit(other))

    def __rmul__(self, other):
        return BinaryOp("*", _lit(other), self)

    def __truediv__(self, other):
        return BinaryOp("/", self, _lit(other))

    def __rtruediv__(self, other):
        return BinaryOp("/", _lit(other), self)

    def is_null(self) -> "IsNull":
        return IsNull(self, negated=False)

    def is_not_null(self) -> "IsNull":
        return IsNull(self, negated=True)

    def isin(self, *values) -> "IsIn":
        vals = values[0] if len(values) == 1 and isinstance(values[0], (list, tuple)) else values
        return IsIn(self, list(vals))

    def __hash__(self):
        return hash(repr(self))

    def semantic_equals(self, other: "Expr") -> bool:
        return repr(self) == repr(other)


class Col(Expr):
    def __init__(self, name: str):
        self.name = name

    def _collect_refs(self, out: Set[str]) -> None:
        out.add(self.name)

    def __repr__(self):
        return f"col({self.name})"


class Lit(Expr):
    def __init__(self, value):
        self.value = value

    def __repr__(self):
        return f"lit({self.value!r})"


class BinaryOp(Expr):
    COMPARISONS = ("==", "!=", "<", "<=", ">", ">=")
    BOOLEAN = ("and", "or")
    ARITHMETIC = ("+", "-", "*", "/")

    def __init__(self, op: str, left: Expr, right: Expr):
        assert op in self.COMPARISONS + self.BOOLEAN + self.ARITHMETIC, op
        self.op = op
        self.left = left
        self.right = right

    def children(self) -> Sequence[Expr]:
        return (self.left, self.right)

    def __repr__(self):
        return f"({self.left!r} {self.op} {self.right!r})"


class Not(Expr):
    def __init__(self, child: Expr):
        self.child = child

    def children(self) -> Sequence[Expr]:
        return (self.child,)

    def __repr__(self):
        return f"(not {self.child!r})"


class IsNull(Expr):
    """IS NULL / IS NOT NULL — the only expressions that observe the validity lane
    directly (and whose result is itself never null)."""

    def __init__(self, child: Expr, negated: bool = False):
        self.child = child
        self.negated = negated

    def children(self) -> Sequence[Expr]:
        return (self.child,)

    def __repr__(self):
        return f"({self.child!r} is {'not ' if self.negated else ''}null)"


class IsIn(Expr):
    def __init__(self, child: Expr, values: Sequence):
        self.child = child
        self.values = list(values)

    def children(self) -> Sequence[Expr]:
        return (self.child,)

    def __repr__(self):
        return f"({self.child!r} in {self.values!r})"


class Udf(Expr):
    """A user-defined column function applied row-wise to its argument
    expressions — the engine's escape hatch for logic the expression IR cannot
    express (reference: Catalyst `ScalaUDF`, wrapped by the serde at
    `index/serde/package.scala:59-186`).

    HOST-evaluated by contract: the function runs on decoded Python values on
    the host, never on device — a UDF column is the one engine surface that
    opts out of the TPU compute path. Null handling mirrors Spark's
    reference-type UDFs: null inputs arrive as None; returning None makes the
    result null. Rewrite rules remain applicable around UDFs (an index still
    fires when the UDF only consumes columns the index covers)."""

    def __init__(self, fn, dtype: str, args: Sequence["Expr"], name: Optional[str] = None):
        self.fn = fn
        self.dtype = dtype
        self.args = list(args)
        self.name = name or getattr(fn, "__name__", "udf")

    def children(self) -> Sequence["Expr"]:
        return tuple(self.args)

    def __repr__(self):
        # repr keys several caches (compiled predicates, filtered-scan
        # concats), so it must carry FUNCTION identity: two distinct lambdas
        # both named "<lambda>" over the same args are different expressions.
        # The uid is stable per function object and never reused (monotonic).
        args = ", ".join(repr(a) for a in self.args)
        return f"udf:{self.name}#{_udf_uid(self.fn)}({args})"


import itertools as _itertools
import weakref as _weakref

_udf_uids: "_weakref.WeakKeyDictionary" = _weakref.WeakKeyDictionary()
_udf_counter = _itertools.count()


_udf_uids_strong: dict = {}  # id(fn) -> (fn, uid) for non-weakref-able callables


def _udf_uid(fn) -> int:
    """Monotonic id per function OBJECT (weak-keyed: ids die with their
    functions and are never reused — unlike id(), which the allocator
    recycles). Non-weakref-able callables (e.g. numpy ufuncs) get a
    strong-keyed entry: the kept reference pins id(fn) against reuse."""
    try:
        u = _udf_uids.get(fn)
        if u is None:
            u = next(_udf_counter)
            _udf_uids[fn] = u
        return u
    except TypeError:
        ent = _udf_uids_strong.get(id(fn))
        if ent is None or ent[0] is not fn:
            ent = (fn, next(_udf_counter))
            _udf_uids_strong[id(fn)] = ent
        return ent[1]


def udf(fn, dtype: str, name: Optional[str] = None):
    """Wrap a plain Python function as a column expression factory:

        to_tier = udf(lambda qty: "big" if qty > 25 else "small", "string")
        df.with_column("tier", to_tier(col("qty")))

    `dtype` declares the result type ("int64", "float64", "bool", "string", …).
    See `Udf` for the host-evaluation and null contract."""

    def make(*args) -> Udf:
        return Udf(fn, dtype, [_lit(a) for a in args], name)

    make.fn = fn
    make.dtype = dtype
    return make


def _lit(v) -> Expr:
    return v if isinstance(v, Expr) else Lit(v)


def col(name: str) -> Col:
    return Col(name)


def lit(value) -> Lit:
    return Lit(value)


# ---------------------------------------------------------------------------
# Analysis helpers used by the rewrite rules
# ---------------------------------------------------------------------------


def canonical_condition_repr(e: Expr, case_sensitive: bool = False) -> str:
    """Cache-key form of a condition: under case-INsensitive resolution,
    column spellings are normalized so `col("X") == 1` and `col("x") == 1`
    share one cache entry (they read the same data) instead of duplicating
    it. Injective per distinct condition — the structure mirrors each node's
    repr; unknown node types fall back to repr."""
    if case_sensitive:
        return repr(e)

    def walk(x: Expr) -> str:
        if isinstance(x, Col):
            return f"col({x.name.lower()})"
        if isinstance(x, Lit):
            return repr(x)
        if isinstance(x, BinaryOp):
            return f"({walk(x.left)} {x.op} {walk(x.right)})"
        if isinstance(x, Not):
            return f"(not {walk(x.child)})"
        if isinstance(x, IsNull):
            return f"({walk(x.child)} is {'not ' if x.negated else ''}null)"
        if isinstance(x, IsIn):
            return f"({walk(x.child)} in {x.values!r})"
        if isinstance(x, Udf):
            args = ", ".join(walk(a) for a in x.args)
            return f"udf:{x.name}#{_udf_uid(x.fn)}({args})"
        return repr(x)

    return walk(e)


def split_conjuncts(e: Expr) -> List[Expr]:
    """Flatten a tree of `and`s into conjuncts (CNF split)."""
    if isinstance(e, BinaryOp) and e.op == "and":
        return split_conjuncts(e.left) + split_conjuncts(e.right)
    return [e]


def extract_equi_join_keys(condition: Expr):
    """If the condition is pure equi-join CNF (`==` joined by `and`, each side a bare
    column), return the list of (left_col_name, right_col_name) pairs; else None.
    Mirrors the reference's applicability check (`JoinIndexRule.scala:188-194`).

    The caller still must orient each pair against the actual child plans (a == may be
    written `right.c == left.c`)."""
    pairs = []
    for conj in split_conjuncts(condition):
        if not (isinstance(conj, BinaryOp) and conj.op == "=="):
            return None
        l, r = conj.left, conj.right
        if not (isinstance(l, Col) and isinstance(r, Col)):
            return None
        pairs.append((l.name, r.name))
    return pairs
