"""Streaming scan→filter→aggregate executor: the read-side pipeline.

`bench` r05 measured the materialized aggregate path decoding and concatenating
the FULL multi-file table on the host before any reduction starts (cold indexed
reads spent 1.34 s of 1.35 s in I/O; the 8M scan aggregate materialized ~500 MB
it immediately reduced away). This module mirrors `index/build_pipeline.py` on
the query side:

1. **Decode** — `engine.io.iter_file_tables` feeds per-file tables in sorted
   order through the per-column scan cache, with a bounded decode pool
   (shared ``HYPERSPACE_BUILD_DECODE_THREADS`` contract) running up to
   ``HYPERSPACE_QUERY_PREFETCH_FILES`` files ahead of the consumer.
2. **Chunk** — each file splits into row slices of at most
   ``HYPERSPACE_QUERY_CHUNK_ROWS`` (numpy views; chunk boundaries never change
   values or output order).
3. **Filter / projections** — `FilterExec`/`ProjectExec`/`WithColumnExec`
   apply per chunk through their `execute_stream` generators, so selective
   filters shrink chunks before any reduction.
4. **Reduce with carry** — `ops.aggregate.StreamAggregator` reduces every
   chunk to per-group partial states (the fused jitted hash/sort/segment
   programs on the device path, `reduceat` on the CPU backend) and carries the
   accumulators across chunks, merging by exact key records. The full concat
   is never materialized.

``HYPERSPACE_QUERY_STREAMING=0`` disables the whole path: every aggregate runs
today's materialized execution byte-for-byte. Streamed results equal the
materialized path's exactly for integer/count/min/max outputs and to
float-associativity rounding for float sum/avg (docs/query-pipeline.md).

Per-stage busy timings (decode/eval/partial/merge), wall clock, and the
overlap ratio ride `telemetry.profiling.record_query_stages` and surface in
``bench.py``'s ``bench_detail.query_stages``.
"""

from __future__ import annotations

import os
from typing import List, Optional

from .table import Column, Table

ENV_QUERY_STREAMING = "HYPERSPACE_QUERY_STREAMING"
ENV_QUERY_CHUNK_ROWS = "HYPERSPACE_QUERY_CHUNK_ROWS"
_DEFAULT_QUERY_CHUNK_ROWS = 4_000_000


def streaming_enabled() -> bool:
    """Default ON; ``HYPERSPACE_QUERY_STREAMING=0`` is the materialized
    fallback (preserves the pre-streaming execution exactly)."""
    return os.environ.get(ENV_QUERY_STREAMING, "") != "0"


def query_chunk_rows() -> int:
    return max(
        1,
        int(
            os.environ.get(ENV_QUERY_CHUNK_ROWS, _DEFAULT_QUERY_CHUNK_ROWS)
            or _DEFAULT_QUERY_CHUNK_ROWS
        ),
    )


def split_chunks(t: Table, chunk_rows: int) -> List[Table]:
    """Row-slice a table into pipeline chunks (numpy views — chunk boundaries
    have no effect on output order or values). Same slicing as the build
    pipeline's `_split_chunks`."""
    if t.num_rows <= chunk_rows:
        return [t]
    out = []
    for lo in range(0, t.num_rows, chunk_rows):
        hi = min(lo + chunk_rows, t.num_rows)
        out.append(
            Table(
                {
                    n: Column(
                        c.dtype,
                        c.data[lo:hi],
                        c.dictionary,
                        None if c.validity is None else c.validity[lo:hi],
                    )
                    for n, c in t.columns.items()
                }
            )
        )
    return out


def compact_mask_indices(mask):
    """Surviving row indices of a chunk's predicate mask. The whole-table
    filter's `nonzero_indices` compiles one program PER SURVIVOR COUNT
    (`jnp.nonzero(size=n)`) — fine once per query, ~0.3 s of XLA-CPU compile
    per CHUNK here, where every chunk survives differently. CPU backend:
    plain numpy (the mask is host-resident anyway). Device path: pow2-capped
    `size` so compiles stay log2-bounded."""
    import jax.numpy as jnp
    import numpy as np

    from ..ops.backend import use_device_path

    if not use_device_path():
        return np.nonzero(np.asarray(mask))[0]
    mask = jnp.asarray(mask)
    n = int(mask.sum())
    if n == 0:
        return np.empty(0, np.int64)
    cap = 1 << max(n - 1, 1).bit_length()
    return np.asarray(jnp.nonzero(mask, size=cap, fill_value=0)[0])[:n]


def timed(stages, name: str):
    """`stages.timed(name)`, or a no-op context when telemetry is off."""
    if stages is None:
        import contextlib

        return contextlib.nullcontext()
    return stages.timed(name)


def stream_aggregate(agg_exec, ctx) -> Optional[Table]:
    """Run a `HashAggregateExec` over its child's chunk stream with the
    chunk-carry aggregator. Returns None only when no chunk arrived (the
    caller owns the fallback); faults mid-stream propagate — the scan cache
    only ever holds successful decodes, so a failed query poisons nothing."""
    from ..ops.aggregate import StreamAggregator, _empty_result
    from ..ops.backend import use_device_path
    from ..telemetry.profiling import StageTimings, record_query_stages

    import numpy as np

    stages = StageTimings(
        mode="stream-device" if use_device_path() else "stream-cpu"
    )
    agg = StreamAggregator(agg_exec.group_keys, agg_exec.aggs, stages=stages)
    # 0-row schema template accumulated across ALL chunks: the empty-input
    # result must carry the same concat-PROMOTED dtypes (mixed-width files,
    # union dictionaries) the materialized path would produce.
    template: Optional[Table] = None
    none_idx = np.empty(0, np.int64)
    n_chunks = 0
    for chunk in agg_exec.child.execute_stream(ctx, stages):
        zero = chunk.take(none_idx)
        template = zero if template is None else Table.concat([template, zero])
        n_chunks += 1
        agg.add_chunk(chunk)
    out = agg.finalize()
    if out is None:
        if template is None:
            return None  # nothing streamed: caller falls back
        out = _empty_result(template, agg_exec.group_keys, agg_exec.aggs)
    summary = stages.summary()
    summary.update(
        {
            "chunks": n_chunks,
            "rows": agg.rows,
            "groups": out.num_rows,
        }
    )
    record_query_stages(summary)
    return out
