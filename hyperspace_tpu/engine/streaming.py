"""Streaming scan→filter→aggregate executor: the read-side pipeline.

`bench` r05 measured the materialized aggregate path decoding and concatenating
the FULL multi-file table on the host before any reduction starts (cold indexed
reads spent 1.34 s of 1.35 s in I/O; the 8M scan aggregate materialized ~500 MB
it immediately reduced away). This module mirrors `index/build_pipeline.py` on
the query side:

1. **Decode** — `engine.io.iter_file_tables` feeds per-file tables in sorted
   order through the per-column scan cache, with a bounded decode pool
   (shared ``HYPERSPACE_BUILD_DECODE_THREADS`` contract) running up to
   ``HYPERSPACE_QUERY_PREFETCH_FILES`` files ahead of the consumer. With a
   pushdown predicate (`engine.pushdown`, via `ScanExec.pushdown`), each file
   decodes only the row groups its footer zone maps cannot exclude — pruned
   bytes never enter the stream, so they are never staged or filtered either.
2. **Chunk** — each (pruned) file table splits into row slices of at most
   ``HYPERSPACE_QUERY_CHUNK_ROWS`` (numpy views; chunk boundaries never change
   values or output order, and align to the surviving row groups'
   concatenation by construction).
3. **Filter / projections** — `FilterExec`/`ProjectExec`/`WithColumnExec`
   apply per chunk through their `execute_stream` generators, so selective
   filters shrink chunks before any reduction.
4. **Reduce with carry** — `ops.aggregate.StreamAggregator` reduces every
   chunk to per-group partial states (the fused jitted hash/sort/segment
   programs on the device path, `reduceat` on the CPU backend) and carries the
   accumulators across chunks, merging by exact key records. The full concat
   is never materialized.

``HYPERSPACE_QUERY_STREAMING=0`` disables the whole path: every aggregate runs
today's materialized execution byte-for-byte. Streamed results equal the
materialized path's exactly for integer/count/min/max outputs and to
float-associativity rounding for float sum/avg (docs/query-pipeline.md).

`stream_join_aggregate` (below) is the JOIN-side twin: a grouped aggregate
over a bucketed inner join streams verified pair chunks — gather + expression
chain per chunk on the shared decode-pool contract — straight into the same
`StreamAggregator`, so the join output never materializes whole
(docs/join-pipeline.md).

Late materialization rides both streams by construction: chunks carry string
columns as dictionary codes (under encoded execution the decode stage
produces them without ever flattening — docs/encoded-execution.md), filters
and group-bys and pair verification all run on codes, and only the gathered
survivors that reach the output boundary ever decode.

Per-stage busy timings (decode/eval/partial/merge), wall clock, and the
overlap ratio ride `telemetry.profiling.record_query_stages` and surface in
``bench.py``'s ``bench_detail.query_stages``.
"""

from __future__ import annotations

import os
from typing import List, Optional

from .table import Column, Table

ENV_QUERY_STREAMING = "HYPERSPACE_QUERY_STREAMING"
ENV_QUERY_CHUNK_ROWS = "HYPERSPACE_QUERY_CHUNK_ROWS"
_DEFAULT_QUERY_CHUNK_ROWS = 4_000_000
#: Pair-chunk size of the streamed join→aggregate (rows of JOIN OUTPUT per
#: chunk). Smaller than the scan chunk default: each join chunk materializes
#: every payload column of both sides for its pair slice.
ENV_JOIN_CHUNK_ROWS = "HYPERSPACE_JOIN_CHUNK_ROWS"
_DEFAULT_JOIN_CHUNK_ROWS = 2_000_000
#: Multiway star-join gate: ``HYPERSPACE_MULTIWAY=0`` keeps recognized star
#: joins on the cascaded binary execution byte-for-byte (recognition itself
#: is also suppressed at rule time, so the plan class does not change).
ENV_MULTIWAY = "HYPERSPACE_MULTIWAY"


def multiway_enabled() -> bool:
    """Default ON; ``HYPERSPACE_MULTIWAY=0`` is the cascaded fallback
    (preserves pre-star execution exactly). Unset hands the knob to the
    adaptive planner when one decided this query — an explicit flag always
    wins (`docs/planner.md`)."""
    raw = os.environ.get(ENV_MULTIWAY, "")
    if raw != "":
        return raw != "0"
    from ..plananalysis.planner import decided_value

    decided = decided_value("multiway")
    return True if decided is None else bool(decided)


def streaming_enabled() -> bool:
    """Default ON; ``HYPERSPACE_QUERY_STREAMING=0`` is the materialized
    fallback (preserves the pre-streaming execution exactly). Unset hands
    the knob to the adaptive planner when one decided this query — an
    explicit flag always wins (`docs/planner.md`)."""
    raw = os.environ.get(ENV_QUERY_STREAMING, "")
    if raw != "":
        return raw != "0"
    from ..plananalysis.planner import decided_value

    decided = decided_value("streaming")
    return True if decided is None else bool(decided)


def query_chunk_rows() -> int:
    raw = os.environ.get(ENV_QUERY_CHUNK_ROWS, "")
    if raw != "":
        try:
            return max(1, int(raw))
        except ValueError:
            return _DEFAULT_QUERY_CHUNK_ROWS
    from ..plananalysis.planner import decided_value

    decided = decided_value("chunk_rows")
    if decided is not None:
        return max(1, int(decided))
    return _DEFAULT_QUERY_CHUNK_ROWS


def join_chunk_rows() -> int:
    return max(
        1,
        int(
            os.environ.get(ENV_JOIN_CHUNK_ROWS, _DEFAULT_JOIN_CHUNK_ROWS)
            or _DEFAULT_JOIN_CHUNK_ROWS
        ),
    )


def split_chunks(t: Table, chunk_rows: int) -> List[Table]:
    """Row-slice a table into pipeline chunks (numpy views — chunk boundaries
    have no effect on output order or values). Same slicing as the build
    pipeline's `_split_chunks`."""
    if t.num_rows <= chunk_rows:
        return [t]
    out = []
    for lo in range(0, t.num_rows, chunk_rows):
        hi = min(lo + chunk_rows, t.num_rows)
        out.append(
            Table(
                {
                    n: Column(
                        c.dtype,
                        c.data[lo:hi],
                        c.dictionary,
                        None if c.validity is None else c.validity[lo:hi],
                    )
                    for n, c in t.columns.items()
                }
            )
        )
    return out


def compact_mask_indices(mask):
    """Surviving row indices of a chunk's predicate mask. The whole-table
    filter's `nonzero_indices` compiles one program PER SURVIVOR COUNT
    (`jnp.nonzero(size=n)`) — fine once per query, ~0.3 s of XLA-CPU compile
    per CHUNK here, where every chunk survives differently. CPU backend:
    plain numpy (the mask is host-resident anyway). Device path: pow2-capped
    `size` so compiles stay log2-bounded."""
    import jax.numpy as jnp
    import numpy as np

    from ..ops.backend import use_device_path

    if not use_device_path():
        return np.nonzero(np.asarray(mask))[0]
    mask = jnp.asarray(mask)
    n = int(mask.sum())
    if n == 0:
        return np.empty(0, np.int64)
    cap = 1 << max(n - 1, 1).bit_length()
    return np.asarray(jnp.nonzero(mask, size=cap, fill_value=0)[0])[:n]


def timed(stages, name: str):
    """`stages.timed(name)`, or a no-op context when telemetry is off."""
    if stages is None:
        import contextlib

        return contextlib.nullcontext()
    return stages.timed(name)


def stream_aggregate(agg_exec, ctx) -> Optional[Table]:
    """Run a `HashAggregateExec` over its child's chunk stream with the
    chunk-carry aggregator. Returns None only when no chunk arrived (the
    caller owns the fallback); faults mid-stream propagate — the scan cache
    only ever holds successful decodes, so a failed query poisons nothing."""
    from ..ops.aggregate import StreamAggregator, _empty_result
    from ..ops.backend import use_device_path
    from ..telemetry.profiling import StageTimings, record_query_stages

    import numpy as np

    stages = StageTimings(
        mode="stream-device" if use_device_path() else "stream-cpu"
    )
    agg = StreamAggregator(agg_exec.group_keys, agg_exec.aggs, stages=stages)
    # 0-row schema template accumulated across ALL chunks: the empty-input
    # result must carry the same concat-PROMOTED dtypes (mixed-width files,
    # union dictionaries) the materialized path would produce.
    template: Optional[Table] = None
    none_idx = np.empty(0, np.int64)
    n_chunks = 0
    from .. import resilience

    for chunk in agg_exec.child.execute_stream(ctx, stages):
        # Chunk-boundary cancellation: a deadlined query stops between
        # chunks; nothing partial was cached (only-cache-on-success).
        resilience.check_deadline("query.stream")
        zero = chunk.take(none_idx)
        template = zero if template is None else Table.concat([template, zero])
        n_chunks += 1
        agg.add_chunk(chunk)
    out = agg.finalize()
    if out is None:
        if template is None:
            return None  # nothing streamed: caller falls back
        out = _empty_result(template, agg_exec.group_keys, agg_exec.aggs)
    summary = stages.summary()
    summary.update(
        {
            "chunks": n_chunks,
            "rows": agg.rows,
            "groups": out.num_rows,
        }
    )
    record_query_stages(summary)
    return out


# ---------------------------------------------------------------------------
# Streamed bucketed-join → aggregate (the write-side twin: join pair chunks
# flow straight into the chunk-carry aggregator; the joined table never
# materializes whole)
# ---------------------------------------------------------------------------


def _resolve_named_columns(out_names, chain, names):
    """Resolve aggregate names over a join output's name→Column mapping to
    SOURCE Column objects. None when any name is shadowed by a withColumn in
    the chain (computed — no source column) or does not resolve uniquely."""
    from .physical import WithColumnExec

    shadowed = {
        op.col_name.lower() for op in chain if isinstance(op, WithColumnExec)
    }
    cols = []
    for name in names:
        if name.lower() in shadowed:
            return None
        c = out_names.get(name)
        if c is None:
            ci = [k for k in out_names if k.lower() == name.lower()]
            if len(ci) != 1:
                return None
            c = out_names[ci[0]]
        cols.append(c)
    return cols


def _resolve_source_columns(left: Table, right: Table, chain, names):
    """Resolve aggregate names over the join's output naming (left wins the
    unsuffixed name; colliding right columns answer to `<name>_r`, exactly
    `_assemble_join`'s rule) to SOURCE Column objects."""
    out_names = dict(left.columns)
    for n, c in right.columns.items():
        out_names[n if n not in out_names else f"{n}_r"] = c
    return _resolve_named_columns(out_names, chain, names)


def star_output_columns(fact: Table, dim_tables):
    """Column-name → source Column mapping of a star join's output: the
    cascade applies `_assemble_join`'s naming fold-wise (the left side of
    join k is the fact already joined with dims 0..k-1), so a colliding name
    takes `<name>_r` — and a THIRD table colliding on the same name
    OVERWRITES the existing `_r` entry, exactly as the cascaded execution
    does. The streamed star path must replicate that quirk verbatim to stay
    byte-identical."""
    out_names = dict(fact.columns)
    for dt in dim_tables:
        for n, c in dt.columns.items():
            out_names[n if n not in out_names else f"{n}_r"] = c
    return out_names


def _agg_input_dtype(name: str, left: Table, right: Table, chain):
    """Declared dtype of one aggregate input over the join output: the
    shadowing withColumn's DECLARED dtype when the chain computes it, else the
    source column's dtype; None when unresolvable."""
    from .physical import WithColumnExec

    for op in chain:
        if isinstance(op, WithColumnExec) and op.col_name.lower() == name.lower():
            return op.dtype
    cols = _resolve_source_columns(left, right, (), [name])
    return cols[0].dtype if cols is not None else None


def _float_fold_free(agg_exec, left: Table, right: Table, chain) -> bool:
    """True when every sum/avg input is PROVABLY non-float: integer partial
    states accumulate exactly, so the chunked fold is bitwise-equal to the
    one-pass fold regardless of chunk boundaries — the admission condition
    for the RECORD-MERGE carry, whose hash-sorted partials would reorder a
    float fold even within one chunk. Float sums stream only through the
    direct-cells hint, where the per-chunk fold is the one-pass bincount
    verbatim: bitwise-identical to the materialized fallback when the stream
    fits one chunk (every test-scale shape), and within float-associativity
    rounding once multiple chunks fold partial cell sums (the documented
    streaming contract, docs/join-pipeline.md — same contract as PR 2's
    scan-side stream)."""
    for _out, fn, cname in agg_exec.aggs:
        if fn in ("sum", "avg") and cname is not None:
            dtype = _agg_input_dtype(cname, left, right, chain)
            if dtype is None or dtype in ("float32", "float64"):
                return False
    return True


def stream_join_aggregate(agg_exec, join_exec, chain, ctx) -> Optional[Table]:
    """Run a `HashAggregateExec` over a bucketed INNER join as a chunk-carry
    stream: verified pair chunks gather their payload columns and evaluate the
    WithColumn/Project chain PER CHUNK (on a bounded worker pool riding the
    shared decode-pool contract, overlapping the next chunk's verification and
    the aggregator's fold), and reduce into `StreamAggregator` — with the
    direct-address cells fast path when the SOURCE group-key columns qualify.
    The full join output never materializes.

    The verified pairs and classed probe ranges are inserted into the engine
    memos ONLY after every chunk streamed successfully — a mid-stream fault
    (e.g. a failing gather) propagates cleanly and caches nothing partial, so
    the retry recomputes from scratch. Returns None when the shape doesn't
    apply (caller falls back to the materialized path)."""
    import numpy as np

    from ..exceptions import HyperspaceException
    from ..ops import bucket_join as bj
    from ..ops.aggregate import StreamAggregator, _empty_result, direct_stream_hint
    from ..telemetry.profiling import StageTimings, record_join_stages
    from . import io as engine_io
    from . import physical as phys

    try:
        left, l_starts = join_exec.left.execute_concat(ctx)
        right, r_starts = join_exec.right.execute_concat(ctx)
    except HyperspaceException:
        return None
    if left.num_rows == 0 or right.num_rows == 0:
        return None  # the materialized fallback is trivially cheap here
    if (
        ctx.session is not None
        and ctx.session.mesh_for(left.num_rows + right.num_rows) is not None
    ):
        return None  # the sharded probe owns mesh-scale execution

    group_keys = agg_exec.group_keys
    src_keys = _resolve_source_columns(left, right, chain, group_keys)
    hint = (
        direct_stream_hint(src_keys, agg_exec.aggs) if src_keys is not None else None
    )
    if hint is None and not _float_fold_free(agg_exec, left, right, chain):
        # The record-merge carry would reorder a float fold even at one
        # chunk; without the direct-cells hint those shapes stay
        # materialized (always byte-identical).
        return None

    stages = StageTimings(mode="join-stream")
    subkey = phys._pair_subkey(
        join_exec.left_keys,
        join_exec.right_keys,
        join_exec.left,
        join_exec.right,
        left,
        right,
    )
    rows_key = phys._pair_rows_key(join_exec.left, join_exec.right, ctx)

    # Warm path: an earlier query (count/collect/materialized aggregate) on
    # these rows already cached the VERIFIED pairs — start at the gathers.
    verified, cached = phys._peek_two_table("pairs", left, right, subkey, rows_key)
    from ..telemetry import tracing

    tracing.set_attr("pairs_memo", "hit" if verified else "miss")
    plan = ranges = None
    ranges_hit = False
    if verified:
        li_all, ri_all = cached
    else:
        with stages.timed("pad"):
            plan = phys._classed_plan_cached(
                join_exec, left, right, l_starts, r_starts, subkey, rows_key
            )
        ranges_hit, ranges = phys._peek_two_table(
            "pairs", left, right, ("cprobe", plan.mode) + subkey, rows_key
        )
        if not ranges_hit:
            with stages.timed("probe"):
                ranges = bj.probe_classed(plan)
        with stages.timed("expand"):
            li_all, ri_all = bj.classed_pairs(plan, ranges)

    agg = StreamAggregator(group_keys, agg_exec.aggs, stages=stages, direct_hint=hint)

    n = int(len(li_all))
    chunk_rows = join_chunk_rows()
    slices = [
        (lo, min(lo + chunk_rows, n)) for lo in range(0, n, chunk_rows)
    ] or [(0, 0)]
    lkeys, rkeys = join_exec.left_keys, join_exec.right_keys
    verified_parts: List[tuple] = []
    template: Optional[Table] = None

    def build_chunk(lo: int, hi: int):
        from .physical import WithColumnExec, _assemble_join, _verify_pairs

        li_c, ri_c = li_all[lo:hi], ri_all[lo:hi]
        if not verified:
            with stages.timed("verify"):
                li_c, ri_c = _verify_pairs(left, right, lkeys, rkeys, li_c, ri_c)
        with stages.timed("gather"):
            t = _assemble_join(left, right, li_c, ri_c, "inner")
        with stages.timed("eval"):
            for op in reversed(chain):  # innermost (closest to the join) first
                t = (
                    op._apply(t)
                    if isinstance(op, WithColumnExec)
                    else t.select(op.column_names)
                )
        return li_c, ri_c, t

    none_idx = np.empty(0, np.int64)

    def consume(res) -> None:
        nonlocal template
        li_c, ri_c, t = res
        if not verified:
            verified_parts.append((li_c, ri_c))
        if template is None:
            template = t.take(none_idx)
        agg.add_chunk(t)

    from .. import resilience

    workers = min(2, engine_io.decode_pool_size(len(slices)))
    if workers <= 1 or len(slices) == 1:
        for lo, hi in slices:
            # Pair-chunk-boundary cancellation: a mid-stream deadline (like a
            # mid-stream fault) propagates cleanly — the memos below are
            # populated only after EVERY chunk streamed successfully.
            resilience.check_deadline("query.join_stream")
            consume(build_chunk(lo, hi))
    else:
        from collections import deque
        from concurrent.futures import ThreadPoolExecutor

        from ..telemetry import accounting as _accounting

        # Workers adopt the submitting query's ledger and deadline scope
        # (the io.py pool contract): without this, chunk work on pool
        # threads — including any XLA compiles its device programs trigger —
        # billed to NOTHING instead of the query that caused it.
        led = _accounting.current_ledger()
        sc = resilience.current_scope()

        def build_chunk_adopted(lo: int, hi: int):
            with _accounting.use_ledger(led), resilience.use_scope(sc):
                return build_chunk(lo, hi)

        pool = ThreadPoolExecutor(max_workers=workers)
        try:
            pending: "deque" = deque()
            i = 0
            while i < len(slices) or pending:
                resilience.check_deadline("query.join_stream")
                # Depth-bounded: at most workers+1 chunks in flight keeps
                # resident chunk memory bounded while the NEXT chunk's
                # verify/gather overlaps this one's aggregator fold.
                while i < len(slices) and len(pending) < workers + 1:
                    pending.append(pool.submit(build_chunk_adopted, *slices[i]))
                    i += 1
                consume(pending.popleft().result())
        finally:
            pool.shutdown(wait=False, cancel_futures=True)

    # EVERY chunk streamed successfully: NOW (and only now) populate the
    # memos, so warm queries — streamed or materialized, counts included —
    # start from the verified pairs exactly as after a materialized run.
    if not verified:
        if verified_parts:
            li_v = np.concatenate([p[0] for p in verified_parts])
            ri_v = np.concatenate([p[1] for p in verified_parts])
        else:
            li_v = ri_v = np.empty(0, np.int64)
        phys._cached_two_table(
            "pairs", left, right, subkey, lambda: (li_v, ri_v), rows_key=rows_key
        )
        if plan is not None and ranges is not None and not ranges_hit:
            phys._cached_two_table(
                "pairs",
                left,
                right,
                ("cprobe", plan.mode) + subkey,
                lambda: ranges,
                rows_key=rows_key,
            )

    out = agg.finalize()
    if out is None:
        if template is None:
            return None
        out = _empty_result(template, group_keys, agg_exec.aggs)
    summary = stages.summary()
    summary.update(
        {
            "chunks": agg.chunks,
            "pairs": n,
            "groups": out.num_rows,
            "direct_cells": hint is not None,
            "classes": None if plan is None else len(plan.segments),
            "outliers": None if plan is None else int(len(plan.outlier_ids)),
            "join_mode": None if plan is None else plan.mode,
        }
    )
    record_join_stages(summary)
    return out


# ---------------------------------------------------------------------------
# Streamed multiway star-join → aggregate: probe EVERY dimension's covering
# index per fact chunk and fold survivor compositions straight into the
# aggregator — the cascaded plan's intermediate fact never materializes
# ---------------------------------------------------------------------------


def _star_agg_input_dtype(name: str, out_names, chain):
    """Declared dtype of one aggregate input over the star output: the
    shadowing withColumn's DECLARED dtype when the chain computes it, else
    the source column's dtype; None when unresolvable."""
    from .physical import WithColumnExec

    for op in chain:
        if isinstance(op, WithColumnExec) and op.col_name.lower() == name.lower():
            return op.dtype
    cols = _resolve_named_columns(out_names, (), [name])
    return cols[0].dtype if cols is not None else None


def _star_float_fold_free(agg_exec, out_names, chain) -> bool:
    """Star twin of `_float_fold_free`: every sum/avg input provably
    non-float — integer partial states accumulate exactly, so the chunked
    fold equals the one-pass fold bitwise regardless of chunk boundaries.
    Float sums stream only through the direct-cells hint (same admission and
    same documented rounding contract as the binary streamed join)."""
    for _out, fn, cname in agg_exec.aggs:
        if fn in ("sum", "avg") and cname is not None:
            dtype = _star_agg_input_dtype(cname, out_names, chain)
            if dtype is None or dtype in ("float32", "float64"):
                return False
    return True


def stream_star_aggregate(agg_exec, star_exec, chain, ctx) -> Optional[Table]:
    """Run a `HashAggregateExec` over a recognized N-way star join as a
    chunk-carry stream. Per DIMENSION (once, up front): hash the fact's FK
    columns into that dimension's bucket space, lay the fact out in bucket
    order on the fly (`bucket_join.fact_bucket_layout`), build the joint
    size-classed plan against the dimension's covering-index concat
    (`build_classed_plan` — padding classes and outlier handling intact),
    probe, expand and exactly verify — yielding the dimension's match list
    in fact-major order. Per FACT CHUNK: compose every dimension's match
    counts into the output row count (the product), enumerate compositions
    with a per-row odometer, gather fact + all dimension payloads, evaluate
    the WithColumn/Project chain, and fold into `StreamAggregator` — with
    the direct-address cells fast path when the source group keys qualify.
    The intermediate fact of the cascaded plan never materializes.

    Per-dimension verified matches ride the engine pair memos keyed
    ``("star",) + pair_subkey`` — inserted ONLY after every chunk streamed
    successfully, so a mid-stream fault caches nothing partial. Returns None
    when the shape doesn't apply (the caller falls through and the
    `MultiwayJoinExec` executes its byte-identical cascade)."""
    import time

    import numpy as np

    from ..exceptions import HyperspaceException
    from ..ops import bucket_join as bj
    from ..ops.aggregate import StreamAggregator, _empty_result, direct_stream_hint
    from ..ops.backend import use_device_path
    from ..ops.hashing import bucket_id
    from ..telemetry.profiling import StageTimings, record_join_stages
    from . import physical as phys
    from .encoded_device import stage_codes

    try:
        fact = star_exec.fact.execute(ctx)
        dim_sides = []
        for dim_exec, fkeys, dkeys, index_name, num_buckets in star_exec.dims:
            dt, d_starts = dim_exec.execute_concat(ctx)
            dim_sides.append(
                (dim_exec, fkeys, dkeys, index_name, num_buckets, dt, d_starts)
            )
    except HyperspaceException:
        return None
    if fact.num_rows == 0 or any(s[5].num_rows == 0 for s in dim_sides):
        return None  # the cascaded fallback is trivially cheap here
    total_rows = fact.num_rows + sum(s[5].num_rows for s in dim_sides)
    if ctx.session is not None and ctx.session.mesh_for(total_rows) is not None:
        return None  # the sharded probe owns mesh-scale execution

    group_keys = agg_exec.group_keys
    out_names = star_output_columns(fact, [s[5] for s in dim_sides])
    src_keys = _resolve_named_columns(out_names, chain, group_keys)
    hint = (
        direct_stream_hint(src_keys, agg_exec.aggs) if src_keys is not None else None
    )
    if hint is None and not _star_float_fold_free(agg_exec, out_names, chain):
        # Same admission as the binary streamed join: without the
        # direct-cells hint, a float fold through the record-merge carry
        # would reorder — those shapes stay on the cascade (byte-identical).
        return None

    stages = StageTimings(mode="star-stream")
    n_fact = fact.num_rows
    per_dim = []  # (dim_table, counts, match_starts, ri_fact_major) per dim
    dim_stats: List[dict] = []
    memo_todo: List[tuple] = []

    for dim_exec, fkeys, dkeys, index_name, num_buckets, dt, d_starts in dim_sides:
        t0 = time.monotonic()
        subkey = ("star",) + phys._pair_subkey(
            list(fkeys), list(dkeys), star_exec.fact, dim_exec, fact, dt
        )
        rows_key = phys._pair_rows_key(star_exec.fact, dim_exec, ctx)
        hit, cached = phys._peek_two_table("pairs", fact, dt, subkey, rows_key)
        if hit:
            li, ri = cached
            stat = {
                "index": index_name,
                "buckets": int(num_buckets),
                "pairs": int(len(li)),
                "memo": "hit",
            }
        else:
            with stages.timed("pad"):
                # The fact was never bucket-partitioned on THIS dimension's
                # keys: hash its FK columns into the dimension's bucket
                # space (the exact build-time hash — narrow string codes
                # hash via dh_table[codes], so values agree) and lay it out
                # in bucket order on the fly.
                fk_cols = [fact.column(k) for k in fkeys]
                bid = np.asarray(
                    bucket_id(
                        fk_cols,
                        [stage_codes(c, "star_probe") for c in fk_cols],
                        num_buckets,
                    )
                )
                perm, f_starts = bj.fact_bucket_layout(bid, num_buckets)
                l_flags, r_flags = phys._joint_float_flags(
                    fact, dt, list(fkeys), list(dkeys)
                )
                l_vals = np.asarray(
                    phys._table_key64(fact, list(fkeys), l_flags)
                )[perm]
                r_vals = np.asarray(phys._table_key64(dt, list(dkeys), r_flags))
                plan = bj.build_classed_plan(
                    l_vals,
                    r_vals,
                    f_starts,
                    np.asarray(d_starts, np.int64),
                    "hash",
                    device=use_device_path(),
                )
            pad_s = time.monotonic() - t0
            t1 = time.monotonic()
            with stages.timed("probe"):
                ranges = bj.probe_classed(plan)
            with stages.timed("expand"):
                pli, ri = bj.classed_pairs(plan, ranges)
            li = perm[pli]  # bucket-layout positions → original fact rows
            probe_s = time.monotonic() - t1
            t2 = time.monotonic()
            with stages.timed("verify"):
                li, ri = phys._verify_pairs(
                    fact, dt, list(fkeys), list(dkeys), li, ri
                )
            # Fact-major order (stable: within one fact row, matches keep
            # the deterministic bucket-major probe order) — the layout the
            # per-chunk odometer composes from.
            order = np.argsort(li, kind="stable")
            li, ri = li[order], ri[order]
            verify_s = time.monotonic() - t2
            memo_todo.append((dt, subkey, rows_key, li, ri))
            stat = {
                "index": index_name,
                "buckets": int(num_buckets),
                "pairs": int(len(li)),
                "memo": "miss",
                "pad_s": round(pad_s, 5),
                "probe_s": round(probe_s, 5),
                "verify_s": round(verify_s, 5),
            }
        counts = np.bincount(li, minlength=n_fact).astype(np.int64)
        mstarts = np.zeros(n_fact + 1, np.int64)
        np.cumsum(counts, out=mstarts[1:])
        per_dim.append((dt, counts, mstarts, ri))
        dim_stats.append(stat)

    ndims = len(per_dim)
    # Output rows per fact row = the product of its per-dimension match
    # counts (the star's survivor composition); chunk boundaries slice FACT
    # rows so each chunk's output stays near the join chunk budget.
    K = per_dim[0][1].copy()
    for _dt, counts, _ms, _ri in per_dim[1:]:
        K = K * counts
    out_starts = np.zeros(n_fact + 1, np.int64)
    np.cumsum(K, out=out_starts[1:])
    total_pairs = int(out_starts[-1])
    chunk_rows = join_chunk_rows()
    bounds = [0]
    while bounds[-1] < n_fact:
        lo = bounds[-1]
        hi = (
            int(
                np.searchsorted(
                    out_starts, out_starts[lo] + chunk_rows, side="right"
                )
            )
            - 1
        )
        bounds.append(min(max(hi, lo + 1), n_fact))

    agg = StreamAggregator(
        group_keys, agg_exec.aggs, stages=stages, direct_hint=hint
    )

    def build_chunk(lo: int, hi: int) -> Table:
        from .physical import WithColumnExec

        Kc = K[lo:hi]
        nz = np.nonzero(Kc)[0]
        rows = nz + lo
        Kr = Kc[nz]
        tot = int(Kr.sum())
        with stages.timed("gather"):
            if tot == 0:
                fact_idx = np.empty(0, np.int64)
                sels = [np.empty(0, np.int64)] * ndims
            else:
                fact_idx = np.repeat(rows, Kr)
                ends = np.cumsum(Kr)
                off = np.arange(tot, dtype=np.int64) - np.repeat(ends - Kr, Kr)
                # Per-row odometer over the dimensions (last dim varies
                # fastest): composition j of fact row i selects match
                # (j // stride_d) % count_d from each dimension's list.
                strides: List = [None] * ndims
                stride = np.ones(len(rows), np.int64)
                for d in range(ndims - 1, -1, -1):
                    strides[d] = stride
                    stride = stride * per_dim[d][1][rows]
                sels = []
                for d in range(ndims):
                    st_e = np.repeat(strides[d], Kr)
                    cnt_e = np.repeat(per_dim[d][1][rows], Kr)
                    sels.append((off // st_e) % cnt_e)
            parts = [fact.take(fact_idx)]
            for d in range(ndims):
                dt_d, _counts, mstarts_d, ri_d = per_dim[d]
                if tot == 0:
                    dim_idx = np.empty(0, np.int64)
                else:
                    dim_idx = ri_d[np.repeat(mstarts_d[rows], Kr) + sels[d]]
                parts.append(dt_d.take(dim_idx))
        with stages.timed("eval"):
            cols = {}
            for p in parts:
                for n, c in p.columns.items():
                    cols[n if n not in cols else f"{n}_r"] = c
            t = Table(cols)
            for op in reversed(chain):  # innermost (closest to the join) first
                t = (
                    op._apply(t)
                    if isinstance(op, WithColumnExec)
                    else t.select(op.column_names)
                )
        return t

    from .. import resilience

    template: Optional[Table] = None
    none_idx = np.empty(0, np.int64)
    n_chunks = 0
    for lo, hi in zip(bounds, bounds[1:]):
        # Chunk-boundary cancellation: a mid-stream deadline (like a
        # mid-stream fault) propagates cleanly — the memos below are
        # populated only after EVERY chunk streamed successfully.
        resilience.check_deadline("query.star_stream")
        t = build_chunk(lo, hi)
        if template is None:
            template = t.take(none_idx)
        n_chunks += 1
        agg.add_chunk(t)

    # EVERY chunk streamed successfully: NOW (and only now) populate the
    # per-dimension pair memos, so warm star queries start at composition.
    for dt_m, subkey, rows_key, li_v, ri_v in memo_todo:
        phys._cached_two_table(
            "pairs",
            fact,
            dt_m,
            subkey,
            lambda li_v=li_v, ri_v=ri_v: (li_v, ri_v),
            rows_key=rows_key,
        )

    out = agg.finalize()
    if out is None:
        if template is None:
            return None
        out = _empty_result(template, group_keys, agg_exec.aggs)
    summary = stages.summary()
    summary.update(
        {
            "chunks": n_chunks,
            "pairs": total_pairs,
            "groups": out.num_rows,
            "direct_cells": hint is not None,
            "join_mode": "star",
            "star_dims": dim_stats,
        }
    )
    record_join_stages(summary)
    return out
