"""TPU-native columnar query engine (the Spark replacement layer).

x64 is enabled at import: lakehouse data is routinely int64 (ids, timestamps), and the
engine's join keys are 64-bit hashes. XLA:TPU lowers s64 vector ops; f64 columns are
computed in f64 on CPU and may be downcast on TPU backends without f64 support.
"""

import jax as _jax

_jax.config.update("jax_enable_x64", True)

from .expr import BinaryOp, Col, Expr, IsIn, Lit, Not, Udf, col, lit, udf  # noqa: F401,E402
from .logical import (  # noqa: F401,E402
    BucketSpec,
    FilterNode,
    JoinNode,
    LogicalPlan,
    ProjectNode,
    ScanNode,
    SourceRelation,
)
from .schema import Field, Schema  # noqa: F401,E402
from .session import DataFrame, DataFrameReader, HyperspaceSession  # noqa: F401,E402
from .table import Column, Table  # noqa: F401,E402
