"""Physical plan + executor.

The engine analogue of Spark's SparkPlan/physical operators, executed with JAX device
ops. Operator names matter: the explain subsystem counts them to show what a rewrite
eliminated (`PhysicalOperatorAnalyzer.scala:30-57` counts `ShuffleExchange` removed),
and the E2E tests assert which files a scan touched.

Join strategy (TPU-first):
- General equi-join: ShuffleExchange markers on both sides + a global hash-key
  sort-merge (`ops.join.merge_join_pairs` over `ops.hashing.key64`), with exact
  re-verification of key equality so hash collisions cannot corrupt results.
- Co-bucketed index join (set up by the join rewrite rule): both sides arrive
  hash-partitioned into the same number of buckets on the join keys, so the merge runs
  per bucket pair with NO exchange — the whole point of the covering-index design
  (reference `JoinIndexRule.scala:137-162`). On a device mesh the bucket axis shards
  with zero cross-device traffic.
"""

from __future__ import annotations

import contextlib
import os
import re
from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..exceptions import CorruptIndexError, HyperspaceException
from ..ops.hashing import key64
from ..ops.join import merge_join_pairs, nonzero_indices
from ..telemetry import metrics as _metrics
from ..telemetry import tracing as _tracing
from . import io as engine_io
from .device_cache import device_array
from .evaluate import evaluate_predicate
from .expr import Col, Expr, extract_equi_join_keys
from .logical import (
    AggregateNode,
    BucketSpec,
    ExceptNode,
    FilterNode,
    IntersectNode,
    JoinNode,
    LimitNode,
    LogicalPlan,
    OrderByNode,
    ProjectNode,
    ScanNode,
    SourceRelation,
    StarJoinNode,
    UnionNode,
    WithColumnNode,
)
from .schema import Schema
from .table import Column, Table, align_dictionaries

_BUCKET_FILE_RE = re.compile(r"part-(\d+)")


class ExecContext:
    def __init__(self, session=None):
        self.session = session
        # The adaptive planner's decisions for this query (None when the
        # planner is off or nothing was decided) — captured at construction
        # so physical operators hold the same object the ambient gates read.
        from ..plananalysis.planner import current_decisions

        self.plan_decisions = current_decisions()


_footer_count_cache: Dict[tuple, int] = {}


def _footer_row_count(files, file_format: str) -> Optional[int]:
    """Total row count from parquet footers — no column decode, no device work
    (the analogue of Spark's metadata-only count). None for non-parquet formats
    (CSV/JSON carry no row-count metadata)."""
    if file_format not in ("parquet", "delta"):
        return None
    import pyarrow.parquet as pq

    total = 0
    for f in files:
        key = (f.path, f.size, f.modified_time)
        hit = _footer_count_cache.get(key)
        if hit is None:
            try:
                hit = pq.ParquetFile(f.path).metadata.num_rows
            except Exception:
                return None
            _footer_count_cache[key] = hit
        total += hit
    return total


def _traced_node_method(kind: str, fn):
    """Wrap one executor entry point (`execute` / `execute_count` /
    `execute_concat`) in a query-trace span. While tracing is inactive the
    wrapper is one predicate check — no span, no allocation, no device work
    (the acceptance bar: tracing off must not move the warm p50s). While
    active, the span records the operator (`op:<name>`), the node identity
    (`node_id` — what `explain(analyze=True)` joins the rendered tree on),
    and the output row count."""
    import functools

    @functools.wraps(fn)
    def wrapper(self, ctx):
        if not _tracing.active():
            return fn(self, ctx)
        with _tracing.span(
            f"op:{self.name}", node_id=id(self), op=self.simple_string(), kind=kind
        ) as sp:
            out = fn(self, ctx)
            rows = getattr(out, "num_rows", None)
            if rows is None and isinstance(out, tuple) and out:
                rows = getattr(out[0], "num_rows", None)  # execute_concat
            if rows is None and isinstance(out, int):
                rows = out  # execute_count
            if rows is not None:
                sp.set_attr("rows_out", int(rows))
            return out

    wrapper._hyperspace_traced = True
    return wrapper


class PhysicalNode:
    name = "Physical"

    def __init_subclass__(cls, **kwargs):
        # Every operator's executor entry points are span-wrapped at class
        # creation, so per-operator tracing needs no edits in the operators
        # themselves (and new operators inherit it automatically).
        super().__init_subclass__(**kwargs)
        for m in ("execute", "execute_count", "execute_concat"):
            fn = cls.__dict__.get(m)
            if callable(fn) and not getattr(fn, "_hyperspace_traced", False):
                setattr(cls, m, _traced_node_method(m, fn))

    def children(self) -> Sequence["PhysicalNode"]:
        return ()

    def execute(self, ctx: ExecContext) -> Table:
        raise NotImplementedError

    def execute_count(self, ctx: ExecContext) -> int:
        """Row count of this node's output. Default materializes; operators whose
        count is knowable without assembling the output (scans via parquet
        footers, joins via verified pair counts, projections) override."""
        return self.execute(ctx).num_rows

    def simple_string(self) -> str:
        return self.name

    def format_line(self, indent: int) -> str:
        """One tree line for this node — the single source of the tree format (the
        explain renderer reuses it for highlight-aware output)."""
        return "  " * indent + ("+- " if indent else "") + self.simple_string()

    def tree_string(self, indent: int = 0) -> str:
        lines = [self.format_line(indent)]
        for c in self.children():
            lines.append(c.tree_string(indent + 1))
        return "\n".join(lines)

    def collect_nodes(self) -> List["PhysicalNode"]:
        out: List[PhysicalNode] = [self]
        for c in self.children():
            out.extend(c.collect_nodes())
        return out


def _resolution_case_sensitive(ctx, schema_names) -> bool:
    """Effective case sensitivity for pushdown conjunct resolution: the
    session conf, FORCED case-sensitive when the schema case-collides (the
    same guard as `FilterExec._condition_key` — with both 'X' and 'x'
    present, resolution is exact-match-first and the spellings read
    different columns)."""
    cs = (
        ctx.session.hs_conf.case_sensitive
        if ctx is not None and ctx.session is not None
        else False
    )
    if len({n.lower() for n in schema_names}) != len(schema_names):
        cs = True
    return cs


def _set_pruning_attrs(stats: Dict[str, int]) -> None:
    """Surface one scan's row-group pruning outcome on the current trace span
    (rendered by explain(analyze=True)); no-op when nothing pruned."""
    if not stats:
        return
    _tracing.set_attr("row_groups_scanned", int(stats.get("row_groups_scanned", 0)))
    _tracing.set_attr("row_groups_skipped", int(stats.get("row_groups_skipped", 0)))


@contextlib.contextmanager
def _corruption_guard(relation: SourceRelation):
    """Decode failures on INDEX data files (truncated/corrupt bucket files,
    vanished files) re-raise as `CorruptIndexError` carrying the index name —
    the signal `DataFrame.collect/count` quarantines on and re-plans around
    (source-scan fallback, results stay correct). Classified framework errors
    pass through unchanged: an injected transient fault that exhausted its
    retries, a query timeout, or a blown retry budget is NOT corruption and
    must fail the query, not condemn the index. Classification is limited to
    DECODE-LAYER error types: the pyarrow exception family (ArrowInvalid ⊂
    ValueError, ArrowIOError ⊂ OSError, but ArrowTypeError ⊂ TypeError — the
    whole family counts, a failed index decode is a failed index decode)
    plus plain ValueError/OSError/EOFError — a MemoryError or an engine bug
    (bare TypeError, ...) must surface raw, never masquerade as a corrupt
    index."""
    try:
        yield
    except HyperspaceException:
        raise
    except Exception as e:
        import pyarrow as pa

        decode_layer = isinstance(
            e, (ValueError, OSError, EOFError, pa.lib.ArrowException)
        )
        if relation.index_name and decode_layer:
            raise CorruptIndexError(
                f"index '{relation.index_name}' data failed to decode "
                f"({type(e).__name__}: {e})",
                index_name=relation.index_name,
            ) from e
        raise


def _default_scan_columns(relation: SourceRelation, columns):
    """Effective column list when `columns` is None ("everything"): for an
    INDEX relation, "everything" means the VISIBLE schema — the internal
    lineage column is read only when explicitly requested (the planner pushes
    it for a delete-prune filter's condition; the logical `ScanNode`
    output_schema hides it from every other consumer). None = no lineage in
    the schema: keep the plain read-all path."""
    if columns is not None or not relation.index_name:
        return columns
    from .logical import internal_column

    names = relation.schema.names
    visible = [n for n in names if not internal_column(n)]
    return visible if len(visible) != len(names) else None


class ScanExec(PhysicalNode):
    name = "Scan"

    def __init__(self, relation: SourceRelation, columns: Optional[List[str]] = None):
        self.relation = relation
        self.columns = columns
        #: Conjunctive filter of the FilterExec DIRECTLY above this scan, set
        #: by the planner. Purely advisory pruning: with it set, execute /
        #: execute_stream may omit rows the predicate provably rejects (the
        #: owning filter drops them anyway), by skipping parquet row groups
        #: whose footer zone maps exclude the conjuncts. execute_count keeps
        #: reporting the FULL file row count — the owning filter never counts
        #: through the scan.
        self.pushdown: Optional[Expr] = None

    def _pushdown_pred(self, ctx):
        """The compiled `ScanPredicate`, or None whenever pushdown cannot
        apply (disabled, non-parquet, bucketed/hybrid relation, or no
        prunable conjunct)."""
        if self.pushdown is None:
            return None
        rel = self.relation
        if rel.file_format not in ("parquet", "delta"):
            return None
        if rel.hybrid_append is not None or rel.bucket_spec is not None:
            return None
        from .pushdown import ScanPredicate, pushdown_enabled

        if not pushdown_enabled():
            return None
        return ScanPredicate.from_condition(
            self.pushdown, _resolution_case_sensitive(ctx, rel.schema.names)
        )

    def execute(self, ctx) -> Table:
        if self.relation.hybrid_append is not None and self.relation.bucket_spec is not None:
            # Demoted bucketed index scan (general join path / plain read): still must
            # merge the hybrid-appended rows.
            return BucketedIndexScanExec(self.relation, self.columns).execute(ctx)
        cols = _default_scan_columns(self.relation, self.columns)
        files = [f.path for f in self.relation.files]
        if not files:
            # Every file pruned (data skipping) or an empty source: empty table.
            names = cols or self.relation.schema.names
            return Table(
                {n: _empty_column(self.relation.schema.field(n).dtype) for n in names}
            )
        partitions = None
        if self.relation.partition_spec is not None:
            partitions = (self.relation.partition_spec, self.relation.root_paths)
        stats: Dict[str, int] = {}
        with _corruption_guard(self.relation):
            out = engine_io.read_files(
                files,
                self.relation.file_format,
                cols,
                partitions=partitions,
                pushdown=self._pushdown_pred(ctx),
                pruning_stats=stats,
            )
        _set_pruning_attrs(stats)
        return out

    def execute_count(self, ctx) -> int:
        rel = self.relation
        if rel.hybrid_append is not None and rel.bucket_spec is not None:
            return BucketedIndexScanExec(rel, self.columns).execute_count(ctx)
        n = _footer_row_count(rel.files, rel.file_format)
        return n if n is not None else self.execute(ctx).num_rows

    def can_stream(self) -> bool:
        """Whether this scan can feed the streaming executor: a plain file
        read (a demoted bucketed scan with hybrid-appended rows must merge
        buckets, which is whole-scan work)."""
        return self.relation.hybrid_append is None and bool(self.relation.files)

    def execute_stream(self, ctx, stages=None):
        """Ordered chunk iterator over this scan: per-file tables (decoded on
        the shared pool ahead of the consumer, through the per-column scan
        cache) split into row chunks. Chunk boundaries never change values or
        concat order, so consuming this stream through `Table.concat` equals
        `execute` exactly. With a pushdown predicate, the per-file tables
        carry only the surviving row groups — chunks align to them and
        pruned bytes never enter the stream."""
        from .streaming import query_chunk_rows, split_chunks

        cols = _default_scan_columns(self.relation, self.columns)
        files = [f.path for f in self.relation.files]
        partitions = None
        if self.relation.partition_spec is not None:
            partitions = (self.relation.partition_spec, self.relation.root_paths)
        on_decode = None if stages is None else (lambda s: stages.add("decode", s))
        chunk_rows = query_chunk_rows()
        stats: Dict[str, int] = {}
        with _corruption_guard(self.relation):
            for t in engine_io.iter_file_tables(
                files,
                self.relation.file_format,
                cols,
                partitions,
                on_decode=on_decode,
                pushdown=self._pushdown_pred(ctx),
                pruning_stats=stats,
            ):
                for ch in split_chunks(t, chunk_rows):
                    yield ch
        _set_pruning_attrs(stats)

    def simple_string(self):
        cols = f" [{', '.join(self.columns)}]" if self.columns else ""
        tag = f" index={self.relation.index_name}" if self.relation.index_name else ""
        if self.relation.pruned_by:
            tag += f" (files pruned by {','.join(self.relation.pruned_by)})"
        return f"Scan{tag} {','.join(self.relation.root_paths)}{cols}"


class BucketedIndexScanExec(PhysicalNode):
    """Reads index data preserving bucket structure (list of per-bucket tables).

    Only appears under a SortMergeJoinExec in bucketed mode; its bucket ids come from
    the `part-<bucket>` file naming contract of the bucketed writer."""

    name = "BucketedIndexScan"

    def __init__(self, relation: SourceRelation, columns: Optional[List[str]] = None):
        assert relation.bucket_spec is not None
        self.relation = relation
        self.columns = columns

    def _assemble_buckets(self, read_one) -> List[Optional[Table]]:
        """Per-bucket tables from this scan's `part-<bucket>` files, each
        file's table produced by `read_one(path)` — THE bucket-assembly loop
        (file order, bucket-id parse, per-bucket concat), shared by the plain
        and row-group-pruned paths so their row order can never diverge."""
        spec = self.relation.bucket_spec
        buckets: List[Optional[Table]] = [None] * spec.num_buckets
        for f in self.relation.files:
            m = _BUCKET_FILE_RE.search(os.path.basename(f.path))
            if m is None:
                raise HyperspaceException(f"Not a bucketed index file: {f.path}")
            b = int(m.group(1))
            t = read_one(f.path)
            buckets[b] = t if buckets[b] is None else Table.concat([buckets[b], t])
        return buckets

    @staticmethod
    def _concat_with_starts(buckets, empty_table) -> Tuple[Table, np.ndarray]:
        """One contiguous table + bucket start offsets from per-bucket tables
        — shared tail of the plain and pruned concats."""
        sizes = [0 if t is None else t.num_rows for t in buckets]
        starts = np.zeros(len(buckets) + 1, dtype=np.int64)
        np.cumsum(sizes, out=starts[1:])
        tables = [t for t in buckets if t is not None and t.num_rows > 0]
        return (Table.concat(tables) if tables else empty_table()), starts

    def execute_buckets(self, ctx) -> List[Optional[Table]]:
        cols = _default_scan_columns(self.relation, self.columns)
        # Cold reads: decode every cache-cold bucket file on the shared pool
        # FIRST (pyarrow releases the GIL), then assemble serially from the
        # warm cache — r05 measured 1.34 s of a 1.35 s cold indexed read in
        # back-to-back single-threaded bucket-file decodes here.
        with _corruption_guard(self.relation):
            engine_io.warm_file_cache(
                [f.path for f in self.relation.files], self.relation.file_format, cols
            )
            buckets = self._assemble_buckets(
                lambda p: engine_io.read_files([p], self.relation.file_format, cols)
            )
        if self.relation.hybrid_append is not None:
            self._merge_appended(buckets)
        return buckets

    def _merge_appended(self, buckets: List[Optional[Table]]) -> None:
        """Hybrid Scan shuffle-union: bucketize the appended source rows with the
        index's own partitioning (same hash, same bucket count) and merge them into
        the bucket tables — the on-the-fly analogue of the index build, so the
        co-bucketed join stays correct with no shuffle of the INDEX data."""
        from ..config import IndexConstants
        from ..ops.partition import bucketize_table

        from .logical import internal_column

        ha = self.relation.hybrid_append
        spec = self.relation.bucket_spec
        wanted = (
            _default_scan_columns(self.relation, self.columns)
            or self.relation.schema.names
        )
        source_cols = [c for c in wanted if not internal_column(c)]
        partitions = None
        if ha.partition_spec is not None:
            partitions = (ha.partition_spec, ha.root_paths)
        # Appended source files re-read per query (their bucketization depends
        # on query-time state): decode the cold ones on the shared pool.
        # These are LAKE decodes, so they ride the PR-7 resilience contract
        # like every other lake-touching site: the per-file reads retry
        # transient faults inside `engine_io` (`retry_io("io.decode", …)` at
        # the decode funnels), and the per-file loop is a deadline boundary —
        # a deadlined query stops between appended files instead of decoding
        # the whole delta first.
        from .. import resilience as _resilience

        engine_io.warm_file_cache(
            [f.path for f in ha.files],
            ha.file_format,
            engine_io.file_columns_for(source_cols, partitions),
        )
        parts = []
        for f in ha.files:
            _resilience.check_deadline("hybrid.merge_appended")
            t = engine_io.read_files(
                [f.path], ha.file_format, source_cols, partitions=partitions
            )
            internal = [c for c in wanted if internal_column(c)]
            if internal:
                lineage_col = internal[0]  # the scan's requested spelling
                cols = dict(t.columns)
                cols[lineage_col] = Table.from_pydict(
                    {lineage_col: [f.path] * t.num_rows}
                ).column(lineage_col)
                t = Table(cols)
            parts.append(t)
        appended = Table.concat(parts) if len(parts) > 1 else parts[0]
        appended = appended.select(wanted)
        sorted_t, starts = bucketize_table(
            appended, list(spec.bucket_columns), spec.num_buckets
        )
        for b in range(spec.num_buckets):
            lo, hi = int(starts[b]), int(starts[b + 1])
            if hi <= lo:
                continue
            part = sorted_t.take(np.arange(lo, hi))
            buckets[b] = part if buckets[b] is None else Table.concat([buckets[b], part])

    def execute_pruned_concat(self, ctx, condition) -> Optional[Tuple[Table, np.ndarray]]:
        """Row-group-PRUNED concat of this bucketed scan under `condition`:
        each `part-<bucket>` file decodes only the row groups whose footer
        zone maps can satisfy the condition (the build writes buckets with
        bounded, key-sorted row groups precisely so equality/range filters
        resolve inside a bucket file). Returns (table, starts) over the
        SURVIVING rows — a row-subset of `execute_concat`'s table in the same
        order, so applying the condition afterwards yields byte-identical
        rows and bucket boundaries.

        None whenever the pruned path cannot apply (pushdown disabled,
        hybrid-appended rows, unreadable footers, no prunable conjunct, or
        nothing actually pruned) — the caller then takes the plain path,
        which also populates the full bucketed-concat cache exactly as
        before."""
        from .pushdown import ScanPredicate, pushdown_enabled

        rel = self.relation
        if not pushdown_enabled() or rel.hybrid_append is not None:
            return None
        if rel.file_format not in ("parquet", "delta"):
            return None
        pred = ScanPredicate.from_condition(
            condition, _resolution_case_sensitive(ctx, rel.schema.names)
        )
        if pred is None:
            return None
        cols = _default_scan_columns(rel, self.columns)
        selections = engine_io._pushdown_selections(
            [f.path for f in rel.files], rel.file_format, pred
        )
        if selections is None:
            return None
        stats: Dict[str, int] = {}
        engine_io._record_pruning(selections, stats)
        sel_of = dict(
            zip([f.path for f in rel.files], selections)
        )
        # Decode the cold (pruned or whole) files on the shared pool first,
        # then assemble serially from the warm cache — the pruned twin of
        # `execute_buckets`' warm_file_cache step.
        with _corruption_guard(rel):
            engine_io.warm_file_cache(
                [f.path for f in rel.files], rel.file_format, cols, selections=sel_of
            )
            buckets = self._assemble_buckets(
                lambda p: engine_io.pruned_file_table(
                    p, rel.file_format, cols, *sel_of[p]
                )
            )
        table, starts = self._concat_with_starts(buckets, self.empty_table)
        # The pruned path never consults the bucketed-concat cache — report
        # that honestly (every cold bucketed scan carries a cache verdict).
        _tracing.set_attr("bucketed_cache", "pruned-bypass")
        _set_pruning_attrs(stats)
        return table, starts

    def empty_table(self) -> Table:
        """Empty table with this scan's (pruned) schema."""
        names = (
            _default_scan_columns(self.relation, self.columns)
            or self.relation.schema.names
        )
        return Table(
            {n: _empty_column(self.relation.schema.field(n).dtype) for n in names}
        )

    def rows_token(self, ctx=None) -> tuple:
        """Identity of this scan's ROW SET AND ORDER, independent of column
        pruning: the index log entry id + the file inventory (+ hybrid-append
        inventory). Two prunings of the same scan concat the same buckets in
        the same order, so join pair indices computed against one apply
        verbatim to the other — the pairs cache keys on this, not on the
        (column-pruned) table identity. The log entry id leads: it advances on
        EVERY refresh/vacuum/optimize, so a rebuilt index can never serve
        stale pair indices even if its rewritten files alias the
        (path, size, mtime-ms) stats of the old ones."""
        ha = self.relation.hybrid_append
        ha_key = ()
        if ha is not None:
            ha_key = (
                tuple((f.path, f.size, f.modified_time) for f in ha.files),
                tuple(ha.root_paths),
            )
        return (
            ("log", self.relation.index_name, getattr(self.relation, "log_entry_id", None)),
            tuple((f.path, f.size, f.modified_time) for f in self.relation.files),
            ha_key,
        )

    def _concat_cache_key(self):
        """Steady-state cache key: the row identity + pruned columns. Any
        change to the source or appended file set changes the key, the same
        freshness contract every scan cache rides."""
        return self.rows_token() + (
            # None (all columns) must not share a key with [] (zero columns).
            ("<all>",) if self.columns is None else tuple(self.columns),
        )

    def execute_concat(self, ctx) -> Tuple[Table, np.ndarray]:
        """The scan as one contiguous table + bucket start offsets (bucket b =
        rows[starts[b]:starts[b+1]]), cached across queries."""
        from .scan_cache import global_bucketed_cache

        key = self._concat_cache_key()
        if key is not None:
            hit = global_bucketed_cache().get(key)
            if hit is not None:
                _tracing.set_attr("bucketed_cache", "hit")
                return hit
            _tracing.set_attr("bucketed_cache", "miss")
        else:
            # key None = the cache was never consulted and the result will
            # not be stored — a rerun can NOT hit, and the annotated tree
            # must not suggest otherwise.
            _tracing.set_attr("bucketed_cache", "uncacheable")

        def _assemble() -> Tuple[Table, np.ndarray]:
            buckets = self.execute_buckets(ctx)
            table, starts = self._concat_with_starts(buckets, self.empty_table)
            if key is not None:
                global_bucketed_cache().put(key, table, starts)
            return table, starts

        if key is None:
            return _assemble()
        # Single-flight over the bucketed-concat entry: two concurrent cold
        # indexed joins re-assemble the per-bucket files once; the follower
        # is served from the entry the leader put (`serve.singleflight`).
        from ..serve import singleflight as _singleflight

        def _reprobe():
            hit = global_bucketed_cache().get(key)
            if hit is not None:
                # Correct the earlier 'miss' stamp: this node did no
                # assembly — it was served by another query's flight.
                _tracing.set_attr("bucketed_cache", "dedup_hit")
            return hit

        return _singleflight.shared(("bucketed", key), _assemble, _reprobe)

    def execute(self, ctx) -> Table:
        return self.execute_concat(ctx)[0]

    def execute_count(self, ctx) -> int:
        n = _footer_row_count(self.relation.files, "parquet")  # index data is parquet
        ha = self.relation.hybrid_append
        if n is None:
            return self.execute(ctx).num_rows
        if ha is not None:
            appended = _footer_row_count(ha.files, ha.file_format)
            if appended is None:
                return self.execute(ctx).num_rows
            n += appended
        return n

    def simple_string(self):
        spec = self.relation.bucket_spec
        return (
            f"BucketedIndexScan index={self.relation.index_name} "
            f"buckets={spec.num_buckets} by {list(spec.bucket_columns)}"
        )


def _empty_column(dtype: str) -> Column:
    if dtype == "string":
        return Column("string", np.empty(0, np.int32), np.empty(0, "<U1"))
    return Column(dtype, np.empty(0, np.dtype(dtype)))


class FilterExec(PhysicalNode):
    name = "Filter"

    def __init__(self, condition: Expr, child: PhysicalNode):
        self.condition = condition
        self.child = child

    def children(self):
        return (self.child,)

    def execute(self, ctx) -> Table:
        t = self.child.execute(ctx)
        if t.num_rows == 0:
            return self._strip_internal(t)
        mask = evaluate_predicate(self.condition, t)
        return self._strip_internal(t.take(nonzero_indices(mask)))

    def _strip_internal(self, t: Table) -> Table:
        """Drop an index scan's internal lineage column once this filter —
        the delete-prune wrapper, the column's ONLY legitimate consumer —
        has evaluated: the logical schema hides the column, so nothing
        above may see it (whole-table operators like Union would otherwise
        diverge from their logical schema check)."""
        rel = getattr(self.child, "relation", None)
        if rel is None or not rel.index_name:
            return t
        from .logical import internal_column

        refs = {r.lower() for r in self.condition.references()}
        drop = [
            c for c in t.column_names if internal_column(c) and c.lower() in refs
        ]
        if not drop:
            return t
        return t.select([c for c in t.column_names if c not in drop])

    def can_stream(self) -> bool:
        return getattr(self.child, "can_stream", lambda: False)()

    def execute_stream(self, ctx, stages=None):
        """Per-chunk filtering: the predicate program runs over each chunk and
        survivors compact immediately, so selective filters shrink the stream
        before any downstream evaluation. Empty chunks still flow (they carry
        the schema for the empty-result shape)."""
        from .streaming import compact_mask_indices, timed

        for t in self.child.execute_stream(ctx, stages):
            if t.num_rows == 0:
                yield self._strip_internal(t)
                continue
            with timed(stages, "eval"):
                mask = evaluate_predicate(self.condition, t)
                out = self._strip_internal(t.take(compact_mask_indices(mask)))
            yield out

    def execute_concat(self, ctx) -> Tuple[Table, np.ndarray]:
        """Filtered bucketed scan, with bucket structure PRESERVED: a filter
        never moves a row across buckets and compaction keeps in-bucket order,
        so the co-bucketed join stays sound over the filtered table — the
        engine analogue of Spark propagating outputPartitioning through
        FilterExec (which is what lets the reference's bucketed index joins
        keep their no-shuffle property under side filters). Steady-state
        cached beside the bucketed concats, keyed by the underlying scan's
        file-inventory key + the condition."""
        child = self.child
        if not isinstance(child, BucketedIndexScanExec):
            raise HyperspaceException(
                "execute_concat requires a bucketed scan child"
            )
        from .scan_cache import global_filtered_cache

        base_key = child._concat_cache_key()
        key = (
            None
            if base_key is None
            else ("filtered", base_key, self._condition_key(ctx))
        )
        if key is not None:
            hit = global_filtered_cache().get(key)
            if hit is not None:
                return hit
        # Cold: try the row-group-pruned bucket assembly — the pruned table
        # is a row-subset of the full concat in identical order, so the
        # filter below yields byte-identical rows AND identical bucket
        # boundaries (surviving-row counts per bucket are what both paths
        # searchsort over). The cache entry under `key` is therefore the same
        # value either way. Skipped when the FULL concat is already warm
        # (filtering in memory beats re-decoding pruned row groups from
        # disk); when nothing prunes, the plain path runs and populates the
        # full bucketed-concat cache exactly as before.
        from .scan_cache import global_bucketed_cache

        def _assemble() -> Tuple[Table, np.ndarray]:
            pruned = None
            if base_key is None or not global_bucketed_cache().contains(base_key):
                pruned = child.execute_pruned_concat(ctx, self.condition)
            if pruned is not None:
                table, starts = pruned
            else:
                table, starts = child.execute_concat(ctx)
            if table.num_rows:
                mask = evaluate_predicate(self.condition, table)
                keep = nonzero_indices(mask)  # ascending → in-bucket order kept
                # Kept rows before each original bucket boundary = new boundary.
                new_starts = np.searchsorted(keep, np.asarray(starts))
                table = table.take(keep)
                starts = new_starts
            table = self._strip_internal(table)
            if key is not None:
                global_filtered_cache().put(key, table, starts)
            return table, starts

        if key is None:
            return _assemble()
        # Single-flight beside the filtered-concat cache (the key already
        # leads with "filtered" — the flight namespace below keeps it apart
        # from the raw bucketed flights either way).
        from ..serve import singleflight as _singleflight

        return _singleflight.shared(
            ("filtered_concat", key), _assemble, lambda: global_filtered_cache().get(key)
        )

    def _condition_key(self, ctx) -> str:
        """Cache-key spelling of the condition. Spelling normalization is only
        sound when no two schema columns collide case-insensitively:
        Table._resolve is exact-match-first, so with both 'X' and 'x' present,
        col('X') and col('x') read DIFFERENT columns and must not share a
        cache entry."""
        from .expr import canonical_condition_repr

        cs = _resolution_case_sensitive(ctx, self.child.relation.schema.names)
        return canonical_condition_repr(self.condition, cs)

    def rows_token(self, ctx=None):
        """Row identity of the filtered bucketed scan (see
        `BucketedIndexScanExec.rows_token`): the child's row identity + the
        condition. None when the child can't provide one."""
        child = self.child
        if not isinstance(child, BucketedIndexScanExec):
            return None
        return ("filtered-rows", child.rows_token(ctx), self._condition_key(ctx))

    def simple_string(self):
        return f"Filter {self.condition!r}"


class ProjectExec(PhysicalNode):
    name = "Project"

    def __init__(self, column_names: Sequence[str], child: PhysicalNode):
        self.column_names = list(column_names)
        self.child = child

    def children(self):
        return (self.child,)

    def execute(self, ctx) -> Table:
        return self.child.execute(ctx).select(self.column_names)

    def execute_count(self, ctx) -> int:
        return self.child.execute_count(ctx)  # projection preserves row count

    def can_stream(self) -> bool:
        return getattr(self.child, "can_stream", lambda: False)()

    def execute_stream(self, ctx, stages=None):
        for t in self.child.execute_stream(ctx, stages):
            yield t.select(self.column_names)

    def simple_string(self):
        return f"Project [{', '.join(self.column_names)}]"


class UnionExec(PhysicalNode):
    name = "Union"

    def __init__(self, children: Sequence[PhysicalNode]):
        self._children = list(children)

    def children(self):
        return tuple(self._children)

    def execute(self, ctx) -> Table:
        tables = [c.execute(ctx) for c in self._children]
        # Align column order/spelling to the first child before concatenating.
        names = tables[0].column_names
        tables = [t if t.column_names == names else t.select(names) for t in tables]
        return Table.concat([t for t in tables])

    def execute_count(self, ctx) -> int:
        return sum(c.execute_count(ctx) for c in self._children)

    def simple_string(self):
        return f"Union ({len(self._children)})"


class SetOpExec(PhysicalNode):
    """INTERSECT / EXCEPT with DISTINCT set semantics over whole rows.

    Row equality is the engine's canonical null-aware record equality (the
    aggregate path's `_key_records`: data + validity lanes, nulls equal each
    other), computed over the two sides re-encoded through `Table.concat` so
    string codes are comparable across tables. Output rows are the left side's
    first occurrence of each surviving distinct record, in left order."""

    def __init__(self, op: str, left: PhysicalNode, right: PhysicalNode):
        self.op = op  # "intersect" | "except"
        self.left = left
        self.right = right

    @property
    def name(self):
        return self.op.capitalize()

    def children(self):
        return (self.left, self.right)

    def execute(self, ctx) -> Table:
        from ..ops.aggregate import _key_records

        lt = self.left.execute(ctx)
        rt = self.right.execute(ctx)
        names = lt.column_names
        if rt.num_rows == 0:
            combined = lt
        else:
            # concat re-encodes strings over union dictionaries → codes (and
            # therefore records) are comparable across the two sides.
            combined = Table.concat([lt, rt.select(names)])
        recs = _key_records(combined, names) if combined.num_rows else None
        if recs is None:
            return lt
        l_recs, r_recs = recs[: lt.num_rows], recs[lt.num_rows :]
        uniq, first_idx = np.unique(l_recs, return_index=True)
        if self.op == "intersect":
            keep = np.isin(uniq, np.unique(r_recs)) if len(r_recs) else np.zeros(len(uniq), bool)
        else:
            keep = ~np.isin(uniq, np.unique(r_recs)) if len(r_recs) else np.ones(len(uniq), bool)
        return lt.take(np.sort(first_idx[keep]))

    def simple_string(self):
        return self.name


class ExchangeInfo:
    """Partition layout a ShuffleExchange attaches to its output table: rows are
    grouped into `len(starts)-1` hash partitions (sorted by key64 within each), so
    a downstream merge join of two tables exchanged on compatible keys over the
    same mesh runs co-partitioned with no further communication. `blocks` is the
    DEVICE-RESIDENT sharded key layout — the probe consumes it directly, so the
    exchanged keys never round-trip through the host."""

    def __init__(self, mesh, keys: List[str], starts: np.ndarray, blocks):
        self.mesh = mesh
        self.keys = keys
        self.starts = starts
        self.blocks = blocks


class ShuffleExchangeExec(PhysicalNode):
    """Hash-repartition — the operator the bucketed index path eliminates.

    In distributed mode (ambient device mesh) this is a REAL exchange: rows ride a
    two-pass `lax.all_to_all` to their hash partition's device and come back
    partition-grouped, with the layout attached for the downstream merge join
    (the engine analogue of Spark's ShuffleExchangeExec). On a single device it is
    a pass-through — one memory space needs no data movement; the node still
    matters there as the operator explain's diff reports as eliminated."""

    name = "ShuffleExchange"

    def __init__(self, keys: Sequence[str], child: PhysicalNode):
        self.keys = list(keys)
        self.child = child

    def children(self):
        return (self.child,)

    def exchange_table(self, mesh, t: Table, partitions_per_device: int = 8) -> Table:
        """The real exchange: rows ride the all_to_all to their partition's device;
        the partition layout is attached for the downstream co-partitioned join."""
        from ..parallel.table_ops import distributed_exchange_table

        exchanged, starts, blocks = distributed_exchange_table(
            mesh, t, self.keys, partitions_per_device
        )
        exchanged.exchange_info = ExchangeInfo(
            mesh, [k.lower() for k in self.keys], starts, blocks
        )
        return exchanged

    def execute(self, ctx) -> Table:
        # Standalone execution. Under a SortMergeJoin the parent orchestrates the
        # exchange instead (the enable decision must be made per-join: a one-sided
        # exchange would pay the all_to_all and never use the layout).
        t = self.child.execute(ctx)
        mesh = ctx.session.mesh_for(t.num_rows) if ctx.session is not None else None
        if mesh is None or t.num_rows == 0:
            return t
        return self.exchange_table(mesh, t, _partitions_per_device(ctx))

    def execute_count(self, ctx) -> int:
        return self.child.execute_count(ctx)  # exchange moves rows, never drops

    def simple_string(self):
        return f"ShuffleExchange hashpartitioning({', '.join(self.keys)})"


class SortExec(PhysicalNode):
    """Sort marker (the SMJ's required child ordering).

    Pass-through at execution time: in distributed mode the upstream exchange
    already returns rows key64-sorted within each partition, and the single-device
    merge join sorts by key hash internally (`merge_join_pairs`) — physically
    reordering here would double the work either way. The node exists for
    plan-shape honesty — it is one of the operators the bucketed index path
    eliminates, which explain's operator diff reports."""

    name = "Sort"

    def __init__(self, keys: Sequence[str], child: PhysicalNode):
        self.keys = list(keys)
        self.child = child

    def children(self):
        return (self.child,)

    def execute(self, ctx) -> Table:
        return self.child.execute(ctx)

    def execute_count(self, ctx) -> int:
        return self.child.execute_count(ctx)

    def simple_string(self):
        return f"Sort [{', '.join(self.keys)}]"


class WithColumnExec(PhysicalNode):
    name = "WithColumn"

    def __init__(self, col_name: str, expr: Expr, child: PhysicalNode, dtype: Optional[str] = None):
        self.col_name = col_name
        self.expr = expr
        self.child = child
        self.dtype = dtype  # declared schema dtype; execution conforms to it

    def children(self):
        return (self.child,)

    def execute(self, ctx) -> Table:
        return self._apply(self.child.execute(ctx))

    def can_stream(self) -> bool:
        return getattr(self.child, "can_stream", lambda: False)()

    def execute_stream(self, ctx, stages=None):
        from .streaming import timed

        for t in self.child.execute_stream(ctx, stages):
            with timed(stages, "eval"):
                out = self._apply(t)
            yield out

    def _apply(self, t: Table) -> Table:
        """Evaluate the expression over one (chunk) table — expressions are
        row-wise, so per-chunk evaluation equals whole-table evaluation."""
        from .evaluate import evaluate_column

        new_col = evaluate_column(self.expr, t)
        if (
            self.dtype is not None
            and self.dtype != "string"
            and not new_col.is_string
            and new_col.data.dtype != np.dtype(self.dtype)
        ):
            # Backend promotion quirks (e.g. jax int32/int32 division) must not
            # leak into the schema contract: cast to the DECLARED dtype.
            new_col = Column(
                self.dtype, new_col.data.astype(np.dtype(self.dtype)), None, new_col.validity
            )
        out: Dict[str, Column] = {}
        replaced = False
        for n, c in t.columns.items():
            if n.lower() == self.col_name.lower():
                out[n] = new_col
                replaced = True
            else:
                out[n] = c
        if not replaced:
            out[self.col_name] = new_col
        return Table(out)

    def execute_count(self, ctx) -> int:
        return self.child.execute_count(ctx)  # adds a column, never rows

    def simple_string(self):
        return f"WithColumn {self.col_name} = {self.expr!r}"


class _JoinedDeviceEnv:
    """Virtual joined-table column environment on DEVICE arrays: resolves output
    names of a bucketed inner join (left wins the unsuffixed name; colliding
    right columns answer to `<name>_r`, mirroring `_assemble_join`) to lazily
    gathered device columns, plus computed (withColumn) columns evaluated over
    them. Nothing row-scale touches the host."""

    def __init__(self, left: Table, right: Table, li, ri, num_rows: int):
        self.left = left
        self.right = right
        self.li = li
        self.ri = ri
        self.num_rows = num_rows
        self._cache: Dict[str, object] = {}
        self._computed: Dict[str, object] = {}
        # The join's output naming, built EXACTLY like _assemble_join builds it
        # (left names first; right names keep their spelling unless taken, else
        # <name>_r) — a literal right-side "x_r" column and a collision-renamed
        # one must resolve identically on both paths.
        names: Dict[str, tuple] = {}
        for n in left.column_names:
            names[n] = ("l", n)
        for n in right.column_names:
            names[n if n not in names else f"{n}_r"] = ("r", n)
        self._names = names

    def _gather(self, side: str, col: Column):
        from ..ops.aggregate import DevCol
        from .encoded_device import stage_codes, widen_for_gather

        idx = self.li if side == "l" else self.ri
        # Upload narrow codes, gather the SURVIVING rows, widen on device:
        # the H2D transfer moves the compressed lane; DevCol consumers keep
        # seeing int32 codes (late materialization stays downstream).
        arr = stage_codes(col, "join_gather")[idx]
        if col.is_string:
            arr = widen_for_gather(arr)
        valid = (
            device_array(col.validity)[idx] if col.validity is not None else None
        )
        return DevCol(col.dtype, arr, col.dictionary, valid)

    def prefetch(self, names) -> None:
        """Gather EVERY named source column (+ validity lanes) in ONE compiled
        program — on a remote PJRT transport each eager gather is a dispatch
        round-trip, so a 6-column aggregate pays 1 RTT here instead of ~8.
        Unresolvable/computed names are skipped (get() handles them)."""
        from ..ops.aggregate import DevCol

        plan: Dict[str, Column] = {}  # lname -> source column
        sides, arrays = [], []
        for name in names:
            lname = name.lower()
            if lname in self._cache or lname in self._computed or lname in plan:
                continue
            try:
                side, col = self._resolve_source(name)
            except KeyError:
                continue
            plan[lname] = col
            sides.append(side)
            from .encoded_device import stage_codes

            arrays.append(stage_codes(col, "join_gather"))
            if col.validity is not None:
                sides.append(side)
                arrays.append(device_array(col.validity))
        if not plan:
            return
        gathered = _gather_many_jit(tuple(sides), self.li, self.ri, *arrays)
        i = 0
        for lname, col in plan.items():
            arr = gathered[i]
            if col.is_string:
                # Narrow-staged codes widen AFTER the gather (on device, over
                # surviving rows only) so DevCol consumers see int32 codes.
                from .encoded_device import widen_for_gather

                arr = widen_for_gather(arr)
            i += 1
            valid = None
            if col.validity is not None:
                valid = gathered[i]
                i += 1
            self._cache[lname] = DevCol(col.dtype, arr, col.dictionary, valid)

    def get(self, name: str):
        lname = name.lower()
        hit = self._cache.get(lname)
        if hit is not None:
            return hit
        if lname in self._computed:
            dc = self._computed[lname]
        else:
            dc = self._gather(*self._resolve_source(name))
        self._cache[lname] = dc
        return dc

    def _resolve_source(self, name: str):
        # Table-style resolution over the join's output names: exact match
        # first, then unique case-insensitive match.
        ent = self._names.get(name)
        if ent is None:
            ci = [k for k in self._names if k.lower() == name.lower()]
            if len(ci) != 1:
                raise KeyError(name)
            ent = self._names[ci[0]]
        side, src = ent
        table = self.left if side == "l" else self.right
        return side, table.columns[src]

    def add_computed(self, name: str, expr: Expr, dtype: Optional[str]) -> None:
        """Evaluate a withColumn expression over this env (device arrays via the
        compiled-predicate facade machinery) and register the result."""
        from ..ops.aggregate import DevCol
        from .evaluate import (
            _collect_col_spellings,
            _PredColMeta,
            _PredTableFacade,
            evaluate,
        )

        metas, devcols = {}, {}
        for sp in _collect_col_spellings(expr):
            dc = self.get(sp)
            metas[sp] = _PredColMeta(dc.is_string, dc.dictionary, dc.validity is not None)
            devcols[sp] = dc.arr
            if dc.validity is not None:
                devcols[f"__valid__{sp}"] = dc.validity
        v = evaluate(expr, _PredTableFacade(self.num_rows, metas), devcols)
        n = self.num_rows
        if v.kind == "str":
            from .encoded_device import widen_for_gather

            out = DevCol(
                "string", widen_for_gather(v.arr), np.asarray(v.dictionary), v.valid
            )
        elif v.kind == "lit":
            if isinstance(v.value, str):
                out = DevCol(
                    "string", jnp.zeros(n, jnp.int32), np.asarray([v.value]), None
                )
            else:
                arr = jnp.full((n,), v.value)
                out = DevCol(str(arr.dtype), arr, None, None)
        else:
            arr = v.arr
            valid = v.valid
            if arr.ndim == 0:
                arr = jnp.full((n,), arr)
            if valid is not None:
                if valid.ndim == 0:
                    valid = jnp.broadcast_to(valid, arr.shape)
                # Canonical fill at invalid slots keeps the nulls-cluster
                # invariant for hashing/grouping (mirrors evaluate_column).
                arr = jnp.where(valid, arr, jnp.zeros((), arr.dtype))
            if (
                dtype is not None
                and dtype != "string"
                and str(arr.dtype) != dtype
            ):
                # Backend promotion quirks must not leak into the schema
                # contract: conform to the DECLARED dtype (WithColumnExec rule).
                arr = arr.astype(np.dtype(dtype))
            out = DevCol(dtype or str(arr.dtype), arr, None, valid)
        self._computed[name.lower()] = out
        self._cache.pop(name.lower(), None)  # computed shadows a source column


class HashAggregateExec(PhysicalNode):
    """Grouped aggregation via device hash-sort + segment reductions
    (`ops.aggregate.hash_aggregate`)."""

    name = "HashAggregate"

    def __init__(self, group_keys: Sequence[str], aggs: Sequence[tuple], child: PhysicalNode):
        self.group_keys = list(group_keys)
        self.aggs = [tuple(a) for a in aggs]
        self.child = child

    def children(self):
        return (self.child,)

    def execute(self, ctx) -> Table:
        from ..ops.aggregate import hash_aggregate

        out = self._try_stream_star_agg(ctx)
        if out is not None:
            return out
        out = self._try_fused_join_agg(ctx)
        if out is not None:
            return out
        out = self._try_stream_join_agg(ctx)
        if out is not None:
            return out
        out = self._try_stream_agg(ctx)
        if out is not None:
            return out
        return hash_aggregate(self.child.execute(ctx), self.group_keys, self.aggs)

    def _try_stream_star_agg(self, ctx) -> Optional[Table]:
        """Streamed multiway star-join→aggregate: when this aggregate sits on
        a chain of WithColumn/Project operators over a recognized
        `MultiwayJoinExec`, every dimension's covering index is probed per
        fact chunk and survivor compositions fold straight into the
        chunk-carry `StreamAggregator` — the intermediate fact of the
        cascaded plan never materializes (`engine.streaming.
        stream_star_aggregate`). Returns None whenever the shape doesn't
        apply or the multiway/streaming gates are off — the MultiwayJoinExec
        then executes its byte-identical cascade. Shape problems fall back;
        execution errors propagate (and leave no partial pair memo)."""
        from ..ops.aggregate import streaming_agg_supported
        from ..ops.bucket_join import size_classes_enabled
        from .streaming import (
            multiway_enabled,
            stream_star_aggregate,
            streaming_enabled,
        )

        if not multiway_enabled():
            return None
        if not streaming_enabled() or not size_classes_enabled():
            return None
        if not self.group_keys or not streaming_agg_supported(
            self.group_keys, self.aggs
        ):
            return None
        chain: List[PhysicalNode] = []
        node = self.child
        while isinstance(node, (WithColumnExec, ProjectExec)):
            chain.append(node)
            node = node.child
        if not isinstance(node, MultiwayJoinExec):
            return None
        return stream_star_aggregate(self, node, chain, ctx)

    def _try_stream_join_agg(self, ctx) -> Optional[Table]:
        """Streamed bucketed-join→aggregate: when this aggregate sits on a
        chain of WithColumn/Project operators over a bucketed INNER join,
        verified pair chunks flow straight into the chunk-carry
        `StreamAggregator` — payload gathers + expression evaluation run
        per chunk (overlapped on the shared decode-pool contract) and the
        full join output never materializes (`engine.streaming.
        stream_join_aggregate`). Returns None whenever the shape doesn't
        apply or ``HYPERSPACE_QUERY_STREAMING=0`` — the materialized path is
        always correct. Shape problems fall back; execution errors propagate
        (and leave no partial pair memo behind)."""
        from ..ops.aggregate import streaming_agg_supported
        from ..ops.bucket_join import size_classes_enabled
        from .streaming import stream_join_aggregate, streaming_enabled

        if not streaming_enabled() or not size_classes_enabled():
            return None
        if not self.group_keys or not streaming_agg_supported(
            self.group_keys, self.aggs
        ):
            return None
        chain: List[PhysicalNode] = []
        node = self.child
        while isinstance(node, (WithColumnExec, ProjectExec)):
            chain.append(node)
            node = node.child
        if not (
            isinstance(node, SortMergeJoinExec)
            and node.bucketed
            and node.how == "inner"
        ):
            return None
        return stream_join_aggregate(self, node, chain, ctx)

    def _try_stream_agg(self, ctx) -> Optional[Table]:
        """Streaming chunk-carry execution: when this aggregate sits on a
        chain of Filter/Project/WithColumn operators over a plain MULTI-FILE
        scan, file decode (bounded pool, per-column scan cache) overlaps the
        per-chunk filter+reduce work and the full concat never materializes
        (`engine.streaming`). Returns None whenever the shape doesn't apply
        or ``HYPERSPACE_QUERY_STREAMING=0`` — the materialized path is always
        correct. Shape errors fall back; execution errors (e.g. a decoder
        fault mid-stream) propagate."""
        from ..ops.aggregate import streaming_agg_supported
        from .streaming import stream_aggregate, streaming_enabled

        if not streaming_enabled():
            return None
        if not streaming_agg_supported(self.group_keys, self.aggs):
            return None
        node = self.child
        while isinstance(node, (FilterExec, ProjectExec, WithColumnExec)):
            node = node.child
        if type(node) is not ScanExec or not node.can_stream():
            return None
        if len(node.relation.files) < 2:
            # Single-file sources have nothing to overlap; the one-pass path
            # is strictly cheaper (and stays byte-identical for floats).
            return None
        return stream_aggregate(self, ctx)

    def _try_fused_join_agg(self, ctx) -> Optional[Table]:
        """Fused bucketed-join→aggregate: when this aggregate sits on a chain of
        WithColumn/Project operators over a bucketed INNER join, the whole
        pipeline — probe, pair expansion+verification, payload gathers,
        computed columns, group-by — runs on DEVICE arrays; only per-group
        results cross the host boundary. The unfused path materializes the
        joined table on host (8M-pair gathers + re-upload per query), which
        dominated the measured post-join aggregation time on TPU (round-4
        verdict: agg_speedup 1.7x, Q14 negative). Returns None whenever the
        shape doesn't apply — the unfused path is always correct."""
        from ..ops.backend import use_device_path

        if not use_device_path():
            return None
        if not self.group_keys or any(fn == "count_distinct" for _, fn, _ in self.aggs):
            return None
        withcols: List[WithColumnExec] = []
        node = self.child
        while isinstance(node, (WithColumnExec, ProjectExec)):
            if isinstance(node, WithColumnExec):
                withcols.append(node)
            node = node.child
        if not (
            isinstance(node, SortMergeJoinExec)
            and node.bucketed
            and node.how == "inner"
        ):
            return None
        join = node
        try:
            left, l_starts = join.left.execute_concat(ctx)
            right, r_starts = join.right.execute_concat(ctx)
        except HyperspaceException:
            return None
        if left.num_rows == 0 or right.num_rows == 0:
            return None
        mesh = (
            ctx.session.mesh_for(left.num_rows + right.num_rows)
            if ctx.session is not None
            else None
        )
        if mesh is not None:
            return None  # the sharded probe owns mesh-scale execution
        # Device pairs are cached per (left, right) table identity like the
        # host pairs in `_bucketed_pairs` — the fused probe + expansion +
        # verification + compaction (the dominant device cost of a steady-
        # state aggregate; probe alone measured 1.15 s at 8M on TPU) runs
        # once per table pair, not once per query. HBM pinning rides the
        # device-memo byte budget. A legitimately-empty join caches None.
        base_subkey = _pair_subkey(
            join.left_keys, join.right_keys, join.left, join.right, left, right
        )
        rows_key = _pair_rows_key(join.left, join.right, ctx)
        pairs = _cached_two_table(
            "pairs",
            left,
            right,
            ("dev",) + base_subkey,
            lambda: join._device_pairs_compacted(
                left, right, l_starts, r_starts, base_subkey, rows_key
            ),
            rows_key=rows_key,
        )
        if pairs is None:
            return None
        li, ri, n_keep, out_cap = pairs
        row_valid = None if n_keep == out_cap else jnp.arange(out_cap) < n_keep
        try:
            env = _JoinedDeviceEnv(left, right, li, ri, out_cap)
            # One batched gather for every SOURCE column this aggregate will
            # touch. Shadow-aware in execution order: a reference resolves to
            # the source value only until some withColumn shadows the name —
            # after that it reads the computed column, so prefetching the
            # source would be a full-pair-count gather thrown away.
            needed = []
            shadowed: set = set()
            for wc in reversed(withcols):  # execution order: innermost first
                needed += [
                    n
                    for n in sorted(wc.expr.references())
                    if n.lower() not in shadowed
                ]
                shadowed.add(wc.col_name.lower())
            needed += [
                n
                for n in (
                    list(self.group_keys)
                    + [cn for _, _, cn in self.aggs if cn is not None]
                )
                if n.lower() not in shadowed
            ]
            env.prefetch(needed)
            for wc in reversed(withcols):  # innermost applies first
                env.add_computed(wc.col_name, wc.expr, wc.dtype)
            from ..ops.aggregate import hash_aggregate_device

            cols = {}
            for k in self.group_keys:
                cols[k] = env.get(k)
            for _, fn, cn in self.aggs:
                if cn is not None and cn not in cols:
                    cols[cn] = env.get(cn)
            return hash_aggregate_device(cols, row_valid, self.group_keys, self.aggs)
        except (HyperspaceException, KeyError):
            # Unsupported expression/column shape: the unfused path handles it.
            return None

    def simple_string(self):
        aggs = ", ".join(
            f"{o}={fn}({c if c is not None else '*'})" for o, fn, c in self.aggs
        )
        return f"HashAggregate [{', '.join(self.group_keys)}] [{aggs}]"


class OrderByExec(PhysicalNode):
    """Total ORDER BY — a presentation operator whose output returns to the host
    anyway, so the sort runs as one host lexsort over the (validity, value) lanes.
    String columns sort by dictionary code (dictionaries are sorted, so code order
    IS value order). Nulls: Spark default — first ascending, last descending."""

    name = "OrderBy"

    def __init__(self, keys: Sequence[tuple], child: PhysicalNode):
        self.keys = [(k, bool(asc)) for k, asc in keys]
        self.child = child

    def children(self):
        return (self.child,)

    def execute_count(self, ctx) -> int:
        return self.child.execute_count(ctx)

    def execute(self, ctx) -> Table:
        t = self.child.execute(ctx)
        if t.num_rows <= 1:
            return t
        lanes = []
        # np.lexsort sorts by the LAST key first → feed (value, validity) pairs in
        # reverse key order, validity after value so it is the more-significant lane.
        for name, asc in reversed(self.keys):
            c = t.column(name)
            data = c.data.astype(np.int64) if c.is_string else c.data
            valid = c.validity if c.validity is not None else np.ones(t.num_rows, bool)
            if asc:
                lanes.append(data)
                lanes.append(valid)  # False (nulls) sorts first
            else:
                # Descending via negated DENSE RANK (negating raw int64 would
                # overflow at INT64_MIN; equal values must share a rank so
                # less-significant lanes still break ties).
                _, inv = np.unique(data, return_inverse=True)
                lanes.append(-inv.astype(np.int64))
                lanes.append(~valid)  # nulls last
        order = np.lexsort(tuple(lanes))
        return t.take(order)

    def simple_string(self):
        keys = ", ".join(f"{k} {'ASC' if a else 'DESC'}" for k, a in self.keys)
        return f"OrderBy [{keys}]"


class LimitExec(PhysicalNode):
    name = "Limit"

    def __init__(self, n: int, child: PhysicalNode):
        self.n = int(n)
        self.child = child

    def children(self):
        return (self.child,)

    def execute(self, ctx) -> Table:
        t = self._scan_prefix(ctx)
        if t is None:
            t = self.child.execute(ctx)
        if t.num_rows <= self.n:
            return t
        return t.take(np.arange(self.n))

    def _scan_prefix(self, ctx) -> Optional[Table]:
        """Limit directly over a plain multi-file scan (optionally through a
        projection — it preserves row count): stop reading files once `n` rows
        are in hand (parquet footers give per-file counts for free) — the
        interactive `show()`/head path must not decode a whole table."""
        child = self.child
        project = None
        if isinstance(child, ProjectExec):
            project = child
            child = child.child
        if not isinstance(child, ScanExec):
            return None
        rel = child.relation
        if (
            rel.hybrid_append is not None
            or rel.bucket_spec is not None
            or rel.partition_spec is not None
            or rel.file_format not in ("parquet", "delta")
            or len(rel.files) <= 1
        ):
            return None
        picked, total = [], 0
        for f in rel.files:
            picked.append(f)
            cnt = _footer_row_count([f], rel.file_format)
            if cnt is None:
                return None  # unreadable footer: take the generic path
            total += cnt
            if total >= self.n:
                break
        if len(picked) == len(rel.files):
            return None  # needs every file anyway
        t = engine_io.read_files(
            [f.path for f in picked], rel.file_format, child.columns
        )
        return t.select(project.column_names) if project is not None else t

    def execute_count(self, ctx) -> int:
        return min(self.n, self.child.execute_count(ctx))

    def simple_string(self):
        return f"Limit {self.n}"


def _null_table_like(table: Table, n: int) -> Table:
    """n rows of all-null columns with `table`'s schema (outer-join fill side)."""
    out: Dict[str, Column] = {}
    invalid = np.zeros(n, dtype=bool)
    for name, c in table.columns.items():
        if c.is_string:
            d = c.dictionary if len(c.dictionary) else np.array([""], dtype="<U1")
            out[name] = Column(c.dtype, np.zeros(n, np.int32), d, invalid.copy())
        else:
            out[name] = Column(c.dtype, np.zeros(n, c.data.dtype), None, invalid.copy())
    return Table(out)


def _assemble_join(
    left: Table, right: Table, li: np.ndarray, ri: np.ndarray, how: str
) -> Table:
    """Assemble the join output from VERIFIED inner pairs. Outer variants append
    the unmatched rows of a side paired with all-null columns of the other; semi/
    anti project the left side only. Null-key and hash-collision pairs were
    already dropped, so their rows land in the unmatched set — exactly SQL's
    outer-join semantics for null keys."""
    if how == "left_semi":
        return left.take(np.unique(li))
    if how == "left_anti":
        mask = np.ones(left.num_rows, dtype=bool)
        mask[li] = False
        return left.take(np.nonzero(mask)[0])
    lt_parts = [left.take(li)]
    rt_parts = [right.take(ri)]
    if how in ("left", "full"):
        mask = np.ones(left.num_rows, dtype=bool)
        mask[li] = False
        idx = np.nonzero(mask)[0]
        if len(idx):
            lt_parts.append(left.take(idx))
            rt_parts.append(_null_table_like(right, len(idx)))
    if how in ("right", "full"):
        mask = np.ones(right.num_rows, dtype=bool)
        mask[ri] = False
        idx = np.nonzero(mask)[0]
        if len(idx):
            lt_parts.append(_null_table_like(left, len(idx)))
            rt_parts.append(right.take(idx))
    lt = Table.concat(lt_parts) if len(lt_parts) > 1 else lt_parts[0]
    rt = Table.concat(rt_parts) if len(rt_parts) > 1 else rt_parts[0]
    out: Dict[str, Column] = dict(lt.columns)
    for n, c in rt.columns.items():
        out[n if n not in out else f"{n}_r"] = c
    return Table(out)


def _verify_pairs(
    left: Table,
    right: Table,
    left_keys: List[str],
    right_keys: List[str],
    li: np.ndarray,
    ri: np.ndarray,
) -> Tuple[np.ndarray, np.ndarray]:
    """Verify candidate pairs: drop 64-bit hash collisions via exact key equality,
    and pairs involving null keys (SQL: null never equals anything, itself
    included; null slots share a fill value, so the equality check alone can't see
    them)."""
    lcols = [left.column(k) for k in left_keys]
    rcols = [right.column(k) for k in right_keys]
    if len(li):
        keep = np.ones(len(li), dtype=bool)
        for lk, rk, lc, rc in zip(left_keys, right_keys, lcols, rcols):
            if lc.is_string != rc.is_string:
                raise HyperspaceException("Join key type mismatch (string vs numeric)")
            if lc.is_string:
                # Compare codes through the cached union-dictionary alignment:
                # aligned codes are equal iff the strings are (dictionaries are
                # sorted-unique), and the alignment is computed once per table
                # pair, not per query — no full-column decode on the hot path.
                la, ra = _aligned_key_codes(left, right, lk, rk)
                keep &= la[li] == ra[ri]
            else:
                keep &= lc.data[li] == rc.data[ri]
            if lc.validity is not None:
                keep &= lc.validity[li]
            if rc.validity is not None:
                keep &= rc.validity[ri]
        if not keep.all():
            li, ri = li[keep], ri[keep]
    return li, ri


def _gather_verified(
    left: Table,
    right: Table,
    left_keys: List[str],
    right_keys: List[str],
    li: np.ndarray,
    ri: np.ndarray,
    how: str = "inner",
) -> Table:
    """Verify candidate pairs then assemble the output for the join type."""
    li, ri = _verify_pairs(left, right, left_keys, right_keys, li, ri)
    return _assemble_join(left, right, li, ri, how)


_key64_cache: Dict[int, tuple] = {}
_padded_cache: Dict[int, tuple] = {}
_verify_cache: Dict[tuple, tuple] = {}
_pairs_cache: Dict[tuple, tuple] = {}
_classed_cache: Dict[tuple, tuple] = {}  # size-classed join plans (two-table)
_CACHES = {
    "k64": _key64_cache,
    "pad": _padded_cache,
    "ver": _verify_cache,
    "pairs": _pairs_cache,
    "cpad": _classed_cache,
}
# Two-table-entry tags ((wr_left, wr_right, value) structure); the rest hold
# per-table entries ((weakref, {subkey: val})).
_TWO_TABLE_TAGS = ("ver", "pairs", "cpad")
_CACHE_TAGS = {id(_key64_cache): "k64", id(_padded_cache): "pad"}

# Registry counters bound ONCE per memo tag: the memo lookups are warm-path
# (every steady-state query), so the per-hit cost stays one locked int add —
# no name formatting, no registry lookup.
_MEMO_HITS = {t: _metrics.counter(f"memo.{t}.hits") for t in _CACHES}
_MEMO_MISSES = {t: _metrics.counter(f"memo.{t}.misses") for t in _CACHES}
_MEMO_PEEK_HITS = {t: _metrics.counter(f"memo.{t}.peek_hits") for t in _CACHES}
_MEMO_EVICTIONS = _metrics.counter("memo.evictions")
# Footprint watermarks (device cost observatory): live device bytes pinned by
# the join memos, and the process-lifetime high-water mark.
_MEMO_BYTES = _metrics.gauge("memo.device_cache.bytes")
_MEMO_BYTES_PEAK = _metrics.gauge("memo.device_cache.bytes_peak")


def _note_memo_bytes() -> None:
    """Publish the memo footprint gauges (called with `_cache_lock` held,
    after any `_device_cache_bytes` mutation)."""
    _MEMO_BYTES.set(_device_cache_bytes)
    _MEMO_BYTES_PEAK.set_max(_device_cache_bytes)

# Concurrent queries (thread-local active sessions) share these memos; the
# byte accounting is read-modify-write and eviction iterates the recency dict,
# so every mutation runs under one lock. RLock: weakref eviction callbacks can
# fire re-entrantly inside guarded sections (e.g. during an insert).
import threading as _threading

_cache_lock = _threading.RLock()

# Device-resident memo budget. The padded/key64 reps pin device memory (~2x key
# bytes per join-key set) independent of the host-table scan caches, so they get
# their own byte bound: least-recently-used TABLE entries are dropped when the
# total crosses the budget (re-derivable at the cost of one re-pad).
# Env-tunable so the bench can stress the eviction machinery deliberately.
_DEVICE_CACHE_BUDGET_BYTES = int(
    os.environ.get("HYPERSPACE_DEVICE_CACHE_BUDGET", 2 << 30)
)
_device_cache_bytes = 0
_device_cache_evictions = 0


def device_cache_stats() -> Dict[str, int]:
    """Live device-memo accounting (bytes pinned, lifetime evictions) — consumed
    by the bench artifact so cache pressure is measured, not modeled."""
    with _cache_lock:
        return {
            "bytes": _device_cache_bytes,
            "evictions": _device_cache_evictions,
            "budget": _DEVICE_CACHE_BUDGET_BYTES,
        }


def set_device_cache_budget(n_bytes: int) -> None:
    global _DEVICE_CACHE_BUDGET_BYTES
    with _cache_lock:
        _DEVICE_CACHE_BUDGET_BYTES = int(n_bytes)

# Missing-vs-cached-None discriminator: build_dist_blocks legitimately returns
# None (empty side), and that negative result must be a cache hit too.
_MISS = object()

# One recency order across all three caches: (tag, key) in LRU→MRU insertion
# order. Eviction pops from the front; hits and inserts re-append.
_recency: Dict[tuple, None] = {}


def _touch(tag, key) -> None:
    with _cache_lock:
        _recency.pop((tag, key), None)
        _recency[(tag, key)] = None


def _entry_nbytes(tag: str, ent) -> int:
    if tag in _TWO_TABLE_TAGS:  # two-table entries: (wr_left, wr_right, value)
        return _val_nbytes(ent[2])
    return sum(_val_nbytes(v) for v in ent[1].values())


def clear_device_memos() -> None:
    """Drop EVERY device-side memo (key64/padded/classed reps, verify lanes,
    pairs). The bench's cold-executor measurements use this to re-run the
    probe/pad pipeline from scratch without tearing down scan caches."""
    global _device_cache_bytes
    with _cache_lock:
        for c in _CACHES.values():
            c.clear()
        _recency.clear()
        _device_cache_bytes = 0
        _note_memo_bytes()


def _drop_entry(tag: str, key) -> None:
    global _device_cache_bytes
    with _cache_lock:
        _recency.pop((tag, key), None)
        dropped = _CACHES[tag].pop(key, None)
        if dropped is not None:
            _device_cache_bytes -= _entry_nbytes(tag, dropped)
            _note_memo_bytes()


def _evict_over_budget(protect: tuple) -> None:
    """Evict the least-recently-used entry across ALL device caches until under
    budget, never evicting the entry just inserted (`protect`)."""
    global _device_cache_evictions
    with _cache_lock:
        while _device_cache_bytes > _DEVICE_CACHE_BUDGET_BYTES:
            victim = next((rk for rk in _recency if rk != protect), None)
            if victim is None:
                return
            _drop_entry(*victim)
            _device_cache_evictions += 1
            _MEMO_EVICTIONS.inc()


def _val_nbytes(val) -> int:
    total = 0
    stack = [val]
    while stack:
        x = stack.pop()
        if isinstance(x, (tuple, list)):
            stack.extend(x)
        else:
            total += int(getattr(x, "nbytes", 0) or 0)
    return total


def _cached_by_table(cache: Dict[int, tuple], table: Table, subkey, compute):
    """Per-table-identity memo (weakref-keyed so entries die with their tables —
    which are themselves owned by the scan caches). Byte-bounded: when the total
    device bytes held across the key64/padded/verify caches exceed the budget,
    the least-recently-used entry across all three is evicted."""
    import weakref

    global _device_cache_bytes
    tag = _CACHE_TAGS[id(cache)]
    key = id(table)
    with _cache_lock:
        ent = cache.get(key)
        if ent is not None and ent[0]() is table:
            hit = ent[1].get(subkey, _MISS)
            if hit is not _MISS:
                _touch(tag, key)
                _MEMO_HITS[tag].inc()
                return hit
    _MEMO_MISSES[tag].inc()

    def _flight_probe():
        with _cache_lock:
            ent = cache.get(key)
            if ent is not None and ent[0]() is table:
                hit = ent[1].get(subkey, _MISS)
                if hit is not _MISS:
                    _touch(tag, key)
                    return hit
        return None

    def _compute_and_insert():
        # Compute AND insert inside the flight (still outside the cache lock:
        # device work must not serialize queries): `shared`'s contract is
        # that the leader's attempt populates the cache before followers are
        # woken — inserting after the flight released would let a woken
        # follower's probe miss and re-run the same pad/key64 program.
        global _device_cache_bytes
        val = compute()
        nbytes = _val_nbytes(val)
        with _cache_lock:
            ent = cache.get(key)  # re-read: another thread may have raced
            if ent is None or ent[0]() is not table:
                if ent is not None:
                    # Stale id(table) reuse before the old weakref callback
                    # ran: the displaced entry's bytes must leave the
                    # accounting.
                    _device_cache_bytes -= _entry_nbytes(tag, ent)

                def _evict(wr, tag=tag, key=key):
                    # Only drop the entry this weakref installed: a dead
                    # table's id can be reused by a NEW table before this
                    # deferred callback runs, and the replacement entry must
                    # survive it.
                    ent_now = _CACHES[tag].get(key)
                    if ent_now is not None and ent_now[0] is wr:
                        _drop_entry(tag, key)

                cache[key] = (weakref.ref(table, _evict), {subkey: val})
                _device_cache_bytes += nbytes
            elif subkey not in ent[1]:
                ent[1][subkey] = val
                _device_cache_bytes += nbytes
            else:
                val = ent[1][subkey]  # raced: keep the first insert's accounting
            _touch(tag, key)
            _note_memo_bytes()
            _evict_over_budget((tag, key))
        return val

    from ..serve import singleflight as _singleflight

    # Single-flight over the compute+insert: two queries racing the same
    # cold memo entry run ONE device program; followers are served by
    # `_flight_probe` against the entry the leader inserted.
    return _singleflight.shared(
        ("memo", tag, key, subkey), _compute_and_insert, _flight_probe
    )


def _two_table_key(left: Table, right: Table, subkey: tuple, rows_key):
    """Cache key + entry-validity predicate for the two-table memos.

    Default (rows_key None): keyed by table identity — a hit requires the
    entry's weakrefs to point at EXACTLY these tables (id-reuse guard).

    With a rows_key (value identity: file inventories + conditions), the key
    is projection-independent — pairs computed against one column pruning of
    a scan serve every other pruning of the same rows. The weakrefs then only
    manage lifetime/accounting: a hit requires both producer tables to still
    be alive (their death invalidates nothing semantically, but the entry's
    memory accounting dies with them)."""
    if rows_key is None:
        key = (id(left), id(right)) + subkey
        valid = lambda ent: ent[0]() is left and ent[1]() is right
    else:
        key = rows_key + subkey
        valid = lambda ent: ent[0]() is not None and ent[1]() is not None
    return key, valid


def _cached_two_table(
    tag: str, left: Table, right: Table, subkey: tuple, compute, rows_key=None
):
    """Per-table-pair memo with the same byte accounting and id-reuse guards
    as `_cached_by_table`: entries die with EITHER table (each weakref may
    only drop the entry it installed). See `_two_table_key` for keying."""
    import weakref

    global _device_cache_bytes
    cache = _CACHES[tag]
    key, valid = _two_table_key(left, right, subkey, rows_key)
    with _cache_lock:
        ent = cache.get(key)
        if ent is not None and valid(ent):
            _touch(tag, key)
            _MEMO_HITS[tag].inc()
            return ent[2]
    _MEMO_MISSES[tag].inc()

    def _flight_probe():
        with _cache_lock:
            ent = cache.get(key)
            if ent is not None and valid(ent):
                _touch(tag, key)
                return ent[2]
        return None

    def _compute_and_insert():
        # Compute AND insert inside the flight (same contract as
        # `_cached_by_table`): followers wake to a populated entry.
        global _device_cache_bytes
        val = compute()

        def _evict(wr, key=key):
            ent_now = cache.get(key)
            if ent_now is not None and (ent_now[0] is wr or ent_now[1] is wr):
                _drop_entry(tag, key)

        with _cache_lock:
            ent = cache.get(key)  # re-read under the lock
            if ent is not None:
                if valid(ent):
                    _touch(tag, key)
                    return ent[2]
                _device_cache_bytes -= _val_nbytes(ent[2])
            cache[key] = (weakref.ref(left, _evict), weakref.ref(right, _evict), val)
            _device_cache_bytes += _val_nbytes(val)
            _touch(tag, key)
            _note_memo_bytes()
            _evict_over_budget((tag, key))
        return val

    from ..serve import singleflight as _singleflight

    # Single-flight over the compute+insert: concurrent identical joins run
    # ONE probe/verify program per pair key instead of one per query.
    return _singleflight.shared(("memo", tag, key), _compute_and_insert, _flight_probe)


def _peek_two_table(
    tag: str, left: Table, right: Table, subkey: tuple, rows_key=None
):
    """Read-only probe of a `_cached_two_table` entry: (hit, value). Lets a
    cheaper consumer (e.g. a count) reuse work a richer query already paid
    for, without computing anything on a miss."""
    cache = _CACHES[tag]
    key, valid = _two_table_key(left, right, subkey, rows_key)
    with _cache_lock:
        ent = cache.get(key)
        if ent is not None and valid(ent):
            _touch(tag, key)
            _MEMO_PEEK_HITS[tag].inc()
            return True, ent[2]
    return False, None


def _pair_rows_key(lnode, rnode, ctx):
    """Projection-independent rows key for a join's pair caches, when both
    children can state their row identity (bucketed scans / bucket-preserving
    filters). None falls back to table-identity keying."""
    lt = getattr(lnode, "rows_token", None)
    rt = getattr(rnode, "rows_token", None)
    if lt is None or rt is None:
        return None
    ltok, rtok = lt(ctx), rt(ctx)
    if ltok is None or rtok is None:
        return None
    return (ltok, rtok)


def _probe_ranges_cached(l_rep, r_rep, left: Table, right: Table, subkey, rows_key):
    """Probe ranges (lo, counts) through the pairs memo: the probe is the
    dominant steady-state device cost (1.15 s at 8M on TPU in round 4) and
    its output is a pure function of the two reps — which are themselves
    pure functions of row identity + keys + mode (the mode rides the cache
    subkey: a hybrid-append flip from value to hash re-keys). Returns
    (lo, counts) in the canonical probe orientation (deterministic from the
    rep capacities; callers recompute it with `probe_orientation`)."""
    from ..ops.bucket_join import (
        probe_keys_promoted,
        probe_orientation,
        probe_ranges,
    )

    a, b, _swapped = probe_orientation(l_rep, r_rep)

    def compute():
        ak, bk = probe_keys_promoted(a.keys, b.keys)
        return probe_ranges(ak, bk, a.lengths, b.lengths)

    return _cached_two_table(
        "pairs", left, right, ("probe", l_rep.mode) + subkey, compute, rows_key
    )


def _value_mode_column(table: Table, keys: List[str]):
    """The single join-key Column when the side is even ELIGIBLE for value
    mode (one numeric non-bool null-free key); None otherwise. The data-level
    checks (NaN, in-bucket sortedness) happen in `value_mode_vals`."""
    if len(keys) != 1:
        return None
    c = table.column(keys[0])
    if c.is_string or c.data.dtype == np.bool_ or getattr(c, "validity", None) is not None:
        return None
    return c


def _classed_plan_cached(
    self_join, left: Table, right: Table, l_starts, r_starts, subkey, rows_key
):
    """The joint size-classed layout of one bucketed join pair, cached per
    table pair (tag "cpad", same byte budget/lifetime as the dense padded
    reps). The mode decision is JOINT by construction: both sides go
    value-direct only when both qualify (single numeric null-free key, sorted
    buckets, no NaN); otherwise both pad by key64 hash."""
    from ..ops.backend import use_device_path
    from ..ops.bucket_join import (
        _outlier_factor,
        build_classed_plan,
        value_mode_vals,
    )

    l_keys, r_keys = self_join.left_keys, self_join.right_keys

    def compute():
        device = use_device_path()
        lc = _value_mode_column(left, l_keys)
        rc = _value_mode_column(right, r_keys)
        if lc is not None and rc is not None:
            lv = value_mode_vals(lc.data, l_starts)
            rv = value_mode_vals(rc.data, r_starts)
            if lv is not None and rv is not None:
                plan = build_classed_plan(
                    lv, rv, l_starts, r_starts, "value", device=device
                )
                if plan is not None:
                    return plan
        lk = np.asarray(_table_key64(left, list(l_keys)))
        rk = np.asarray(_table_key64(right, list(r_keys)))
        return build_classed_plan(lk, rk, l_starts, r_starts, "hash", device=device)

    # The outlier factor is a PLAN INPUT (it decides the partition), so it
    # rides the subkey: flipping HYPERSPACE_JOIN_OUTLIER_FACTOR mid-session
    # must rebuild the plan, not serve the old partition until eviction.
    return _cached_two_table(
        "cpad", left, right, ("cplan", _outlier_factor()) + subkey, compute, rows_key
    )


def _classed_ranges_cached(plan, left: Table, right: Table, subkey, rows_key):
    """Classed probe output through the pairs memo — the classed analogue of
    `_probe_ranges_cached` (distinct subkey marker, so a mid-session flip of
    HYPERSPACE_JOIN_SIZE_CLASSES can never hand a dense consumer a classed
    value or vice versa)."""
    from ..ops.bucket_join import probe_classed

    return _cached_two_table(
        "pairs",
        left,
        right,
        ("cprobe", plan.mode) + subkey,
        lambda: probe_classed(plan),
        rows_key,
    )


def _relation_sig(node) -> Optional[tuple]:
    """Identity of a join side's UNDERLYING relation for the general-path
    pairs memo: index log entry id + source-file signature. Table-identity
    keying alone cannot distinguish a refreshed/vacuumed index whose rewritten
    files alias the (path, size, mtime-ms) stats of the old ones — the log
    entry id ALWAYS advances across refresh/vacuum/optimize, so stale pair
    indices can never serve a rebuilt table."""
    while node is not None and getattr(node, "relation", None) is None:
        node = getattr(node, "child", None)
    rel = getattr(node, "relation", None)
    if rel is None:
        return None
    return (
        rel.index_name,
        getattr(rel, "log_entry_id", None),
        tuple((f.path, f.size, f.modified_time) for f in rel.files),
    )


def _node_relation_names(node) -> "Optional[List[str]]":
    """The UNDERLYING relation's schema names of a join side (a bucketed scan
    or a filter over one); None when the node has no single relation."""
    rel = getattr(node, "relation", None)
    if rel is None:
        rel = getattr(getattr(node, "child", None), "relation", None)
    if rel is None:
        return None
    return list(rel.schema.names)


def _pair_subkey(left_keys, right_keys, lnode, rnode, left: Table, right: Table) -> tuple:
    """Join-key component of the pair-cache keys. Spelling-normalized
    (lowercased) ONLY when no schema column case-collides — the same guard as
    `FilterExec._condition_key`: with both 'K' and 'k' present, resolution is
    exact-match-first, so joins on 'K' and on 'k' read DIFFERENT columns and
    must not share a cache entry (the projection-independent rows key would
    otherwise make them collide).

    The guard reads the UNDERLYING relation schemas (via the exec nodes), not
    the pruned tables' column names: rows_key-keyed pair entries are shared
    across PRUNINGS of the same scan, and two prunings of a case-colliding
    schema can disagree when only one of them kept both spellings. Falls back
    to the pruned tables' names when a side has no single relation."""
    l_names = _node_relation_names(lnode)
    r_names = _node_relation_names(rnode)
    names = (
        l_names if l_names is not None else list(left.column_names)
    ) + (r_names if r_names is not None else list(right.column_names))
    if len({n.lower() for n in names}) != len(set(names)):
        return tuple(left_keys), tuple(right_keys)
    return (
        tuple(k.lower() for k in left_keys),
        tuple(k.lower() for k in right_keys),
    )


def _aligned_key_codes(left: Table, right: Table, lkey: str, rkey: str):
    """Union-dictionary-aligned code arrays for one string join-key pair, cached
    per (left, right) table identity so steady-state verification never decodes
    the raw strings (`_gather_verified` previously decoded both full columns per
    query)."""

    def compute():
        lc, rc = align_dictionaries(left.column(lkey), right.column(rkey))
        return lc.data, rc.data

    return _cached_two_table(
        "ver", left, right, (lkey.lower(), rkey.lower()), compute
    )


def _padded_rep(table: Table, starts: np.ndarray, keys: List[str], force_hash: bool = False):
    """Device-resident padded-bucket representation of one join side, cached per
    table identity. Single numeric null-free keys go value-direct (the index build
    already sorted each bucket by the key, so the query needs no hash and no
    argsort — just the probe); everything else pads by key64 hash. `force_hash`
    re-derives the hash rep when the OTHER side can't go value-direct — the probe
    requires both sides in the same key space."""
    from ..ops.bucket_join import pad_buckets_by_hash, pad_buckets_by_value

    kt = (tuple(k.lower() for k in keys), force_hash)

    def compute():
        if not force_hash and len(keys) == 1:
            c = table.column(keys[0])
            if (
                not c.is_string
                and c.data.dtype != np.bool_
                and getattr(c, "validity", None) is None
            ):
                rep = pad_buckets_by_value(device_array(c.data), starts)
                if rep is not None:
                    return rep
        return pad_buckets_by_hash(_table_key64(table, list(keys)), starts)

    return _cached_by_table(_padded_cache, table, kt, compute)


def _partitions_per_device(ctx) -> int:
    """Exchange partitions per device (conf-tunable; was a hardcoded 8)."""
    if ctx is None or ctx.session is None:
        return 8
    return ctx.session.hs_conf.partitions_per_device


def _dist_blocks(table: Table, starts: np.ndarray, keys: List[str], mesh):
    """Sharded block layout of a bucketed side, cached per table identity (same
    lifetime as the padded reps): built once per (table, mesh, keys) — steady-state
    sharded joins start at the probe with zero host→device key traffic."""
    from ..parallel.table_ops import build_dist_blocks

    subkey = ("dist", tuple(k.lower() for k in keys), id(mesh), mesh.devices.size)

    def compute():
        return build_dist_blocks(mesh, _table_key64(table, list(keys)), starts)

    return _cached_by_table(_padded_cache, table, subkey, compute)


def _table_key64(table: Table, keys: List[str], force_float=None):
    """Join key64 of a table, cached per table identity.

    Bucketed scans return the SAME Table object across queries (BucketedConcatCache),
    so the hashed key column stays device-resident between queries instead of being
    re-uploaded and re-hashed — the steady-state indexed join starts at the probe.
    `force_float[i]` hashes numeric key i in the cross-kind float64 space (the
    JOINT decision of both join sides — see `_joint_float_flags`)."""

    def compute():
        from .encoded_device import stage_codes

        cols = [table.column(k) for k in keys]
        # String keys stage as narrow dictionary codes when they qualify
        # (encoded_device.py): the hash lane gathers dh_table[codes], so the
        # key64 VALUES are identical — only the upload bytes shrink.
        return key64(
            cols, [stage_codes(c, "join_key64") for c in cols], force_float
        )

    subkey = (
        tuple(k.lower() for k in keys),
        None if force_float is None else tuple(force_float),
    )
    return _cached_by_table(_key64_cache, table, subkey, compute)


def _joint_float_flags(lt: Table, rt: Table, lkeys: List[str], rkeys: List[str]):
    """Per-key-pair cross-kind decision: when one side's key column is float
    and the other's is int, BOTH sides must hash in the float64 space (the
    join's equality is numpy-promoted float64 equality — Spark casts both
    sides to double). Returns PER-SIDE flag lists (l_flags, r_flags), each
    None when nothing on that side needs forcing: float columns hash in
    float64 naturally, so only the INT side of a mixed pair gets a flag —
    keeping the float side's cached key64 entry shared with same-kind joins."""
    l_flags, r_flags = [], []
    for lk, rk in zip(lkeys, rkeys):
        lc, rc = lt.column(lk), rt.column(rk)
        if lc.is_string or rc.is_string:
            l_flags.append(False)
            r_flags.append(False)
            continue
        lf = np.issubdtype(lc.data.dtype, np.floating)
        rf = np.issubdtype(rc.data.dtype, np.floating)
        l_flags.append(rf and not lf)  # int left of a mixed pair
        r_flags.append(lf and not rf)  # int right of a mixed pair
    return (
        l_flags if any(l_flags) else None,
        r_flags if any(r_flags) else None,
    )


def _join_pairs(
    left: Table, right: Table, left_keys: List[str], right_keys: List[str]
) -> Tuple[np.ndarray, np.ndarray]:
    """Hash-key merge join pair indices with exact verification."""
    l_flags, r_flags = _joint_float_flags(left, right, left_keys, right_keys)
    li, ri = merge_join_pairs(
        _table_key64(left, left_keys, l_flags),
        _table_key64(right, right_keys, r_flags),
    )
    return _verify_pairs(left, right, left_keys, right_keys, li, ri)


def _verify_lanes(
    left: Table, right: Table, left_keys: List[str], right_keys: List[str]
):
    """Device inputs for the fused pair-verification programs: per key pair the
    comparable value arrays (union-dictionary-aligned codes for strings) plus
    any validity lanes — the device mirror of `_verify_pairs`' semantics."""
    from .encoded_device import stage_aligned

    lanes, flat = [], []
    for lk, rk in zip(left_keys, right_keys):
        lc, rc = left.column(lk), right.column(rk)
        if lc.is_string != rc.is_string:
            raise HyperspaceException("Join key type mismatch (string vs numeric)")
        if lc.is_string:
            la, ra = _aligned_key_codes(left, right, lk, rk)
            # Union-aligned codes stage narrow when the source columns
            # qualify: the verification compares code VALUES for equality,
            # which narrowing preserves (encoded_device.py).
            flat.append(stage_aligned(la, lc, "join_verify"))
            flat.append(stage_aligned(ra, rc, "join_verify"))
        else:
            la, ra = lc.data, rc.data
            flat.append(device_array(la))
            flat.append(device_array(ra))
        lv = lc.validity is not None
        rv = rc.validity is not None
        lanes.append((lv, rv))
        if lv:
            flat.append(device_array(lc.validity))
        if rv:
            flat.append(device_array(rc.validity))
    return tuple(lanes), flat


import jax as _jax

from ..telemetry.compile_log import observed_jit as _observed_jit


@_observed_jit(label="physical.gather_many", static_argnums=(0,))
def _gather_many_jit(sides: tuple, li, ri, *arrays):
    """Batch gather through the join pair indices: one program for all
    payload columns of a fused join→aggregate."""
    return tuple(a[li if s == "l" else ri] for s, a in zip(sides, arrays))


@_observed_jit(label="physical.verified_keep", static_argnums=(0,))
def _verified_keep_jit(lanes: tuple, li, ri, valid, *flat):
    """Pair-validity mask on device: candidate (li, ri) pairs survive iff every
    key pair compares EQUAL on actual values (codes for strings) and no key slot
    is null — exactly `_verify_pairs`, without leaving the device."""
    keep = valid
    i = 0
    for lv, rv in lanes:
        la, ra = flat[i], flat[i + 1]
        i += 2
        keep = keep & (la[li] == ra[ri])
        if lv:
            keep = keep & flat[i][li]
            i += 1
        if rv:
            keep = keep & flat[i][ri]
            i += 1
    return keep


@_observed_jit(label="physical.verified_count", static_argnums=(0,))
def _verified_count_jit(lanes: tuple, li, ri, valid, *flat):
    return _verified_keep_jit(lanes, li, ri, valid, *flat).sum(dtype=jnp.int64)


@_observed_jit(label="physical.verified_match_counts", static_argnums=(0, 1, 2))
def _verified_match_counts_jit(lanes: tuple, lcap: int, rcap: int, li, ri, valid, *flat):
    """(verified pair count, distinct matched left rows, distinct matched
    right rows) in one program — everything every join type's COUNT needs
    (outer fills, semi/anti) without materializing pairs. `lcap`/`rcap` are
    POW2-QUANTIZED row-count caps (padding scatters nothing and sums zero),
    so growing tables share compiled programs instead of recompiling per
    exact size."""
    keep = _verified_keep_jit(lanes, li, ri, valid, *flat)
    k32 = keep.astype(jnp.int32)
    lmask = jnp.zeros(lcap, jnp.int32).at[li].max(k32, mode="drop")
    rmask = jnp.zeros(rcap, jnp.int32).at[ri].max(k32, mode="drop")
    return (
        keep.sum(dtype=jnp.int64),
        lmask.sum(dtype=jnp.int64),
        rmask.sum(dtype=jnp.int64),
    )


def _value_inner_count_body(lv, rv, xp=jnp):
    """Inner-join count over a single null-free numeric key pair, on ACTUAL
    values: sort one side, range-probe the other, sum — no candidate
    expansion and no verification pass (value equality IS the join
    condition; the promotion matches `_verify_pairs`' numpy-promoted
    equality). NaN probes count zero (NaN == NaN is false in SQL and in the
    verify path); right-side NaNs sort past every real probe value. The ONE
    home of these semantics: traced in `_value_inner_count_jit` (device) and
    run on host arrays by the CPU scan-count path (xp=np)."""
    # NUMPY's promotion lattice, not JAX's: _verify_pairs (the oracle this
    # must match) compares via numpy, where int64 x float32 -> float64; JAX
    # would give float32 and a 2^24-magnitude int key could falsely match.
    common = np.promote_types(np.dtype(lv.dtype), np.dtype(rv.dtype))
    lv = lv.astype(common)
    rv = rv.astype(common)
    # HOST probes get sorted first: the count is order-invariant, and
    # binary-searching with sorted probes turns the haystack accesses
    # sequential — unsorted 8M probes into a 1M haystack measured 7.4 s on
    # host (a cache miss per search step) vs ~0.7 s with the probe sort
    # included. The device program keeps unsorted probes (its vectorized
    # searchsorted was the measured round-5 baseline; the sort would be pure
    # added work there).
    probes = xp.sort(lv) if xp is np else lv
    r_sorted = xp.sort(rv)
    lo = xp.searchsorted(r_sorted, probes, side="left")
    hi = xp.searchsorted(r_sorted, probes, side="right")
    counts = hi - lo
    if np.issubdtype(common, np.floating):
        counts = xp.where(xp.isnan(probes), 0, counts)
    return counts.sum(dtype=np.int64)


@_observed_jit(label="physical.value_inner_count")
def _value_inner_count_jit(lv, rv):
    return _value_inner_count_body(lv, rv)


def _count_from_match_stats(
    how: str, n_pairs: int, lm: int, rm: int, n_left: int, n_right: int
) -> int:
    """Join-output row count from (verified pairs, matched-left, matched-right)
    — the ONE home of the per-join-type arithmetic, shared by the host path
    (np.unique stats), the device fast path, and the empty-side case (all
    stats zero)."""
    if how == "inner":
        return n_pairs
    if how == "left_semi":
        return lm
    if how == "left_anti":
        return n_left - lm
    n = n_pairs
    if how in ("left", "full"):
        n += n_left - lm
    if how in ("right", "full"):
        n += n_right - rm
    return n


class SortMergeJoinExec(PhysicalNode):
    name = "SortMergeJoin"

    def __init__(
        self,
        left: PhysicalNode,
        right: PhysicalNode,
        left_keys: List[str],
        right_keys: List[str],
        bucketed: bool = False,
        how: str = "inner",
    ):
        self.left = left
        self.right = right
        self.left_keys = left_keys
        self.right_keys = right_keys
        self.bucketed = bucketed
        self.how = how

    def children(self):
        return (self.left, self.right)

    @staticmethod
    def _unwrap_exchange(node: PhysicalNode) -> Optional[ShuffleExchangeExec]:
        if isinstance(node, SortExec):
            node = node.child
        return node if isinstance(node, ShuffleExchangeExec) else None

    def execute(self, ctx) -> Table:
        left, right, li, ri = self._compute_pairs(ctx)
        return _assemble_join(left, right, li, ri, self.how)

    def execute_count(self, ctx) -> int:
        """Count the join output WITHOUT assembling it: the verified pair count
        (+ per-side unmatched counts for outer variants) is the answer — a
        count-only query skips the whole gather/concat of payload columns.
        Bucketed inner joins go further: the count never leaves the device
        (`_bucketed_count_fast`)."""
        pre = None
        if self.bucketed and self.how == "inner":
            n = self._bucketed_count_fast(ctx)
            if n is not None:
                return n
        elif not self.bucketed:
            # Children execute ONCE: the fast path and the fallback share them.
            pre = self._exec_general_children(ctx)
            n = self._general_count_fast(ctx, pre)
            if n is not None:
                return n
        left, right, li, ri = self._compute_pairs(ctx, pre)
        how = self.how
        if how == "inner":
            return len(li)
        lm = len(np.unique(li))
        rm = len(np.unique(ri)) if how in ("right", "full") else 0
        return _count_from_match_stats(
            how, len(li), lm, rm, left.num_rows, right.num_rows
        )

    def _exec_general_children(self, ctx):
        """Execute both (non-bucketed) children BELOW any exchange markers:
        (lex, rex, lt, rt) with lex/rex None when no joint exchange applies."""
        lex = self._unwrap_exchange(self.left)
        rex = self._unwrap_exchange(self.right)
        if lex is not None and rex is not None and ctx.session is not None:
            return lex, rex, lex.child.execute(ctx), rex.child.execute(ctx)
        return None, None, self.left.execute(ctx), self.right.execute(ctx)

    def _compute_pairs(self, ctx, pre=None) -> Tuple[Table, Table, np.ndarray, np.ndarray]:
        """Execute both children and produce the VERIFIED join pair indices.
        `pre` threads already-executed children in (the count fast path shares
        its execution with this fallback)."""
        if self.bucketed:
            return self._bucketed_pairs(ctx)
        lex, rex, lt, rt = pre if pre is not None else self._exec_general_children(ctx)
        if lex is not None and rex is not None:
            # Joint exchange decision: both sides exchange over the mesh, or
            # neither — a one-sided exchange would pay a full all_to_all whose
            # co-partition layout the join could never use. Cross-kind key
            # pairs (int ⋈ float) also skip it: the exchange hashes each side
            # in its own kind's space, which would break co-partitioning in
            # the joint float64 space the mixed join compares in.
            mixed = lt.num_rows > 0 and rt.num_rows > 0 and any(
                f is not None
                for f in _joint_float_flags(
                    lt, rt, self.left_keys, self.right_keys
                )
            )
            mesh = ctx.session.mesh_for(lt.num_rows + rt.num_rows)
            if mesh is not None and not mixed and lt.num_rows > 0 and rt.num_rows > 0:
                ppd = _partitions_per_device(ctx)
                lt = lex.exchange_table(mesh, lt, ppd)
                rt = rex.exchange_table(mesh, rt, ppd)
        pairs = self._copartitioned_pairs(lt, rt)
        if pairs is not None:
            li, ri = _verify_pairs(
                lt, rt, self.left_keys, self.right_keys, pairs[0], pairs[1]
            )
            return lt, rt, li, ri
        if (
            getattr(lt, "exchange_info", None) is not None
            or getattr(rt, "exchange_info", None) is not None
        ):
            # Exchanged tables are fresh objects every query — nothing to memo.
            li, ri = _join_pairs(lt, rt, self.left_keys, self.right_keys)
            return lt, rt, li, ri
        # GENERAL-path pairs memo: like the bucketed path's, verified pairs
        # are a pure function of the two tables + keys, and the child tables
        # are stable objects across queries (the concat/scan caches own them)
        # — so the host sort+probe+verify (2.4 s of the 8M CPU Q3 aggregate,
        # re-run per query before this) computes once per table pair. Entries
        # ride the shared device-memo byte budget and die with their tables.
        # The per-side relation signatures (index log entry id + file
        # inventory) re-key the memo across index refresh/vacuum even when
        # the producing Table object's identity survives.
        subkey = (
            ("general",)
            + _pair_subkey(
                self.left_keys, self.right_keys, self.left, self.right, lt, rt
            )
            + (_relation_sig(self.left), _relation_sig(self.right))
        )
        li, ri = _cached_two_table(
            "pairs",
            lt,
            rt,
            subkey,
            lambda: _join_pairs(lt, rt, self.left_keys, self.right_keys),
        )
        return lt, rt, li, ri

    def _copartitioned_pairs(self, lt: Table, rt: Table):
        """Distributed general join: when both children came through a real
        ShuffleExchange on this join's keys over the same mesh, partition p of both
        sides lives on the same device — probe them there with zero collectives."""
        li = getattr(lt, "exchange_info", None)
        ri = getattr(rt, "exchange_info", None)
        if li is None or ri is None or li.mesh is not ri.mesh:
            return None
        if len(li.starts) != len(ri.starts):
            return None
        if li.keys != [k.lower() for k in self.left_keys]:
            return None
        if ri.keys != [k.lower() for k in self.right_keys]:
            return None
        from ..parallel.table_ops import probe_dist_blocks

        # The exchanged key blocks are still on device — probe them directly.
        return probe_dist_blocks(li.mesh, li.blocks, ri.blocks)

    def _bucketed_pairs(self, ctx) -> Tuple[Table, Table, np.ndarray, np.ndarray]:
        """Batched co-bucketed merge join: equal keys are co-located by construction
        (both sides hash-partitioned with the same function and bucket count), so all
        bucket pairs join independently — executed as ONE device program over padded
        [num_buckets, cap] matrices (`ops.bucket_join`), with no data exchange."""
        assert isinstance(self.left, (BucketedIndexScanExec, FilterExec))
        assert isinstance(self.right, (BucketedIndexScanExec, FilterExec))
        from ..ops.bucket_join import probe_padded

        left, l_starts = self.left.execute_concat(ctx)
        right, r_starts = self.right.execute_concat(ctx)
        if left.num_rows == 0 or right.num_rows == 0:
            return left, right, np.empty(0, np.int64), np.empty(0, np.int64)
        # The VERIFIED pair arrays are cached per row identity — pairs are a
        # pure function of the two row sets and the keys, INDEPENDENT of the
        # execution strategy (mesh-sharded or single-device), so one memo
        # covers both: a steady-state query that needs the joined rows
        # (counts, aggregates, collects) skips probe + expansion +
        # verification entirely (~1 s of the 8M CPU Q3 aggregate). The padded
        # reps / block layouts underneath stay cached for the cold paths.
        subkey = _pair_subkey(
            self.left_keys, self.right_keys, self.left, self.right, left, right
        )
        rows_key = _pair_rows_key(self.left, self.right, ctx)

        computed = []

        def compute():
            computed.append(True)
            pairs = None
            mesh = (
                ctx.session.mesh_for(left.num_rows + right.num_rows)
                if ctx.session is not None
                else None
            )
            if mesh is not None:
                from ..ops.bucket_join import mesh_probe_skew_safe

                if mesh_probe_skew_safe(l_starts, r_starts):
                    # Sharded probe: each device joins its own bucket range
                    # with zero collectives (non-divisible bucket counts are
                    # padded with empty virtual buckets inside). Outlier-
                    # skewed bucket layouts skip this (the global-cap padding
                    # would multiply every device's probe area) and stay on
                    # the PR-3 size-classed executor below.
                    from ..parallel.table_ops import probe_dist_blocks

                    l_blocks = _dist_blocks(left, l_starts, self.left_keys, mesh)
                    r_blocks = _dist_blocks(right, r_starts, self.right_keys, mesh)
                    if l_blocks is not None and r_blocks is not None:
                        pairs = probe_dist_blocks(mesh, l_blocks, r_blocks)
            if pairs is None:
                from ..ops.bucket_join import (
                    classed_pairs,
                    size_classes_enabled,
                )

                if size_classes_enabled():
                    # Skew-aware layout: per-capacity-class padded probes +
                    # host merges for oversized outlier buckets, expanded to
                    # bucket-major host pairs. Ranges ride the probe memo — a
                    # count on the same rows has usually probed already.
                    plan = _classed_plan_cached(
                        self, left, right, l_starts, r_starts, subkey, rows_key
                    )
                    ranges = _classed_ranges_cached(
                        plan, left, right, subkey, rows_key
                    )
                    pairs = classed_pairs(plan, ranges)
                else:
                    l_rep, r_rep = self._reconciled_reps(
                        left, right, l_starts, r_starts
                    )
                    ranges = _probe_ranges_cached(
                        l_rep, r_rep, left, right, subkey, rows_key
                    )
                    pairs = probe_padded(l_rep, r_rep, ranges=ranges)
            return _verify_pairs(
                left, right, self.left_keys, self.right_keys, pairs[0], pairs[1]
            )

        li, ri = _cached_two_table(
            "pairs", left, right, subkey, compute, rows_key=rows_key
        )
        _tracing.set_attr("pairs_memo", "miss" if computed else "hit")
        return left, right, li, ri

    def _reconciled_reps(self, left: Table, right: Table, l_starts, r_starts):
        """Cached padded reps for both sides in ONE joint mode: if one side
        can't go value-direct (e.g. multi-file buckets after incremental
        refresh), both probe by hash — value keys and key64 hashes live in
        different spaces."""
        l_rep = _padded_rep(left, l_starts, self.left_keys)
        r_rep = _padded_rep(right, r_starts, self.right_keys)
        if l_rep.mode != r_rep.mode:
            if l_rep.mode == "value":
                l_rep = _padded_rep(left, l_starts, self.left_keys, force_hash=True)
            else:
                r_rep = _padded_rep(right, r_starts, self.right_keys, force_hash=True)
        return l_rep, r_rep

    def _bucketed_count_fast(self, ctx) -> Optional[int]:
        """Inner-join row count that never leaves the device.

        Value-direct reps compare ACTUAL key values in the probe (same promoted
        space as `_verify_pairs`' equality), so the probe counts are already
        exact — the count is one device reduction of the count matrix, with no
        pair expansion at all. Hash reps compute the same verified compacted
        device pairs the fused join→aggregate uses (shared memo). Returns
        None when this path does not apply (mesh-sharded execution, or hash
        mode on the CPU backend where the host expansion measured faster)."""
        from ..ops.backend import use_device_path
        from ..ops.bucket_join import _counts_total

        left, l_starts = self.left.execute_concat(ctx)
        right, r_starts = self.right.execute_concat(ctx)
        if left.num_rows == 0 or right.num_rows == 0:
            return 0
        # Cross-query reuse FIRST (even under a mesh): an aggregate/collect
        # over these same ROWS (any column pruning, any execution strategy)
        # has already computed and cached the verified pairs — the count is
        # free.
        subkey = _pair_subkey(
            self.left_keys, self.right_keys, self.left, self.right, left, right
        )
        rows_key = _pair_rows_key(self.left, self.right, ctx)
        hit, val = _peek_two_table("pairs", left, right, subkey, rows_key)
        if hit:
            return len(val[0])
        hit, val = _peek_two_table("pairs", left, right, ("dev",) + subkey, rows_key)
        if hit:
            return 0 if val is None else int(val[2])
        mesh = (
            ctx.session.mesh_for(left.num_rows + right.num_rows)
            if ctx.session is not None
            else None
        )
        if mesh is not None:
            return None  # the sharded probe owns mesh-scale execution
        from ..ops.bucket_join import size_classes_enabled

        if size_classes_enabled():
            plan = _classed_plan_cached(
                self, left, right, l_starts, r_starts, subkey, rows_key
            )
            if plan.mode != "value" and not use_device_path():
                return None  # hash-mode CPU counts ride the host pairs path
            if plan.mode == "value":
                # Value-direct classed probe counts are exact (outlier merges
                # included); repeat counts read `total` off the cached ranges.
                ranges = _classed_ranges_cached(
                    plan, left, right, subkey, rows_key
                )
                return ranges.total
            pairs = _cached_two_table(
                "pairs",
                left,
                right,
                ("dev",) + subkey,
                lambda: self._device_pairs_compacted(
                    left, right, l_starts, r_starts, subkey, rows_key
                ),
                rows_key=rows_key,
            )
            return 0 if pairs is None else int(pairs[2])
        l_rep, r_rep = self._reconciled_reps(left, right, l_starts, r_starts)
        if l_rep.mode != "value" and not use_device_path():
            # Hash-mode counts on the CPU backend take the host expansion path;
            # bailing BEFORE the probe avoids running it twice.
            return None
        if l_rep.mode == "value":
            # Value-direct: probe counts are exact. The probe RANGES are an
            # intermediate shared by counts, aggregates and collects, so they
            # ride the pairs memo keyed by row identity — a repeated count is
            # one reduction over the cached count matrix, not a re-probe
            # (1.15 s at 8M on TPU in round 4).
            _lo, counts = _probe_ranges_cached(
                l_rep, r_rep, left, right, subkey, rows_key
            )
            return int(_counts_total(counts))
        # Hash mode on the device path: the verified compacted device pairs
        # are the SAME artifact the fused join→aggregate caches — compute
        # through the shared memo so a count warms the aggregate and vice
        # versa, and repeats read n_keep straight from the cache.
        pairs = _cached_two_table(
            "pairs",
            left,
            right,
            ("dev",) + subkey,
            lambda: self._device_pairs_compacted(
                left, right, l_starts, r_starts, subkey, rows_key
            ),
            rows_key=rows_key,
        )
        return 0 if pairs is None else int(pairs[2])

    def _general_count_fast(self, ctx, pre) -> Optional[int]:
        """Inner-join row count for the GENERAL (non-bucketed) path without
        pulling pairs to the host: the global sort+probe (`_merge_phase_a`)
        already runs on device; candidate enumeration + exact verification
        reuse the bucketed machinery as its one-bucket special case. On the
        relay the old path pulled ~16 bytes per candidate pair to the host —
        this keeps the NON-indexed baseline count on-device too, so the bench
        compares two equally-tuned paths — the value-direct branch has a host
        twin for the CPU backend under the same principle. `pre` carries the
        already-executed children (shared with the `_compute_pairs`
        fallback). None when not applicable (hash mode on the CPU backend,
        mesh execution)."""
        from ..ops.backend import use_device_path
        from ..ops.bucket_join import _cap_pow2, _expand_pairs_dev
        from ..ops.join import _merge_phase_a

        device = use_device_path()
        _lex, _rex, lt, rt = pre
        how = self.how
        if lt.num_rows == 0 or rt.num_rows == 0:
            # No pairs exist: the shared arithmetic at all-zero match stats.
            return _count_from_match_stats(how, 0, 0, 0, lt.num_rows, rt.num_rows)
        if (
            ctx.session is not None
            and ctx.session.mesh_for(lt.num_rows + rt.num_rows) is not None
        ):
            return None  # the distributed exchange path owns mesh-scale counts
        if how == "inner" and len(self.left_keys) == 1:
            # Value-direct: a single null-free numeric key needs no hashing,
            # no candidate expansion, and no verification — one program
            # (device) or one sort+probe (host).
            lc = lt.column(self.left_keys[0])
            rc = rt.column(self.right_keys[0])
            if (
                not lc.is_string
                and not rc.is_string
                and lc.validity is None
                and rc.validity is None
                and lc.data.dtype != np.bool_
                and rc.data.dtype != np.bool_
            ):
                if device:
                    return int(
                        _value_inner_count_jit(
                            device_array(lc.data), device_array(rc.data)
                        )
                    )
                return int(_value_inner_count_body(lc.data, rc.data, xp=np))
        if not device:
            return None  # hash-mode counts on CPU ride the host pairs path
        l_flags, r_flags = _joint_float_flags(lt, rt, self.left_keys, self.right_keys)
        lk = _table_key64(lt, self.left_keys, l_flags)
        rk = _table_key64(rt, self.right_keys, r_flags)
        l_order, r_order, lo, counts, total_dev = _merge_phase_a(lk, rk)
        total = int(total_dev)
        if total == 0:
            n_pairs = lm = rm = 0
        else:
            starts_l = jnp.asarray(np.asarray([0, lt.num_rows], np.int64))
            starts_r = jnp.asarray(np.asarray([0, rt.num_rows], np.int64))
            li, ri, valid = _expand_pairs_dev(
                _cap_pow2(total),
                True,
                lo[None, :],
                counts[None, :],
                starts_l,
                starts_r,
                l_order[None, :],
                r_order[None, :],
            )
            lanes, flat = _verify_lanes(lt, rt, self.left_keys, self.right_keys)
            if how == "inner":
                return int(_verified_count_jit(lanes, li, ri, valid, *flat))
            n_pairs, lm, rm = (
                int(x)
                for x in _verified_match_counts_jit(
                    lanes,
                    _cap_pow2(lt.num_rows),
                    _cap_pow2(rt.num_rows),
                    li,
                    ri,
                    valid,
                    *flat,
                )
            )
        return _count_from_match_stats(
            how, n_pairs, lm, rm, lt.num_rows, rt.num_rows
        )

    def _device_pairs_compacted(
        self, left: Table, right: Table, l_starts, r_starts,
        subkey=None, rows_key=None,
    ):
        """VERIFIED inner-join pairs as DEVICE arrays, compacted and padded to a
        static pow2 size: (li, ri, n_keep, out_cap) with slots >= n_keep
        repeating the first real pair. The whole pipeline — probe, expansion,
        exact verification, compaction — runs on device; nothing row-scale
        crosses the host boundary. Feeds the fused join→aggregate path.
        Returns None for empty joins (caller falls back). With `subkey` (the
        bare pair subkey) the probe ranges ride the probe memo, so a count
        that probed these rows already hands its ranges to this expansion."""
        from ..ops.bucket_join import (
            _cap_pow2,
            _compact_pairs_dev,
            _counts_total,
            _expand_pairs_dev,
            classed_pairs_dev,
            probe_classed,
            probe_keys_promoted,
            probe_orientation,
            probe_ranges,
            size_classes_enabled,
        )

        if size_classes_enabled():
            plan = _classed_plan_cached(
                self, left, right, l_starts, r_starts,
                subkey if subkey is not None else (), rows_key,
            )
            if subkey is not None:
                ranges = _classed_ranges_cached(
                    plan, left, right, subkey, rows_key
                )
            else:
                ranges = probe_classed(plan)
            total = ranges.total
            if total == 0:
                return None
            expanded = classed_pairs_dev(plan, ranges)
            if expanded is None:
                return None
            li, ri, valid = expanded
            out_cap = int(li.shape[0])
            has_order = plan.mode == "hash"
        else:
            l_rep, r_rep = self._reconciled_reps(left, right, l_starts, r_starts)
            a, b, swapped = probe_orientation(l_rep, r_rep)
            if subkey is not None:
                lo, counts = _probe_ranges_cached(
                    l_rep, r_rep, left, right, subkey, rows_key
                )
            else:
                ak, bk = probe_keys_promoted(a.keys, b.keys)
                lo, counts = probe_ranges(ak, bk, a.lengths, b.lengths)
            total = int(_counts_total(counts))
            if total == 0:
                return None
            out_cap = _cap_pow2(total)
            has_order = l_rep.mode == "hash"
            dummy = jnp.zeros((1, 1), dtype=jnp.int64)
            ai, bi, valid = _expand_pairs_dev(
                out_cap,
                has_order,
                lo,
                counts,
                device_array(a.starts),
                device_array(b.starts),
                device_array(a.order) if has_order else dummy,
                device_array(b.order) if has_order else dummy,
            )
            li, ri = (bi, ai) if swapped else (ai, bi)
        if has_order:
            # Hash candidates: exact-equality + null-key verification on device.
            lanes, flat = _verify_lanes(left, right, self.left_keys, self.right_keys)
            keep = _verified_keep_jit(lanes, li, ri, valid, *flat)
            n_keep = int(keep.sum())
        else:
            # Value-direct probes compared actual keys: every in-range pair is real.
            keep = valid
            n_keep = total
        if n_keep == 0:
            return None
        if n_keep == out_cap:
            return li, ri, n_keep, out_cap
        out2 = _cap_pow2(n_keep)
        li2, ri2 = _compact_pairs_dev(out2, li, ri, keep)
        return li2, ri2, n_keep, out2

    def simple_string(self):
        mode = " (bucketed, no exchange)" if self.bucketed else ""
        how = f" {self.how}" if self.how != "inner" else ""
        pairs = ", ".join(f"{l}={r}" for l, r in zip(self.left_keys, self.right_keys))
        return f"SortMergeJoin{how} [{pairs}]{mode}"


class MultiwayJoinExec(PhysicalNode):
    """N-way star join (one fact, 2+ dimensions, all inner equi-joins on fact
    FKs) planned from a recognized `StarJoinNode`. Carries BOTH executions:
    `cascade` is the fully-planned cascaded binary-join tree, and
    `execute`/`execute_count` delegate to it — so any consumer that is not
    the streamed star→aggregate path (materializing queries, counts, the
    `HYPERSPACE_MULTIWAY` runtime gate off, planner picking the cascade arm)
    gets byte-identical cascaded results with no extra machinery. The
    streamed path (`streaming.stream_star_aggregate`, entered from
    `HashAggregateExec`) is the only consumer of `fact` and `dims`: per fact
    chunk it probes every dimension's covering index and folds straight into
    the aggregator, never materializing the intermediate fact."""

    name = "MultiwayJoin"

    def __init__(self, fact: PhysicalNode, dims, cascade: PhysicalNode):
        self.fact = fact
        # One (dim_exec, fact_keys, dim_keys, index_name, num_buckets) per
        # dimension, innermost join first — the cascade's fold order, which
        # fixes output column naming and the odometer's digit order.
        self.dims = list(dims)
        self.cascade = cascade

    def children(self):
        return (self.fact,) + tuple(d[0] for d in self.dims) + (self.cascade,)

    def execute(self, ctx) -> Table:
        return self.cascade.execute(ctx)

    def execute_count(self, ctx) -> int:
        return self.cascade.execute_count(ctx)

    def simple_string(self):
        names = ", ".join(d[3] or "?" for d in self.dims)
        return f"MultiwayJoin ({len(self.dims)} dims: {names})"


# ---------------------------------------------------------------------------
# Planner: logical → physical
# ---------------------------------------------------------------------------


def _orient_join_keys(
    pairs: List[Tuple[str, str]], left_schema: Schema, right_schema: Schema
) -> Tuple[List[str], List[str]]:
    """Orient each (a, b) condition pair as (left_col, right_col). A name
    resolving on BOTH sides is refused loudly — the same rule as the join
    rewrite's `_orient_pairs` (a silent left-to-right guess could join on the
    wrong columns; the reference requires every condition attribute to resolve
    to exactly one base relation, `JoinIndexRule.scala:287-326`)."""
    lkeys, rkeys = [], []
    for a, b in pairs:
        a_in_l, a_in_r = a in left_schema, a in right_schema
        b_in_l, b_in_r = b in left_schema, b in right_schema
        if a.lower() == b.lower() and a_in_l and b_in_r:
            # Same name on both operands: any orientation means
            # left.name == right.name — unambiguous by construction.
            lkeys.append(a)
            rkeys.append(b)
        elif a_in_l and b_in_r and not (a_in_r or b_in_l):
            lkeys.append(a)
            rkeys.append(b)
        elif a_in_r and b_in_l and not (a_in_l or b_in_r):
            lkeys.append(b)
            rkeys.append(a)
        elif (a_in_l and a_in_r) or (b_in_l and b_in_r):
            raise HyperspaceException(
                f"Ambiguous join condition column(s) {a!r}/{b!r}: a name "
                "resolves on both sides; qualify by renaming before the join"
            )
        else:
            # Unresolvable, or both columns live on the same single side —
            # the condition does not span the join.
            raise HyperspaceException(
                f"Cannot resolve join condition column(s) {a!r}/{b!r}"
            )
    return lkeys, rkeys


def plan_physical(
    logical: LogicalPlan,
    required: Optional[List[str]] = None,
    case_sensitive: bool = False,
) -> PhysicalNode:
    """Compile a logical plan to a physical one, pushing column pruning into scans.

    `case_sensitive` governs how `required` names match schema names
    (`hyperspace.resolution.caseSensitive`; default matches Spark's
    case-insensitive resolution)."""
    from ..util.resolver_utils import resolution_key

    def key(s: str) -> str:
        return resolution_key(s, case_sensitive)
    if isinstance(logical, ScanNode):
        rel = logical.relation
        cols = None
        if required is not None:
            wanted = {key(r) for r in required}
            cols = [n for n in rel.schema.names if key(n) in wanted]
            if not cols and rel.schema.names:
                # A computed-only projection (e.g. select of a pure-literal
                # with_column) references no source columns; keep one so the
                # scan still carries the row count.
                cols = [rel.schema.names[0]]
        if rel.bucket_spec is not None:
            return BucketedIndexScanExec(rel, cols)
        return ScanExec(rel, cols)

    if isinstance(logical, FilterNode):
        child_required = None
        refs = sorted(logical.condition.references())
        if required is not None:
            child_required = list(dict.fromkeys(list(required) + refs))
        else:
            # "Everything" excludes a scan's HIDDEN columns (the index lineage
            # column): a condition referencing one (the delete-prune filter)
            # must request it explicitly alongside the visible schema.
            visible = {n.lower() for n in logical.child.output_schema.names}
            if any(r.lower() not in visible for r in refs):
                child_required = list(
                    dict.fromkeys(list(logical.child.output_schema.names) + refs)
                )
        child_phys = plan_physical(logical.child, child_required, case_sensitive)
        if type(child_phys) is ScanExec:
            # Thread the filter's conjuncts into the scan it sits on: the
            # scan may skip parquet row groups whose zone maps prove no row
            # can pass this exact filter (advisory — the FilterExec still
            # evaluates the full condition over whatever the scan returns).
            child_phys.pushdown = logical.condition
        return FilterExec(logical.condition, child_phys)

    if isinstance(logical, ProjectNode):
        return ProjectExec(
            logical.column_names, plan_physical(logical.child, list(logical.column_names), case_sensitive)
        )

    if isinstance(logical, UnionNode):
        return UnionExec([plan_physical(c, required, case_sensitive) for c in logical.children()])

    if isinstance(logical, (IntersectNode, ExceptNode)):
        # Set-op row equality spans EVERY column: children cannot be pruned to
        # the outer projection (a projection above still prunes the output).
        return SetOpExec(
            "intersect" if isinstance(logical, IntersectNode) else "except",
            plan_physical(logical.left, None, case_sensitive),
            plan_physical(logical.right, None, case_sensitive),
        )

    if isinstance(logical, WithColumnNode):
        if required is not None and all(
            key(r) != key(logical.name) for r in required
        ):
            # The computed column is pruned out downstream: skip the evaluation
            # entirely (it cannot change row count or other columns).
            return plan_physical(logical.child, required, case_sensitive)
        child_required = None
        if required is not None:
            keep = [r for r in required if key(r) != key(logical.name)]
            child_required = list(
                dict.fromkeys(keep + sorted(logical.expr.references()))
            )
        return WithColumnExec(
            logical.name,
            logical.expr,
            plan_physical(logical.child, child_required, case_sensitive),
            dtype=logical.output_schema.field(logical.name).dtype,
        )

    if isinstance(logical, AggregateNode):
        # The aggregate consumes only its group keys + agg inputs; push that set
        # down as the pruning frontier (outer `required` cannot reach past an
        # aggregate — its outputs are new names).
        child_required = list(dict.fromkeys(logical.references()))
        if not child_required:
            # Pure count(*): keep one column so the scan still yields row counts.
            child_required = logical.child.output_schema.names[:1] or None
        return HashAggregateExec(
            logical.group_keys, logical.aggs, plan_physical(logical.child, child_required, case_sensitive)
        )

    if isinstance(logical, OrderByNode):
        child_required = None
        if required is not None:
            child_required = list(
                dict.fromkeys(list(required) + [k for k, _ in logical.keys])
            )
        return OrderByExec(logical.keys, plan_physical(logical.child, child_required, case_sensitive))

    if isinstance(logical, LimitNode):
        return LimitExec(logical.n, plan_physical(logical.child, required, case_sensitive))

    if isinstance(logical, JoinNode):
        pairs = extract_equi_join_keys(logical.condition)
        if pairs is None:
            raise HyperspaceException(
                f"Only equi-joins are supported: {logical.condition!r}"
            )
        how = logical.how
        lschema, rschema = logical.left.output_schema, logical.right.output_schema
        lkeys, rkeys = _orient_join_keys(pairs, lschema, rschema)

        lreq = rreq = None
        if required is not None:
            req = {key(r) for r in required}
            lreq = [n for n in lschema.names if key(n) in req] + lkeys
            rreq = [n for n in rschema.names if key(n) in req] + rkeys
            lreq = list(dict.fromkeys(lreq))
            rreq = list(dict.fromkeys(rreq))
        if how in ("left_semi", "left_anti"):
            # Semi/anti output only the left side; the right scan needs its keys.
            rreq = list(dict.fromkeys(rkeys))

        lphys = plan_physical(logical.left, lreq, case_sensitive)
        rphys = plan_physical(logical.right, rreq, case_sensitive)

        # Bucketed fast path: both sides are bucketed index scans — possibly
        # under a filter, which preserves bucket membership and in-bucket
        # order (`FilterExec.execute_concat`) — partitioned on exactly the
        # join keys, listing bucket columns in the same order under the L→R
        # key mapping, with equal bucket counts → no exchange needed. (This
        # is the planner-side re-check of the join rule's compatibility
        # condition.) ALL join types ride it: the bucketed probe yields the
        # verified inner pairs, from which _assemble_join / the match-stats
        # count derive outer/semi/anti results exactly as the general path
        # does.
        def _as_bucketed(phys: PhysicalNode) -> Optional[BucketedIndexScanExec]:
            if isinstance(phys, BucketedIndexScanExec):
                return phys
            if isinstance(phys, FilterExec) and isinstance(
                phys.child, BucketedIndexScanExec
            ):
                return phys.child
            return None

        lbucket = _as_bucketed(lphys)
        rbucket = _as_bucketed(rphys)
        if lbucket is not None and rbucket is not None:
            lspec = lbucket.relation.bucket_spec
            rspec = rbucket.relation.bucket_spec
            # A left key equated to two different right keys (l.a==r.x AND l.a==r.y)
            # cannot ride the bucketed path: bucketing covers only one of the pairs.
            # Name matching honors the session's resolution mode via key()
            # (in case-sensitive mode, columns differing only by case must
            # not be conflated when deciding the no-shuffle path).
            pair_map: Dict[str, str] = {}
            consistent = True
            for l, r in zip(lkeys, rkeys):
                if key(pair_map.get(key(l), r)) != key(r):
                    consistent = False
                    break
                pair_map[key(l)] = r
            lbc = list(lspec.bucket_columns)
            rbc = list(rspec.bucket_columns)
            if (
                consistent
                and len(set(key(k) for k in lkeys)) == len(lkeys)
                and lspec.num_buckets == rspec.num_buckets
                and {key(c) for c in lbc} == {key(k) for k in lkeys}
                and [key(pair_map.get(key(c), "")) for c in lbc]
                == [key(c) for c in rbc]
            ):
                # Join keys in bucket-column order so per-bucket key hashing pairs up.
                jl = lbc
                jr = [pair_map[key(c)] for c in lbc]
                # Kind compatibility: bucket assignment hashed each column in
                # its OWN kind at build time, so an int-bucketed side is not
                # co-located with a float-bucketed one even for equal values —
                # mixed pairs take the general join (float64 joint hashing).
                def _kind(schema, name):
                    dt = schema.field(name).dtype
                    return "f" if dt in ("float32", "float64") else (
                        "s" if dt == "string" else "i"
                    )

                kinds_ok = all(
                    _kind(lbucket.relation.schema, a)
                    == _kind(rbucket.relation.schema, b)
                    for a, b in zip(jl, jr)
                )
                if kinds_ok:
                    return SortMergeJoinExec(
                        lphys, rphys, jl, jr, bucketed=True, how=how
                    )

        # General path: exchange + sort both sides.
        if isinstance(lphys, BucketedIndexScanExec):
            lphys = ScanExec(lphys.relation, lphys.columns)
        if isinstance(rphys, BucketedIndexScanExec):
            rphys = ScanExec(rphys.relation, rphys.columns)
        lside = SortExec(lkeys, ShuffleExchangeExec(lkeys, lphys))
        rside = SortExec(rkeys, ShuffleExchangeExec(rkeys, rphys))
        return SortMergeJoinExec(lside, rside, lkeys, rkeys, bucketed=False, how=how)

    if isinstance(logical, StarJoinNode):
        # The cascade is planned exactly as if the wrapper did not exist —
        # it is the byte-identical execution for every non-streamed consumer
        # and the fallback whenever the star side plan cannot be completed.
        cascade = plan_physical(logical.cascade, required, case_sensitive)
        try:
            chain: List[JoinNode] = []
            cur: LogicalPlan = logical.cascade
            while isinstance(cur, JoinNode):
                chain.append(cur)
                cur = cur.left
            if len(chain) != len(logical.dims) or any(
                j.how != "inner" for j in chain
            ):
                return cascade
            fact = plan_physical(
                cur, list(logical.fact_required), case_sensitive
            )
            dims = []
            for d in logical.dims:
                dim_phys = plan_physical(
                    d.plan, list(d.dim_required), case_sensitive
                )
                probe = dim_phys
                if isinstance(probe, FilterExec):
                    probe = probe.child
                if not isinstance(probe, BucketedIndexScanExec):
                    # The dimension's covering index lost its bucketed scan
                    # shape (e.g. a later rule rewrote it): the per-bucket
                    # probe has no layout to work with — run the cascade.
                    return cascade
                dims.append(
                    (
                        dim_phys,
                        list(d.fact_keys),
                        list(d.dim_keys),
                        d.index_name,
                        int(d.num_buckets),
                    )
                )
            return MultiwayJoinExec(fact, dims, cascade)
        except HyperspaceException:
            return cascade

    raise HyperspaceException(f"Cannot plan logical node: {logical.simple_string()}")
