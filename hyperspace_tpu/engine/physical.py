"""Physical plan + executor.

The engine analogue of Spark's SparkPlan/physical operators, executed with JAX device
ops. Operator names matter: the explain subsystem counts them to show what a rewrite
eliminated (`PhysicalOperatorAnalyzer.scala:30-57` counts `ShuffleExchange` removed),
and the E2E tests assert which files a scan touched.

Join strategy (TPU-first):
- General equi-join: ShuffleExchange markers on both sides + a global hash-key
  sort-merge (`ops.join.merge_join_pairs` over `ops.hashing.key64`), with exact
  re-verification of key equality so hash collisions cannot corrupt results.
- Co-bucketed index join (set up by the join rewrite rule): both sides arrive
  hash-partitioned into the same number of buckets on the join keys, so the merge runs
  per bucket pair with NO exchange — the whole point of the covering-index design
  (reference `JoinIndexRule.scala:137-162`). On a device mesh the bucket axis shards
  with zero cross-device traffic.
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..exceptions import HyperspaceException
from ..ops.hashing import key64
from ..ops.join import merge_join_pairs, nonzero_indices
from . import io as engine_io
from .evaluate import evaluate_predicate
from .expr import Col, Expr, extract_equi_join_keys
from .logical import (
    BucketSpec,
    FilterNode,
    JoinNode,
    LogicalPlan,
    ProjectNode,
    ScanNode,
    SourceRelation,
    UnionNode,
)
from .schema import Schema
from .table import Column, Table, align_dictionaries

_BUCKET_FILE_RE = re.compile(r"part-(\d+)")


class ExecContext:
    def __init__(self, session=None):
        self.session = session


class PhysicalNode:
    name = "Physical"

    def children(self) -> Sequence["PhysicalNode"]:
        return ()

    def execute(self, ctx: ExecContext) -> Table:
        raise NotImplementedError

    def simple_string(self) -> str:
        return self.name

    def format_line(self, indent: int) -> str:
        """One tree line for this node — the single source of the tree format (the
        explain renderer reuses it for highlight-aware output)."""
        return "  " * indent + ("+- " if indent else "") + self.simple_string()

    def tree_string(self, indent: int = 0) -> str:
        lines = [self.format_line(indent)]
        for c in self.children():
            lines.append(c.tree_string(indent + 1))
        return "\n".join(lines)

    def collect_nodes(self) -> List["PhysicalNode"]:
        out: List[PhysicalNode] = [self]
        for c in self.children():
            out.extend(c.collect_nodes())
        return out


class ScanExec(PhysicalNode):
    name = "Scan"

    def __init__(self, relation: SourceRelation, columns: Optional[List[str]] = None):
        self.relation = relation
        self.columns = columns

    def execute(self, ctx) -> Table:
        if self.relation.hybrid_append is not None and self.relation.bucket_spec is not None:
            # Demoted bucketed index scan (general join path / plain read): still must
            # merge the hybrid-appended rows.
            return BucketedIndexScanExec(self.relation, self.columns).execute(ctx)
        files = [f.path for f in self.relation.files]
        if not files:
            # Every file pruned (data skipping) or an empty source: empty table.
            names = self.columns or self.relation.schema.names
            return Table(
                {n: _empty_column(self.relation.schema.field(n).dtype) for n in names}
            )
        return engine_io.read_files(files, self.relation.file_format, self.columns)

    def simple_string(self):
        cols = f" [{', '.join(self.columns)}]" if self.columns else ""
        tag = f" index={self.relation.index_name}" if self.relation.index_name else ""
        if self.relation.pruned_by:
            tag += f" (files pruned by {','.join(self.relation.pruned_by)})"
        return f"Scan{tag} {','.join(self.relation.root_paths)}{cols}"


class BucketedIndexScanExec(PhysicalNode):
    """Reads index data preserving bucket structure (list of per-bucket tables).

    Only appears under a SortMergeJoinExec in bucketed mode; its bucket ids come from
    the `part-<bucket>` file naming contract of the bucketed writer."""

    name = "BucketedIndexScan"

    def __init__(self, relation: SourceRelation, columns: Optional[List[str]] = None):
        assert relation.bucket_spec is not None
        self.relation = relation
        self.columns = columns

    def execute_buckets(self, ctx) -> List[Optional[Table]]:
        spec = self.relation.bucket_spec
        buckets: List[Optional[Table]] = [None] * spec.num_buckets
        for f in self.relation.files:
            m = _BUCKET_FILE_RE.search(os.path.basename(f.path))
            if m is None:
                raise HyperspaceException(f"Not a bucketed index file: {f.path}")
            b = int(m.group(1))
            t = engine_io.read_files([f.path], self.relation.file_format, self.columns)
            buckets[b] = t if buckets[b] is None else Table.concat([buckets[b], t])
        if self.relation.hybrid_append is not None:
            self._merge_appended(buckets)
        return buckets

    def _merge_appended(self, buckets: List[Optional[Table]]) -> None:
        """Hybrid Scan shuffle-union: bucketize the appended source rows with the
        index's own partitioning (same hash, same bucket count) and merge them into
        the bucket tables — the on-the-fly analogue of the index build, so the
        co-bucketed join stays correct with no shuffle of the INDEX data."""
        from ..config import IndexConstants
        from ..ops.partition import bucketize_table

        ha = self.relation.hybrid_append
        spec = self.relation.bucket_spec
        wanted = self.columns or self.relation.schema.names
        lineage_col = IndexConstants.DATA_FILE_NAME_COLUMN
        source_cols = [c for c in wanted if c.lower() != lineage_col]
        parts = []
        for f in ha.files:
            t = engine_io.read_files([f.path], ha.file_format, source_cols)
            if any(c.lower() == lineage_col for c in wanted):
                cols = dict(t.columns)
                cols[lineage_col] = Table.from_pydict(
                    {lineage_col: [f.path] * t.num_rows}
                ).column(lineage_col)
                t = Table(cols)
            parts.append(t)
        appended = Table.concat(parts) if len(parts) > 1 else parts[0]
        appended = appended.select(wanted)
        sorted_t, starts = bucketize_table(
            appended, list(spec.bucket_columns), spec.num_buckets
        )
        for b in range(spec.num_buckets):
            lo, hi = int(starts[b]), int(starts[b + 1])
            if hi <= lo:
                continue
            part = sorted_t.take(np.arange(lo, hi))
            buckets[b] = part if buckets[b] is None else Table.concat([buckets[b], part])

    def empty_table(self) -> Table:
        """Empty table with this scan's (pruned) schema."""
        names = self.columns or self.relation.schema.names
        return Table(
            {n: _empty_column(self.relation.schema.field(n).dtype) for n in names}
        )

    def execute(self, ctx) -> Table:
        tables = [t for t in self.execute_buckets(ctx) if t is not None]
        if not tables:
            return self.empty_table()
        return Table.concat(tables)

    def simple_string(self):
        spec = self.relation.bucket_spec
        return (
            f"BucketedIndexScan index={self.relation.index_name} "
            f"buckets={spec.num_buckets} by {list(spec.bucket_columns)}"
        )


def _empty_column(dtype: str) -> Column:
    if dtype == "string":
        return Column("string", np.empty(0, np.int32), np.empty(0, "<U1"))
    return Column(dtype, np.empty(0, np.dtype(dtype)))


class FilterExec(PhysicalNode):
    name = "Filter"

    def __init__(self, condition: Expr, child: PhysicalNode):
        self.condition = condition
        self.child = child

    def children(self):
        return (self.child,)

    def execute(self, ctx) -> Table:
        t = self.child.execute(ctx)
        if t.num_rows == 0:
            return t
        mask = evaluate_predicate(self.condition, t)
        return t.take(nonzero_indices(mask))

    def simple_string(self):
        return f"Filter {self.condition!r}"


class ProjectExec(PhysicalNode):
    name = "Project"

    def __init__(self, column_names: Sequence[str], child: PhysicalNode):
        self.column_names = list(column_names)
        self.child = child

    def children(self):
        return (self.child,)

    def execute(self, ctx) -> Table:
        return self.child.execute(ctx).select(self.column_names)

    def simple_string(self):
        return f"Project [{', '.join(self.column_names)}]"


class UnionExec(PhysicalNode):
    name = "Union"

    def __init__(self, children: Sequence[PhysicalNode]):
        self._children = list(children)

    def children(self):
        return tuple(self._children)

    def execute(self, ctx) -> Table:
        tables = [c.execute(ctx) for c in self._children]
        # Align column order/spelling to the first child before concatenating.
        names = tables[0].column_names
        tables = [t if t.column_names == names else t.select(names) for t in tables]
        return Table.concat([t for t in tables])

    def simple_string(self):
        return f"Union ({len(self._children)})"


class ShuffleExchangeExec(PhysicalNode):
    """Hash-repartition marker — the operator the bucketed index path eliminates.

    Single-process execution is a pass-through (all data shares one memory space); the
    distributed executor replaces it with an all-to-all over the device mesh. Its
    presence/absence in the plan is what explain's operator diff reports."""

    name = "ShuffleExchange"

    def __init__(self, keys: Sequence[str], child: PhysicalNode):
        self.keys = list(keys)
        self.child = child

    def children(self):
        return (self.child,)

    def execute(self, ctx) -> Table:
        return self.child.execute(ctx)

    def simple_string(self):
        return f"ShuffleExchange hashpartitioning({', '.join(self.keys)})"


class SortExec(PhysicalNode):
    """Sort marker (the SMJ's required child ordering).

    Pass-through at execution time: the merge join sorts by key hash internally
    (`merge_join_pairs`), so physically reordering here would double the work. The
    node exists for plan-shape honesty — it is one of the operators the bucketed
    index path eliminates, which explain's operator diff reports."""

    name = "Sort"

    def __init__(self, keys: Sequence[str], child: PhysicalNode):
        self.keys = list(keys)
        self.child = child

    def children(self):
        return (self.child,)

    def execute(self, ctx) -> Table:
        return self.child.execute(ctx)

    def simple_string(self):
        return f"Sort [{', '.join(self.keys)}]"


def _gather_verified(
    left: Table,
    right: Table,
    left_keys: List[str],
    right_keys: List[str],
    li: np.ndarray,
    ri: np.ndarray,
) -> Table:
    """Gather matched rows, dropping 64-bit hash collisions via exact key equality."""
    lcols = [left.column(k) for k in left_keys]
    rcols = [right.column(k) for k in right_keys]
    if len(li):
        keep = np.ones(len(li), dtype=bool)
        for lc, rc in zip(lcols, rcols):
            if lc.is_string != rc.is_string:
                raise HyperspaceException("Join key type mismatch (string vs numeric)")
            lv = lc.decode()[li]
            rv = rc.decode()[ri]
            keep &= lv == rv
        if not keep.all():
            li, ri = li[keep], ri[keep]
    lt = left.take(li)
    rt = right.take(ri)
    out: Dict[str, Column] = dict(lt.columns)
    for n, c in rt.columns.items():
        out[n if n not in out else f"{n}_r"] = c
    return Table(out)


def _table_key64(table: Table, keys: List[str]):
    cols = [table.column(k) for k in keys]
    return key64(cols, [jnp.asarray(c.data) for c in cols])


def _join_tables(
    left: Table,
    right: Table,
    left_keys: List[str],
    right_keys: List[str],
) -> Table:
    """Hash-key merge join of two tables with exact verification."""
    li, ri = merge_join_pairs(
        _table_key64(left, left_keys), _table_key64(right, right_keys)
    )
    return _gather_verified(left, right, left_keys, right_keys, li, ri)


class SortMergeJoinExec(PhysicalNode):
    name = "SortMergeJoin"

    def __init__(
        self,
        left: PhysicalNode,
        right: PhysicalNode,
        left_keys: List[str],
        right_keys: List[str],
        bucketed: bool = False,
    ):
        self.left = left
        self.right = right
        self.left_keys = left_keys
        self.right_keys = right_keys
        self.bucketed = bucketed

    def children(self):
        return (self.left, self.right)

    def execute(self, ctx) -> Table:
        if self.bucketed:
            return self._execute_bucketed(ctx)
        lt = self.left.execute(ctx)
        rt = self.right.execute(ctx)
        return _join_tables(lt, rt, self.left_keys, self.right_keys)

    def _execute_bucketed(self, ctx) -> Table:
        """Batched co-bucketed merge join: equal keys are co-located by construction
        (both sides hash-partitioned with the same function and bucket count), so all
        bucket pairs join independently — executed as ONE device program over padded
        [num_buckets, cap] matrices (`ops.bucket_join`), with no data exchange."""
        assert isinstance(self.left, BucketedIndexScanExec)
        assert isinstance(self.right, BucketedIndexScanExec)
        from ..ops.bucket_join import bucketed_merge_join_pairs

        def concat_with_starts(scan: BucketedIndexScanExec):
            buckets = scan.execute_buckets(ctx)
            sizes = [0 if t is None else t.num_rows for t in buckets]
            starts = np.zeros(len(buckets) + 1, dtype=np.int64)
            np.cumsum(sizes, out=starts[1:])
            tables = [t for t in buckets if t is not None and t.num_rows > 0]
            if not tables:
                return scan.empty_table(), starts
            return Table.concat(tables), starts

        left, l_starts = concat_with_starts(self.left)
        right, r_starts = concat_with_starts(self.right)
        if left.num_rows == 0 or right.num_rows == 0:
            return _gather_verified(
                left, right, self.left_keys, self.right_keys,
                np.empty(0, np.int64), np.empty(0, np.int64),
            )
        li, ri = bucketed_merge_join_pairs(
            _table_key64(left, self.left_keys),
            l_starts,
            _table_key64(right, self.right_keys),
            r_starts,
        )
        return _gather_verified(left, right, self.left_keys, self.right_keys, li, ri)

    def simple_string(self):
        mode = " (bucketed, no exchange)" if self.bucketed else ""
        pairs = ", ".join(f"{l}={r}" for l, r in zip(self.left_keys, self.right_keys))
        return f"SortMergeJoin [{pairs}]{mode}"


# ---------------------------------------------------------------------------
# Planner: logical → physical
# ---------------------------------------------------------------------------


def _orient_join_keys(
    pairs: List[Tuple[str, str]], left_schema: Schema, right_schema: Schema
) -> Tuple[List[str], List[str]]:
    lkeys, rkeys = [], []
    for a, b in pairs:
        a_in_l, a_in_r = a in left_schema, a in right_schema
        b_in_l, b_in_r = b in left_schema, b in right_schema
        if a_in_l and b_in_r and not (a_in_r and b_in_l):
            lkeys.append(a)
            rkeys.append(b)
        elif a_in_r and b_in_l and not (a_in_l and b_in_r):
            lkeys.append(b)
            rkeys.append(a)
        elif a_in_l and b_in_r:
            # Ambiguous (name exists on both sides): default left-to-right.
            lkeys.append(a)
            rkeys.append(b)
        else:
            raise HyperspaceException(
                f"Cannot resolve join condition column(s) {a!r}/{b!r}"
            )
    return lkeys, rkeys


def plan_physical(logical: LogicalPlan, required: Optional[List[str]] = None) -> PhysicalNode:
    """Compile a logical plan to a physical one, pushing column pruning into scans."""
    if isinstance(logical, ScanNode):
        rel = logical.relation
        cols = None
        if required is not None:
            wanted = {r.lower() for r in required}
            cols = [n for n in rel.schema.names if n.lower() in wanted]
        if rel.bucket_spec is not None:
            return BucketedIndexScanExec(rel, cols)
        return ScanExec(rel, cols)

    if isinstance(logical, FilterNode):
        child_required = None
        if required is not None:
            child_required = list(dict.fromkeys(list(required) + sorted(logical.condition.references())))
        return FilterExec(logical.condition, plan_physical(logical.child, child_required))

    if isinstance(logical, ProjectNode):
        return ProjectExec(
            logical.column_names, plan_physical(logical.child, list(logical.column_names))
        )

    if isinstance(logical, UnionNode):
        return UnionExec([plan_physical(c, required) for c in logical.children()])

    if isinstance(logical, JoinNode):
        if logical.how != "inner":
            raise HyperspaceException(f"Unsupported join type: {logical.how}")
        pairs = extract_equi_join_keys(logical.condition)
        if pairs is None:
            raise HyperspaceException(
                f"Only equi-joins are supported: {logical.condition!r}"
            )
        lschema, rschema = logical.left.output_schema, logical.right.output_schema
        lkeys, rkeys = _orient_join_keys(pairs, lschema, rschema)

        lreq = rreq = None
        if required is not None:
            req = {r.lower() for r in required}
            lreq = [n for n in lschema.names if n.lower() in req] + lkeys
            rreq = [n for n in rschema.names if n.lower() in req] + rkeys
            lreq = list(dict.fromkeys(lreq))
            rreq = list(dict.fromkeys(rreq))

        lphys = plan_physical(logical.left, lreq)
        rphys = plan_physical(logical.right, rreq)

        # Bucketed fast path: both sides are bucketed index scans, partitioned on
        # exactly the join keys, listing bucket columns in the same order under the
        # L→R key mapping, with equal bucket counts → no exchange needed. (This is
        # the planner-side re-check of the join rule's compatibility condition.)
        if isinstance(lphys, BucketedIndexScanExec) and isinstance(
            rphys, BucketedIndexScanExec
        ):
            lspec = lphys.relation.bucket_spec
            rspec = rphys.relation.bucket_spec
            # A left key equated to two different right keys (l.a==r.x AND l.a==r.y)
            # cannot ride the bucketed path: bucketing covers only one of the pairs.
            pair_map: Dict[str, str] = {}
            consistent = True
            for l, r in zip(lkeys, rkeys):
                if pair_map.get(l.lower(), r).lower() != r.lower():
                    consistent = False
                    break
                pair_map[l.lower()] = r
            lbc = list(lspec.bucket_columns)
            rbc = list(rspec.bucket_columns)
            if (
                consistent
                and len(set(k.lower() for k in lkeys)) == len(lkeys)
                and lspec.num_buckets == rspec.num_buckets
                and {c.lower() for c in lbc} == {k.lower() for k in lkeys}
                and [pair_map.get(c.lower(), "").lower() for c in lbc]
                == [c.lower() for c in rbc]
            ):
                # Join keys in bucket-column order so per-bucket key hashing pairs up.
                jl = lbc
                jr = [pair_map[c.lower()] for c in lbc]
                return SortMergeJoinExec(lphys, rphys, jl, jr, bucketed=True)

        # General path: exchange + sort both sides.
        if isinstance(lphys, BucketedIndexScanExec):
            lphys = ScanExec(lphys.relation, lphys.columns)
        if isinstance(rphys, BucketedIndexScanExec):
            rphys = ScanExec(rphys.relation, rphys.columns)
        lside = SortExec(lkeys, ShuffleExchangeExec(lkeys, lphys))
        rside = SortExec(rkeys, ShuffleExchangeExec(rkeys, rphys))
        return SortMergeJoinExec(lside, rside, lkeys, rkeys, bucketed=False)

    raise HyperspaceException(f"Cannot plan logical node: {logical.simple_string()}")
