"""Hive-style partitioned sources: `root/key=value/.../file.parquet`.

The reference indexes partitioned datasets — Spark's PartitioningAwareFileIndex
turns `key=value` path segments into columns, and index creation pulls missing
partition columns into the index when lineage is on
(`CreateActionBase.scala:176-188`; partitioned cases throughout
`E2EHyperspaceRulesTests.scala`). The engine analogue: discover the partition
layout once at scan resolution, append the (per-file constant) partition columns
at read time, and let everything downstream — signatures, rules, the index build —
see them as ordinary columns.

Values are URL-decoded; `__HIVE_DEFAULT_PARTITION__` is NULL (Spark's spelling of
a null partition value). Column types: int64 when every non-null value parses as
an integer, else string (Spark's inference, minus the fractional/date cases the
engine's type system folds into strings anyway).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple
from urllib.parse import unquote

import numpy as np

from ..exceptions import HyperspaceException
from .schema import INT64, STRING, Field
from .table import Column, Table

HIVE_NULL = "__HIVE_DEFAULT_PARTITION__"


@dataclass(frozen=True)
class PartitionSpec:
    """Ordered partition columns + inferred dtypes (int64 | string)."""

    columns: Tuple[str, ...]
    dtypes: Tuple[str, ...]

    def to_json(self) -> dict:
        return {"columns": list(self.columns), "dtypes": list(self.dtypes)}

    @staticmethod
    def from_json(d: Optional[dict]) -> Optional["PartitionSpec"]:
        if d is None:
            return None
        return PartitionSpec(tuple(d["columns"]), tuple(d["dtypes"]))

    @property
    def fields(self) -> List[Field]:
        return [Field(n, t) for n, t in zip(self.columns, self.dtypes)]


def _segments(root_paths: Sequence[str], path: str) -> Optional[List[Tuple[str, str]]]:
    """`key=value` components between the (best-matching) root and the file."""
    norm = os.path.normpath(path)
    best = None
    for r in root_paths:
        rn = os.path.normpath(r)
        if norm == rn or norm.startswith(rn + os.sep):
            if best is None or len(rn) > len(best):
                best = rn
    if best is None or norm == best:
        return None
    out = []
    for comp in os.path.relpath(os.path.dirname(norm), best).split(os.sep):
        if comp in (".", ""):
            continue
        if "=" not in comp:
            return None  # mixed layout: a non-partition dir level → not partitioned
        k, v = comp.split("=", 1)
        if not k:
            return None
        out.append((k, unquote(v)))
    return out if out else None


def discover(root_paths: Sequence[str], file_paths: Sequence[str]) -> Optional[PartitionSpec]:
    """Partition layout of a file inventory; None when the source is unpartitioned.
    Every file must agree on the column sequence (Spark rejects mixed layouts)."""
    per_file = []
    for p in file_paths:
        segs = _segments(root_paths, p)
        if segs is None:
            return None
        per_file.append(segs)
    names = tuple(k for k, _ in per_file[0])
    for segs in per_file[1:]:
        if tuple(k for k, _ in segs) != names:
            raise HyperspaceException(
                f"Inconsistent partition layout: {names} vs {tuple(k for k, _ in segs)}"
            )
    dtypes = []
    for i in range(len(names)):
        vals = [segs[i][1] for segs in per_file if segs[i][1] != HIVE_NULL]
        dtypes.append(INT64 if vals and all(_is_int(v) for v in vals) else STRING)
    return PartitionSpec(names, tuple(dtypes))


def _is_int(s: str) -> bool:
    try:
        int(s)
        return True
    except ValueError:
        return False


def values_for(
    spec: PartitionSpec, root_paths: Sequence[str], path: str
) -> Tuple[Optional[object], ...]:
    """This file's partition value per spec column (None = hive null)."""
    segs = _segments(root_paths, path)
    if segs is None or tuple(k for k, _ in segs) != spec.columns:
        raise HyperspaceException(f"File does not match partition layout: {path}")
    out = []
    for (_, v), dt in zip(segs, spec.dtypes):
        if v == HIVE_NULL:
            out.append(None)
        else:
            out.append(int(v) if dt == INT64 else v)
    return tuple(out)


def constant_columns(
    spec: PartitionSpec,
    values: Tuple[Optional[object], ...],
    n: int,
    wanted: Optional[Sequence[str]] = None,
) -> List[Tuple[str, Column]]:
    """The partition columns as n-row constants (only those in `wanted`)."""
    wanted_l = None if wanted is None else {w.lower() for w in wanted}
    out = []
    for name, dt, v in zip(spec.columns, spec.dtypes, values):
        if wanted_l is not None and name.lower() not in wanted_l:
            continue
        if v is None:
            validity = np.zeros(n, bool)
            if dt == STRING:
                col = Column(STRING, np.zeros(n, np.int32), np.array([""], "<U1"), validity)
            else:
                col = Column(dt, np.zeros(n, np.dtype(dt)), None, validity)
        elif dt == STRING:
            col = Column(STRING, np.zeros(n, np.int32), np.array([str(v)]), None)
        else:
            col = Column(dt, np.full(n, v, np.dtype(dt)), None, None)
        out.append((name, col))
    return out


def append_partition_columns(
    table: Table,
    spec: PartitionSpec,
    root_paths: Sequence[str],
    path: str,
    wanted: Optional[Sequence[str]] = None,
) -> Table:
    vals = values_for(spec, root_paths, path)
    consts = constant_columns(spec, vals, table.num_rows, wanted)
    if not consts:
        return table
    cols = dict(table.columns)
    for name, col in consts:
        cols[name] = col
    return Table(cols)
