"""Schema model for columnar tables.

The engine analogue of Spark's StructType (which the reference stores as
`schemaString` JSON in the index metadata, `IndexLogEntry.scala:231-239`). Kept
deliberately small: the six types the TPU execution path supports.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import List, Sequence

import numpy as np

INT32 = "int32"
INT64 = "int64"
FLOAT32 = "float32"
FLOAT64 = "float64"
BOOL = "bool"
STRING = "string"

_NUMPY_TO_TYPE = {
    np.dtype(np.int32): INT32,
    np.dtype(np.int64): INT64,
    np.dtype(np.float32): FLOAT32,
    np.dtype(np.float64): FLOAT64,
    np.dtype(np.bool_): BOOL,
}

_TYPE_TO_NUMPY = {
    INT32: np.dtype(np.int32),
    INT64: np.dtype(np.int64),
    FLOAT32: np.dtype(np.float32),
    FLOAT64: np.dtype(np.float64),
    BOOL: np.dtype(np.bool_),
}


def dtype_from_numpy(dt: np.dtype) -> str:
    if dt in _NUMPY_TO_TYPE:
        return _NUMPY_TO_TYPE[dt]
    if dt.kind in ("U", "O", "S"):
        return STRING
    raise ValueError(f"Unsupported numpy dtype: {dt}")


def numpy_dtype(type_name: str) -> np.dtype:
    if type_name == STRING:
        raise ValueError("string columns are dictionary-encoded; no direct numpy dtype")
    return _TYPE_TO_NUMPY[type_name]


@dataclass(frozen=True)
class Field:
    name: str
    dtype: str

    def to_json(self) -> dict:
        return {"name": self.name, "type": self.dtype}


@dataclass(frozen=True)
class Schema:
    fields: tuple

    def __init__(self, fields: Sequence[Field]):
        object.__setattr__(self, "fields", tuple(fields))

    @property
    def names(self) -> List[str]:
        return [f.name for f in self.fields]

    def field(self, name: str) -> Field:
        """Resolve a field by name: exact match first, then unique case-insensitive
        match (Spark-default case-insensitive resolution, which the reference's
        E2E suite exercises both ways)."""
        for f in self.fields:
            if f.name == name:
                return f
        ci = [f for f in self.fields if f.name.lower() == name.lower()]
        if len(ci) == 1:
            return ci[0]
        raise KeyError(name)

    def __contains__(self, name: str) -> bool:
        try:
            self.field(name)
            return True
        except KeyError:
            return False

    def select(self, names: Sequence[str]) -> "Schema":
        return Schema([self.field(n) for n in names])

    def to_json_string(self) -> str:
        return json.dumps({"fields": [f.to_json() for f in self.fields]})

    @staticmethod
    def from_json_string(s: str) -> "Schema":
        d = json.loads(s)
        return Schema([Field(f["name"], f["type"]) for f in d["fields"]])

    def __eq__(self, other):
        return isinstance(other, Schema) and self.fields == other.fields

    def __hash__(self):
        return hash(self.fields)

    def __repr__(self):
        inner = ", ".join(f"{f.name}:{f.dtype}" for f in self.fields)
        return f"Schema({inner})"
