"""Columnar in-memory table: numpy on host, JAX arrays on device.

The engine analogue of a materialized Spark DataFrame partition. Design points:

- **Strings are dictionary-encoded** with a *sorted* dictionary, so int32 codes are
  order-preserving within a column: range filters on strings become integer compares on
  device, and the index build's sort-by-string is an integer sort (TPU arrays are
  numeric; SURVEY §7 "hard parts").
- Host representation is authoritative; `device_columns()` materializes jnp arrays for
  the jitted compute path.
- **Nulls ride as validity masks** over dense filled storage (numeric fill 0,
  string fill code 0), so device kernels stay static-shape and branch-free; null
  SEMANTICS live at the boundaries — predicate evaluation carries a validity lane
  (SQL: a comparison with null is not true), join verification drops pairs with
  null keys (null never equals null), and display/IO decode back to None.
  `validity=None` means all-valid and keeps the null-free fast paths untouched.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..exceptions import HyperspaceException
from .schema import BOOL, STRING, Field, Schema, dtype_from_numpy


@dataclass
class Column:
    """One column: numeric data, or dictionary-encoded strings (codes + dictionary),
    plus an optional validity mask (True = valid; None = no nulls)."""

    dtype: str
    data: np.ndarray  # numeric values, or int32 codes into `dictionary`
    dictionary: Optional[np.ndarray] = None  # sorted unique strings (dtype '<U*')
    validity: Optional[np.ndarray] = None  # bool mask, True = valid

    def __post_init__(self):
        if self.dtype == STRING:
            assert self.dictionary is not None
            assert self.data.dtype == np.int32
        else:
            assert self.dictionary is None
        if self.validity is not None:
            assert self.validity.dtype == np.bool_
            if self.validity.all():
                self.validity = None  # normalize: all-valid keeps fast paths

    def __len__(self) -> int:
        return len(self.data)

    @property
    def is_string(self) -> bool:
        return self.dtype == STRING

    @property
    def has_nulls(self) -> bool:
        return self.validity is not None

    def decode(self) -> np.ndarray:
        """Materialize RAW values (strings decoded through the dictionary). Null
        slots hold the fill value — compute-path only; pair with `validity` or use
        `decode_objects` for user-facing values."""
        if self.is_string:
            return self.dictionary[self.data]
        return self.data

    def decode_objects(self) -> np.ndarray:
        """User-facing values: object array with None at null slots (no-copy pass
        through to `decode()` when the column has no nulls)."""
        raw = self.decode()
        if self.validity is None:
            return raw
        out = raw.astype(object)
        out[~self.validity] = None
        return out

    def take(self, indices: np.ndarray) -> "Column":
        v = self.validity[indices] if self.validity is not None else None
        out = Column(self.dtype, self.data[indices], self.dictionary, v)
        if getattr(self, "_encoded_read", False):
            # The encoded-read provenance marker survives row selection: the
            # codes are the same dictionary's (engine/encoded_device.py gates
            # device code staging on it in auto mode).
            out._encoded_read = True
        return out

    @staticmethod
    def from_values(values: np.ndarray) -> "Column":
        """Ingest a numpy array; strings get dictionary-encoded with a sorted dict;
        None entries in object arrays become nulls (validity mask + fill)."""
        validity = None
        if values.dtype.kind == "O":
            null_mask = np.asarray([v is None for v in values], dtype=bool)
            if null_mask.any():
                validity = ~null_mask
                fill = next((v for v in values if v is not None), "")
                values = np.asarray([fill if v is None else v for v in values])
            else:
                values = np.asarray(values.tolist())
            if values.dtype.kind == "O":
                values = values.astype(str)
        if values.dtype.kind in ("U", "S"):
            dictionary, codes = np.unique(values, return_inverse=True)
            codes = codes.astype(np.int32)
            if validity is not None:
                codes = np.where(validity, codes, np.int32(0))
            return Column(STRING, codes, dictionary, validity)
        col_vals = values
        if validity is not None:
            fill0 = np.zeros((), dtype=col_vals.dtype)
            col_vals = np.where(validity, col_vals, fill0)
        return Column(dtype_from_numpy(col_vals.dtype), col_vals, None, validity)


def _remap_codes(col: Column, new_dictionary: np.ndarray) -> np.ndarray:
    """Remap a string column's codes into a (sorted) superset dictionary."""
    positions = np.searchsorted(new_dictionary, col.dictionary)
    return positions.astype(np.int32)[col.data]


def align_dictionaries(a: Column, b: Column):
    """Re-encode two string columns over their union dictionary so codes are directly
    comparable across tables (needed for cross-table joins on strings).

    Shared-dictionary fast path (encoded execution): when both sides already
    carry the SAME sorted dictionary — e.g. two scans of one index, or both
    sides of a self-join — the union is the dictionary itself and every remap
    is the identity, so the columns come back untouched and comparisons run
    directly on the existing codes. Only a real dictionary MISMATCH (files
    built over different value sets) pays the union re-encode."""
    if not (a.is_string and b.is_string):
        raise ValueError("align_dictionaries requires string columns")
    if a.dictionary is b.dictionary or np.array_equal(a.dictionary, b.dictionary):
        from .encoding import VERIFY_SHARED_DICT

        VERIFY_SHARED_DICT.inc()
        return a, b
    from .encoding import VERIFY_REALIGNED

    VERIFY_REALIGNED.inc()
    union = np.union1d(a.dictionary, b.dictionary)
    return (
        Column(STRING, _remap_codes(a, union), union, a.validity),
        Column(STRING, _remap_codes(b, union), union, b.validity),
    )


class Table:
    """Ordered name→Column mapping with equal lengths."""

    def __init__(self, columns: Dict[str, Column]):
        lengths = {len(c) for c in columns.values()}
        if len(lengths) > 1:
            raise ValueError(f"ragged columns: { {k: len(v) for k, v in columns.items()} }")
        self.columns: Dict[str, Column] = dict(columns)
        self.num_rows: int = lengths.pop() if lengths else 0

    @property
    def schema(self) -> Schema:
        return Schema([Field(n, c.dtype) for n, c in self.columns.items()])

    @property
    def column_names(self) -> List[str]:
        return list(self.columns.keys())

    def _resolve(self, name: str) -> str:
        """Exact match first, then unique case-insensitive match (Spark-default
        case-insensitive column resolution)."""
        if name in self.columns:
            return name
        ci = [n for n in self.columns if n.lower() == name.lower()]
        if len(ci) == 1:
            return ci[0]
        raise KeyError(name)

    def column(self, name: str) -> Column:
        return self.columns[self._resolve(name)]

    def select(self, names: Sequence[str]) -> "Table":
        # Output columns keep the *requested* spelling (resolution is case-insensitive
        # but the user's projection names win, matching Spark's output naming).
        return Table({n: self.columns[self._resolve(n)] for n in names})

    def take(self, indices: np.ndarray) -> "Table":
        return Table({n: c.take(indices) for n, c in self.columns.items()})

    def rename(self, mapping: Dict[str, str]) -> "Table":
        return Table({mapping.get(n, n): c for n, c in self.columns.items()})

    def to_pydict(self) -> Dict[str, list]:
        return {n: c.decode_objects().tolist() for n, c in self.columns.items()}

    def rows(self) -> List[tuple]:
        decoded = [c.decode_objects() for c in self.columns.values()]
        return [tuple(col[i] for col in decoded) for i in range(self.num_rows)]

    def sorted_rows(self) -> List[tuple]:
        """Canonical row order for result comparison — the reference E2E oracle
        compares sorted collected rows (`E2EHyperspaceRulesTests.scala:454-470`)."""
        return sorted(self.rows(), key=lambda r: tuple(str(x) for x in r))

    @staticmethod
    def from_pydict(data: Dict[str, list]) -> "Table":
        return Table({n: Column.from_values(np.asarray(v)) for n, v in data.items()})

    @staticmethod
    def concat(tables: List["Table"]) -> "Table":
        """Concatenate tables with identical column names/types (multi-file scans).
        String columns are re-encoded over the union dictionary."""
        if not tables:
            return Table({})
        names = tables[0].column_names
        out: Dict[str, Column] = {}
        for n in names:
            cols = [t.columns[n] for t in tables]
            if any(c.validity is not None for c in cols):
                validity = np.concatenate(
                    [
                        c.validity
                        if c.validity is not None
                        else np.ones(len(c), dtype=bool)
                        for c in cols
                    ]
                )
            else:
                validity = None
            if cols[0].is_string:
                union = cols[0].dictionary
                for c in cols[1:]:
                    union = np.union1d(union, c.dictionary)
                codes = np.concatenate([_remap_codes(c, union) for c in cols])
                out[n] = Column(STRING, codes, union, validity)
                if all(getattr(c, "_encoded_read", False) for c in cols):
                    # Every child rode an encoded read → the union column did.
                    out[n]._encoded_read = True
            else:
                data = np.concatenate([c.data for c in cols])
                # Mixed numeric widths promote in the concatenate; the dtype
                # label must describe the promoted data, not the first child.
                out[n] = Column(dtype_from_numpy(data.dtype), data, None, validity)
        return Table(out)

    def __repr__(self):
        return f"Table({self.schema}, rows={self.num_rows})"
