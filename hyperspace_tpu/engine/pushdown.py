"""Predicate pushdown: conjunct normalization + the zone-map evaluator.

One home for the "can a value zone [min, max] (+ null counts) contain a row
satisfying `col op literal`?" decision, shared by every pruning consumer:

- the scan layer's row-group pruning (`engine.io.read_files` /
  `iter_file_tables` evaluate a `ScanPredicate` against parquet footer
  zone maps and decode only qualifying row groups),
- the filtered bucketed index scan (`FilterExec.execute_concat` pruning
  inside `part-<bucket>` files),
- `DataSkippingFilterRule`'s MinMaxSketch — both the per-FILE sketch and its
  per-ROW-GROUP variant prune through `minmax_keeps`/`zone_keeps` here.

The footer cache these decisions read (`engine.io.footer_metadata`) also
records per-column-chunk ENCODING facts (`FileFooterMeta.dict_cols`), which
is how the encoded execution path chooses codes-through vs flatten per
column without decoding anything (docs/encoded-execution.md).

Soundness contract: a zone is pruned only when NO row in it can satisfy the
conjunct under the engine's evaluation semantics (`engine.evaluate`):
comparisons with null are unknown and WHERE drops unknowns, so an all-null
zone satisfies no comparison; float zones are never pruned on `!=` (a NaN row
satisfies `x != lit` but parquet min/max statistics exclude NaN); any type
mismatch keeps the zone. Pruned rows are therefore exactly rows the
downstream filter would have dropped — results are byte-identical with
pruning on or off (the ``HYPERSPACE_SCAN_PUSHDOWN=0`` oracle, pinned by
tests/test_scan_pushdown.py).
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence, Tuple

from .expr import BinaryOp, Col, Expr, IsIn, IsNull, Lit, split_conjuncts

#: On/off switch for the whole row-group pushdown path (scan + bucketed
#: filter pruning). ``0`` = the byte-identical whole-file fallback — the same
#: contract style as ``HYPERSPACE_QUERY_STREAMING`` / size classes.
ENV_SCAN_PUSHDOWN = "HYPERSPACE_SCAN_PUSHDOWN"


def pushdown_enabled() -> bool:
    """Default ON; ``HYPERSPACE_SCAN_PUSHDOWN=0`` disables every row-group
    pruning decision (whole files decode exactly as before the pushdown).
    Unset defers to the adaptive planner's per-query decision when one is
    ambient — explicit flags always win (`docs/planner.md`)."""
    raw = os.environ.get(ENV_SCAN_PUSHDOWN, "")
    if raw != "":
        return raw != "0"
    from ..plananalysis.planner import decided_value

    decided = decided_value("pushdown")
    return True if decided is None else bool(decided)


_FLIPPED = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "==": "==", "!=": "!="}


def normalize_conjunct(e: Expr) -> Optional[tuple]:
    """(op, column_name, literal(s)) for zone-prunable conjunct shapes:

    - ``(cmp, col, value)`` for `col <cmp> lit` (either orientation),
    - ``("in", col, [values])`` for `col IN [...]`,
    - ``("isnull" | "isnotnull", col, None)``.

    None for anything else (arithmetic, OR, UDFs, col-vs-col) — those
    conjuncts simply cannot prune."""
    if isinstance(e, IsIn) and isinstance(e.child, Col):
        return ("in", e.child.name, e.values)
    if isinstance(e, IsNull) and isinstance(e.child, Col):
        return ("isnotnull" if e.negated else "isnull", e.child.name, None)
    if not isinstance(e, BinaryOp) or e.op not in BinaryOp.COMPARISONS:
        return None
    l, r = e.left, e.right
    if isinstance(l, Col) and isinstance(r, Lit):
        return (e.op, l.name, r.value)
    if isinstance(l, Lit) and isinstance(r, Col):
        return (_FLIPPED[e.op], r.name, l.value)
    return None


def prunable_conjuncts(condition: Expr) -> List[tuple]:
    """The normalized prunable conjuncts of a condition's CNF split."""
    out = []
    for c in split_conjuncts(condition):
        n = normalize_conjunct(c)
        if n is not None:
            out.append(n)
    return out


def _is_floatish(v) -> bool:
    import numpy as np

    return isinstance(v, (float, np.floating))


def minmax_keeps(op: str, value, mn, mx) -> bool:
    """Can a zone with value range [mn, mx] contain a row satisfying
    `col op value`? Conservative: incomparable types and `!=` keep (the
    NaN-aware `!=` refinement lives in `zone_keeps`, which knows the
    zone's null/float facts)."""
    try:
        if op == "==":
            return mn <= value <= mx
        if op == "<":
            return mn < value
        if op == "<=":
            return mn <= value
        if op == ">":
            return mx > value
        if op == ">=":
            return mx >= value
    except TypeError:
        return True  # incomparable types: never prune
    return True  # "!=" and anything else: cannot prune here


class ZoneStats:
    """One zone's statistics: value bounds over the NON-NULL rows (valid only
    when `has_minmax`) plus the null count (None = unknown). For parquet row
    groups these come straight from the footer's column-chunk statistics."""

    __slots__ = ("mn", "mx", "has_minmax", "null_count")

    def __init__(self, mn=None, mx=None, has_minmax: bool = False, null_count=None):
        self.mn = mn
        self.mx = mx
        self.has_minmax = has_minmax
        self.null_count = null_count


def zone_keeps(op: str, value, st: ZoneStats, zone_rows: int) -> bool:
    """Can a zone of `zone_rows` rows with stats `st` contain a row the
    conjunct keeps? THE pruning decision (see module docstring for the
    soundness contract)."""
    if op == "isnull":
        return st.null_count is None or st.null_count > 0
    if op == "isnotnull":
        return st.null_count is None or st.null_count < zone_rows
    # Value-matching conjuncts: a comparison with null is unknown and WHERE
    # drops unknowns, so an all-null zone satisfies nothing.
    if st.null_count is not None and st.null_count >= zone_rows:
        return False
    if not st.has_minmax:
        return True
    try:
        if op == "in":
            return any(minmax_keeps("==", v, st.mn, st.mx) for v in value)
        if op == "!=":
            # Prunable only when EVERY row equals the literal: constant
            # zone, no nulls unknown-ness needed (nulls fail != too), and
            # no float lanes (a NaN row satisfies != but is invisible to
            # parquet min/max statistics).
            if _is_floatish(st.mn) or _is_floatish(st.mx) or _is_floatish(value):
                return True
            return not (st.mn == st.mx == value)
        return minmax_keeps(op, value, st.mn, st.mx)
    except TypeError:
        return True


def _resolve_name(name: str, names: Sequence[str], case_sensitive: bool) -> Optional[str]:
    """Resolve a conjunct's column spelling against a file's schema names —
    exact match first, then unique case-insensitive (Table._resolve's rule);
    None when unresolved (the conjunct cannot prune this file)."""
    if name in names:
        return name
    if case_sensitive:
        return None
    ci = [n for n in names if n.lower() == name.lower()]
    return ci[0] if len(ci) == 1 else None


class ScanPredicate:
    """A query's conjunctive filter compiled to its zone-prunable conjuncts,
    carried down the scan path (`read_files` / `iter_file_tables`) by the
    physical plan. Stateless against any particular file: `select_row_groups`
    resolves the conjuncts per footer metadata."""

    __slots__ = ("conjuncts", "case_sensitive")

    def __init__(self, conjuncts: List[tuple], case_sensitive: bool = False):
        self.conjuncts = conjuncts
        self.case_sensitive = case_sensitive

    @staticmethod
    def from_condition(
        condition: Expr, case_sensitive: bool = False
    ) -> Optional["ScanPredicate"]:
        """None when no conjunct is prunable (the scan runs exactly as
        without pushdown — no footer reads, no key changes)."""
        cj = prunable_conjuncts(condition)
        return ScanPredicate(cj, case_sensitive) if cj else None

    def select_row_groups(self, meta) -> Optional[Tuple[int, ...]]:
        """Surviving row-group indices of one file (`meta` is an
        `engine.io.FileFooterMeta`). None = every row group survives (the
        caller keeps the plain whole-file path and its cache keys); a tuple
        (possibly empty) = a real pruning decision."""
        resolved = []
        for op, name, value in self.conjuncts:
            rn = _resolve_name(name, meta.names, self.case_sensitive)
            if rn is not None:
                resolved.append((op, rn, value))
        if not resolved:
            return None
        keep: List[int] = []
        dropped = False
        for i, rg in enumerate(meta.row_groups):
            ok = True
            for op, rn, value in resolved:
                st = rg.stats.get(rn)
                if st is None:
                    continue
                if not zone_keeps(op, value, st, rg.num_rows):
                    ok = False
                    break
            if ok:
                keep.append(i)
            else:
                dropped = True
        return tuple(keep) if dropped else None
