"""File IO: parquet/csv/json readers and parquet writer (pyarrow-backed).

The engine analogue of Spark's DataSource file formats. Source relations resolve their
file inventory eagerly at read time (InMemoryFileIndex-style), which is what the
file-based signature provider fingerprints.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.csv as pa_csv
import pyarrow.json as pa_json
import pyarrow.parquet as pq

from ..exceptions import HyperspaceException
from ..storage.filesystem import FileStatus, FileSystem, LocalFileSystem
from ..telemetry import metrics as _metrics
from ..util.path_utils import is_data_path
from .schema import BOOL, FLOAT32, FLOAT64, INT32, INT64, STRING, Field, Schema
from .table import Column, Table

_FORMAT_EXTENSIONS = {
    "parquet": (".parquet",),
    "csv": (".csv",),
    "json": (".json",),
    "orc": (".orc",),
}

#: Decode-pool width knob, shared by every concurrent worker stage in the
#: engine: `read_files`, the streaming chunk iterator, the bucketed-scan
#: cache warmer, the pipelined index build (`index/build_pipeline.py`
#: imports this name), and the streamed join→aggregate's payload
#: gather/eval workers (`engine/streaming.stream_join_aggregate`) — ONE
#: threading contract for build and query, and ``=1`` forces every one of
#: them serial (the determinism-test configuration).
ENV_DECODE_THREADS = "HYPERSPACE_BUILD_DECODE_THREADS"

#: How many files the streaming chunk iterator may hold in flight ahead of
#: the consumer — the BINDING memory bound (the decode pool is capped at this
#: depth). Default 16 matches `read_files`' behavior of decoding every cold
#: file concurrently; memory-constrained deployments lower it.
ENV_PREFETCH_FILES = "HYPERSPACE_QUERY_PREFETCH_FILES"
_DEFAULT_PREFETCH_FILES = 16

# Decode-pool work counters, bound once (incremented per cold-file decode).
_DECODE_FILES = _metrics.counter("io.decode.files")
_DECODE_SECONDS = _metrics.histogram("io.decode.seconds")


def decode_pool_size(n_files: int) -> int:
    """Worker count for decoding `n_files` cold files: honors
    ``HYPERSPACE_BUILD_DECODE_THREADS`` (``1`` = the serial path, >1 = an
    explicit cap), defaulting to ``min(16, n_files)``."""
    raw = int(os.environ.get(ENV_DECODE_THREADS, "0") or 0)
    if raw == 1:
        return 1
    if raw > 1:
        return min(raw, n_files)
    return min(16, n_files)


def prefetch_depth() -> int:
    """In-flight file budget of the streaming chunk iterator (≥1)."""
    return max(
        1,
        int(os.environ.get(ENV_PREFETCH_FILES, _DEFAULT_PREFETCH_FILES)
            or _DEFAULT_PREFETCH_FILES),
    )


def list_data_files(path: str, file_format: str, fs: Optional[FileSystem] = None) -> List[FileStatus]:
    """Resolve the data files of a root path (file or directory, recursive), applying
    the metadata filter to every component below the root."""
    fs = fs or LocalFileSystem()
    if not fs.exists(path):
        raise HyperspaceException(f"Path does not exist: {path}")
    if not fs.is_dir(path):
        return [fs.get_status(path)]
    rootnorm = os.path.normpath(path)
    exts = _FORMAT_EXTENSIONS.get(file_format, ())

    out = []
    for st in fs.list_leaf_files(path):
        rel = os.path.relpath(os.path.normpath(st.path), rootnorm)
        if not all(is_data_path(p) for p in rel.split(os.sep)):
            continue
        if exts and not st.path.endswith(exts):
            continue
        out.append(st)
    return out


def _arrow_to_table(at: pa.Table) -> Table:
    cols: Dict[str, Column] = {}
    for name in at.column_names:
        arr = at.column(name)
        if pa.types.is_temporal(arr.type):
            # Dates/timestamps ride as strings (CSV/JSON readers infer them; the
            # engine's type system keeps them lexicographically ordered strings).
            arr = arr.cast(pa.string())
        validity = None
        if arr.null_count > 0:
            # Nulls → validity mask over dense filled storage (numeric fill 0,
            # string fill ""): keeps device kernels static-shape; semantics are
            # applied at evaluation/join/display boundaries.
            validity = ~np.asarray(arr.is_null().combine_chunks().to_numpy(zero_copy_only=False))
            if pa.types.is_string(arr.type) or pa.types.is_large_string(arr.type) or pa.types.is_dictionary(arr.type):
                arr = arr.fill_null("")
            elif pa.types.is_boolean(arr.type):
                arr = arr.fill_null(False)
            elif pa.types.is_floating(arr.type):
                arr = arr.fill_null(0.0)
            else:
                arr = arr.fill_null(0)
        np_arr = arr.to_numpy(zero_copy_only=False)
        if np_arr.dtype.kind == "O":
            np_arr = np.asarray([str(x) for x in np_arr])
        c = Column.from_values(np_arr)
        if validity is not None:
            # Re-apply canonical fills in code/data space (from_values saw fills).
            data = c.data.copy()
            data[~validity] = 0
            c = Column(c.dtype, data, c.dictionary, validity)
        cols[name] = c
    return Table(cols)


def _read_one(path: str, file_format: str, columns: Optional[List[str]] = None) -> Table:
    if file_format == "delta":
        file_format = "parquet"  # delta data files are parquet
    if file_format == "parquet":
        return _arrow_to_table(pq.read_table(path, columns=columns))
    if file_format == "orc":
        # Reference format whitelist includes ORC (LogicalPlanSerDeUtils.scala:223-243).
        from pyarrow import orc as pa_orc

        return _arrow_to_table(pa_orc.ORCFile(path).read(columns=columns))
    if file_format == "csv":
        # Keep date-like strings as strings (no timestamp inference) — the engine's
        # type system treats temporal values as lexicographically ordered strings.
        # Empty string cells read as null (Spark CSV default), not "".
        at = pa_csv.read_csv(
            path,
            convert_options=pa_csv.ConvertOptions(
                timestamp_parsers=[], strings_can_be_null=True
            ),
        )
    elif file_format == "json":
        at = _read_json_lines(path)
    else:
        raise HyperspaceException(f"Unsupported file format: {file_format}")
    if columns:
        at = at.select(columns)
    return _arrow_to_table(at)


def _read_json_lines(path: str) -> pa.Table:
    """Line-delimited JSON reader via stdlib — unlike pyarrow.json it never reinterprets
    date-like strings as timestamps."""
    import json as _json

    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                rows.append(_json.loads(line))
    if not rows:
        raise HyperspaceException(f"Empty JSON file: {path}")
    names = list(rows[0].keys())
    return pa.table({n: pa.array([r[n] for r in rows]) for n in names})


def file_columns_for(columns: Optional[List[str]], partitions) -> Optional[List[str]]:
    """The column subset to request FROM THE FILE for a wanted projection:
    partition columns are path facts, not file content, so they are stripped
    here and re-appended per file by `append_partition_columns`."""
    if partitions is None:
        return columns
    spec, _roots = partitions
    pset = {c.lower() for c in spec.columns}
    if columns is None:
        return None
    file_columns = [c for c in columns if c.lower() not in pset]
    if not file_columns:
        # Only partition columns requested: still need row counts, so read
        # the file's own columns and drop them in the select below.
        return None
    return file_columns


def file_table(path: str, file_format: str, file_columns: Optional[List[str]]) -> Table:
    """Decoded table of ONE data file through the per-file scan cache — the
    shared decode primitive of `read_files` and the pipelined index build.

    The cache stores columns, not column tuples, so a warm file decodes ONLY
    the columns no earlier projection touched (e.g. an index build over
    (a, b, c) after a query that scanned (a, b) decodes just c)."""
    from .scan_cache import global_scan_cache

    t = global_scan_cache().get(path, file_columns)
    if t is not None:
        return t
    return _decode_into_cache(path, file_format, file_columns)


def _decode_into_cache(
    path: str, file_format: str, file_columns: Optional[List[str]]
) -> Table:
    """The miss half of `file_table`: decode only the cold columns when the
    cache can tell which those are, else the full projection. The caller has
    already counted the miss (no double accounting)."""
    import time as _time

    from .scan_cache import global_scan_cache

    t0 = _time.monotonic()
    cache = global_scan_cache()
    missing = cache.missing_columns(path, file_columns)
    if missing and missing != list(file_columns or []):
        cache.put(path, missing, _read_one(path, file_format, missing))
        t = cache.get(path, file_columns, record=False)
        if t is not None:
            _DECODE_FILES.inc()
            _DECODE_SECONDS.observe(_time.monotonic() - t0)
            return t  # assembled: warm columns + the freshly decoded rest
    t = _read_one(path, file_format, file_columns)
    cache.put(path, file_columns, t)
    _DECODE_FILES.inc()
    _DECODE_SECONDS.observe(_time.monotonic() - t0)
    return t


def decorate_file_table(
    t: Table,
    path: str,
    partitions,
    columns: Optional[List[str]],
) -> Table:
    """Apply the per-file post-decode steps of `read_files` to one file's raw
    table: append hive-partition columns and project to the wanted order."""
    if partitions is None:
        return t
    from .partitioning import append_partition_columns

    spec, roots = partitions
    t = append_partition_columns(t, spec, roots, path, wanted=columns)
    if columns is not None:
        t = t.select(columns)
    return t


def warm_file_cache(
    paths: List[str], file_format: str, file_columns: Optional[List[str]]
) -> None:
    """Concurrently decode the cache-cold files among `paths` into the per-file
    scan cache (shared decode-pool contract). Callers that must consume files
    in a fixed order one at a time (the bucketed index scan) call this first so
    the serial consumption loop runs fully warm — cold indexed reads previously
    decoded every bucket file back-to-back on one thread."""
    from .scan_cache import global_scan_cache

    cache = global_scan_cache()
    missing = [p for p in paths if cache.missing_columns(p, file_columns) != []]
    workers = decode_pool_size(len(missing)) if missing else 0
    if len(missing) > 1 and workers > 1:
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=workers) as pool:
            list(
                pool.map(
                    lambda p: _decode_into_cache(p, file_format, file_columns),
                    missing,
                )
            )


def iter_file_tables(
    files: List[str],
    file_format: str,
    columns: Optional[List[str]] = None,
    partitions=None,
    on_decode=None,
):
    """Ordered per-file table iterator with bounded decode prefetch — the
    read-side twin of the build pipeline's decode stage. Files decode on a
    pool (shared `decode_pool_size` contract, through the per-column scan
    cache) up to `prefetch_depth()` files ahead of the consumer, and are
    yielded in sorted-file order so downstream results are independent of
    decode completion order. A decode failure propagates at the failed file's
    yield point; already-submitted decodes finish into the cache harmlessly
    (the cache only ever stores successful decodes — no poisoned entries).

    `on_decode(seconds)` observes each file's decode wall time (telemetry)."""
    if not files:
        return
    import time as _time
    from concurrent.futures import ThreadPoolExecutor

    file_columns = file_columns_for(columns, partitions)
    ordered = sorted(files)

    def decode_one(path: str) -> Table:
        t0 = _time.monotonic()
        t = file_table(path, file_format, file_columns)
        if on_decode is not None:
            on_decode(_time.monotonic() - t0)
        return t

    # The prefetch depth is the binding in-flight bound: more decode workers
    # than undelivered-file slots could only grow resident memory past it.
    depth = prefetch_depth()
    workers = min(decode_pool_size(len(ordered)), depth)
    if workers <= 1:
        for f in ordered:
            yield decorate_file_table(decode_one(f), f, partitions, columns)
        return
    from collections import deque

    pool = ThreadPoolExecutor(max_workers=workers)
    try:
        pending: "deque" = deque()
        i = 0
        while i < len(ordered) or pending:
            while i < len(ordered) and len(pending) < depth:
                pending.append((ordered[i], pool.submit(decode_one, ordered[i])))
                i += 1
            f, fut = pending.popleft()
            yield decorate_file_table(fut.result(), f, partitions, columns)
    finally:
        pool.shutdown(wait=False, cancel_futures=True)


def concat_cache_probe(
    files: List[str],
    file_format: str,
    columns: Optional[List[str]],
    partitions,
) -> Tuple[Optional[tuple], Optional[Table]]:
    """(key, cached table or None) for the multi-file concat cache. Key =
    per-file (path,size,mtime) + columns + partition layout, so any file
    rewrite (or a different partition interpretation of the same files)
    invalidates. Shared by `read_files` and the pipelined index build (a warm
    source concat skips the build's whole decode stage)."""
    if len(files) <= 1:
        return None, None
    from .scan_cache import global_concat_cache

    try:
        stats = []
        for p in sorted(files):
            st = os.stat(p)
            stats.append((p, st.st_size, int(st.st_mtime * 1000)))
        part_marker = None
        if partitions is not None:
            pspec, proots = partitions
            part_marker = (tuple(pspec.columns), tuple(pspec.dtypes), tuple(proots))
        concat_key = (
            "concat",
            file_format,
            tuple(stats),
            # None (all columns) must not share a key with [] (zero columns).
            ("<all>",) if columns is None else tuple(columns),
            part_marker,
        )
    except OSError:
        return None, None
    hit = global_concat_cache().get(concat_key)
    return concat_key, hit[0] if hit is not None else None


def read_files(
    files: List[str],
    file_format: str,
    columns: Optional[List[str]] = None,
    partitions=None,
) -> Table:
    """Read + concat data files. `partitions` = (PartitionSpec, root_paths) for
    hive-partitioned sources: the per-file cache holds the RAW file content (the
    partition values are path facts, not file content) and the constant partition
    columns are appended per file before the concat."""
    if not files:
        raise HyperspaceException("No data files to read.")
    from .scan_cache import global_concat_cache

    # Multi-file concat cache: re-assembling N per-file tables (and re-unioning
    # string dictionaries) per query dominates repeated multi-file scans — e.g.
    # a filter-index scan over num_buckets small files.
    concat_key, cached = concat_cache_probe(files, file_format, columns, partitions)
    if cached is not None:
        return cached

    file_columns = file_columns_for(columns, partitions)

    from .scan_cache import global_scan_cache

    cache = global_scan_cache()
    ordered = sorted(files)
    tables: List[Optional[Table]] = [cache.get(f, file_columns) for f in ordered]
    missing = [i for i, t in enumerate(tables) if t is None]
    workers = decode_pool_size(len(missing)) if missing else 0
    if len(missing) > 1 and workers > 1:
        # Decode cache misses concurrently: parquet/csv decode is pyarrow C++ work
        # that releases the GIL, so a thread pool gives real parallelism (SURVEY §7
        # "overlap decode; don't let the device idle on file I/O"). Fully-warm
        # scans never pay the pool setup. The worker count rides the shared
        # HYPERSPACE_BUILD_DECODE_THREADS contract (`decode_pool_size`), so
        # `=1` forces the serial path here exactly as it does for the build.
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=workers) as pool:
            decoded = list(
                pool.map(
                    lambda i: _decode_into_cache(ordered[i], file_format, file_columns),
                    missing,
                )
            )
        for i, t in zip(missing, decoded):
            tables[i] = t
    else:
        for i in missing:
            tables[i] = _decode_into_cache(ordered[i], file_format, file_columns)

    if partitions is not None:
        tables = [
            decorate_file_table(t, f, partitions, columns)
            for f, t in zip(ordered, tables)
        ]
    out = tables[0] if len(tables) == 1 else Table.concat(tables)
    if concat_key is not None:
        global_concat_cache().put(concat_key, out, None)
    return out


def infer_schema(files: List[str], file_format: str) -> Schema:
    """Schema from the first file's footer/sample (cheap; no full read for parquet)."""
    if not files:
        raise HyperspaceException("No data files to infer schema from.")
    f = sorted(files)[0]
    if file_format in ("parquet", "delta"):
        return arrow_schema_to_schema(pq.read_schema(f))
    if file_format == "orc":
        from pyarrow import orc as pa_orc

        return arrow_schema_to_schema(pa_orc.ORCFile(f).schema)
    return _read_one(f, file_format).schema


_ARROW_TO_DTYPE = {
    pa.int32(): INT32,
    pa.int64(): INT64,
    pa.float32(): FLOAT32,
    pa.float64(): FLOAT64,
    pa.bool_(): BOOL,
}


def arrow_schema_to_schema(sch: pa.Schema) -> Schema:
    fields = []
    for f in sch:
        if f.type in _ARROW_TO_DTYPE:
            fields.append(Field(f.name, _ARROW_TO_DTYPE[f.type]))
        elif pa.types.is_string(f.type) or pa.types.is_large_string(f.type):
            fields.append(Field(f.name, STRING))
        elif pa.types.is_dictionary(f.type):
            fields.append(Field(f.name, STRING))
        elif pa.types.is_temporal(f.type):
            fields.append(Field(f.name, STRING))
        elif pa.types.is_integer(f.type):
            fields.append(Field(f.name, INT64))
        else:
            raise HyperspaceException(f"Unsupported arrow type: {f.type} ({f.name})")
    return Schema(fields)


def table_to_arrow(table: Table) -> pa.Table:
    arrays = []
    names = []
    for name, col in table.columns.items():
        names.append(name)
        mask = None if col.validity is None else ~col.validity
        arrays.append(pa.array(col.decode(), mask=mask))
    return pa.table(dict(zip(names, arrays)))


def write_parquet(table: Table, path: str) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    pq.write_table(table_to_arrow(table), path)


def write_orc(table: Table, path: str) -> None:
    from pyarrow import orc as pa_orc

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    pa_orc.write_table(table_to_arrow(table), path)


def write_csv(table: Table, path: str) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    pa_csv.write_csv(table_to_arrow(table), path)


def write_json(table: Table, path: str) -> None:
    """Line-delimited JSON writer (pyarrow has no JSON writer)."""
    import json as _json

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    cols = {n: c.decode_objects() for n, c in table.columns.items()}
    with open(path, "w") as f:
        for i in range(table.num_rows):
            row = {n: v[i].item() if hasattr(v[i], "item") else v[i] for n, v in cols.items()}
            f.write(_json.dumps(row) + "\n")
