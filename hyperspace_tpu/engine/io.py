"""File IO: parquet/csv/json readers and parquet writer (pyarrow-backed).

The engine analogue of Spark's DataSource file formats. Source relations resolve their
file inventory eagerly at read time (InMemoryFileIndex-style), which is what the
file-based signature provider fingerprints.

Selective reads (PR 5): parquet footer metadata — row-group boundaries plus
per-column min/max/null-count zone maps — is parsed once per file and cached
under the scan-cache budget (`footer_metadata`). A `ScanPredicate`
(`engine.pushdown`) handed to `read_files`/`iter_file_tables` prunes at
row-group granularity through those zone maps: only qualifying row groups
decode (`pruned_file_table`), cached under selection-aware keys.
``HYPERSPACE_SCAN_PUSHDOWN=0`` disables all of it — the byte-identical
whole-file fallback.

Encoded execution (ISSUE 8): dictionary-encoded string columns — identified
per column chunk from the same footer cache (`FileFooterMeta.dict_cols`) —
are read with pyarrow's ``read_dictionary`` and converted to engine columns
in CODE SPACE (`engine.encoding.dictionary_array_to_column`), and string
columns write back out as compacted arrow dictionary arrays
(`table_to_arrow(encode_dictionaries=True)`); the N decoded strings never
materialize at either boundary. ``HYPERSPACE_ENCODED_EXEC=0`` is the
byte-identical decoded fallback (docs/encoded-execution.md).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.csv as pa_csv
import pyarrow.json as pa_json
import pyarrow.parquet as pq

from .. import resilience as _resilience
from ..exceptions import (
    HyperspaceException,
    QueryTimeoutError,
    RetryBudgetExceededError,
)
from ..serve import singleflight as _singleflight
from ..storage.filesystem import FileStatus, FileSystem, LocalFileSystem
from ..telemetry import accounting as _accounting
from ..telemetry import faults as _faults
from ..telemetry import metrics as _metrics
from ..telemetry import stage_ledger as _stage_ledger
from ..util.path_utils import is_data_path
from . import encoding as _encoding
from .schema import BOOL, FLOAT32, FLOAT64, INT32, INT64, STRING, Field, Schema
from .table import Column, Table

_FORMAT_EXTENSIONS = {
    "parquet": (".parquet",),
    "csv": (".csv",),
    "json": (".json",),
    "orc": (".orc",),
}

#: Decode-pool width knob, shared by every concurrent worker stage in the
#: engine: `read_files`, the streaming chunk iterator, the bucketed-scan
#: cache warmer, the pipelined index build (`index/build_pipeline.py`
#: imports this name), and the streamed join→aggregate's payload
#: gather/eval workers (`engine/streaming.stream_join_aggregate`) — ONE
#: threading contract for build and query, and ``=1`` forces every one of
#: them serial (the determinism-test configuration).
ENV_DECODE_THREADS = "HYPERSPACE_BUILD_DECODE_THREADS"

#: How many files the streaming chunk iterator may hold in flight ahead of
#: the consumer — the BINDING memory bound (the decode pool is capped at this
#: depth). Default 16 matches `read_files`' behavior of decoding every cold
#: file concurrently; memory-constrained deployments lower it.
ENV_PREFETCH_FILES = "HYPERSPACE_QUERY_PREFETCH_FILES"
_DEFAULT_PREFETCH_FILES = 16

#: Row-group cap of the per-bucket index files the build writes
#: (`index/build_pipeline._BucketWriter` AND the serial writer in
#: `index/builder.py` — the byte-identity contract requires one value).
#: Bounded, key-sorted row groups give the footer zone maps sub-file
#: resolution, so indexed point lookups and range filters prune INSIDE a
#: bucket file, not just across bucket files.
ENV_INDEX_ROW_GROUP_ROWS = "HYPERSPACE_INDEX_ROW_GROUP_ROWS"
_DEFAULT_INDEX_ROW_GROUP_ROWS = 65536


def index_row_group_rows() -> int:
    """Row cap of one row group in a written index bucket file (≥1)."""
    return max(
        1,
        int(
            os.environ.get(ENV_INDEX_ROW_GROUP_ROWS, _DEFAULT_INDEX_ROW_GROUP_ROWS)
            or _DEFAULT_INDEX_ROW_GROUP_ROWS
        ),
    )


# Decode-pool work counters, bound once (incremented per cold-file decode).
_DECODE_FILES = _metrics.counter("io.decode.files")
_DECODE_SECONDS = _metrics.histogram("io.decode.seconds")
# Decode-pool saturation: decodes currently EXECUTING (every decode path —
# read_files pool, streaming prefetch, cache warmer, pipelined build — funnels
# through the two _decode_*_into_cache functions below), plus the session
# high-water mark. The key admission signal for the future scheduler.
_DECODE_IN_FLIGHT = _metrics.gauge("io.decode.in_flight")
_DECODE_IN_FLIGHT_PEAK = _metrics.gauge("io.decode.in_flight_peak")


def _decode_begin() -> None:
    _DECODE_IN_FLIGHT.inc()
    _DECODE_IN_FLIGHT_PEAK.set_max(_DECODE_IN_FLIGHT.value)


def _decode_end(t0: float) -> None:
    """Close one decode's accounting: in-flight gauge down, work counters up,
    and the task-seconds charged to the ambient query's ledger (pool paths
    adopt the submitter's ledger via `accounting.use_ledger`)."""
    import time as _time

    dt = _time.monotonic() - t0
    _DECODE_IN_FLIGHT.dec()
    _DECODE_FILES.inc()
    _DECODE_SECONDS.observe(dt)
    _accounting.add("decode_files", 1)
    _accounting.add("decode_task_s", dt)

# Footer-metadata cache traffic + row-group pruning outcomes
# (`bench_detail.io_pruning` and the per-scan span attrs read them). The
# row-group counters tick per pruning SCAN that actually assembles (a warm
# concat-cache hit never inflates them — `_record_pruning`); the byte
# counters tick only at real pruned DECODES (`_record_decoded_bytes`).
_FOOTER_HITS = _metrics.counter("io.footer.hits")
_FOOTER_MISSES = _metrics.counter("io.footer.misses")
_RG_SCANNED = _metrics.counter("io.pruning.row_groups_scanned")
_RG_SKIPPED = _metrics.counter("io.pruning.row_groups_skipped")
_RG_BYTES_DECODED = _metrics.counter("io.pruning.bytes_decoded")
_RG_BYTES_SKIPPED = _metrics.counter("io.pruning.bytes_skipped")


def decode_pool_size(n_files: int) -> int:
    """Worker count for decoding `n_files` cold files: honors
    ``HYPERSPACE_BUILD_DECODE_THREADS`` (``1`` = the serial path, >1 = an
    explicit cap), defaulting to ``min(16, n_files)``."""
    raw = int(os.environ.get(ENV_DECODE_THREADS, "0") or 0)
    if raw == 1:
        return 1
    if raw > 1:
        return min(raw, n_files)
    return min(16, n_files)


def prefetch_depth() -> int:
    """In-flight file budget of the streaming chunk iterator (≥1)."""
    return max(
        1,
        int(os.environ.get(ENV_PREFETCH_FILES, _DEFAULT_PREFETCH_FILES)
            or _DEFAULT_PREFETCH_FILES),
    )


def list_data_files(path: str, file_format: str, fs: Optional[FileSystem] = None) -> List[FileStatus]:
    """Resolve the data files of a root path (file or directory, recursive), applying
    the metadata filter to every component below the root."""
    fs = fs or LocalFileSystem()
    if not fs.exists(path):
        raise HyperspaceException(f"Path does not exist: {path}")
    if not fs.is_dir(path):
        return [fs.get_status(path)]
    rootnorm = os.path.normpath(path)
    exts = _FORMAT_EXTENSIONS.get(file_format, ())

    out = []
    for st in fs.list_leaf_files(path):
        rel = os.path.relpath(os.path.normpath(st.path), rootnorm)
        if not all(is_data_path(p) for p in rel.split(os.sep)):
            continue
        if exts and not st.path.endswith(exts):
            continue
        out.append(st)
    return out


def _arrow_to_table(at: pa.Table) -> Table:
    cols: Dict[str, Column] = {}
    for name in at.column_names:
        arr = at.column(name)
        if pa.types.is_dictionary(arr.type) and _encoding.encoded_exec_enabled():
            # Encoded execution: a dictionary-typed arrow column converts in
            # CODE SPACE (O(N) int remap + O(D log D) dict sort) — the N
            # string objects are never materialized. Byte-identical to the
            # flatten path below; None = fall back (non-string values or a
            # dictionary over HYPERSPACE_ENCODED_DICT_MAX).
            c = _encoding.dictionary_array_to_column(arr)
            if c is not None:
                _encoding.COLUMNS_ENCODED.inc()
                _encoding.record_encoded_kept(_encoding.column_nbytes(c))
                cols[name] = c
                continue
            _encoding.COLUMNS_FLATTENED.inc()
        if pa.types.is_temporal(arr.type):
            # Dates/timestamps ride as strings (CSV/JSON readers infer them; the
            # engine's type system keeps them lexicographically ordered strings).
            arr = arr.cast(pa.string())
        validity = None
        if arr.null_count > 0:
            # Nulls → validity mask over dense filled storage (numeric fill 0,
            # string fill ""): keeps device kernels static-shape; semantics are
            # applied at evaluation/join/display boundaries.
            validity = ~np.asarray(arr.is_null().combine_chunks().to_numpy(zero_copy_only=False))
            if pa.types.is_string(arr.type) or pa.types.is_large_string(arr.type) or pa.types.is_dictionary(arr.type):
                arr = arr.fill_null("")
            elif pa.types.is_boolean(arr.type):
                arr = arr.fill_null(False)
            elif pa.types.is_floating(arr.type):
                arr = arr.fill_null(0.0)
            else:
                arr = arr.fill_null(0)
        np_arr = arr.to_numpy(zero_copy_only=False)
        if np_arr.dtype.kind == "O":
            # A ZERO-row object array must stay a string column (np.asarray of
            # an empty list would infer float64): all-pruned row-group reads
            # and empty files concat against real string columns.
            np_arr = (
                np.empty(0, dtype="<U1")
                if len(np_arr) == 0
                else np.asarray([str(x) for x in np_arr])
            )
        # Materialized half of the byte split: this column crossed the lake
        # boundary as flat raw values (for strings, the full N-value array
        # the encoded path avoids).
        _encoding.record_materialized(np_arr.nbytes)
        c = Column.from_values(np_arr)
        if validity is not None:
            # Re-apply canonical fills in code/data space (from_values saw fills).
            data = c.data.copy()
            data[~validity] = 0
            c = Column(c.dtype, data, c.dictionary, validity)
        cols[name] = c
    return Table(cols)


def _read_one(path: str, file_format: str, columns: Optional[List[str]] = None) -> Table:
    _faults.check("io.decode")
    if file_format == "delta":
        file_format = "parquet"  # delta data files are parquet
    if file_format == "parquet":
        if not _encoding.encoded_exec_enabled():
            return _arrow_to_table(pq.read_table(path, columns=columns))
        # Encoded execution: the per-column dictionary-read choice comes from
        # the footer cache's encoding facts (`FileFooterMeta.dict_cols`). A
        # WARM footer decides with no file open at all; a cache miss parses
        # from THIS read's own open — so a file that takes no dictionary read
        # (numeric-only index buckets, plain-encoded strings) costs exactly
        # ONE open, same as the decoded path, and its zone maps land in the
        # cache for free. Only a file that really reads dictionary pays a
        # second open (dwarfed by the decode it avoids), and only when cold.
        from .scan_cache import global_scan_cache

        meta = global_scan_cache().get_meta(path)
        if meta is not None:
            _FOOTER_HITS.inc()  # the same accounting footer_metadata would do
            rd = _encoding.dict_read_columns(meta, columns)
            if rd:
                return _arrow_to_table(
                    pq.read_table(path, columns=columns, read_dictionary=rd)
                )
            with pq.ParquetFile(path) as pf:
                return _arrow_to_table(pf.read(columns=columns))
        with pq.ParquetFile(path) as pf:
            meta = footer_metadata(path, file_format, _pf=pf)
            rd = _encoding.dict_read_columns(meta, columns)
            if not rd:
                return _arrow_to_table(pf.read(columns=columns))
        return _arrow_to_table(
            pq.read_table(path, columns=columns, read_dictionary=rd)
        )
    if file_format == "orc":
        # Reference format whitelist includes ORC (LogicalPlanSerDeUtils.scala:223-243).
        from pyarrow import orc as pa_orc

        return _arrow_to_table(pa_orc.ORCFile(path).read(columns=columns))
    if file_format == "csv":
        # Keep date-like strings as strings (no timestamp inference) — the engine's
        # type system treats temporal values as lexicographically ordered strings.
        # Empty string cells read as null (Spark CSV default), not "".
        at = pa_csv.read_csv(
            path,
            convert_options=pa_csv.ConvertOptions(
                timestamp_parsers=[], strings_can_be_null=True
            ),
        )
    elif file_format == "json":
        at = _read_json_lines(path)
    else:
        raise HyperspaceException(f"Unsupported file format: {file_format}")
    if columns:
        at = at.select(columns)
    return _arrow_to_table(at)


def _read_json_lines(path: str) -> pa.Table:
    """Line-delimited JSON reader via stdlib — unlike pyarrow.json it never reinterprets
    date-like strings as timestamps."""
    import json as _json

    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                rows.append(_json.loads(line))
    if not rows:
        raise HyperspaceException(f"Empty JSON file: {path}")
    names = list(rows[0].keys())
    return pa.table({n: pa.array([r[n] for r in rows]) for n in names})


# ---------------------------------------------------------------------------
# Parquet footer metadata: row-group boundaries + per-column zone maps,
# parsed ONCE per (path, size, mtime) and cached under the scan-cache budget
# so pruning decisions never re-open footers.
# ---------------------------------------------------------------------------


class RowGroupMeta:
    """One row group's shape + per-column `ZoneStats` and byte sizes
    (keys = schema names; `col_bytes` holds each column chunk's uncompressed
    size, so byte counters can report the columns actually decoded)."""

    __slots__ = ("num_rows", "total_bytes", "stats", "col_bytes")

    def __init__(self, num_rows: int, total_bytes: int, stats: dict, col_bytes: dict):
        self.num_rows = num_rows
        self.total_bytes = total_bytes
        self.stats = stats
        self.col_bytes = col_bytes


class FileFooterMeta:
    """One parquet file's footer facts: row count, arrow schema (for empty
    reads and columns=None name order), the row-group zone maps, and
    `dict_cols` — per column, whether EVERY row-group chunk is
    dictionary-encoded on disk (string values only): the fact the encoded
    execution path reads to choose codes-through vs flatten per column."""

    __slots__ = ("num_rows", "names", "arrow_schema", "row_groups", "dict_cols")

    def __init__(self, num_rows, names, arrow_schema, row_groups, dict_cols=None):
        self.num_rows = num_rows
        self.names = names
        self.arrow_schema = arrow_schema
        self.row_groups = row_groups
        self.dict_cols = dict_cols or {}


def _stat_value(v):
    """Parquet statistics value → the comparison space the engine evaluates
    in (UTF-8 byte arrays decode to str; undecodable bytes = unusable)."""
    if isinstance(v, bytes):
        try:
            return v.decode("utf-8")
        except UnicodeDecodeError:
            return None
    return v


def _parse_footer_meta(path: str, pf: Optional["pq.ParquetFile"] = None) -> FileFooterMeta:
    """`pf` reuses a caller's already-open handle (the cold decode path parses
    the footer from the SAME open that will serve the read — one footer open
    per cold file, not two); the caller keeps ownership of its handle."""
    from .pushdown import ZoneStats

    _faults.check("io.footer")
    if pf is not None:
        return _footer_meta_from_open(pf)
    with pq.ParquetFile(path) as f:
        return _footer_meta_from_open(f)


def _footer_meta_from_open(pf: "pq.ParquetFile") -> FileFooterMeta:
    from .pushdown import ZoneStats

    md = pf.metadata
    schema = pf.schema_arrow
    names = list(schema.names)
    # Column-chunk order == schema leaf order; zone maps are recorded only
    # for FLAT schemas (leaf count == field count) — nested leaves would
    # mis-align names, and the engine reads flat tables anyway.
    flat = md.num_columns == len(names)
    # Per-column encoded-execution eligibility: string values AND a
    # dictionary page in EVERY row-group chunk (the encodings tuple always
    # lists PLAIN for the dictionary page itself, so `has_dictionary_page`
    # is the reliable discriminator).
    dict_cols: Dict[str, bool] = {}
    if flat:
        for f in schema:
            vt = f.type.value_type if pa.types.is_dictionary(f.type) else f.type
            dict_cols[f.name] = bool(
                pa.types.is_string(vt) or pa.types.is_large_string(vt)
            ) and md.num_row_groups > 0
    row_groups: List[RowGroupMeta] = []
    for i in range(md.num_row_groups):
        rg = md.row_group(i)
        stats: Dict[str, object] = {}
        col_bytes: Dict[str, int] = {}
        if flat:
            for j in range(rg.num_columns):
                chunk = rg.column(j)
                col_bytes[names[j]] = int(chunk.total_uncompressed_size)
                if not chunk.has_dictionary_page:
                    dict_cols[names[j]] = False
                st = chunk.statistics
                if st is None:
                    stats[names[j]] = ZoneStats()
                    continue
                mn = mx = None
                has = bool(st.has_min_max)
                if has:
                    mn = _stat_value(st.min)
                    mx = _stat_value(st.max)
                    has = mn is not None and mx is not None
                nulls = st.null_count if st.has_null_count else None
                stats[names[j]] = ZoneStats(mn, mx, has, nulls)
        row_groups.append(
            RowGroupMeta(rg.num_rows, rg.total_byte_size, stats, col_bytes)
        )
    return FileFooterMeta(md.num_rows, names, schema, row_groups, dict_cols)


def _meta_nbytes(meta: FileFooterMeta) -> int:
    """Byte estimate for the scan-cache budget: footers are tiny next to
    decoded columns, but unbounded growth over huge lakes must still evict."""
    per_rg = 64 + 96 * max(1, len(meta.names))
    return 512 + per_rg * max(1, len(meta.row_groups))


def footer_metadata(
    path: str, file_format: str = "parquet", _pf=None
) -> Optional[FileFooterMeta]:
    """Footer metadata of one parquet file through the scan cache (freshness =
    the cache's (path, size, mtime) base). None for non-parquet formats or an
    unreadable footer — callers then skip pruning for the file. `_pf` lets the
    cold decode path donate its already-open `pq.ParquetFile` so a cache miss
    costs no second footer open (the caller keeps handle ownership)."""
    if file_format not in ("parquet", "delta"):
        return None
    from .scan_cache import global_scan_cache

    cache = global_scan_cache()
    meta = cache.get_meta(path)
    if meta is not None:
        _FOOTER_HITS.inc()
        return meta
    _FOOTER_MISSES.inc()

    def _parse_and_cache() -> Optional[FileFooterMeta]:
        try:
            # Transient footer-read faults retry with backoff; a PERSISTENT
            # parse failure still degrades to "no pruning" — a corrupt footer
            # must never break the scan, only its selectivity.
            meta = _resilience.retry_io(
                "io.footer", lambda: _parse_footer_meta(path, _pf)
            )
        except (QueryTimeoutError, RetryBudgetExceededError):
            # Deadline and retry budget are QUERY contracts, not pruning
            # details: swallowing either here would let a deadlined/budget-
            # blown query limp on, burning more retries per footer.
            raise
        except Exception:
            return None  # unreadable footer: never break the scan over pruning
        cache.put_meta(path, meta, _meta_nbytes(meta))
        return meta

    # Single-flight: concurrent cold scans of the same lake otherwise parse
    # every footer once per caller. A follower is served from the entry the
    # leader cached; an unreadable footer (leader returned None, nothing
    # cached) degrades to each caller paying its own parse attempt — exactly
    # the pre-serving cost. The donated `_pf` handle is only ever touched by
    # the thread that owns it (the leader path of its own call).
    return _singleflight.shared(
        ("meta", path), _parse_and_cache, lambda: cache.get_meta(path)
    )


def _pushdown_selections(ordered: List[str], file_format: str, pushdown):
    """Per-file row-group selections of one scan: a list aligned with
    `ordered` of (meta, sel) — sel None = keep every row group — or None when
    pushdown is inapplicable or prunes NOTHING anywhere (the caller then runs
    the plain whole-file path with unchanged cache keys). Pure decision — no
    counters (`_record_pruning` ticks them only for scans that actually
    assemble, so a concat-cache hit never inflates them)."""
    if pushdown is None or file_format not in ("parquet", "delta"):
        return None
    out = []
    any_pruned = False
    for p in ordered:
        meta = footer_metadata(p, file_format)
        sel = pushdown.select_row_groups(meta) if meta is not None else None
        out.append((meta, sel))
        if sel is not None:
            any_pruned = True
    return out if any_pruned else None


def _record_pruning(selections, pruning_stats=None) -> None:
    """Tick the row-group decision counters for one scan that is really
    assembling its result (a scan fully served by the concat cache never
    gets here). Byte counters are decode-truth instead: they tick inside
    `_decode_rg_into_cache`, so per-file cache hits cannot inflate
    ``bytes_decoded``. `pruning_stats` (a dict) receives this scan's
    scanned/skipped totals for the per-scan span attrs."""
    scanned = skipped = 0
    for meta, sel in selections:
        if meta is None:
            continue
        n = len(meta.row_groups)
        if sel is None:
            scanned += n
        else:
            scanned += len(sel)
            skipped += n - len(sel)
    _RG_SCANNED.inc(scanned)
    _RG_SKIPPED.inc(skipped)
    if pruning_stats is not None:
        pruning_stats["row_groups_scanned"] = (
            pruning_stats.get("row_groups_scanned", 0) + scanned
        )
        pruning_stats["row_groups_skipped"] = (
            pruning_stats.get("row_groups_skipped", 0) + skipped
        )


def file_columns_for(columns: Optional[List[str]], partitions) -> Optional[List[str]]:
    """The column subset to request FROM THE FILE for a wanted projection:
    partition columns are path facts, not file content, so they are stripped
    here and re-appended per file by `append_partition_columns`."""
    if partitions is None:
        return columns
    spec, _roots = partitions
    pset = {c.lower() for c in spec.columns}
    if columns is None:
        return None
    file_columns = [c for c in columns if c.lower() not in pset]
    if not file_columns:
        # Only partition columns requested: still need row counts, so read
        # the file's own columns and drop them in the select below.
        return None
    return file_columns


def file_table(path: str, file_format: str, file_columns: Optional[List[str]]) -> Table:
    """Decoded table of ONE data file through the per-file scan cache — the
    shared decode primitive of `read_files` and the pipelined index build.

    The cache stores columns, not column tuples, so a warm file decodes ONLY
    the columns no earlier projection touched (e.g. an index build over
    (a, b, c) after a query that scanned (a, b) decodes just c)."""
    from .scan_cache import global_scan_cache

    t = global_scan_cache().get(path, file_columns)
    if t is not None:
        return t
    return _decode_into_cache(path, file_format, file_columns)


def _cols_key(columns: Optional[List[str]]) -> tuple:
    """Flight-key spelling of a projection (None = all columns must never
    alias an explicit empty projection — same rule as the concat key)."""
    return ("<all>",) if columns is None else tuple(columns)


def _decode_into_cache(
    path: str, file_format: str, file_columns: Optional[List[str]]
) -> Table:
    """The miss half of `file_table`, under single-flight: N concurrent cold
    requests for the same (file, projection) run ONE decode — the leader runs
    `_decode_into_cache_miss`, followers block and are served from the entry
    it cached (`serve.singleflight`; record=False because each follower's own
    request already counted its miss at the probe — one request, one count).
    A leader failure clears the flight and each follower retries
    independently: no poisoned entries, composing with the retry contract
    inside the miss body."""
    from .scan_cache import global_scan_cache

    from ..serve import replicas as _replicas

    return _singleflight.shared(
        ("file", path, _cols_key(file_columns)),
        # Cross-replica discipline OUTSIDE the miss body (serve.replicas;
        # no-op at one env read without a fleet): an owned file decodes
        # directly, a foreign cold file first takes the fleet's on-lake
        # lease so K replicas hitting one cold file serialize onto the page
        # cache the first decode warmed — the in-process flight above it
        # keeps deduplicating threads exactly as before.
        lambda: _replicas.coordinate_decode(
            path, lambda: _decode_into_cache_miss(path, file_format, file_columns)
        ),
        lambda: global_scan_cache().get(path, file_columns, record=False),
    )


def _decode_into_cache_miss(
    path: str, file_format: str, file_columns: Optional[List[str]]
) -> Table:
    """Decode only the cold columns when the cache can tell which those are,
    else the full projection. The caller has already counted the miss (no
    double accounting)."""
    import time as _time

    from .scan_cache import global_scan_cache

    t0 = _time.monotonic()
    _decode_begin()
    try:
        cache = global_scan_cache()
        missing = cache.missing_columns(path, file_columns)
        if missing and missing != list(file_columns or []):
            cache.put(
                path,
                missing,
                # Transient decode faults retry with backoff (the cache only
                # ever stores the eventual SUCCESS — a retried decode is
                # indistinguishable from a clean one downstream).
                _resilience.retry_io(
                    "io.decode", lambda: _read_one(path, file_format, missing)
                ),
            )
            t = cache.get(path, file_columns, record=False)
            if t is not None:
                _decode_end(t0)
                return t  # assembled: warm columns + the freshly decoded rest
        t = _resilience.retry_io(
            "io.decode", lambda: _read_one(path, file_format, file_columns)
        )
        cache.put(path, file_columns, t)
        _decode_end(t0)
        return t
    except BaseException:
        _DECODE_IN_FLIGHT.dec()  # failed decode still leaves the pool
        raise


def _empty_file_table(meta: FileFooterMeta, file_columns: Optional[List[str]]) -> Table:
    """0-row table with one file's exact decoded dtypes (from its footer
    schema, no byte decoded) — the ALL-PRUNED outcome. The empty table still
    flows into concats/streams so dtype promotion and union dictionaries
    match the unpruned path exactly."""
    at = meta.arrow_schema.empty_table()
    if file_columns is not None:
        at = at.select(file_columns)
    return _arrow_to_table(at)


def _read_row_groups_one(path: str, sel, columns: Optional[List[str]]) -> Table:
    """Decode ONLY the row groups in `sel` (ascending indices) — pruned bytes
    are never decoded. Row order is the file's own (row groups in index
    order), so the surviving rows appear exactly as in a whole-file read
    minus the pruned groups."""
    _faults.check("io.decode")
    rd = []
    if _encoding.encoded_exec_enabled():
        # The pruning decision that produced `sel` already cached this
        # footer, so the encoding facts are a cache hit by construction —
        # deciding before the open keeps every pruned read at ONE open.
        meta = footer_metadata(path, "parquet")
        rd = _encoding.dict_read_columns(meta, columns)
    if rd:
        with pq.ParquetFile(path, read_dictionary=rd) as pf:
            return _arrow_to_table(pf.read_row_groups(list(sel), columns=columns))
    with pq.ParquetFile(path) as pf:
        return _arrow_to_table(pf.read_row_groups(list(sel), columns=columns))


def selection_columns(
    file_columns: Optional[List[str]], meta: FileFooterMeta
) -> List[str]:
    """THE explicit column list of a selection-keyed cache entry: the
    requested projection, or the footer's whole-file order for columns=None.
    Every selection put/get/warm site resolves through here — the key space
    must be computed identically everywhere (selection entries never consult
    the whole-file ("names",) record)."""
    return list(file_columns) if file_columns is not None else list(meta.names)


def pruned_file_table(
    path: str,
    file_format: str,
    file_columns: Optional[List[str]],
    meta: FileFooterMeta,
    sel,
) -> Table:
    """Decoded table of ONE file under a row-group selection, through the
    per-file scan cache. `sel` None = the plain whole-file path (identical
    behavior AND cache keys to a non-pushdown read); a tuple = the pruned
    decode, cached under selection-aware keys so it can never alias the
    whole-file entries."""
    if sel is None:
        return file_table(path, file_format, file_columns)
    if len(sel) == 0:
        return _empty_file_table(meta, file_columns)
    from .scan_cache import global_scan_cache

    cols = selection_columns(file_columns, meta)
    sel = tuple(sel)
    t = global_scan_cache().get(path, cols, sel=sel)
    if t is not None:
        return t
    return _decode_rg_into_cache(path, cols, sel, meta)


def _record_decoded_bytes(
    meta: Optional[FileFooterMeta], sel: tuple, decoded_cols: List[str]
) -> None:
    """Decode-truth byte counters: ticked ONLY when a pruned decode really
    runs, never on cache hits, and only for the column chunks actually
    decoded — ``bytes_decoded``/``bytes_skipped`` measure bytes, not
    decisions. Skipped bytes are the SAME columns' chunks in the pruned row
    groups (what a whole-file read of this projection would have paid)."""
    if meta is None:
        return

    def cols_bytes(rg) -> int:
        if not rg.col_bytes:
            return rg.total_bytes
        return sum(rg.col_bytes.get(c, 0) for c in decoded_cols)

    keep = set(sel)
    decoded = sum(cols_bytes(rg) for i, rg in enumerate(meta.row_groups) if i in keep)
    skipped = sum(
        cols_bytes(rg) for i, rg in enumerate(meta.row_groups) if i not in keep
    )
    _RG_BYTES_DECODED.inc(decoded)
    _RG_BYTES_SKIPPED.inc(skipped)
    # Ledger mirror: the SAME values at the SAME site, so a query's
    # bytes_decoded reconciles with the io.pruning.* counters by construction.
    _accounting.add("bytes_decoded", decoded)
    _accounting.add("bytes_skipped", skipped)


def _decode_rg_into_cache(
    path: str, cols: List[str], sel: tuple, meta: Optional[FileFooterMeta] = None
) -> Table:
    """The miss half of `pruned_file_table`, under single-flight keyed by the
    SELECTION-aware cache key: two concurrent identical pruned reads decode
    once, while DISTINCT selections (or a whole-file read) of the same file
    can never share a flight — exactly the aliasing rule of the cache entries
    the flights guard."""
    from .scan_cache import global_scan_cache

    from ..serve import replicas as _replicas

    return _singleflight.shared(
        ("file", path, tuple(cols), tuple(sel)),
        # Same cross-replica guard as the whole-file flight: routed by FILE
        # (not selection) so one replica owns all of a file's pruned reads.
        lambda: _replicas.coordinate_decode(
            path, lambda: _decode_rg_into_cache_miss(path, cols, sel, meta)
        ),
        lambda: global_scan_cache().get(path, cols, record=False, sel=sel),
    )


def _decode_rg_into_cache_miss(
    path: str, cols: List[str], sel: tuple, meta: Optional[FileFooterMeta] = None
) -> Table:
    """Decode only the cold columns of the selection when the cache can tell
    which those are. The cache only ever stores successful decodes — a fault
    mid-scan leaves no partial selection entry behind (pinned by
    tests/test_scan_pushdown.py)."""
    import time as _time

    from .scan_cache import global_scan_cache

    t0 = _time.monotonic()
    _decode_begin()
    try:
        cache = global_scan_cache()
        missing = cache.missing_columns(path, cols, sel=sel)
        if missing and missing != cols:
            cache.put(
                path,
                missing,
                _resilience.retry_io(
                    "io.decode", lambda: _read_row_groups_one(path, sel, missing)
                ),
                sel=sel,
            )
            t = cache.get(path, cols, record=False, sel=sel)
            if t is not None:
                _record_decoded_bytes(meta, sel, missing)
                _decode_end(t0)
                return t
        t = _resilience.retry_io(
            "io.decode", lambda: _read_row_groups_one(path, sel, cols)
        )
        cache.put(path, cols, t, sel=sel)
        _record_decoded_bytes(meta, sel, cols)
        _decode_end(t0)
        return t
    except BaseException:
        _DECODE_IN_FLIGHT.dec()
        raise


def decorate_file_table(
    t: Table,
    path: str,
    partitions,
    columns: Optional[List[str]],
) -> Table:
    """Apply the per-file post-decode steps of `read_files` to one file's raw
    table: append hive-partition columns and project to the wanted order."""
    if partitions is None:
        return t
    from .partitioning import append_partition_columns

    spec, roots = partitions
    t = append_partition_columns(t, spec, roots, path, wanted=columns)
    if columns is not None:
        t = t.select(columns)
    return t


def warm_file_cache(
    paths: List[str],
    file_format: str,
    file_columns: Optional[List[str]],
    selections=None,
) -> None:
    """Concurrently decode the cache-cold files among `paths` into the per-file
    scan cache (shared decode-pool contract). Callers that must consume files
    in a fixed order one at a time (the bucketed index scan) call this first so
    the serial consumption loop runs fully warm — cold indexed reads previously
    decoded every bucket file back-to-back on one thread.

    `selections` (path → (meta, sel), from `_pushdown_selections`) warms the
    SELECTION-keyed entries for files a pushdown decision pruned: the pool
    decodes exactly the surviving row groups."""
    from .scan_cache import global_scan_cache

    cache = global_scan_cache()
    jobs = []  # (path, sel_or_None, explicit cols for the sel path)
    for p in paths:
        meta, sel = (selections or {}).get(p, (None, None))
        if sel is None:
            if cache.missing_columns(p, file_columns) != []:
                jobs.append((p, None, None))
        elif len(sel) > 0:
            cols = selection_columns(file_columns, meta)
            if cache.missing_columns(p, cols, sel=tuple(sel)) != []:
                jobs.append((p, tuple(sel), cols))
    workers = decode_pool_size(len(jobs)) if jobs else 0
    if len(jobs) > 1 and workers > 1:
        from concurrent.futures import ThreadPoolExecutor

        led = _accounting.current_ledger()  # charge workers to the submitter
        sc = _resilience.current_scope()  # workers honor the query deadline
        stage = _stage_ledger.worker_stage("decode")  # bill the submit stage

        def warm_one(job):
            p, sel, cols = job
            with _accounting.use_ledger(led), _resilience.use_scope(
                sc
            ), _stage_ledger.stage_scope(stage):
                _faults.check("pool.worker")
                if sel is None:
                    _decode_into_cache(p, file_format, file_columns)
                else:
                    meta, _sel = (selections or {}).get(p, (None, None))
                    _decode_rg_into_cache(p, cols, sel, meta)

        with ThreadPoolExecutor(max_workers=workers) as pool:
            list(pool.map(warm_one, jobs))


def iter_file_tables(
    files: List[str],
    file_format: str,
    columns: Optional[List[str]] = None,
    partitions=None,
    on_decode=None,
    pushdown=None,
    pruning_stats=None,
):
    """Ordered per-file table iterator with bounded decode prefetch — the
    read-side twin of the build pipeline's decode stage. Files decode on a
    pool (shared `decode_pool_size` contract, through the per-column scan
    cache) up to `prefetch_depth()` files ahead of the consumer, and are
    yielded in sorted-file order so downstream results are independent of
    decode completion order. A decode failure propagates at the failed file's
    yield point; already-submitted decodes finish into the cache harmlessly
    (the cache only ever stores successful decodes — no poisoned entries).

    `pushdown` (a `ScanPredicate`) prunes at ROW-GROUP granularity: each
    file's footer zone maps decide the surviving row groups up front, and the
    per-file tables yielded here carry ONLY those groups — pruned bytes are
    never decoded, staged, or filtered, and the streaming executor's chunks
    then align to the surviving groups by construction. An all-pruned file
    yields its 0-row schema table so downstream dtype promotion matches the
    unpruned stream.

    `on_decode(seconds)` observes each file's decode wall time (telemetry)."""
    if not files:
        return
    import time as _time
    from concurrent.futures import ThreadPoolExecutor

    file_columns = file_columns_for(columns, partitions)
    ordered = sorted(files)
    selections = _pushdown_selections(ordered, file_format, pushdown)
    sel_of = {}
    if selections is not None:
        # The stream always assembles (no concat-cache level), so the
        # decision counters tick per streamed scan.
        _record_pruning(selections, pruning_stats)
        sel_of = dict(zip(ordered, selections))

    led = _accounting.current_ledger()  # pool workers charge the submitter
    sc = _resilience.current_scope()  # workers honor the query deadline
    stage = _stage_ledger.worker_stage("decode")  # bill the submit stage

    def decode_one(path: str) -> Table:
        with _accounting.use_ledger(led), _resilience.use_scope(
            sc
        ), _stage_ledger.stage_scope(stage):
            _faults.check("pool.worker")
            t0 = _time.monotonic()
            meta, sel = sel_of.get(path, (None, None))
            if sel is None:
                t = file_table(path, file_format, file_columns)
            else:
                t = pruned_file_table(path, file_format, file_columns, meta, sel)
            _stage_ledger.note_rows(t.num_rows)
            if on_decode is not None:
                on_decode(_time.monotonic() - t0)
            return t

    # The prefetch depth is the binding in-flight bound: more decode workers
    # than undelivered-file slots could only grow resident memory past it.
    depth = prefetch_depth()
    workers = min(decode_pool_size(len(ordered)), depth)
    if workers <= 1:
        for f in ordered:
            # Chunk/pool-boundary cancellation: a deadlined query stops
            # between files, before paying for the next decode.
            _resilience.check_deadline("io.iter_file_tables")
            yield decorate_file_table(decode_one(f), f, partitions, columns)
        return
    from collections import deque

    pool = ThreadPoolExecutor(max_workers=workers)
    try:
        pending: "deque" = deque()
        i = 0
        while i < len(ordered) or pending:
            _resilience.check_deadline("io.iter_file_tables")
            while i < len(ordered) and len(pending) < depth:
                pending.append((ordered[i], pool.submit(decode_one, ordered[i])))
                i += 1
            f, fut = pending.popleft()
            yield decorate_file_table(fut.result(), f, partitions, columns)
    finally:
        # Cooperative cancellation drains here too: undelivered decodes are
        # cancelled, in-flight ones finish into the cache harmlessly.
        pool.shutdown(wait=False, cancel_futures=True)


def concat_cache_probe(
    files: List[str],
    file_format: str,
    columns: Optional[List[str]],
    partitions,
    selection_marker=None,
) -> Tuple[Optional[tuple], Optional[Table]]:
    """(key, cached table or None) for the multi-file concat cache. Key =
    per-file (path,size,mtime) + columns + partition layout, so any file
    rewrite (or a different partition interpretation of the same files)
    invalidates. Shared by `read_files` and the pipelined index build (a warm
    source concat skips the build's whole decode stage).

    `selection_marker` (the per-file row-group selections of a pushdown scan,
    aligned with the sorted file order) keys PRUNED concats apart from whole
    ones — and two predicates surviving to the same selections share one
    entry, because the selection fully determines the bytes read."""
    if len(files) <= 1:
        return None, None
    from .scan_cache import global_concat_cache

    try:
        stats = []
        for p in sorted(files):
            st = os.stat(p)
            stats.append((p, st.st_size, int(st.st_mtime * 1000)))
        part_marker = None
        if partitions is not None:
            pspec, proots = partitions
            part_marker = (tuple(pspec.columns), tuple(pspec.dtypes), tuple(proots))
        concat_key = (
            "concat",
            file_format,
            tuple(stats),
            # None (all columns) must not share a key with [] (zero columns).
            ("<all>",) if columns is None else tuple(columns),
            part_marker,
        )
        if selection_marker is not None:
            concat_key = concat_key + (("rgsel", selection_marker),)
    except OSError:
        return None, None
    hit = global_concat_cache().get(concat_key)
    return concat_key, hit[0] if hit is not None else None


def read_files(
    files: List[str],
    file_format: str,
    columns: Optional[List[str]] = None,
    partitions=None,
    pushdown=None,
    pruning_stats=None,
) -> Table:
    """Read + concat data files. `partitions` = (PartitionSpec, root_paths) for
    hive-partitioned sources: the per-file cache holds the RAW file content (the
    partition values are path facts, not file content) and the constant partition
    columns are appended per file before the concat.

    `pushdown` (a `ScanPredicate`) evaluates each file's footer zone maps and
    decodes only the qualifying row groups (`pruned_file_table`). When it
    prunes NOTHING, the call is bit-and-key-identical to a pushdown-free read
    — the concat entry stays shared with every other consumer of these files.
    All-pruned files contribute their 0-row schema tables so concat dtype
    promotion and union dictionaries match the whole-file path exactly."""
    if not files:
        raise HyperspaceException("No data files to read.")
    _resilience.check_deadline("io.read_files")
    from .scan_cache import global_concat_cache

    ordered = sorted(files)
    file_columns = file_columns_for(columns, partitions)
    selections = _pushdown_selections(ordered, file_format, pushdown)
    sel_marker = (
        None
        if selections is None
        else tuple(sel for _meta, sel in selections)
    )

    # Multi-file concat cache: re-assembling N per-file tables (and re-unioning
    # string dictionaries) per query dominates repeated multi-file scans — e.g.
    # a filter-index scan over num_buckets small files.
    concat_key, cached = concat_cache_probe(
        files, file_format, columns, partitions, selection_marker=sel_marker
    )
    if cached is not None:
        return cached

    def _assemble() -> Table:
        if selections is not None:
            # Past the concat probe: this scan really assembles, so its
            # pruning decision counts (a warm repeat served above never gets
            # here).
            _record_pruning(selections, pruning_stats)

        from .scan_cache import global_scan_cache

        cache = global_scan_cache()
        if selections is None:
            tables: List[Optional[Table]] = [
                cache.get(f, file_columns) for f in ordered
            ]
            missing = [i for i, t in enumerate(tables) if t is None]
            decode_miss = lambda i: _decode_into_cache(
                ordered[i], file_format, file_columns
            )
        else:
            tables = []
            for f, (meta, sel) in zip(ordered, selections):
                if sel is None:
                    tables.append(cache.get(f, file_columns))
                elif len(sel) == 0:
                    tables.append(_empty_file_table(meta, file_columns))
                else:
                    tables.append(
                        cache.get(
                            f, selection_columns(file_columns, meta), sel=tuple(sel)
                        )
                    )
            missing = [i for i, t in enumerate(tables) if t is None]

            def decode_miss(i: int) -> Table:
                meta, sel = selections[i]
                if sel is None:
                    return _decode_into_cache(ordered[i], file_format, file_columns)
                return _decode_rg_into_cache(
                    ordered[i], selection_columns(file_columns, meta), tuple(sel), meta
                )

        workers = decode_pool_size(len(missing)) if missing else 0
        if len(missing) > 1 and workers > 1:
            # Decode cache misses concurrently: parquet/csv decode is pyarrow
            # C++ work that releases the GIL, so a thread pool gives real
            # parallelism (SURVEY §7 "overlap decode; don't let the device
            # idle on file I/O"). Fully-warm scans never pay the pool setup.
            # The worker count rides the shared HYPERSPACE_BUILD_DECODE_THREADS
            # contract (`decode_pool_size`), so `=1` forces the serial path
            # here exactly as it does for the build.
            from concurrent.futures import ThreadPoolExecutor

            led = _accounting.current_ledger()  # charge workers to the submitter
            sc = _resilience.current_scope()  # workers honor the query deadline
            stage = _stage_ledger.worker_stage("decode")  # bill the submit stage

            def decode_miss_worker(i: int) -> Table:
                with _accounting.use_ledger(led), _resilience.use_scope(
                    sc
                ), _stage_ledger.stage_scope(stage):
                    _faults.check("pool.worker")
                    return decode_miss(i)

            with ThreadPoolExecutor(max_workers=workers) as pool:
                decoded = list(pool.map(decode_miss_worker, missing))
            for i, t in zip(missing, decoded):
                tables[i] = t
        else:
            for i in missing:
                tables[i] = decode_miss(i)

        if partitions is not None:
            tables = [
                decorate_file_table(t, f, partitions, columns)
                for f, t in zip(ordered, tables)
            ]
        out = tables[0] if len(tables) == 1 else Table.concat(tables)
        if concat_key is not None:
            global_concat_cache().put(concat_key, out, None)
        return out

    if concat_key is None:
        # Single file (the per-file flights inside `_decode_into_cache`
        # dedup those) or unstattable inventory: no concat entry to share.
        return _assemble()

    def _reprobe() -> Optional[Table]:
        hit = global_concat_cache().get(concat_key)
        return hit[0] if hit is not None else None

    # Scan-level single-flight: N identical concurrent cold multi-file scans
    # assemble (decode + concat + dictionary union) ONCE; followers are
    # served from the concat entry the leader put — their re-probe records
    # the concat HIT their request really is.
    return _singleflight.shared(("scan",) + concat_key, _assemble, _reprobe)


def infer_schema(files: List[str], file_format: str) -> Schema:
    """Schema from the first file's footer/sample (cheap; no full read for parquet)."""
    if not files:
        raise HyperspaceException("No data files to infer schema from.")
    f = sorted(files)[0]
    if file_format in ("parquet", "delta"):
        return arrow_schema_to_schema(pq.read_schema(f))
    if file_format == "orc":
        from pyarrow import orc as pa_orc

        return arrow_schema_to_schema(pa_orc.ORCFile(f).schema)
    # csv/json infer by decoding the first file — a lake-touching read like
    # any other, so it rides the same transient-retry contract.
    return _resilience.retry_io("io.decode", lambda: _read_one(f, file_format)).schema


_ARROW_TO_DTYPE = {
    pa.int32(): INT32,
    pa.int64(): INT64,
    pa.float32(): FLOAT32,
    pa.float64(): FLOAT64,
    pa.bool_(): BOOL,
}


def arrow_schema_to_schema(sch: pa.Schema) -> Schema:
    fields = []
    for f in sch:
        if f.type in _ARROW_TO_DTYPE:
            fields.append(Field(f.name, _ARROW_TO_DTYPE[f.type]))
        elif pa.types.is_string(f.type) or pa.types.is_large_string(f.type):
            fields.append(Field(f.name, STRING))
        elif pa.types.is_dictionary(f.type):
            fields.append(Field(f.name, STRING))
        elif pa.types.is_temporal(f.type):
            fields.append(Field(f.name, STRING))
        elif pa.types.is_integer(f.type):
            fields.append(Field(f.name, INT64))
        else:
            raise HyperspaceException(f"Unsupported arrow type: {f.type} ({f.name})")
    return Schema(fields)


def table_to_arrow(table: Table, encode_dictionaries: bool = False) -> pa.Table:
    """`encode_dictionaries` (the parquet writer's setting, under the
    ``HYPERSPACE_ENCODED_EXEC`` flag) emits string columns as COMPACTED
    arrow dictionary arrays — D distinct strings cross the boundary instead
    of N decoded ones, and the written bucket files stay dictionary-encoded
    for the encoded read path. The CSV/ORC/JSON writers keep decoded arrays
    (their writers don't all take dictionary input)."""
    arrays = []
    names = []
    encode = encode_dictionaries and _encoding.encoded_exec_enabled()
    for name, col in table.columns.items():
        names.append(name)
        mask = None if col.validity is None else ~col.validity
        if encode and col.is_string:
            arrays.append(
                _encoding.dictionary_arrow_array(col.data, col.dictionary, mask)
            )
        else:
            arrays.append(pa.array(col.decode(), mask=mask))
    return pa.table(dict(zip(names, arrays)))


def checked_write_table(
    at: pa.Table, path: str, row_group_rows: Optional[int] = None
) -> None:
    """THE parquet write primitive of both index writers (serial
    `write_parquet` path and the pipelined `_BucketWriter`) and the session
    helpers: one `storage.write` fault point + bounded transient-retry site.
    A retried write simply overwrites the partial file — `pq.write_table`
    truncates — so the committed bytes are always one clean encode."""

    def _write() -> None:
        _faults.check("storage.write")
        if row_group_rows is None:
            pq.write_table(at, path)
        else:
            pq.write_table(at, path, row_group_size=int(row_group_rows))

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    _resilience.retry_io("storage.write", _write)


def write_parquet(table: Table, path: str, row_group_rows: Optional[int] = None) -> None:
    """`row_group_rows` bounds the written row groups (None = pyarrow's
    default) — the index writers pass `index_row_group_rows()` so footer zone
    maps get sub-file resolution over the key-sorted bucket rows."""
    checked_write_table(
        table_to_arrow(table, encode_dictionaries=True), path, row_group_rows
    )


def write_orc(table: Table, path: str) -> None:
    from pyarrow import orc as pa_orc

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    pa_orc.write_table(table_to_arrow(table), path)


def write_csv(table: Table, path: str) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    pa_csv.write_csv(table_to_arrow(table), path)


def write_json(table: Table, path: str) -> None:
    """Line-delimited JSON writer (pyarrow has no JSON writer)."""
    import json as _json

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    cols = {n: c.decode_objects() for n, c in table.columns.items()}
    with open(path, "w") as f:
        for i in range(table.num_rows):
            row = {n: v[i].item() if hasattr(v[i], "item") else v[i] for n, v in cols.items()}
            f.write(_json.dumps(row) + "\n")
