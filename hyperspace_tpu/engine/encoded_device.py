"""Device-resident encoded execution: stage dictionary CODES across the
host→device boundary, narrowed to the smallest integer width the dictionary
cardinality allows.

String columns are already dictionary-encoded on the host (int32 codes +
sorted `<U*` dictionary — `engine/table.py`), and PR 8 made the HOST half of
the pipeline code-space end to end. The DEVICE half still shipped the full
int32 code lane through every pow2 staging site and the mesh exchange's
padded send matrices. This module is the staging policy for the device half:

- `stage_codes(col, site)` — device-stage a string column's key lane as int8
  (dictionary ≤ 127 entries) or int16 (≤ 32767) instead of int32, through the
  identity-keyed upload cache, with the flat-vs-staged byte split recorded in
  the encoded-staging ledger. Non-qualifying columns stage flat, byte-for-byte
  as before.
- `narrow_codes(col)` — the memoized narrow copy (attached to the Column so
  the id-keyed upload cache keeps hitting across queries).
- `stage_aligned(arr, col, site)` — same policy for DERIVED code arrays (the
  union-dictionary-aligned verify lanes), width chosen from the array's own
  value range, identity-memoized.

Narrowing is value-preserving (codes < 2^width, the null code -1 survives any
signed width), so every consumer — hashing's `dh_table[codes]` gather, sort
operands, adjacent-equality group boundaries, pair-verification compares —
produces bit-identical results from narrow lanes; only the bytes over the
boundary shrink. Code width folds into the jit cache key the same way pow2
caps do: a BOUNDED {int8, int16, int32} class set per program, never a
per-cardinality shape (`tests/test_encoded_device.py` pins this).

Gate: `HYPERSPACE_ENCODED_DEVICE` — unset = auto (on when
`HYPERSPACE_ENCODED_EXEC` is on; per-column staging additionally wants the
column to have ridden an encoded read), `1` = force (every narrowable string
column qualifies), `0` = byte-identical flat-staging fallback in the standing
PR 1–12 oracle style.
"""

from __future__ import annotations

import os
import weakref

import numpy as np

ENV_ENCODED_DEVICE = "HYPERSPACE_ENCODED_DEVICE"

#: Narrow width policy: the null code -1 must survive, so widths are signed
#: and the dictionary must fit the POSITIVE range of the narrow type.
_INT8_MAX_CARD = 127
_INT16_MAX_CARD = 32767


def encoded_device_mode() -> str:
    """"off" | "force" | "auto" (the unset default)."""
    raw = os.environ.get(ENV_ENCODED_DEVICE)
    if raw is None or raw == "":
        return "auto"
    if raw == "0":
        return "off"
    return "force"


def encoded_device_enabled() -> bool:
    """Is the device-resident code path on at all? Auto defers to the master
    encoded-exec switch (`HYPERSPACE_ENCODED_EXEC`) — which, when unset,
    is itself decided per query by the adaptive planner
    (`plananalysis.planner`): one `encoded_exec` decision governs the host
    encoded layer, this device lane, and (transitively) packed code lanes."""
    mode = encoded_device_mode()
    if mode == "off":
        return False
    if mode == "force":
        return True
    from .encoding import encoded_exec_enabled

    return encoded_exec_enabled()


def code_dtype_for(cardinality: int):
    """Smallest signed dtype holding codes [-1, cardinality); None = int32
    already minimal (no narrowing to do)."""
    if cardinality <= _INT8_MAX_CARD:
        return np.int8
    if cardinality <= _INT16_MAX_CARD:
        return np.int16
    return None


def narrowable(col) -> bool:
    """Lane-level gate: may this column's code array travel narrow? Used by
    the mesh exchange and hash staging, where narrowing is provably
    value-identical — only the path-level switch and the width matter."""
    if not encoded_device_enabled():
        return False
    if not getattr(col, "is_string", False) or col.dictionary is None:
        return False
    return code_dtype_for(len(col.dictionary)) is not None


def column_qualifies(col) -> bool:
    """Per-column staging gate: `narrowable` plus, in auto mode, the column
    must have ridden an encoded read (`_encoded_read`, set by
    `encoding.dictionary_array_to_column` and propagated through take/concat)."""
    if not narrowable(col):
        return False
    if encoded_device_mode() == "force":
        return True
    return bool(getattr(col, "_encoded_read", False))


def narrow_codes(col) -> np.ndarray:
    """Narrow copy of a string column's code array, memoized on the Column so
    the identity-keyed upload cache keeps hitting across queries."""
    dt = code_dtype_for(len(col.dictionary))
    if dt is None or col.data.dtype != np.int32:
        return col.data
    cached = getattr(col, "_narrow_codes", None)
    if cached is not None and cached.dtype == dt and len(cached) == len(col.data):
        return cached
    narrow = col.data.astype(dt)
    try:
        col._narrow_codes = narrow
    except Exception:
        pass  # slotted/frozen column subclass: lose the memo, not the narrowing
    return narrow


def _charged_bytes(col, narrow: np.ndarray) -> int:
    """TRUE encoded footprint of a staged code lane: narrow codes + the
    dictionary + the validity lane — the same accounting
    `encoding.column_nbytes` charges the scan cache (the PR-8 fix)."""
    total = int(narrow.nbytes)
    if col.dictionary is not None:
        total += int(col.dictionary.nbytes)
    if col.validity is not None:
        total += int(col.validity.nbytes)
    return total


def widen_for_gather(codes):
    """Widen a narrow (or packed-then-unpacked) code lane to int32 before it
    INDEXES a pow2-padded table: the table's axis size (e.g. a 128-slot hash
    table for a 100-entry dictionary) can exceed the narrow index dtype's
    range. The cast runs on device — the wire already moved narrow/packed
    bytes. ONE home for the widen rule (`ops/hashing.py`, `ops/aggregate.py`,
    `engine/physical.py` gathers, and the packed tier all route here; the
    per-site ad-hoc casts this replaces were the PR 15 wart)."""
    import jax.numpy as jnp

    if codes.dtype != jnp.int32:
        return codes.astype(jnp.int32)
    return codes


def stage_codes(col, site: str):
    """Device-stage a column's key lane: bit-packed sub-byte words when the
    dictionary fits a packed class (`engine/packed_codes.py` — H2D moves
    `bits` bits per code, the device unpacks back to the narrow int8 lane),
    narrow codes when the column merely qualifies for encoded staging, flat
    data (byte-identical legacy path) otherwise."""
    from .device_cache import device_array

    if not column_qualifies(col):
        return device_array(col.data)
    from .packed_codes import packable_bits, stage_packed_codes

    bits = packable_bits(col)
    if bits is not None:
        return stage_packed_codes(col, site, bits)
    narrow = narrow_codes(col)
    if narrow is col.data:
        return device_array(col.data)
    return device_array(
        narrow,
        site=site,
        flat_bytes=int(col.data.nbytes),
        charged_bytes=_charged_bytes(col, narrow),
    )


# Derived code arrays (union-aligned verify lanes) are not Columns, so the
# narrow copies are memoized by array identity; entries die with their source
# arrays (which the two-table alignment cache owns).
_aligned_memo: dict = {}


def _narrow_array(arr: np.ndarray):
    """Narrow an int32 code array by its own value range (the union dictionary
    can exceed either side's), identity-memoized. Returns `arr` unchanged when
    int32 is already minimal."""
    key = id(arr)
    ent = _aligned_memo.get(key)
    if ent is not None and ent[0]() is arr:
        return ent[1]
    hi = int(arr.max(initial=0))
    dt = code_dtype_for(hi + 1)
    narrow = arr if dt is None else arr.astype(dt)
    try:
        ref = weakref.ref(arr, lambda _wr, k=key: _aligned_memo.pop(k, None))
    except TypeError:
        return narrow
    _aligned_memo[key] = (ref, narrow)
    return narrow


def stage_aligned(arr: np.ndarray, col, site: str):
    """Device-stage a derived int32 code array (e.g. union-aligned codes) for
    a qualifying source column; flat staging otherwise."""
    from .device_cache import device_array

    if (
        not isinstance(arr, np.ndarray)
        or arr.dtype != np.int32
        or not column_qualifies(col)
    ):
        return device_array(arr)
    narrow = _narrow_array(arr)
    if narrow is arr:
        return device_array(arr)
    charged = int(narrow.nbytes)
    if col.dictionary is not None:
        charged += int(col.dictionary.nbytes)
    return device_array(
        narrow, site=site, flat_bytes=int(arr.nbytes), charged_bytes=charged
    )
