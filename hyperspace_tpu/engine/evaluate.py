"""Expression evaluation on device arrays.

Filters and join-key computations run as jnp vector ops (VPU work under XLA). String
semantics ride the sorted-dictionary encoding: literal comparisons are translated to
code-space integer comparisons on the host (one dictionary binary-search per literal),
then evaluated on device — no string processing ever reaches the TPU.

Null semantics (SQL/Spark parity) ride a VALIDITY LANE: every evaluation result
carries an optional device bool array marking which slots are non-null. Comparisons
and arithmetic propagate invalidity; AND/OR use Kleene logic (FALSE dominates AND,
TRUE dominates OR); `evaluate_predicate` finally keeps a row only if the value is
true AND valid — a comparison with null is "unknown", and WHERE drops unknowns.
`valid=None` means all-valid, keeping the null-free fast path branch-free.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

from ..exceptions import HyperspaceException
from .expr import BinaryOp, Col, Expr, IsIn, IsNull, Lit, Not, Udf
from .device_cache import device_array
from .table import Column, Table, align_dictionaries


class _Val:
    """Evaluation result: numeric device array, string codes + dictionary, or
    literal — plus the validity lane (None = all valid)."""

    __slots__ = ("kind", "arr", "dictionary", "value", "valid")

    def __init__(self, kind, arr=None, dictionary=None, value=None, valid=None):
        self.kind = kind  # "num" | "str" | "lit"
        self.arr = arr
        self.dictionary = dictionary
        self.value = value
        self.valid = valid  # device bool array, or None


def _and_valid(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return jnp.logical_and(a, b)


def _device(table: Table, devcols: Dict[str, jnp.ndarray], name: str):
    if name not in devcols:
        col = table.column(name)
        if col.is_string:
            # Qualifying string columns UPLOAD narrow dictionary codes and
            # widen on device (encoded_device.py): H2D moves the compressed
            # lane, downstream code-space ops keep seeing int32.
            from .encoded_device import stage_codes

            arr = stage_codes(col, "eval_pred")
            if arr.dtype != jnp.int32:
                arr = arr.astype(jnp.int32)
            devcols[name] = arr
        else:
            devcols[name] = device_array(col.data)
    return devcols[name]


def _col_valid(table: Table, devcols: Dict[str, jnp.ndarray], name: str):
    col = table.column(name)
    if col.validity is None:
        return None
    key = f"__valid__{name}"
    if key not in devcols:
        devcols[key] = device_array(col.validity)
    return devcols[key]


def _str_lit_compare(op: str, codes, dictionary: np.ndarray, lit: str):
    """Translate a string-vs-literal comparison into code space (sorted dictionary ⇒
    codes are order-preserving)."""
    left_cut = int(np.searchsorted(dictionary, lit, side="left"))
    present = left_cut < len(dictionary) and dictionary[left_cut] == lit
    if op == "==":
        if not present:
            return jnp.zeros(codes.shape, dtype=bool)
        return codes == left_cut
    if op == "!=":
        if not present:
            return jnp.ones(codes.shape, dtype=bool)
        return codes != left_cut
    if op == "<":
        return codes < left_cut
    if op == ">=":
        return codes >= left_cut
    right_cut = int(np.searchsorted(dictionary, lit, side="right"))
    if op == "<=":
        return codes < right_cut
    if op == ">":
        return codes >= right_cut
    raise HyperspaceException(f"Unsupported string comparison: {op}")


def evaluate(expr: Expr, table: Table, devcols: Dict[str, jnp.ndarray]) -> _Val:
    if isinstance(expr, Col):
        col = table.column(expr.name)
        arr = _device(table, devcols, expr.name)
        valid = _col_valid(table, devcols, expr.name)
        if col.is_string:
            return _Val("str", arr, col.dictionary, valid=valid)
        return _Val("num", arr, valid=valid)

    if isinstance(expr, Lit):
        return _Val("lit", value=expr.value)

    if isinstance(expr, IsNull):
        v = evaluate(expr.child, table, devcols)
        if v.kind == "lit":
            is_null = v.value is None
            n = table.num_rows
            base = jnp.full((n,), is_null, dtype=bool)
        elif v.valid is None:
            base = jnp.zeros(v.arr.shape, dtype=bool)
        else:
            base = jnp.logical_not(v.valid)
        if expr.negated:
            base = jnp.logical_not(base)
        return _Val("num", base)  # IS [NOT] NULL is never itself null

    if isinstance(expr, Not):
        v = evaluate(expr.child, table, devcols)
        if v.kind != "num":
            raise HyperspaceException("NOT requires a boolean operand")
        return _Val("num", jnp.logical_not(v.arr), valid=v.valid)

    if isinstance(expr, IsIn):
        v = evaluate(expr.child, table, devcols)
        # Kleene: `x IN (v1, NULL)` is TRUE on match, else UNKNOWN (never FALSE) —
        # so NOT(... IN (.., NULL)) must drop non-matching rows, like SQL/Spark.
        had_null = any(x is None for x in expr.values)
        values = [x for x in expr.values if x is not None]
        if v.kind == "str":
            wanted = [str(x) for x in values]
            positions = np.searchsorted(v.dictionary, wanted)
            hits = [
                int(c)
                for c, x in zip(positions, wanted)
                if c < len(v.dictionary) and v.dictionary[c] == x
            ]
            if not hits:
                match = jnp.zeros(v.arr.shape, dtype=bool)
            else:
                match = jnp.isin(v.arr, jnp.asarray(np.asarray(hits, np.int32)))
        else:
            if not values:
                match = jnp.zeros(v.arr.shape, dtype=bool)
            else:
                match = jnp.isin(v.arr, jnp.asarray(np.asarray(values)))
        valid = v.valid
        if had_null:
            valid = _and_valid(valid, match)
        return _Val("num", match, valid=valid)

    if isinstance(expr, Udf):
        return _evaluate_udf(expr, table, devcols)

    if isinstance(expr, BinaryOp):
        l = evaluate(expr.left, table, devcols)
        r = evaluate(expr.right, table, devcols)
        op = expr.op

        if op in BinaryOp.BOOLEAN:
            if l.kind != "num" or r.kind != "num":
                raise HyperspaceException(f"'{op}' requires boolean operands")
            lv, rv = l.arr, r.arr
            if op == "and":
                value = jnp.logical_and(lv, rv)
                if l.valid is None and r.valid is None:
                    valid = None
                else:
                    # Kleene: known iff both known, or either side is a known FALSE.
                    lk = l.valid if l.valid is not None else jnp.ones(lv.shape, bool)
                    rk = r.valid if r.valid is not None else jnp.ones(rv.shape, bool)
                    valid = (lk & rk) | (lk & ~lv) | (rk & ~rv)
            else:
                value = jnp.logical_or(lv, rv)
                if l.valid is None and r.valid is None:
                    valid = None
                else:
                    # Kleene: known iff both known, or either side is a known TRUE.
                    lk = l.valid if l.valid is not None else jnp.ones(lv.shape, bool)
                    rk = r.valid if r.valid is not None else jnp.ones(rv.shape, bool)
                    valid = (lk & rk) | (lk & lv) | (rk & rv)
            return _Val("num", value, valid=valid)

        # A null literal compares unknown against everything.
        if (l.kind == "lit" and l.value is None) or (r.kind == "lit" and r.value is None):
            n = table.num_rows
            return _Val(
                "num", jnp.zeros((n,), dtype=bool), valid=jnp.zeros((n,), dtype=bool)
            )

        valid = _and_valid(l.valid, r.valid)

        # String comparisons.
        if l.kind == "str" or r.kind == "str":
            if op not in BinaryOp.COMPARISONS:
                raise HyperspaceException(f"Arithmetic on strings is not supported: {op}")
            if l.kind == "str" and r.kind == "lit":
                return _Val(
                    "num", _str_lit_compare(op, l.arr, l.dictionary, str(r.value)), valid=valid
                )
            if r.kind == "str" and l.kind == "lit":
                flipped = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "==": "==", "!=": "!="}
                return _Val(
                    "num",
                    _str_lit_compare(flipped[op], r.arr, r.dictionary, str(l.value)),
                    valid=valid,
                )
            if l.kind == "str" and r.kind == "str":
                # Cross-column compare: align over the union dictionary (host), then
                # integer-compare codes on device.
                lc = Column("string", np.asarray(l.arr, dtype=np.int32), l.dictionary)
                rc = Column("string", np.asarray(r.arr, dtype=np.int32), r.dictionary)
                la, ra = align_dictionaries(lc, rc)
                return _Val(
                    "num",
                    _compare(op, jnp.asarray(la.data), jnp.asarray(ra.data)),
                    valid=valid,
                )
            raise HyperspaceException("Cannot compare string with non-string")

        lv = l.arr if l.kind == "num" else jnp.asarray(l.value)
        rv = r.arr if r.kind == "num" else jnp.asarray(r.value)
        if op in BinaryOp.COMPARISONS:
            return _Val("num", _compare(op, lv, rv), valid=valid)
        if op == "+":
            return _Val("num", lv + rv, valid=valid)
        if op == "-":
            return _Val("num", lv - rv, valid=valid)
        if op == "*":
            return _Val("num", lv * rv, valid=valid)
        if op == "/":
            # SQL: x / 0 is NULL, not inf/nan — zero divisors go invalid.
            zero = rv == 0
            safe = jnp.where(zero, jnp.ones_like(rv), rv)
            value = lv / safe
            nonzero = jnp.broadcast_to(~zero, value.shape)
            valid = nonzero if valid is None else (valid & nonzero)
            return _Val("num", jnp.where(nonzero, value, 0.0), valid=valid)

    raise HyperspaceException(f"Cannot evaluate expression: {expr!r}")


def _evaluate_udf(expr: Udf, table, devcols: Dict[str, jnp.ndarray]) -> _Val:
    """HOST evaluation of a user-defined function (the documented contract of
    `expr.Udf`): argument values are pulled to the host, strings decoded, null
    slots delivered as None; the function runs row-wise in Python; the result
    is packaged back under the DECLARED dtype with None → null."""
    n = table.num_rows
    prepared = []
    for a in expr.args:
        if isinstance(a, Col) and isinstance(table, Table):
            # Column args read straight from host storage — round-tripping
            # them through the device (evaluate's _device upload + the pull
            # below) would cost two full-column transfers for host-only work.
            c = table.column(a.name)
            data = c.dictionary[c.data] if c.is_string else c.data
            prepared.append(("arr", data, c.validity))
            continue
        v = evaluate(a, table, devcols)
        if v.kind == "lit":
            prepared.append(("lit", v.value, None))
            continue
        valid = None if v.valid is None else np.asarray(v.valid, bool)
        if v.kind == "str":
            data = np.asarray(v.dictionary)[np.asarray(v.arr)]
        else:
            data = np.asarray(v.arr)
        if data.ndim == 0:
            # Literal arithmetic yields 0-d results (the same case
            # evaluate_column broadcasts): treat as a per-row constant.
            prepared.append(("lit", data.item(), None))
            continue
        if valid is not None and valid.ndim == 0:
            valid = np.full(data.shape, bool(valid))
        prepared.append(("arr", data, valid))
    out = []
    for i in range(n):
        args = []
        for kind, data, valid in prepared:
            if kind == "lit":
                args.append(data)
            elif valid is not None and not valid[i]:
                args.append(None)
            else:
                x = data[i]
                args.append(x.item() if hasattr(x, "item") else x)
        out.append(expr.fn(*args))
    if expr.dtype == "string":
        if n == 0:
            # from_values can't infer stringness from an empty object array.
            return _Val("str", jnp.empty(0, jnp.int32), np.empty(0, "<U1"))
        col = Column.from_values(np.asarray(out, dtype=object))
        return _Val(
            "str",
            jnp.asarray(col.data),
            col.dictionary,
            valid=None if col.validity is None else jnp.asarray(col.validity),
        )
    npdtype = np.dtype(expr.dtype)
    null_mask = np.fromiter((v is None for v in out), bool, count=n)
    fill = np.zeros((), npdtype).item()
    filled = np.asarray([fill if v is None else v for v in out], dtype=npdtype)
    valid = None if not null_mask.any() else jnp.asarray(~null_mask)
    return _Val("num", jnp.asarray(filled), valid=valid)


def _compare(op: str, a, b):
    if op == "==":
        return a == b
    if op == "!=":
        return a != b
    if op == "<":
        return a < b
    if op == "<=":
        return a <= b
    if op == ">":
        return a > b
    if op == ">=":
        return a >= b
    raise HyperspaceException(op)


def evaluate_column(expr: Expr, table: Table) -> Column:
    """Evaluate an arbitrary expression to a materialized Column (the withColumn
    executor). Invalid slots are re-filled with the canonical zero so downstream
    hashing/grouping over computed columns keeps the nulls-cluster invariant."""
    n = table.num_rows
    out = _compiled_eval(expr, table, "value")
    if out is not None:
        arr = np.asarray(out["arr"])
        valid = None if "valid" not in out else np.asarray(out["valid"], dtype=bool)
        if valid is not None and not valid.all():
            arr = np.where(valid, arr, np.zeros((), dtype=arr.dtype))
        from .schema import dtype_from_numpy

        return Column(dtype_from_numpy(arr.dtype), arr, None, valid)
    v = evaluate(expr, table, {})
    if v.kind == "str":
        codes = np.asarray(v.arr, dtype=np.int32)
        valid = None if v.valid is None else np.asarray(v.valid, dtype=bool)
        if valid is not None:
            codes = np.where(valid, codes, 0).astype(np.int32)
        return Column("string", codes, np.asarray(v.dictionary), valid)
    if v.kind == "lit":
        # (A bare None literal never reaches here: infer_expr_dtype rejects it
        # at plan construction.)
        if isinstance(v.value, str):
            return Column(
                "string", np.zeros(n, np.int32), np.asarray([v.value]), None
            )
        arr = np.full(n, v.value)
        if arr.dtype == np.bool_:
            pass
        elif np.issubdtype(arr.dtype, np.integer):
            arr = arr.astype(np.int64)
        else:
            arr = arr.astype(np.float64)
        return Column.from_values(arr)
    arr = np.asarray(v.arr)
    if arr.ndim == 0:
        # Literal arithmetic (e.g. lit(2) * lit(3)) evaluates to a 0-d array;
        # broadcast it to the table length like the bare-literal branch does.
        arr = np.full(n, arr[()], dtype=arr.dtype)
    valid = None if v.valid is None else np.asarray(v.valid, dtype=bool)
    if valid is not None and np.ndim(valid) == 0:
        valid = np.full(n, bool(valid))
    if valid is not None and not valid.all():
        arr = np.where(valid, arr, np.zeros((), dtype=arr.dtype))
    from .schema import dtype_from_numpy

    return Column(dtype_from_numpy(arr.dtype), arr, None, valid)


def _collect_col_spellings(expr: Expr) -> list:
    """Distinct column spellings as WRITTEN in the expression (evaluate() keys
    devcols by the expression's own spelling, so the compiled path must too)."""
    out = []

    def walk(e):
        if isinstance(e, Col):
            if e.name not in out:
                out.append(e.name)
        elif isinstance(e, BinaryOp):
            walk(e.left)
            walk(e.right)
        elif isinstance(e, (Not, IsNull, IsIn)):
            walk(e.child)
        elif isinstance(e, Udf):
            for a in e.args:
                walk(a)

    walk(expr)
    return sorted(out)


def _contains_udf(expr: Expr) -> bool:
    if isinstance(expr, Udf):
        return True
    return any(_contains_udf(c) for c in expr.children())


class _PredColMeta:
    """The column METADATA evaluate() reads at trace time — everything except
    the arrays themselves (those arrive as traced arguments)."""

    __slots__ = ("is_string", "dictionary", "validity")

    def __init__(self, is_string, dictionary, has_validity):
        self.is_string = is_string
        self.dictionary = dictionary
        self.validity = True if has_validity else None  # presence marker only


class _PredTableFacade:
    def __init__(self, num_rows: int, cols: dict):
        self.num_rows = num_rows
        self._cols = cols

    def column(self, name: str):
        return self._cols[name]


# Compiled predicates, LRU-capped. Key pins EVERYTHING the trace depends on:
# the expression (repr is structural + literal-valued), the row count, and per
# referenced spelling the dtype / stringness / dictionary identity / validity
# presence; dictionary liveness is re-verified by weakref on every hit.
from collections import OrderedDict as _OrderedDict
import threading as _threading

_PRED_CACHE: "_OrderedDict[tuple, tuple]" = _OrderedDict()
_PRED_CACHE_MAX = 256
_PRED_UNCACHEABLE: set = set()  # expr reprs whose trace failed (e.g. str-str compare)
_PRED_UNCACHEABLE_MAX = 1024  # bounded: a workload of one-off exprs must not grow it forever
_pred_lock = _threading.RLock()  # concurrent queries share the compiled-predicate memo


def _evaluate_predicate_eager(expr: Expr, table: Table) -> jnp.ndarray:
    v = evaluate(expr, table, {})
    if v.kind != "num" or v.arr.dtype != jnp.bool_:
        raise HyperspaceException(f"Not a boolean predicate: {expr!r}")
    if v.valid is None:
        return v.arr
    return jnp.logical_and(v.arr, v.valid)


def _build_compiled_fn(expr: Expr, facade: _PredTableFacade, spellings: list, mode: str):
    """mode="pred": boolean mask with unknowns dropped. mode="value": the raw
    numeric result as {"arr": ..., ["valid": ...]} (structure is deterministic
    per cache key, so callers can branch on membership)."""
    import jax

    def fn(*flat):
        devcols = {}
        i = 0
        for sp, has_valid in spellings:
            devcols[sp] = flat[i]
            i += 1
            if has_valid:
                devcols[f"__valid__{sp}"] = flat[i]
                i += 1
        v = evaluate(expr, facade, devcols)
        if mode == "pred":
            if v.kind != "num" or v.arr.dtype != jnp.bool_:
                raise HyperspaceException(f"Not a boolean predicate: {expr!r}")
            if v.valid is None:
                return v.arr
            return jnp.logical_and(v.arr, v.valid)
        if v.kind != "num" or v.arr.ndim == 0:
            # String/literal results (host packaging) stay on the eager path.
            raise HyperspaceException("uncompilable value expression")
        out = {"arr": v.arr}
        if v.valid is not None:
            out["valid"] = v.valid
        return out

    from ..telemetry.compile_log import observed_jit as _observed_jit

    return _observed_jit(fn, label="evaluate.compiled_expr")


#: Adaptive fusion guard for live/interactive workloads. The fused program's
#: compile cache keys on repr(expr) — the LITERAL VALUE included — and on the
#: table's exact row count, so an interactive point-lookup mix (rotating
#: literals, index generations flipping under live refresh) minted one ~15 ms
#: XLA compile per (literal, shape). Eager ops cache per SHAPE only (scalars
#: ride as weak-typed arguments) but cost one dispatch per operator, which a
#: warm streamed-aggregate loop over stable shapes measurably feels (~1 ms per
#: chunk at bench scale). So the policy is adaptive, per literal-abstracted
#: expression STRUCTURE, on CPU-backend tables below the size bound: FUSE by
#: default (stable workloads fuse once and stay fused, zero change), and once
#: one structure has minted `HYPERSPACE_PRED_FUSE_MAX_CLASSES` distinct fused
#: programs (= literals rotating or shapes churning — compiles, not reuse),
#: stop fusing it and evaluate eagerly over pow2-padded inputs instead. On the
#: device path every dispatch is a round-trip, so fusion always wins there.
#: MIN_ROWS=0 = always fuse (the pre-existing behavior, the fallback
#: contract); MAX_CLASSES=0 = never fuse below the size bound.
ENV_PRED_FUSE_MIN_ROWS = "HYPERSPACE_PRED_FUSE_MIN_ROWS"
_DEFAULT_PRED_FUSE_MIN_ROWS = 1 << 16
ENV_PRED_FUSE_MAX_CLASSES = "HYPERSPACE_PRED_FUSE_MAX_CLASSES"
_DEFAULT_PRED_FUSE_MAX_CLASSES = 3

_STRUCT_MINTS: Dict[str, int] = {}  # literal-abstracted structure → fused mints
_STRUCT_MINTS_MAX = 4096


def _env_int(key: str, default: int) -> int:
    import os

    try:
        v = os.environ.get(key, "")
        return int(v) if v != "" else default
    except ValueError:
        return default


def _pred_fuse_min_rows() -> int:
    return _env_int(ENV_PRED_FUSE_MIN_ROWS, _DEFAULT_PRED_FUSE_MIN_ROWS)


def _expr_structure(expr: Expr, mode: str) -> str:
    """Literal-abstracted identity of an expression (values → type names) —
    the same canonicalization plan fingerprints use, so `k == 7` and
    `k == 42` are ONE structure."""
    import json as _json

    from ..plananalysis.fingerprint import expr_signature

    return mode + ":" + _json.dumps(expr_signature(expr))


def _compiled_eval(expr: Expr, table: Table, mode: str):
    """Run `expr` over `table` as ONE compiled program per (mode, expression,
    table signature); None when this expression shape must stay eager (e.g.
    host access during trace: cross-column string compares, string/literal
    value results, or a small CPU-backend structure whose fused programs have
    stopped being reused — rotating literals / churning generations; see
    ENV_PRED_FUSE_MAX_CLASSES)."""
    import weakref

    if _contains_udf(expr):
        return None  # UDFs are host-evaluated by contract: never traced
    from ..ops.backend import use_device_path

    small_cpu = not use_device_path() and table.num_rows < _pred_fuse_min_rows()
    r = (mode, repr(expr))
    with _pred_lock:
        if r in _PRED_UNCACHEABLE:
            return None
    try:
        spellings = _collect_col_spellings(expr)
        sig = []
        metas = {}
        dict_refs = []
        for sp in spellings:
            col = table.column(sp)
            has_valid = col.validity is not None
            is_str = col.is_string
            sig.append(
                (
                    sp,
                    str(np.asarray(col.data).dtype),
                    is_str,
                    id(col.dictionary) if is_str else None,
                    has_valid,
                )
            )
            metas[sp] = _PredColMeta(is_str, col.dictionary, has_valid)
            if is_str:
                dict_refs.append((sp, weakref.ref(col.dictionary)))
        key = (r, table.num_rows, tuple(sig))
    except Exception:
        return None

    with _pred_lock:
        ent = _PRED_CACHE.get(key)
        if ent is not None:
            fn, refs, sp_flags = ent
            if all(wr() is table.column(sp).dictionary for sp, wr in refs):
                _PRED_CACHE.move_to_end(key)
            else:
                _PRED_CACHE.pop(key, None)
                ent = None
        if ent is None:
            if small_cpu:
                # Minting yet another fused program for this structure means
                # its literals/shapes are churning, not being reused: go
                # eager (pow2-padded for predicates) from here on.
                struct = _expr_structure(expr, mode)
                mints = _STRUCT_MINTS.get(struct, 0)
                if mints >= _env_int(
                    ENV_PRED_FUSE_MAX_CLASSES, _DEFAULT_PRED_FUSE_MAX_CLASSES
                ):
                    return None
                if len(_STRUCT_MINTS) >= _STRUCT_MINTS_MAX:
                    _STRUCT_MINTS.clear()  # bounded; counts are a heuristic
                _STRUCT_MINTS[struct] = mints + 1
            facade = _PredTableFacade(table.num_rows, metas)
            sp_flags = [(sp, metas[sp].validity is not None) for sp in spellings]
            fn = _build_compiled_fn(expr, facade, sp_flags, mode)
            _PRED_CACHE[key] = (fn, dict_refs, sp_flags)
            while len(_PRED_CACHE) > _PRED_CACHE_MAX:
                _PRED_CACHE.popitem(last=False)
        else:
            fn, _, sp_flags = ent

    from .device_cache import device_array

    flat = []
    for sp, has_valid in sp_flags:
        col = table.column(sp)
        flat.append(device_array(col.data))
        if has_valid:
            flat.append(device_array(col.validity))
    try:
        return fn(*flat)
    except Exception as e:
        # Fall back to the eager path for THIS call; permanently blacklist the
        # (mode, expression) shape only for trace-time failures (host access
        # during trace: TracerError/concretization). A transient device/relay
        # error must not disable compilation for the shape forever.
        import jax

        trace_time = isinstance(
            e,
            (
                jax.errors.TracerArrayConversionError,
                jax.errors.ConcretizationTypeError,
                jax.errors.TracerBoolConversionError,
                HyperspaceException,
                TypeError,
            ),
        )
        with _pred_lock:
            _PRED_CACHE.pop(key, None)
            if trace_time:
                if len(_PRED_UNCACHEABLE) >= _PRED_UNCACHEABLE_MAX:
                    # Bounded: evict an arbitrary old entry rather than refuse
                    # the new one (a refused shape would re-trace and re-fail
                    # on every call — the exact cost the blacklist avoids).
                    _PRED_UNCACHEABLE.pop()
                _PRED_UNCACHEABLE.add(r)
        return None


def _pow2_padded_eager_mask(expr: Expr, table: Table):
    """CPU-backend eager predicate over POW2-PADDED column copies, sliced back
    to the true row count on the host. Eager ops compile per input SHAPE, so a
    live table whose row counts drift (every refresh/compaction generation,
    every hybrid-append merge) minted one ~20 ms XLA compile per new shape on
    the interactive path; padding onto the pow2 grid pins each (expression,
    dtype) pair to at most log2(N) compile classes — the PR-10 mesh compile
    contract applied to predicate evaluation. Padded slots carry zeros (and
    validity False where a mask exists); their mask bits are sliced off before
    anyone sees them. None = not applicable (already pow2, UDF, or a column
    that failed to resolve — the caller falls through to the plain path)."""
    n = table.num_rows
    if n == 0 or _contains_udf(expr):
        return None
    m = 1 << (n - 1).bit_length()
    if m == n:
        return None
    try:
        spellings = _collect_col_spellings(expr)
        cols = {}
        pad_payload = pad_padded = 0
        for sp in spellings:
            c = table.column(sp)
            data = np.asarray(c.data)
            pad_payload += n * int(data.dtype.itemsize)
            pad_padded += (m - n) * int(data.dtype.itemsize)
            data = np.concatenate([data, np.zeros(m - n, dtype=data.dtype)])
            valid = None
            if c.validity is not None:
                valid = np.concatenate([c.validity, np.zeros(m - n, dtype=bool)])
                pad_payload += n
                pad_padded += m - n
            cols[sp] = Column(c.dtype, data, c.dictionary, valid)
            if getattr(c, "_encoded_read", False):
                # Padded copies keep the encoded-read provenance so the
                # eager fallback's device staging still rides narrow codes.
                cols[sp]._encoded_read = True
        from ..telemetry import device_observatory as _devobs

        _devobs.record_pad("eval_mask", pad_payload, pad_padded)
    except Exception:
        return None
    mask = _evaluate_predicate_eager(expr, Table(cols))
    return np.asarray(mask)[:n]


def evaluate_predicate(expr: Expr, table: Table) -> jnp.ndarray:
    """Evaluate a boolean expression over a table → device mask. A row survives
    only when the predicate is TRUE and KNOWN (SQL WHERE drops unknowns).

    Device path: ONE compiled program per (expression, table signature) —
    eager evaluation issues one dispatch per operator, and on a remote PJRT
    transport each dispatch is a round-trip. CPU path below the fusion
    threshold: eager over pow2-padded inputs (shape-stable compile classes,
    literal values never in the compile key)."""
    out = _compiled_eval(expr, table, "pred")
    if out is not None:
        return out
    from ..ops.backend import use_device_path

    if not use_device_path() and table.num_rows < _pred_fuse_min_rows():
        # Size-gated like the fusion guard itself: a LARGE unfusable shape
        # (e.g. a cross-column string compare) must not pay a padded copy of
        # every referenced column per query.
        padded = _pow2_padded_eager_mask(expr, table)
        if padded is not None:
            return padded
    return _evaluate_predicate_eager(expr, table)
