"""Expression evaluation on device arrays.

Filters and join-key computations run as jnp vector ops (VPU work under XLA). String
semantics ride the sorted-dictionary encoding: literal comparisons are translated to
code-space integer comparisons on the host (one dictionary binary-search per literal),
then evaluated on device — no string processing ever reaches the TPU.
"""

from __future__ import annotations

from typing import Dict

import jax.numpy as jnp
import numpy as np

from ..exceptions import HyperspaceException
from .expr import BinaryOp, Col, Expr, IsIn, Lit, Not
from .table import Column, Table, align_dictionaries


class _Val:
    """Evaluation result: numeric device array, string codes + dictionary, or literal."""

    __slots__ = ("kind", "arr", "dictionary", "value")

    def __init__(self, kind, arr=None, dictionary=None, value=None):
        self.kind = kind  # "num" | "str" | "lit"
        self.arr = arr
        self.dictionary = dictionary
        self.value = value


def _device(table: Table, devcols: Dict[str, jnp.ndarray], name: str):
    if name not in devcols:
        devcols[name] = jnp.asarray(table.column(name).data)
    return devcols[name]


def _str_lit_compare(op: str, codes, dictionary: np.ndarray, lit: str):
    """Translate a string-vs-literal comparison into code space (sorted dictionary ⇒
    codes are order-preserving)."""
    left_cut = int(np.searchsorted(dictionary, lit, side="left"))
    present = left_cut < len(dictionary) and dictionary[left_cut] == lit
    if op == "==":
        if not present:
            return jnp.zeros(codes.shape, dtype=bool)
        return codes == left_cut
    if op == "!=":
        if not present:
            return jnp.ones(codes.shape, dtype=bool)
        return codes != left_cut
    if op == "<":
        return codes < left_cut
    if op == ">=":
        return codes >= left_cut
    right_cut = int(np.searchsorted(dictionary, lit, side="right"))
    if op == "<=":
        return codes < right_cut
    if op == ">":
        return codes >= right_cut
    raise HyperspaceException(f"Unsupported string comparison: {op}")


def evaluate(expr: Expr, table: Table, devcols: Dict[str, jnp.ndarray]) -> _Val:
    if isinstance(expr, Col):
        col = table.column(expr.name)
        arr = _device(table, devcols, expr.name)
        if col.is_string:
            return _Val("str", arr, col.dictionary)
        return _Val("num", arr)

    if isinstance(expr, Lit):
        return _Val("lit", value=expr.value)

    if isinstance(expr, Not):
        v = evaluate(expr.child, table, devcols)
        if v.kind != "num":
            raise HyperspaceException("NOT requires a boolean operand")
        return _Val("num", jnp.logical_not(v.arr))

    if isinstance(expr, IsIn):
        v = evaluate(expr.child, table, devcols)
        if v.kind == "str":
            wanted = [str(x) for x in expr.values]
            positions = np.searchsorted(v.dictionary, wanted)
            valid = [
                int(c)
                for c, x in zip(positions, wanted)
                if c < len(v.dictionary) and v.dictionary[c] == x
            ]
            if not valid:
                return _Val("num", jnp.zeros(v.arr.shape, dtype=bool))
            return _Val("num", jnp.isin(v.arr, jnp.asarray(np.asarray(valid, np.int32))))
        return _Val("num", jnp.isin(v.arr, jnp.asarray(np.asarray(expr.values))))

    if isinstance(expr, BinaryOp):
        l = evaluate(expr.left, table, devcols)
        r = evaluate(expr.right, table, devcols)
        op = expr.op

        if op in BinaryOp.BOOLEAN:
            if l.kind != "num" or r.kind != "num":
                raise HyperspaceException(f"'{op}' requires boolean operands")
            f = jnp.logical_and if op == "and" else jnp.logical_or
            return _Val("num", f(l.arr, r.arr))

        # String comparisons.
        if l.kind == "str" or r.kind == "str":
            if op not in BinaryOp.COMPARISONS:
                raise HyperspaceException(f"Arithmetic on strings is not supported: {op}")
            if l.kind == "str" and r.kind == "lit":
                return _Val("num", _str_lit_compare(op, l.arr, l.dictionary, str(r.value)))
            if r.kind == "str" and l.kind == "lit":
                flipped = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "==": "==", "!=": "!="}
                return _Val(
                    "num", _str_lit_compare(flipped[op], r.arr, r.dictionary, str(l.value))
                )
            if l.kind == "str" and r.kind == "str":
                # Cross-column compare: align over the union dictionary (host), then
                # integer-compare codes on device.
                lc = Column("string", np.asarray(l.arr, dtype=np.int32), l.dictionary)
                rc = Column("string", np.asarray(r.arr, dtype=np.int32), r.dictionary)
                la, ra = align_dictionaries(lc, rc)
                return _Val(
                    "num",
                    _compare(op, jnp.asarray(la.data), jnp.asarray(ra.data)),
                )
            raise HyperspaceException("Cannot compare string with non-string")

        lv = l.arr if l.kind == "num" else jnp.asarray(l.value)
        rv = r.arr if r.kind == "num" else jnp.asarray(r.value)
        if op in BinaryOp.COMPARISONS:
            return _Val("num", _compare(op, lv, rv))
        if op == "+":
            return _Val("num", lv + rv)
        if op == "-":
            return _Val("num", lv - rv)
        if op == "*":
            return _Val("num", lv * rv)
        if op == "/":
            return _Val("num", lv / rv)

    raise HyperspaceException(f"Cannot evaluate expression: {expr!r}")


def _compare(op: str, a, b):
    if op == "==":
        return a == b
    if op == "!=":
        return a != b
    if op == "<":
        return a < b
    if op == "<=":
        return a <= b
    if op == ">":
        return a > b
    if op == ">=":
        return a >= b
    raise HyperspaceException(op)


def evaluate_predicate(expr: Expr, table: Table) -> jnp.ndarray:
    """Evaluate a boolean expression over a table → device mask."""
    v = evaluate(expr, table, {})
    if v.kind != "num" or v.arr.dtype != jnp.bool_:
        raise HyperspaceException(f"Not a boolean predicate: {expr!r}")
    return v.arr
