"""File-scan cache: decoded columns keyed by (path, size, mtime, column).

The reference's query path leans on the OS page cache and Spark's in-memory columnar
caching for repeated scans; here the expensive part is parquet decode + dictionary
encoding, so caching the decoded columns per file is the equivalent lever. Safety
comes from the key: it includes the file's size and mtime, so any rewrite of the file
invalidates its entries (same freshness contract the file-based signature relies on).

Storage granularity is PER COLUMN (parquet is columnar: each column group decodes
independently), while the get/put API and hit/miss accounting stay table-level.
That makes warm decodes projection-independent: a query that read (a, b) and a
later index build that wants (a, b, c) share the a/b decode — the build (or any
scan) asks `missing_columns` and decodes ONLY c. Before this, every distinct
column tuple re-decoded the whole set from scratch.

Bounded by approximate bytes with LRU eviction; per-process singleton.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import List, Optional, Tuple

from ..telemetry import accounting as _accounting
from ..telemetry import metrics as _metrics
from .encoding import column_nbytes as _column_nbytes
from .table import Table

# 1 GiB of decoded columns. The per-file level is the DECODE backstop: repeat
# reads of multi-file sources hit the concat/bucketed caches above it, so this
# level earns its keep only for per-file re-reads those levels cannot cache —
# hybrid-append scans (query-time bucketization makes the higher level
# uncacheable) and re-assembly after a higher-level eviction. A 4 GiB default
# measured 0 hits at full budget in round 4 (every hit landed above it); 1 GiB
# bounds the double-caching cost while keeping the backstop.
DEFAULT_CAPACITY_BYTES = int(
    os.environ.get("HYPERSPACE_SCAN_CACHE_BUDGET", 1 << 30)
)


def _bind_cache_metrics(
    cache, name: Optional[str], encoded_hits: bool = False
) -> None:
    """Bind a cache instance's registry mirrors once (warm-path cost = one
    locked int add). Only the NAMED process-wide singletons report to the
    registry; an ad-hoc unnamed instance (tests construct ScanCache directly)
    gets private unregistered metric objects, so it can never double-count
    into — or clobber the byte gauge of — the global caches' series.
    `encoded_hits` registers cache.<name>.encoded_hits — only ScanCache ticks
    it, so other cache kinds must not emit a permanently-zero series."""
    if name is None:
        cache._m_hits = _metrics.Counter("unregistered")
        cache._m_misses = _metrics.Counter("unregistered")
        cache._m_evictions = _metrics.Counter("unregistered")
        cache._m_bytes = _metrics.Gauge("unregistered")
        cache._m_enc_hits = _metrics.Counter("unregistered")
        return
    cache._m_hits = _metrics.counter(f"cache.{name}.hits")
    cache._m_misses = _metrics.counter(f"cache.{name}.misses")
    cache._m_evictions = _metrics.counter(f"cache.{name}.evictions")
    cache._m_bytes = _metrics.gauge(f"cache.{name}.bytes")
    # Hits whose served columns include at least one ENCODED-read entry
    # (codes + dictionary that never flattened — engine/encoding.py): the
    # measure of how much of the warm working set stays in code space.
    cache._m_enc_hits = (
        _metrics.counter(f"cache.{name}.encoded_hits")
        if encoded_hits
        else _metrics.Counter("unregistered")
    )


def _table_nbytes(t: Table) -> int:
    return sum(_column_nbytes(c) for c in t.columns.values())


class ScanCache:
    """Per-column store behind a table-level get/put API.

    Entry kinds under one (path, size, mtime) freshness base:
      - ("col", name)       → one decoded Column (an `encoded` marker records
                              whether it arrived via the encoded read path —
                              codes + dictionary, never flattened — plus its
                              byte size: the TRUE encoded bytes
                              `_column_nbytes` charges, codes + dictionary +
                              validity, never a hypothetical decoded size)
      - ("col", name, sel)  → the column decoded from the row-group subset
                              `sel` (a tuple of row-group indices — the scan
                              pushdown's pruned decodes; a partial decode must
                              never alias the whole-file entry)
      - ("names",)          → the file's full column-name order (for
                              columns=None requests, which must reproduce the
                              decode order)
      - ("meta",)           → the file's parquet FOOTER METADATA (row-group
                              boundaries + per-column min/max/null-count zone
                              maps), cached under the same byte budget so
                              pruning decisions never re-open footers

    Hit/miss counting is per table-level request (a get that assembles from
    columns counts ONE hit), so cache-pressure accounting stays comparable to
    the pre-column-granular cache."""

    def __init__(
        self,
        capacity_bytes: int = DEFAULT_CAPACITY_BYTES,
        name: Optional[str] = None,
    ):
        self._capacity = capacity_bytes
        self._lock = threading.Lock()
        # Entry arity differs by kind — col: (column, encoded, nbytes);
        # names/meta: (value, nbytes). The byte charge is ALWAYS ent[-1]
        # (what eviction reads); ent[1] is only meaningful under a col key.
        self._entries: "OrderedDict[tuple, tuple]" = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.encoded_hits = 0
        _bind_cache_metrics(self, name, encoded_hits=True)

    def stats(self) -> dict:
        return {
            "hits": self.hits,
            "misses": self.misses,
            "evictions": self.evictions,
            "encoded_hits": self.encoded_hits,
            "bytes": self._bytes,
            "budget": self._capacity,
        }

    def _evict_to_capacity_locked(self) -> None:
        """LRU-evict until under budget; caller holds the lock. Size is the
        LAST element of each entry tuple (shared with BucketedConcatCache).
        Evicted bytes are charged to the ambient query's ledger — the query
        whose puts displaced them (the cache-pressure half of the
        accounting; `cache_bytes_charged` is ticked at the put sites)."""
        evicted = 0
        while self._bytes > self._capacity and self._entries:
            _, ent = self._entries.popitem(last=False)
            self._bytes -= ent[-1]
            evicted += ent[-1]
            self.evictions += 1
            self._m_evictions.inc()
        if evicted:
            _accounting.add("cache_bytes_evicted", evicted)
        self._m_bytes.set(self._bytes)

    def set_capacity(self, capacity_bytes: int) -> None:
        with self._lock:
            self._capacity = int(capacity_bytes)
            self._evict_to_capacity_locked()

    def _base(self, path: str):
        try:
            st = os.stat(path)
            return (path, st.st_size, int(st.st_mtime * 1000))
        except OSError:
            return None

    def _names_for_locked(self, base, columns: Optional[List[str]]):
        """The column names a request resolves to (requested order, or the
        recorded whole-file order for columns=None); None when unknown."""
        if columns is not None:
            return list(columns)
        ent = self._entries.get(base + (("names",),))
        if ent is None:
            return None
        self._entries.move_to_end(base + (("names",),))
        return list(ent[0])

    @staticmethod
    def _col_key(n: str, sel) -> tuple:
        """Entry kind of one column: whole-file, or a row-group selection
        (`sel` = sorted tuple of row-group indices). Distinct kinds by
        construction — a pruned decode can never serve a whole-file read."""
        return ("col", n) if sel is None else ("col", n, tuple(sel))

    def get(
        self,
        path: str,
        columns: Optional[List[str]],
        record: bool = True,
        sel=None,
    ) -> Optional[Table]:
        """Assemble the requested table from cached columns. `record=False`
        skips hit/miss accounting (internal re-reads after a partial decode —
        one user-level request must count exactly once). `sel` selects the
        row-group-subset entries instead of the whole-file ones."""
        base = self._base(path)
        if base is None:
            return None
        with self._lock:
            names = self._names_for_locked(base, columns)
            cols = {}
            any_encoded = False
            if names is not None:
                for n in names:
                    ent = self._entries.get(base + (self._col_key(n, sel),))
                    if ent is None:
                        cols = None
                        break
                    cols[n] = ent[0]
                    # Col entries are uniformly (column, encoded, nbytes) —
                    # the only entry kind fetched under a _col_key.
                    any_encoded = any_encoded or ent[1]
            else:
                cols = None
            if cols is None:
                if record:
                    self.misses += 1
                    self._m_misses.inc()
                return None
            for n in names:
                self._entries.move_to_end(base + (self._col_key(n, sel),))
            if record:
                self.hits += 1
                self._m_hits.inc()
                if any_encoded:
                    self.encoded_hits += 1
                    self._m_enc_hits.inc()
            return Table(cols)

    def missing_columns(
        self, path: str, columns: Optional[List[str]], sel=None
    ) -> Optional[List[str]]:
        """The subset of `columns` NOT currently cached for this file — the
        decode-only-what's-cold contract of the pipelined build (and any
        projection-changing scan). None = can't tell (unknown name set for
        columns=None, or the file is unstattable): decode everything."""
        base = self._base(path)
        if base is None:
            return None
        with self._lock:
            names = self._names_for_locked(base, columns)
            if names is None:
                return None
            return [
                n
                for n in names
                if base + (self._col_key(n, sel),) not in self._entries
            ]

    def put(
        self, path: str, columns: Optional[List[str]], table: Table, sel=None
    ) -> None:
        base = self._base(path)
        if base is None:
            return
        with self._lock:
            if columns is None and sel is None:
                key = base + (("names",),)
                if key not in self._entries:
                    self._entries[key] = (list(table.column_names), 0)
            charged = 0
            for n, c in table.columns.items():
                key = base + (self._col_key(n, sel),)
                if key in self._entries:
                    continue
                # The charged size is the ENCODED truth — codes + dictionary
                # + validity (`_column_nbytes`) — never the flattened N-value
                # size the decoded representation would occupy.
                size = _column_nbytes(c)
                if size > self._capacity:
                    continue
                self._entries[key] = (c, getattr(c, "_encoded_read", False), size)
                self._bytes += size
                charged += size
            if charged:
                _accounting.add("cache_bytes_charged", charged)
            self._evict_to_capacity_locked()

    # -- footer metadata (parquet zone maps) --------------------------------
    # Metadata rides the SAME freshness base and LRU/byte budget as the
    # decoded columns (the scan-cache budget bounds it); its own hit/miss
    # accounting lives with the io-layer counters (`io.footer.*`), never the
    # table-level hits/misses above.

    def get_meta(self, path: str):
        base = self._base(path)
        if base is None:
            return None
        with self._lock:
            ent = self._entries.get(base + (("meta",),))
            if ent is None:
                return None
            self._entries.move_to_end(base + (("meta",),))
            return ent[0]

    def put_meta(self, path: str, meta, nbytes: int) -> None:
        base = self._base(path)
        if base is None:
            return
        with self._lock:
            key = base + (("meta",),)
            if key in self._entries or nbytes > self._capacity:
                return
            self._entries[key] = (meta, int(nbytes))
            self._bytes += int(nbytes)
            _accounting.add("cache_bytes_charged", int(nbytes))
            self._evict_to_capacity_locked()

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0
            self._m_bytes.set(0)


_GLOBAL = ScanCache(name="scan")


def global_scan_cache() -> ScanCache:
    return _GLOBAL


class BucketedConcatCache:
    """Concatenated bucketed-index scan results (table + bucket start offsets),
    keyed by the scan's file inventory (path/size/mtime per file) + pruned columns.

    A bucketed index join re-assembles up to `num_buckets` per-bucket tables into
    one contiguous table every query; with the per-file cache alone that concat
    (plus dictionary re-unioning for strings) still runs per query. Steady-state
    indexed queries hit here instead. Freshness rides on the same contract as the
    scan cache: any rewrite of an index file changes its size/mtime and the key."""

    def __init__(self, capacity_bytes: int = 1 << 30, name: Optional[str] = None):
        self._capacity = capacity_bytes
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, Tuple[Table, object, int]]" = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        _bind_cache_metrics(self, name)

    def stats(self) -> dict:
        return {
            "hits": self.hits,
            "misses": self.misses,
            "evictions": self.evictions,
            "bytes": self._bytes,
            "budget": self._capacity,
        }

    _evict_to_capacity_locked = ScanCache._evict_to_capacity_locked

    def set_capacity(self, capacity_bytes: int) -> None:
        with self._lock:
            self._capacity = int(capacity_bytes)
            self._evict_to_capacity_locked()

    def get(self, key) -> Optional[Tuple[Table, object]]:
        with self._lock:
            hit = self._entries.get(key)
            if hit is None:
                self.misses += 1
                self._m_misses.inc()
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            self._m_hits.inc()
            return hit[0], hit[1]

    def contains(self, key) -> bool:
        """Accounting-free peek (no hit/miss counting, no LRU touch) — lets a
        caller choose BETWEEN strategies (e.g. in-memory filtering of a warm
        full concat vs a pruned disk re-assembly) without the probe itself
        distorting the stats the choice is judged by."""
        with self._lock:
            return key in self._entries

    def put(self, key, table: Table, starts) -> None:
        size = _table_nbytes(table)
        if size > self._capacity:
            return
        with self._lock:
            if key in self._entries:
                return
            self._entries[key] = (table, starts, size)
            self._bytes += size
            _accounting.add("cache_bytes_charged", size)
            self._evict_to_capacity_locked()

    def clear(self) -> None:
        """Drop every entry (bench cold-path measurement; stats counters keep
        accumulating so lifetime accounting stays monotonic)."""
        with self._lock:
            self._entries.clear()
            self._bytes = 0
            self._m_bytes.set(0)


_BUCKETED = BucketedConcatCache(name="bucketed_concat")


def global_bucketed_cache() -> BucketedConcatCache:
    return _BUCKETED


# Plain multi-file concat results get their OWN budget so ordinary scans can
# never evict the steady-state bucketed-join entries above.
_CONCAT = BucketedConcatCache(name="concat")


def global_concat_cache() -> BucketedConcatCache:
    return _CONCAT


# Filtered bucketed-concat derivatives get their OWN budget so parameterized
# filter churn (a different literal each query) can never evict the base
# bucketed-join entries above — same isolation rationale as _CONCAT.
_FILTERED = BucketedConcatCache(name="filtered")


def global_filtered_cache() -> BucketedConcatCache:
    return _FILTERED
