"""Session + DataFrame API.

The engine analogue of SparkSession + DataFrame, sized to what the reference's
workflows need: read parquet/csv/json into a lazily-planned DataFrame, filter/select/
join, collect on the TPU execution path. The session carries the conf, the filesystem,
and the optimizer extension point (`extra_optimizations`) that `enable_hyperspace`
plugs the rewrite rules into (the analogue of
`experimentalMethods.extraOptimizations`, reference `package.scala:46-51`).
"""

from __future__ import annotations

import os
from typing import Callable, Dict, List, Optional, Sequence, Union

from ..config import HyperspaceConf, SessionConf
from ..exceptions import HyperspaceException
from ..storage.filesystem import FileSystem, LocalFileSystem
from . import io as engine_io
from .expr import Expr
from .logical import (
    AggregateNode,
    FilterNode,
    JoinNode,
    LimitNode,
    LogicalPlan,
    OrderByNode,
    ProjectNode,
    ScanNode,
    SourceRelation,
    WithColumnNode,
)
from .physical import ExecContext, PhysicalNode, plan_physical
from .schema import Schema
from .table import Table


class DataFrame:
    def __init__(self, session: "HyperspaceSession", plan: LogicalPlan):
        self.session = session
        self.plan = plan

    # -- transformations ----------------------------------------------------

    def filter(self, condition: Expr) -> "DataFrame":
        return DataFrame(self.session, FilterNode(condition, self.plan))

    where = filter

    def select(self, *columns: str) -> "DataFrame":
        names = list(columns[0]) if len(columns) == 1 and isinstance(columns[0], (list, tuple)) else list(columns)
        missing = [n for n in names if n not in self.plan.output_schema]
        if missing:
            raise HyperspaceException(f"Column(s) not found: {missing}")
        return DataFrame(self.session, ProjectNode(names, self.plan))

    def join(self, other: "DataFrame", on: Expr, how: str = "inner") -> "DataFrame":
        return DataFrame(self.session, JoinNode(self.plan, other.plan, on, how))

    def with_column(self, name: str, expr: Expr) -> "DataFrame":
        """Computed column (Spark `withColumn`): replaces a same-named column in
        place, else appends. `df.with_column("revenue", col("price") * (1 - col("discount")))`."""
        return DataFrame(self.session, WithColumnNode(name, expr, self.plan))

    withColumn = with_column

    def group_by(self, *keys: str) -> "GroupedDataFrame":
        names = list(keys[0]) if len(keys) == 1 and isinstance(keys[0], (list, tuple)) else list(keys)
        for n in names:
            self.plan.output_schema.field(n)  # resolve-or-raise
        return GroupedDataFrame(self, names)

    groupBy = group_by

    def agg(self, **aggs) -> "DataFrame":
        """Global aggregation (no grouping): `df.agg(total=("qty", "sum"))`."""
        return GroupedDataFrame(self, []).agg(**aggs)

    def order_by(self, *keys, ascending: bool = True) -> "DataFrame":
        """ORDER BY. Keys are column names or (name, ascending) pairs; the
        `ascending` kwarg is the default for bare names."""
        parsed = []
        for k in keys:
            if isinstance(k, tuple):
                parsed.append((k[0], bool(k[1])))
            else:
                parsed.append((k, ascending))
        return DataFrame(self.session, OrderByNode(parsed, self.plan))

    orderBy = order_by
    sort = order_by

    def limit(self, n: int) -> "DataFrame":
        return DataFrame(self.session, LimitNode(n, self.plan))

    def union(self, other: "DataFrame") -> "DataFrame":
        """Row union (UNION ALL) of same-schema frames — the same UnionNode the
        Hybrid Scan merge uses; use `.distinct()` after for set-union."""
        from .logical import UnionNode

        return DataFrame(self.session, UnionNode([self.plan, other.plan]))

    unionAll = union

    def intersect(self, other: "DataFrame") -> "DataFrame":
        """Distinct rows present in BOTH frames (SQL INTERSECT; nulls compare
        equal to each other, like Spark's)."""
        from .logical import IntersectNode

        return DataFrame(self.session, IntersectNode(self.plan, other.plan))

    def subtract(self, other: "DataFrame") -> "DataFrame":
        """Distinct rows of this frame absent from `other` (SQL EXCEPT;
        Spark's `except`/`subtract`)."""
        from .logical import ExceptNode

        return DataFrame(self.session, ExceptNode(self.plan, other.plan))

    def scalar(self):
        """The single value of a 1x1 result — the scalar-subquery pattern
        (`WHERE x > (SELECT max(...) FROM ...)`) as eager composition:

            df.filter(col("x") > other.group_by().agg(m=("x", "max")).scalar())

        Raises unless the result is exactly one row x one column."""
        t = self.collect()
        if t.num_rows != 1 or len(t.column_names) != 1:
            raise HyperspaceException(
                f"scalar() requires a 1x1 result, got "
                f"{t.num_rows}x{len(t.column_names)}"
            )
        return t.rows()[0][0]

    def drop(self, *columns: str) -> "DataFrame":
        """Project away the named columns (missing names are ignored, like
        Spark's drop). Name matching honors `hyperspace.resolution.caseSensitive`
        like the planner does."""
        from ..util.resolver_utils import resolution_key

        cs = self.session.hs_conf.case_sensitive
        gone = {resolution_key(c, cs) for c in columns}
        keep = [
            n
            for n in self.plan.output_schema.names
            if resolution_key(n, cs) not in gone
        ]
        if not keep:
            raise HyperspaceException("drop() would remove every column")
        return self.select(keep)

    def distinct(self) -> "DataFrame":
        """Row dedup = GROUP BY every column with no aggregates (rides the same
        device hash-sort/segment kernel as aggregation)."""
        return DataFrame(
            self.session, AggregateNode(self.plan.output_schema.names, [], self.plan)
        )

    # -- actions ------------------------------------------------------------

    @property
    def schema(self):
        return self.plan.output_schema

    def optimized_plan(self) -> LogicalPlan:
        return self.session.optimize(self.plan)

    def physical_plan(self) -> PhysicalNode:
        return plan_physical(
            self.optimized_plan(),
            case_sensitive=self.session.hs_conf.case_sensitive,
        )

    def _run_with_quarantine_fallback(self, runner):
        """Plan + execute with the corruption-quarantine fallback: a
        `CorruptIndexError` (a truncated/corrupt index bucket file surfaced by
        the scan layer) QUARANTINES the named index, warns, and re-plans — the
        rules now skip the quarantined index (`rules.rule_utils`), so the
        retry executes against the source data and the result stays correct.
        Bounded by construction: every round quarantines a NEW index
        (`quarantine.mark` returns False on a repeat, which propagates)."""
        import time as _time
        import warnings

        from ..exceptions import CorruptIndexError
        from ..index import quarantine
        from ..plananalysis import attribution as _attribution
        from ..plananalysis import planner as _planner
        from ..telemetry import tracing

        while True:
            with tracing.span("plan"):
                phys = self.physical_plan()
            fp = self._attach_fingerprint(phys)
            decisions = _planner.decide(phys, fp)
            try:
                with _planner.decisions_scope(decisions):
                    pr0 = _planner.prune_counters()
                    t0 = _time.monotonic()
                    out = runner(phys)
                # Feed the measured wall back (outcome store; no-op without a
                # persistent home) — only on success: a quarantine retry's
                # partial wall would poison the arm stats. The row-group
                # pruning counter delta rides along so the class's pushdown
                # selectivity prior is learned, not guessed; so does the
                # per-stage wall snapshot (still-open query scope), which
                # lets the store learn at stage grain.
                _planner.observe(
                    decisions,
                    _time.monotonic() - t0,
                    pruning=_planner.prune_counters(pr0) if pr0 is not None else None,
                    stages=_attribution.query_stage_walls(),
                )
                return out
            except CorruptIndexError as e:
                if not quarantine.mark(e.index_name, reason=str(e), path=e.path):
                    raise
                warnings.warn(
                    f"hyperspace: index '{e.index_name}' quarantined after a "
                    f"corrupt data file; the query falls back to the source "
                    f"scan and stays correct ({e}). Refresh or rebuild the "
                    "index to lift the quarantine.",
                    RuntimeWarning,
                    stacklevel=3,
                )

    def _attach_fingerprint(self, phys: PhysicalNode):
        """Stamp the optimized plan's execution-class fingerprint
        (`plananalysis.fingerprint`) onto the ambient root span and ledger —
        the key the workload history store lands this query under — and
        return it (the adaptive planner's outcome store keys on the same
        class; only an OBSERVING planner — one with a persistent outcome
        home — counts as a consumer). Computed only when a consumer exists
        (history enabled / ledger open / span recording / planner learning);
        with everything off this is one env read + one contextvar read, the
        zero-cost-off contract."""
        from ..plananalysis import fingerprint as _fp
        from ..plananalysis import planner as _planner
        from ..telemetry import accounting, tracing

        try:
            if not _fp.fingerprint_wanted():
                if not (_planner.planner_enabled() and _planner.outcome_dir()):
                    return None
            fp = _fp.plan_fingerprint(phys)
        except Exception:
            return None  # fingerprinting must never fail the query
        accounting.set_value("plan_fingerprint", fp)
        sp = tracing.current_span()
        if sp is not None:
            sp.set_attr("plan_fingerprint", fp)
        return fp

    def collect(self) -> Table:
        from .. import resilience
        from ..telemetry import accounting, tracing

        with resilience.query_scope("query:collect"):
            with tracing.query_span("query:collect") as root:
                out = self._run_with_quarantine_fallback(
                    lambda phys: phys.execute(ExecContext(self.session))
                )
                root.set_attr("rows_out", int(out.num_rows))
                accounting.set_value("rows_produced", int(out.num_rows))
                return out

    def count(self) -> int:
        # Counts never assemble output they don't need: scans answer from parquet
        # footers, joins from verified pair counts (`PhysicalNode.execute_count`).
        from .. import resilience
        from ..telemetry import accounting, tracing

        with resilience.query_scope("query:count"):
            with tracing.query_span("query:count") as root:
                n = self._run_with_quarantine_fallback(
                    lambda phys: phys.execute_count(ExecContext(self.session))
                )
                root.set_attr("rows_out", int(n))
                accounting.set_value("rows_produced", int(n))
                return n

    def to_pydict(self) -> Dict[str, list]:
        return self.collect().to_pydict()

    def sorted_rows(self):
        return self.collect().sorted_rows()

    def explain_string(self) -> str:
        return self.physical_plan().tree_string()

    def explain(self, analyze: bool = False, redirect=None):
        """The physical plan tree; with ``analyze=True`` the query EXECUTES
        under a trace capture and the same tree comes back annotated with each
        node's measured wall time, rows out, cache/memo hits, stage spans
        (probe/verify/gather/…), Pallas fallbacks, and the optimizer-rule
        decisions that shaped it (`plananalysis.analyze`). Returns the string
        when `redirect` is None, else passes it to `redirect` (e.g. print)."""
        if analyze:
            from ..plananalysis.analyze import explain_analyze_string

            s = explain_analyze_string(self)
        else:
            s = self.explain_string()
        if redirect is not None:
            redirect(s)
            return None
        return s

    def show(self, n: int = 20, redirect=print) -> None:
        """Spark-style formatted preview of the first `n` rows."""
        t = self.limit(n + 1).collect()
        truncated = t.num_rows > n
        names = t.column_names
        cols = {c: t.column(c).decode_objects()[:n] for c in names}
        cells = [
            [("null" if v is None else str(v)) for v in cols[c]] for c in names
        ]
        widths = [
            max(len(name), *(len(x) for x in col), 0) if col else len(name)
            for name, col in zip(names, cells)
        ]
        sep = "+" + "+".join("-" * (w + 2) for w in widths) + "+"
        def fmt(vals):
            return "|" + "|".join(f" {v:>{w}} " for v, w in zip(vals, widths)) + "|"
        lines = [sep, fmt(names), sep]
        for i in range(min(n, t.num_rows)):
            lines.append(fmt([cells[j][i] for j in range(len(names))]))
        lines.append(sep)
        if truncated:
            lines.append(f"only showing top {n} row{'s' if n != 1 else ''}")
        redirect("\n".join(lines))


class GroupedDataFrame:
    """`df.group_by(keys)` → aggregation builder (the Spark RelationalGroupedDataset
    analogue, over the SQL aggregates the engine executes on device:
    sum, count, count_distinct, min, max, avg)."""

    def __init__(self, df: DataFrame, keys: List[str]):
        self._df = df
        self._keys = keys

    def agg(self, **aggs) -> DataFrame:
        """`.agg(out_name=("column", "fn"), ...)` with
        fn ∈ sum|count|count_distinct|min|max|avg; `.agg(n=("*", "count"))` is
        count(*)."""
        if not aggs:
            raise HyperspaceException("agg() requires at least one aggregate")
        triples = []
        for out_name, spec in aggs.items():
            if not (isinstance(spec, tuple) and len(spec) == 2):
                raise HyperspaceException(
                    f"agg spec must be (column, fn): {out_name}={spec!r}"
                )
            col, fn = spec
            col = None if col in ("*", None) else col
            triples.append((out_name, fn.lower(), col))
        return DataFrame(
            self._df.session, AggregateNode(self._keys, triples, self._df.plan)
        )

    def count(self) -> DataFrame:
        """Spark-style `groupBy(...).count()` → a `count` column of group sizes."""
        return self.agg(count=("*", "count"))

    def sum(self, *cols: str) -> DataFrame:
        return self.agg(**{f"sum({c})": (c, "sum") for c in cols})

    def min(self, *cols: str) -> DataFrame:
        return self.agg(**{f"min({c})": (c, "min") for c in cols})

    def max(self, *cols: str) -> DataFrame:
        return self.agg(**{f"max({c})": (c, "max") for c in cols})

    def avg(self, *cols: str) -> DataFrame:
        return self.agg(**{f"avg({c})": (c, "avg") for c in cols})


class DataFrameReader:
    def __init__(self, session: "HyperspaceSession"):
        self._session = session

    def _read(self, paths, file_format: str) -> DataFrame:
        path_list = [paths] if isinstance(paths, str) else list(paths)
        files = []
        for p in path_list:
            files.extend(engine_io.list_data_files(p, file_format, self._session.fs))
        if not files:
            raise HyperspaceException(f"No {file_format} files found under {path_list}")
        schema = engine_io.infer_schema([f.path for f in files], file_format)
        # Absolute local paths throughout: partition discovery compares files
        # against roots, and relative spellings must not change the schema.
        # URL-scheme paths (s3://, memory://, ...) pass through untouched —
        # abspath would mangle them ("s3://x" -> "/cwd/s3:/x").
        import re

        def _abs(p: str) -> str:
            return p if re.match(r"^[A-Za-z][A-Za-z0-9+.-]*://", p) else os.path.abspath(p)

        roots = [_abs(p) for p in path_list]
        from ..storage.filesystem import FileStatus

        files = [
            FileStatus(_abs(f.path), f.size, f.modified_time, f.is_dir) for f in files
        ]
        # Hive layout: `key=value` path segments become columns appended to the
        # schema (the PartitioningAwareFileIndex analogue).
        from .partitioning import discover

        spec = discover(roots, [f.path for f in files])
        if spec is not None:
            clash = [c for c in spec.columns if c in schema]
            if clash:
                raise HyperspaceException(
                    f"Partition column(s) also present in data files: {clash}"
                )
            schema = Schema(list(schema.fields) + spec.fields)
        rel = SourceRelation(
            root_paths=roots,
            file_format=file_format,
            schema=schema,
            files=files,
            partition_spec=spec,
        )
        return DataFrame(self._session, ScanNode(rel))

    def parquet(self, *paths) -> DataFrame:
        return self._read(paths if len(paths) > 1 else paths[0], "parquet")

    def csv(self, *paths) -> DataFrame:
        return self._read(paths if len(paths) > 1 else paths[0], "csv")

    def json(self, *paths) -> DataFrame:
        return self._read(paths if len(paths) > 1 else paths[0], "json")

    def orc(self, *paths) -> DataFrame:
        return self._read(paths if len(paths) > 1 else paths[0], "orc")

    def view(self, name: str) -> DataFrame:
        """Read a named view registered with `session.create_view`."""
        return self._session.view(name)

    def delta(self, path: str) -> DataFrame:
        """Snapshot read of a delta-style transactional table (extension): the file
        set is resolved from the `_delta_log`, not a directory listing."""
        from ..storage import delta as delta_log

        files = delta_log.active_files(path, self._session.fs)
        if not files:
            raise HyperspaceException(f"Delta table has no active files: {path}")
        schema = engine_io.infer_schema([f.path for f in files], "delta")
        rel = SourceRelation(
            root_paths=[os.path.abspath(path)],
            file_format="delta",
            schema=schema,
            files=files,
        )
        return DataFrame(self._session, ScanNode(rel))


_compile_cache_done = False


def _enable_compile_cache_once() -> None:
    """Opt-in persistent XLA compilation cache (HYPERSPACE_COMPILE_CACHE_DIR):
    on a remote-compile transport (the axon relay POSTs every distinct program
    shape) a warm cache erases the dominant index-build cost across processes.
    Program shapes are pow2-quantized throughout the engine precisely so this
    warm set stays small. No-op when unset or when the backend cannot
    serialize executables."""
    global _compile_cache_done
    if _compile_cache_done:
        return
    _compile_cache_done = True
    import os

    path = os.environ.get("HYPERSPACE_COMPILE_CACHE_DIR")
    if not path:
        return
    try:
        import jax

        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        try:
            # jax initializes the persistent cache lazily on the FIRST compile
            # and latches the decision: a process that already compiled
            # anything before this knob ran (library user creating a session
            # late) would silently never write entries. Dropping the latched
            # state makes the config take effect from the next compile.
            from jax.experimental.compilation_cache import compilation_cache as _cc

            _cc.reset_cache()
        except Exception:
            pass  # older/newer cache module layout: config alone suffices
    except Exception:
        pass  # an optimization, never a failure mode


class HyperspaceSession:
    """One session = conf + filesystem + optimizer rules + warehouse location."""

    # Active-session context: thread-local first (the reference's
    # Hyperspace.getContext is per-thread, `Hyperspace.scala:108-120`), with
    # the last globally-created session as the cross-thread fallback.
    _active: Optional["HyperspaceSession"] = None
    _active_local = None  # threading.local, created lazily

    def __init__(
        self,
        warehouse: str = ".",
        conf: Optional[SessionConf] = None,
        fs: Optional[FileSystem] = None,
    ):
        self.warehouse = warehouse
        self.conf = conf or SessionConf()
        self.hs_conf = HyperspaceConf(self.conf)
        self.fs = fs or LocalFileSystem()
        # Rule protocol: rule.apply(plan, session) -> plan.
        self.extra_optimizations: List = []
        self._mesh = None
        self._views: Dict[str, LogicalPlan] = {}
        _enable_compile_cache_once()
        import threading

        if HyperspaceSession._active_local is None:
            HyperspaceSession._active_local = threading.local()
        HyperspaceSession._active_local.session = self
        HyperspaceSession._active = self

    @classmethod
    def active(cls) -> "HyperspaceSession":
        """The calling thread's most recent session, else the process-wide
        most recent one (the reference's thread-local getContext semantics
        with its global fallback)."""
        local = getattr(cls._active_local, "session", None) if cls._active_local else None
        if local is not None:
            return local
        if cls._active is None:
            raise HyperspaceException("No active HyperspaceSession.")
        return cls._active

    def mesh_for(self, num_rows: int):
        """The ambient device mesh, when distributed execution should handle this
        many rows — the engine analogue of Spark's ambient cluster. Returns None
        when disabled, below the row threshold, or on a single-device backend
        (where the exchange would be pure overhead)."""
        if not self.hs_conf.distributed_enabled:
            return None
        if num_rows < self.hs_conf.distributed_min_rows:
            return None
        import jax

        if len(jax.devices()) < 2:
            return None
        if self._mesh is None:
            from ..parallel.mesh import make_mesh

            self._mesh = make_mesh()
        return self._mesh

    @property
    def read(self) -> DataFrameReader:
        return DataFrameReader(self)

    # -- named views (the temp-view/catalog-table analogue) ------------------

    def create_view(self, name: str, df: DataFrame, replace: bool = True) -> None:
        """Register `df`'s logical plan under `name` (the createOrReplaceTempView
        analogue). Reading the view resolves to the underlying plan, so the
        index-rewrite rules see straight through it — the reference rewrites
        queries over views the same way
        (`E2EHyperspaceRulesTests.scala:221-247`).

        View NAMES are always case-insensitive (like Spark identifiers, whose
        caseSensitive conf governs column resolution, not table names) — a fixed
        rule, so toggling the conf can never strand a registered view."""
        key = name.lower()
        if not replace and key in self._views:
            raise HyperspaceException(f"View already exists: {name}")
        self._views[key] = df.plan

    def drop_view(self, name: str) -> bool:
        return self._views.pop(name.lower(), None) is not None

    def view(self, name: str) -> DataFrame:
        plan = self._views.get(name.lower())
        if plan is None:
            raise HyperspaceException(f"View not found: {name}")
        return DataFrame(self, plan)

    def optimize(self, plan: LogicalPlan) -> LogicalPlan:
        from ..telemetry import tracing
        from .logical import push_filters_below_computed

        plan = push_filters_below_computed(plan)
        for rule in self.extra_optimizations:
            # One span per rule application under the query's plan span; each
            # rule records its applied/skipped decisions onto it
            # (`rules.rule_utils.record_rule_decision`).
            with tracing.span(f"rule:{type(rule).__name__}"):
                plan = rule.apply(plan, self)
        return plan

    # -- data creation helpers (test/SampleData parity) ---------------------

    def create_table(self, data: Dict[str, list]) -> Table:
        return Table.from_pydict(data)

    def write_parquet(
        self,
        data: Union[Table, Dict[str, list]],
        path: str,
        row_group_rows: Optional[int] = None,
    ) -> None:
        """`row_group_rows` bounds the written parquet row groups (None =
        pyarrow default, one group for typical test sizes) — multi-row-group
        sources are what the scan pushdown's zone maps prune inside."""
        t = data if isinstance(data, Table) else Table.from_pydict(data)
        engine_io.write_parquet(
            t, os.path.join(path, "part-00000.parquet"), row_group_rows=row_group_rows
        )

    def write_orc(self, data: Union[Table, Dict[str, list]], path: str) -> None:
        t = data if isinstance(data, Table) else Table.from_pydict(data)
        engine_io.write_orc(t, os.path.join(path, "part-00000.orc"))

    def write_csv(self, data: Union[Table, Dict[str, list]], path: str) -> None:
        t = data if isinstance(data, Table) else Table.from_pydict(data)
        engine_io.write_csv(t, os.path.join(path, "part-00000.csv"))

    def write_json(self, data: Union[Table, Dict[str, list]], path: str) -> None:
        t = data if isinstance(data, Table) else Table.from_pydict(data)
        engine_io.write_json(t, os.path.join(path, "part-00000.json"))

    def write_delta(
        self, data: Union[Table, Dict[str, list]], path: str, mode: str = "append"
    ) -> None:
        from ..storage import delta as delta_log

        t = data if isinstance(data, Table) else Table.from_pydict(data)
        delta_log.write_delta(t, path, mode, self.fs)
