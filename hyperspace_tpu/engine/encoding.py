"""Encoded execution: dictionary-code string columns end to end.

The engine's `Column` representation has always been codes + sorted
dictionary (`engine/table.py`), and every key-touching kernel already runs in
code space — hashing gathers a per-dictionary blake2b table through the codes
(`ops/hashing.host_hash_dictionary`), the build's partition sort orders by
codes (`ops/partition.host_sort_perm`), and join verification compares
union-aligned codes (`physical._aligned_key_codes`). What DIDN'T run encoded
was the lake boundary: every parquet read flattened dictionary-encoded string
columns to N python strings and re-derived the dictionary with an
O(N log N) string sort (`io._arrow_to_table` → `Column.from_values`), and
every index bucket write decoded N strings back out (`io.table_to_arrow`).
At CPU_BENCH_r05 shapes that flatten/re-sort IS the scan — ~0.7 % of machine
bandwidth reached the kernels (ROADMAP item 4).

This module is the home of the encoded lake boundary:

- **Read** (`dictionary_array_to_column`): a parquet column chunk that is
  dictionary-encoded on disk (the footer's `has_dictionary_page`, recorded
  per column by `io.footer_metadata`) is read with pyarrow's
  ``read_dictionary`` and converted to a `Column` entirely in code space:
  O(N) integer remaps plus one O(D log D) sort of the D *distinct* values —
  never an O(N) string materialization. The result is byte-identical to the
  flatten path (same sorted-unique dictionary of PRESENT values, same codes,
  same validity), pinned by tests/test_encoded_exec.py.
- **Write** (`dictionary_arrow_array`): string columns encode to parquet as
  compacted `pa.DictionaryArray`s — D distinct strings cross the arrow
  boundary instead of N. Both index writers (serial `table_to_arrow` and the
  pipelined `_BucketWriter`) funnel through this ONE helper, so the
  serial == pipelined byte-identity contract holds with the flag on or off.
- **Fallback policy**: a column that isn't dictionary-encoded on disk, or
  whose combined dictionary exceeds ``HYPERSPACE_ENCODED_DICT_MAX`` (near-
  unique strings: code space stops paying), silently takes the flatten path
  — per column, per file. ``HYPERSPACE_ENCODED_EXEC=0`` disables the whole
  path: reads flatten and writes decode exactly as before (the byte-identical
  decoded oracle, same contract style as ``HYPERSPACE_SCAN_PUSHDOWN=0``).

Accounting: `io.pruning.bytes_encoded_kept` counts bytes that entered the
engine still encoded (codes + dictionary), `io.pruning.bytes_materialized`
counts bytes flattened to raw values — together the honest denominator of
the bench's effective-GB/s number (both mirrored into the per-query ledger
and rendered by ``explain(analyze=True)``).
"""

from __future__ import annotations

import os
from typing import List, Optional

import numpy as np
import pyarrow as pa

from ..telemetry import accounting as _accounting
from ..telemetry import metrics as _metrics

#: Master switch. Default ON; ``0`` restores the decoded lake boundary
#: byte-for-byte (reads flatten + re-sort, writes decode N strings).
ENV_ENCODED_EXEC = "HYPERSPACE_ENCODED_EXEC"

#: Columns whose COMBINED per-file dictionary exceeds this many entries fall
#: back to the flatten path: near-unique strings make the dictionary itself
#: the data, and code-space conversion stops beating the O(N) decode.
ENV_ENCODED_DICT_MAX = "HYPERSPACE_ENCODED_DICT_MAX"
_DEFAULT_ENCODED_DICT_MAX = 1 << 20


def encoded_exec_enabled() -> bool:
    """Default ON; ``HYPERSPACE_ENCODED_EXEC=0`` is the byte-identical
    decoded fallback (pinned by tests/test_encoded_exec.py). Unset defers
    to the adaptive planner's per-query decision when one is ambient —
    explicit flags always win (`docs/planner.md`). The encoded-DEVICE
    lane (`encoded_device.py`) rides this gate transitively in its auto
    mode, so one planner decision governs both layers."""
    raw = os.environ.get(ENV_ENCODED_EXEC, "")
    if raw != "":
        return raw != "0"
    from ..plananalysis.planner import decided_value

    decided = decided_value("encoded_exec")
    return True if decided is None else bool(decided)


def encoded_dict_max() -> int:
    """Dictionary-entry ceiling of the encoded read path (≥1)."""
    return max(
        1,
        int(
            os.environ.get(ENV_ENCODED_DICT_MAX, _DEFAULT_ENCODED_DICT_MAX)
            or _DEFAULT_ENCODED_DICT_MAX
        ),
    )


# Per-column decode outcomes (one tick per column per real decode — cache
# hits never inflate these) and the byte split behind the bench's effective
# GB/s: encoded_kept = bytes that entered the engine still as codes +
# dictionary; materialized = bytes flattened to raw value arrays.
COLUMNS_ENCODED = _metrics.counter("io.encoded.columns_encoded")
COLUMNS_FLATTENED = _metrics.counter("io.encoded.columns_flattened")
COLUMNS_DICT_WRITTEN = _metrics.counter("io.encoded.columns_dict_written")
BYTES_ENCODED_KEPT = _metrics.counter("io.pruning.bytes_encoded_kept")
BYTES_MATERIALIZED = _metrics.counter("io.pruning.bytes_materialized")
# Join-verify dictionary reconciliation: both sides sharing one dictionary
# compare codes directly; a mismatch pays the union re-encode once per pair.
VERIFY_SHARED_DICT = _metrics.counter("encoded.verify.shared_dict")
VERIFY_REALIGNED = _metrics.counter("encoded.verify.realigned")


def column_nbytes(c) -> int:
    """TRUE in-memory size of a Column: codes + dictionary + validity. The
    ONE size definition shared by the encoded_kept counter and the scan-cache
    byte charge — keep them from diverging."""
    total = c.data.nbytes
    if c.dictionary is not None:
        total += c.dictionary.nbytes
    if c.validity is not None:
        total += c.validity.nbytes
    return total


def record_encoded_kept(nbytes: int) -> None:
    BYTES_ENCODED_KEPT.inc(nbytes)
    _accounting.add("bytes_encoded_kept", nbytes)


def record_materialized(nbytes: int) -> None:
    BYTES_MATERIALIZED.inc(nbytes)
    _accounting.add("bytes_materialized", nbytes)


def _stringish_value_type(t: "pa.DataType") -> bool:
    return pa.types.is_string(t) or pa.types.is_large_string(t)


def dict_read_columns(meta, columns: Optional[List[str]]) -> List[str]:
    """The subset of a read's columns to request AS DICTIONARY from pyarrow —
    the per-column encoded-execution decision, made from the PR-5 footer
    cache's per-column-chunk encoding facts (`io.footer_metadata` records
    `dict_cols`: every row-group chunk carries a dictionary page AND the
    value type is string). Empty when the flag is off or nothing qualifies —
    the read then runs the plain decoded path untouched."""
    if not encoded_exec_enabled() or meta is None:
        return []
    dict_cols = getattr(meta, "dict_cols", None)
    if not dict_cols:
        return []
    names = columns if columns is not None else meta.names
    return [c for c in names if dict_cols.get(c)]


def _present_codes(valid_codes: np.ndarray, dict_len: int) -> np.ndarray:
    """Ascending distinct codes among `valid_codes` — a presence mask over the
    D dictionary slots, O(N + D), never an O(N log N) sort of the N row codes
    (this runs per column per cold file read and per bucket write)."""
    if not len(valid_codes):
        return np.empty(0, np.int64)
    seen = np.zeros(dict_len, bool)
    seen[valid_codes] = True
    return np.flatnonzero(seen)


def dictionary_array_to_column(arr):
    """Code-space conversion of one arrow dictionary column → engine `Column`,
    or None to fall back to the flatten path (non-string values, dictionary
    over the size knob). BYTE-IDENTICAL to the flatten path by construction:

    - dictionary = sorted unique of the values PRESENT in the data (plus the
      ``""`` null-fill when the column has nulls) — exactly what
      ``np.unique`` over the filled flat values produces;
    - codes = each row's position in that sorted dictionary, with null slots
      canonicalized to 0 (the same refill `io._arrow_to_table` applies);
    - validity = the arrow null mask.

    Work: O(N) integer ops + O(D log D) string sort over the D distinct
    values. The N string objects are never materialized."""
    from .table import Column
    from .schema import STRING

    if isinstance(arr, pa.ChunkedArray):
        if not _stringish_value_type(arr.type.value_type):
            return None
        # The size knob must bail BEFORE the O(N) chunk unification: summed
        # per-chunk dictionary sizes bound the unified size from above, so a
        # near-unique column — what the knob exists to exempt — never pays
        # combine_chunks only to fall back anyway. (Conservative: chunks
        # sharing values may unify under the knob yet flatten here; the
        # fallback is byte-identical, so only the routing differs.)
        if sum(len(c.dictionary) for c in arr.chunks) > encoded_dict_max():
            return None
        arr = arr.combine_chunks()  # unifies per-chunk dictionaries
    elif not _stringish_value_type(arr.type.value_type):
        return None
    if len(arr.dictionary) > encoded_dict_max():
        return None

    validity = None
    indices = arr.indices
    if arr.null_count > 0:
        validity = ~np.asarray(arr.is_null().to_numpy(zero_copy_only=False))
        indices = indices.fill_null(0)
    codes = np.asarray(indices)
    dvals = arr.dictionary.to_numpy(zero_copy_only=False)
    # Same stringification the flatten path applies to its object array —
    # but over D entries, not N.
    dvals = (
        np.empty(0, dtype="<U1")
        if len(dvals) == 0
        else np.asarray([str(x) for x in dvals])
    )
    # Present values come from VALID slots only: null slots' filled indices
    # are representation noise (an all-null column may even carry an EMPTY
    # disk dictionary), and the decoded path's uniquing sees the null fill
    # "" — appended below — not the value a null slot happened to sit on.
    valid_codes = codes if validity is None else codes[validity]
    present = _present_codes(valid_codes, len(dvals))
    vals = dvals[present]
    if validity is not None:
        # The decoded path fills nulls with "" BEFORE uniquing, so the fill
        # value is part of the dictionary whenever the column has nulls.
        vals = np.concatenate([vals, np.asarray([""], dtype=vals.dtype)])
    sorted_dict, inv = np.unique(vals, return_inverse=True)
    remap = np.zeros(max(len(dvals), 1), np.int32)
    remap[present] = inv[: len(present)].astype(np.int32)
    new_codes = remap[codes].astype(np.int32, copy=False)
    if validity is not None:
        new_codes[~validity] = 0  # canonical null fill (matches from_values)
    col = Column(STRING, new_codes, sorted_dict, validity)
    col._encoded_read = True  # cache marker: this column never flattened
    return col


def dictionary_arrow_array(
    codes: np.ndarray, dictionary: np.ndarray, mask: Optional[np.ndarray]
) -> "pa.DictionaryArray":
    """Compacted arrow dictionary array of one string column slice — THE
    write-side primitive shared by `io.table_to_arrow` and the pipelined
    `_BucketWriter` (the serial == pipelined byte-identity contract rides on
    there being exactly one implementation). Compaction matters: a bucket
    slice's codes point into the full union dictionary, and writing that
    dictionary verbatim would replicate every distinct value of the TABLE
    into every `part-<bucket>` file.

    Null slots are EXCLUDED from the present-value set and canonicalized to
    index 0: the two writers reach here with different code values under
    their masks (the pipelined gather round-trips codes through arrow nulls),
    and the written bytes must not depend on that invisible difference."""
    valid_codes = codes if mask is None else codes[~mask]
    present = _present_codes(valid_codes, len(dictionary))
    sub = dictionary[present]  # ascending subset of a sorted dict stays sorted
    remap = np.zeros(max(len(dictionary), 1), np.int32)
    remap[present] = np.arange(len(present), dtype=np.int32)
    new_codes = remap[codes]
    if mask is not None:
        new_codes[mask] = 0
    COLUMNS_DICT_WRITTEN.inc()
    return pa.DictionaryArray.from_arrays(
        pa.array(new_codes, mask=mask), pa.array(sub)
    )
