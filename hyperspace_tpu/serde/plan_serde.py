"""Logical plan serialization.

Parity: reference `index/serde/LogicalPlanSerDeUtils.scala` + wrappers — serialize a
logical plan for persistence in the metadata log (designed-for in the reference, where
the main path stores rawPlan=null; same here: available for the log's `rawPlan` slot
and exercised by tests). The reference needed Kryo + wrapper classes for
non-serializable Catalyst nodes; our IR is plain data, so the format is versioned JSON.
"""

from __future__ import annotations

import base64
import json
from typing import Any, Dict

from ..engine.expr import BinaryOp, Col, Expr, IsIn, IsNull, Lit, Not, Udf
from ..engine.logical import (
    AggregateNode,
    BucketSpec,
    ExceptNode,
    FilterNode,
    IntersectNode,
    JoinNode,
    LimitNode,
    LogicalPlan,
    OrderByNode,
    UnionNode,
    ProjectNode,
    ScanNode,
    SourceRelation,
    WithColumnNode,
)
from ..engine.partitioning import PartitionSpec
from ..engine.schema import Schema
from ..exceptions import HyperspaceException
from ..storage.filesystem import FileStatus

_VERSION = "1"


# -- expressions ------------------------------------------------------------


def expr_to_dict(e: Expr) -> Dict[str, Any]:
    if isinstance(e, Col):
        return {"t": "col", "name": e.name}
    if isinstance(e, Lit):
        v = e.value
        if hasattr(v, "item"):
            v = v.item()
        return {"t": "lit", "value": v}
    if isinstance(e, BinaryOp):
        return {
            "t": "bin",
            "op": e.op,
            "left": expr_to_dict(e.left),
            "right": expr_to_dict(e.right),
        }
    if isinstance(e, Not):
        return {"t": "not", "child": expr_to_dict(e.child)}
    if isinstance(e, IsIn):
        return {"t": "isin", "child": expr_to_dict(e.child), "values": list(e.values)}
    if isinstance(e, IsNull):
        return {"t": "isnull", "child": expr_to_dict(e.child), "negated": e.negated}
    if isinstance(e, Udf):
        # The function itself is not serializable (the same limit the
        # reference's ScalaUDF wrapper has, `serde/package.scala:59-186`):
        # record its import path and re-import at deserialize time — a missing
        # import fails loudly there, never silently at execution.
        fn = e.fn
        return {
            "t": "udf",
            "name": e.name,
            "dtype": e.dtype,
            "module": getattr(fn, "__module__", None),
            "qualname": getattr(fn, "__qualname__", None),
            "args": [expr_to_dict(a) for a in e.args],
        }
    raise HyperspaceException(f"Cannot serialize expression: {e!r}")


def expr_from_dict(d: Dict[str, Any]) -> Expr:
    t = d["t"]
    if t == "col":
        return Col(d["name"])
    if t == "lit":
        return Lit(d["value"])
    if t == "bin":
        return BinaryOp(d["op"], expr_from_dict(d["left"]), expr_from_dict(d["right"]))
    if t == "not":
        return Not(expr_from_dict(d["child"]))
    if t == "isin":
        return IsIn(expr_from_dict(d["child"]), d["values"])
    if t == "isnull":
        return IsNull(expr_from_dict(d["child"]), d.get("negated", False))
    if t == "udf":
        module, qualname = d.get("module"), d.get("qualname")
        if not (module and qualname) or "<" in qualname:
            raise HyperspaceException(
                f"Cannot deserialize UDF {d.get('name')!r}: lambdas and local "
                "functions cannot round-trip; define the UDF at module scope"
            )
        try:
            import importlib

            obj = importlib.import_module(module)
            for part in qualname.split("."):
                obj = getattr(obj, part)
            fn = obj
        except Exception as e:
            # Chain the real cause — an import-time bug must not masquerade
            # as a naming problem.
            raise HyperspaceException(
                f"Cannot deserialize UDF {d.get('name')!r}: importing "
                f"{module}.{qualname} failed: {type(e).__name__}: {e}"
            ) from e
        return Udf(fn, d["dtype"], [expr_from_dict(a) for a in d["args"]], d.get("name"))
    raise HyperspaceException(f"Cannot deserialize expression tag: {t}")


# -- relations / plans ------------------------------------------------------


def _relation_to_dict(rel: SourceRelation) -> Dict[str, Any]:
    return {
        "rootPaths": rel.root_paths,
        "fileFormat": rel.file_format,
        "schema": rel.schema.to_json_string(),
        "options": rel.options,
        "files": [
            {"path": f.path, "size": f.size, "mtime": f.modified_time}
            for f in rel.files
        ],
        "bucketSpec": (
            None
            if rel.bucket_spec is None
            else {
                "numBuckets": rel.bucket_spec.num_buckets,
                "bucketColumns": list(rel.bucket_spec.bucket_columns),
                "sortColumns": list(rel.bucket_spec.sort_columns),
            }
        ),
        "indexName": rel.index_name,
        "partitionSpec": (
            None if rel.partition_spec is None else rel.partition_spec.to_json()
        ),
    }


def _relation_from_dict(d: Dict[str, Any]) -> SourceRelation:
    spec = d.get("bucketSpec")
    return SourceRelation(
        root_paths=d["rootPaths"],
        file_format=d["fileFormat"],
        schema=Schema.from_json_string(d["schema"]),
        files=[
            FileStatus(f["path"], f["size"], f["mtime"], False) for f in d.get("files", [])
        ],
        options=d.get("options", {}),
        bucket_spec=(
            None
            if spec is None
            else BucketSpec(
                spec["numBuckets"],
                tuple(spec["bucketColumns"]),
                tuple(spec["sortColumns"]),
            )
        ),
        index_name=d.get("indexName"),
        partition_spec=PartitionSpec.from_json(d.get("partitionSpec")),
    )


def plan_to_dict(plan: LogicalPlan) -> Dict[str, Any]:
    if isinstance(plan, ScanNode):
        return {"t": "scan", "relation": _relation_to_dict(plan.relation)}
    if isinstance(plan, FilterNode):
        return {
            "t": "filter",
            "condition": expr_to_dict(plan.condition),
            "child": plan_to_dict(plan.child),
        }
    if isinstance(plan, ProjectNode):
        return {"t": "project", "columns": plan.column_names, "child": plan_to_dict(plan.child)}
    if isinstance(plan, JoinNode):
        return {
            "t": "join",
            "how": plan.how,
            "condition": expr_to_dict(plan.condition),
            "left": plan_to_dict(plan.left),
            "right": plan_to_dict(plan.right),
        }
    if isinstance(plan, AggregateNode):
        return {
            "t": "aggregate",
            "groupKeys": list(plan.group_keys),
            "aggs": [[o, fn, c] for o, fn, c in plan.aggs],
            "child": plan_to_dict(plan.child),
        }
    if isinstance(plan, OrderByNode):
        return {
            "t": "orderby",
            "keys": [[k, asc] for k, asc in plan.keys],
            "child": plan_to_dict(plan.child),
        }
    if isinstance(plan, LimitNode):
        return {"t": "limit", "n": plan.n, "child": plan_to_dict(plan.child)}
    if isinstance(plan, WithColumnNode):
        return {
            "t": "withcolumn",
            "name": plan.name,
            "expr": expr_to_dict(plan.expr),
            "child": plan_to_dict(plan.child),
        }
    if isinstance(plan, UnionNode):
        return {
            "t": "union",
            "children": [plan_to_dict(c) for c in plan.children()],
        }
    if isinstance(plan, (IntersectNode, ExceptNode)):
        return {
            "t": "intersect" if isinstance(plan, IntersectNode) else "except",
            "left": plan_to_dict(plan.left),
            "right": plan_to_dict(plan.right),
        }
    raise HyperspaceException(f"Cannot serialize plan node: {plan.simple_string()}")


def plan_from_dict(d: Dict[str, Any]) -> LogicalPlan:
    t = d["t"]
    if t == "scan":
        return ScanNode(_relation_from_dict(d["relation"]))
    if t == "filter":
        return FilterNode(expr_from_dict(d["condition"]), plan_from_dict(d["child"]))
    if t == "project":
        return ProjectNode(d["columns"], plan_from_dict(d["child"]))
    if t == "join":
        return JoinNode(
            plan_from_dict(d["left"]),
            plan_from_dict(d["right"]),
            expr_from_dict(d["condition"]),
            d["how"],
        )
    if t == "aggregate":
        return AggregateNode(
            d["groupKeys"],
            [(o, fn, c) for o, fn, c in d["aggs"]],
            plan_from_dict(d["child"]),
        )
    if t == "orderby":
        return OrderByNode([(k, asc) for k, asc in d["keys"]], plan_from_dict(d["child"]))
    if t == "limit":
        return LimitNode(d["n"], plan_from_dict(d["child"]))
    if t == "withcolumn":
        return WithColumnNode(
            d["name"], expr_from_dict(d["expr"]), plan_from_dict(d["child"])
        )
    if t == "union":
        return UnionNode([plan_from_dict(c) for c in d["children"]])
    if t == "intersect":
        return IntersectNode(plan_from_dict(d["left"]), plan_from_dict(d["right"]))
    if t == "except":
        return ExceptNode(plan_from_dict(d["left"]), plan_from_dict(d["right"]))
    raise HyperspaceException(f"Cannot deserialize plan tag: {t}")


def serialize_plan(plan: LogicalPlan) -> str:
    """Plan → base64 JSON string (the `rawPlan` format; base64 keeps the log entry's
    JSON clean, mirroring the reference's base64-encoded Kryo bytes)."""
    payload = json.dumps({"version": _VERSION, "plan": plan_to_dict(plan)})
    return base64.b64encode(payload.encode("utf-8")).decode("ascii")


def deserialize_plan(s: str) -> LogicalPlan:
    payload = json.loads(base64.b64decode(s.encode("ascii")).decode("utf-8"))
    if payload.get("version") != _VERSION:
        raise HyperspaceException(
            f"Unsupported serialized plan version: {payload.get('version')!r}"
        )
    return plan_from_dict(payload["plan"])
