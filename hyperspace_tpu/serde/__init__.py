from .plan_serde import deserialize_plan, serialize_plan  # noqa: F401
