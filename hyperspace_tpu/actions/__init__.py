from . import states  # noqa: F401
