"""Metadata-only lifecycle actions: Delete, Restore, Vacuum, Cancel.

Parity: reference `actions/DeleteAction.scala` (ACTIVE→DELETED, soft), `RestoreAction`
(DELETED→ACTIVE), `VacuumAction.scala:38-52` (DELETED→DOESNOTEXIST, deletes every data
version dir), `CancelAction.scala:28-76` (any transient → last stable state, rollback
for crashed actions). None of these run a build job; vacuum touches the filesystem.
"""

from __future__ import annotations

import copy
from typing import Optional

from ..exceptions import HyperspaceException
from ..index.data_manager import IndexDataManager
from ..index.log_entry import LogEntry
from ..index.log_manager import IndexLogManager
from ..telemetry.events import (
    CancelActionEvent,
    DeleteActionEvent,
    HyperspaceEvent,
    RestoreActionEvent,
    VacuumActionEvent,
)
from . import states
from .action import Action


class _EntryTransitionAction(Action):
    """Base for actions that carry forward the previous log entry with a new state."""

    def __init__(self, log_manager: IndexLogManager, event_logger=None):
        super().__init__(log_manager, event_logger)
        self._entry_cache: Optional[LogEntry] = None

    def _previous_entry(self) -> LogEntry:
        prev = self._log_manager.get_log(self.base_id)
        if prev is None:
            raise HyperspaceException("Index does not exist.")
        return prev

    def log_entry(self) -> LogEntry:
        if self._entry_cache is None:
            self._entry_cache = copy.deepcopy(self._previous_entry())
        return self._entry_cache

    @property
    def index_name(self) -> str:
        try:
            return self._previous_entry().name  # type: ignore[attr-defined]
        except Exception:
            return ""


class DeleteAction(_EntryTransitionAction):
    transient_state = states.DELETING
    final_state = states.DELETED

    def validate(self) -> None:
        if self._previous_entry().state != states.ACTIVE:
            raise HyperspaceException(
                f"Delete is only supported in {states.ACTIVE} state."
            )

    def event(self, message: str) -> HyperspaceEvent:
        return DeleteActionEvent(index_name=self.index_name, message=message)


class RestoreAction(_EntryTransitionAction):
    transient_state = states.RESTORING
    final_state = states.ACTIVE

    def validate(self) -> None:
        if self._previous_entry().state != states.DELETED:
            raise HyperspaceException(
                f"Restore is only supported in {states.DELETED} state."
            )

    def event(self, message: str) -> HyperspaceEvent:
        return RestoreActionEvent(index_name=self.index_name, message=message)


class VacuumAction(_EntryTransitionAction):
    """Hard delete: removes every data version directory (reference `:46-52`)."""

    transient_state = states.VACUUMING
    final_state = states.DOESNOTEXIST

    def __init__(self, data_manager: IndexDataManager, log_manager, event_logger=None):
        super().__init__(log_manager, event_logger)
        self._data_manager = data_manager

    def validate(self) -> None:
        if self._previous_entry().state != states.DELETED:
            raise HyperspaceException(
                f"Vacuum is only supported in {states.DELETED} state."
            )

    def op(self) -> None:
        latest = self._data_manager.get_latest_version_id()
        if latest is not None:
            for vid in range(latest + 1):
                self._data_manager.delete(vid)

    def event(self, message: str) -> HyperspaceEvent:
        return VacuumActionEvent(index_name=self.index_name, message=message)


class CancelAction(_EntryTransitionAction):
    """Roll a stuck transient state back to the last stable one
    (reference `CancelAction.scala:28-76`): VACUUMING → DOESNOTEXIST; no stable log at
    all → DOESNOTEXIST; otherwise the latest stable entry's state."""

    transient_state = states.CANCELLING

    @property
    def final_state(self) -> str:
        prev = self._previous_entry()
        if prev.state == states.VACUUMING:
            return states.DOESNOTEXIST
        stable = self._log_manager.get_latest_stable_log()
        return stable.state if stable is not None else states.DOESNOTEXIST

    def validate(self) -> None:
        if self._previous_entry().state in states.STABLE_STATES:
            raise HyperspaceException(
                "Cancel is only supported when index is in transient states."
            )

    def event(self, message: str) -> HyperspaceEvent:
        return CancelActionEvent(index_name=self.index_name, message=message)
