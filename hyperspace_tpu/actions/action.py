"""The generic lifecycle action: a two-phase commit on the operation log.

Parity: reference `actions/Action.scala:34-104` — `run()` = validate → begin → op → end,
where `begin()` writes log id base+1 with the transient state and `end()` writes id
base+2 with the final state, then deletes and recreates the `latestStable` pointer.
Telemetry events wrap the whole run; failures are logged and rethrown.
"""

from __future__ import annotations

import time
from typing import Optional

from ..exceptions import ConcurrentWriteError, HyperspaceException, LogCommitError
from ..index.log_entry import LogEntry
from ..index.log_manager import IndexLogManager
from ..telemetry import metrics as _metrics
from ..telemetry.event_logging import EventLogger, NoOpEventLogger
from ..telemetry.events import HyperspaceEvent
from . import states

_RECOVERED = _metrics.counter("index.recovered_transient")


def _recover_stable(
    log_manager: IndexLogManager, orphan: LogEntry, missing_ok: bool = False
) -> Optional[LogEntry]:
    """Resolve a dead writer's orphaned TRANSIENT latest entry to the latest
    STABLE one, so the next action proceeds instead of wedging on the corpse.

    `missing_ok` governs a log with NO stable entry at all (a killed FIRST
    create): create treats that as "nothing durable ever committed" and
    proceeds (returns None); refresh/optimize need a real prior entry and
    raise. Safe under a writer that is actually still alive: both writers
    share the same `base_id` (the orphan's id), so their next log writes
    contest the same id and the operation-log CAS lets exactly one win — the
    loser aborts cleanly with `ConcurrentWriteError`."""
    stable = log_manager.get_latest_stable_log()
    if stable is None and not missing_ok:
        raise HyperspaceException(
            f"Index log has only a transient entry (state {orphan.state}) and "
            "no stable state to recover to; run cancel() or vacuum."
        )
    _RECOVERED.inc()
    return stable


class Action:
    """Subclasses define: transient_state, final_state, validate(), op(), log_entry(),
    event() (reference's abstract members)."""

    def __init__(self, log_manager: IndexLogManager, event_logger: Optional[EventLogger] = None):
        self._log_manager = log_manager
        self._event_logger = event_logger or NoOpEventLogger()
        self._base_id: Optional[int] = None

    # -- abstract -----------------------------------------------------------

    @property
    def transient_state(self) -> str:
        raise NotImplementedError

    @property
    def final_state(self) -> str:
        raise NotImplementedError

    def validate(self) -> None:
        """Raise HyperspaceException if the action is not allowed in the current state."""

    def op(self) -> None:
        """The action body (Spark-job analogue: the TPU build for create/refresh)."""

    def log_entry(self) -> LogEntry:
        """The metadata record to commit at end()."""
        raise NotImplementedError

    def event(self, message: str) -> HyperspaceEvent:
        raise NotImplementedError

    # -- the FSM ------------------------------------------------------------

    @property
    def base_id(self) -> int:
        if self._base_id is None:
            latest = self._log_manager.get_latest_id()
            self._base_id = latest if latest is not None else -1
        return self._base_id

    def begin(self) -> None:
        """Write id base+1 with the transient state (reference `Action.scala:48-54`).
        An OCC conflict here means a concurrent writer won the race."""
        entry = self.log_entry()
        entry.state = self.transient_state
        entry.timestamp = int(time.time() * 1000)
        if not self._log_manager.write_log(self.base_id + 1, entry):
            # Classified OCC loss (subclass keeps the reference message for
            # existing callers matching on it).
            raise ConcurrentWriteError(
                "Another Index operation is in progress. Please retry."
            )

    def end(self) -> None:
        """Write id base+2 with the final state and refresh `latestStable`
        (reference `Action.scala:59-74`)."""
        entry = self.log_entry()
        entry.state = self.final_state
        entry.timestamp = int(time.time() * 1000)
        final_id = self.base_id + 2
        if not self._log_manager.write_log(final_id, entry):
            raise ConcurrentWriteError(
                "Another Index operation is in progress. Please retry."
            )
        if entry.state in states.STABLE_STATES:
            # The pointer writes used to return ignored bools (and the real
            # impl's failure mode is actually an fs exception): a failed
            # latestStable refresh silently left a STALE pointer that every
            # reader would then trust. Classified now — the numbered entry is
            # committed either way, so a failed pointer is recoverable (the
            # reader-side fallback scans ids descending), but the action must
            # report it rather than claim clean success. The CREATE decides
            # success: it replaces any existing pointer, so even a failed
            # delete is harmless once the create lands.
            try:
                self._log_manager.delete_latest_stable_log()
            except Exception:
                pass  # superseded by the create below, which overwrites
            try:
                created = self._log_manager.create_latest_stable_log(final_id)
            except Exception as e:
                raise LogCommitError(
                    f"Committed log id {final_id} but the latestStable "
                    f"pointer refresh failed ({type(e).__name__}: {e}); "
                    "readers fall back to the id scan until the next "
                    "successful action."
                ) from e
            if not created:
                raise LogCommitError(
                    f"Committed log id {final_id} but could not refresh the "
                    "latestStable pointer; readers fall back to the id scan "
                    "until the next successful action."
                )

    def run(self) -> None:
        """validate → begin → op → end, wrapped in telemetry (reference `:83-101`)."""
        self._event_logger.log_event(self.event("Operation Started."))
        try:
            self.validate()
            self.begin()
            self.op()
            self.end()
            self._event_logger.log_event(self.event("Operation Succeeded."))
        except Exception as e:  # log + rethrow (reference behavior)
            self._event_logger.log_event(self.event(f"Operation Failed: {e}"))
            raise
