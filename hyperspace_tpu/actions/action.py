"""The generic lifecycle action: a two-phase commit on the operation log.

Parity: reference `actions/Action.scala:34-104` — `run()` = validate → begin → op → end,
where `begin()` writes log id base+1 with the transient state and `end()` writes id
base+2 with the final state, then deletes and recreates the `latestStable` pointer.
Telemetry events wrap the whole run; failures are logged and rethrown.
"""

from __future__ import annotations

import time
from typing import Optional

from ..exceptions import HyperspaceException
from ..index.log_entry import LogEntry
from ..index.log_manager import IndexLogManager
from ..telemetry.event_logging import EventLogger, NoOpEventLogger
from ..telemetry.events import HyperspaceEvent
from . import states


class Action:
    """Subclasses define: transient_state, final_state, validate(), op(), log_entry(),
    event() (reference's abstract members)."""

    def __init__(self, log_manager: IndexLogManager, event_logger: Optional[EventLogger] = None):
        self._log_manager = log_manager
        self._event_logger = event_logger or NoOpEventLogger()
        self._base_id: Optional[int] = None

    # -- abstract -----------------------------------------------------------

    @property
    def transient_state(self) -> str:
        raise NotImplementedError

    @property
    def final_state(self) -> str:
        raise NotImplementedError

    def validate(self) -> None:
        """Raise HyperspaceException if the action is not allowed in the current state."""

    def op(self) -> None:
        """The action body (Spark-job analogue: the TPU build for create/refresh)."""

    def log_entry(self) -> LogEntry:
        """The metadata record to commit at end()."""
        raise NotImplementedError

    def event(self, message: str) -> HyperspaceEvent:
        raise NotImplementedError

    # -- the FSM ------------------------------------------------------------

    @property
    def base_id(self) -> int:
        if self._base_id is None:
            latest = self._log_manager.get_latest_id()
            self._base_id = latest if latest is not None else -1
        return self._base_id

    def begin(self) -> None:
        """Write id base+1 with the transient state (reference `Action.scala:48-54`).
        An OCC conflict here means a concurrent writer won the race."""
        entry = self.log_entry()
        entry.state = self.transient_state
        entry.timestamp = int(time.time() * 1000)
        if not self._log_manager.write_log(self.base_id + 1, entry):
            raise HyperspaceException(
                "Another Index operation is in progress. Please retry."
            )

    def end(self) -> None:
        """Write id base+2 with the final state and refresh `latestStable`
        (reference `Action.scala:59-74`)."""
        entry = self.log_entry()
        entry.state = self.final_state
        entry.timestamp = int(time.time() * 1000)
        final_id = self.base_id + 2
        if not self._log_manager.write_log(final_id, entry):
            raise HyperspaceException(
                "Another Index operation is in progress. Please retry."
            )
        if entry.state in states.STABLE_STATES:
            self._log_manager.delete_latest_stable_log()
            self._log_manager.create_latest_stable_log(final_id)

    def run(self) -> None:
        """validate → begin → op → end, wrapped in telemetry (reference `:83-101`)."""
        self._event_logger.log_event(self.event("Operation Started."))
        try:
            self.validate()
            self.begin()
            self.op()
            self.end()
            self._event_logger.log_event(self.event("Operation Succeeded."))
        except Exception as e:  # log + rethrow (reference behavior)
            self._event_logger.log_event(self.event(f"Operation Failed: {e}"))
            raise
