"""RefreshAction: ACTIVE → REFRESHING → ACTIVE (full rebuild).

Parity: reference `actions/RefreshAction.scala:31-86` — reconstructs the source
dataframe from the previous log entry's `Relation` (root paths / schema / format /
options) and rewrites the index into the next version directory. The new log entry
carries a fresh signature over the current source files.

Extension (north-star, absent from the v0 reference): ``mode="incremental"`` indexes
only files appended since the recorded inventory and ``optimizeIndex`` compacts — see
`actions/optimize.py`.
"""

from __future__ import annotations

from typing import Optional

from ..exceptions import HyperspaceException
from ..index.log_entry import IndexLogEntry, LogEntry


class NothingToRefreshError(HyperspaceException):
    """Incremental refresh found no appended or deleted source files: the
    index already covers the current source. A TYPED signal so mode="auto"
    can no-op on it without matching message wording."""
from ..telemetry.events import HyperspaceEvent, RefreshActionEvent
from . import states
from .action import Action, _recover_stable
from .create import IndexerBuilder


#: Warm handoff (docs/reliability.md "Live tables"): after an action commits
#: its data directory and before its log commit flips readers onto the new
#: generation, the writer decodes the fresh files into the shared scan cache —
#: the first interactive query on the new generation pays a cache hit, not a
#: cold parquet decode. "0" opts out (the deltas re-decode lazily as before).
ENV_REFRESH_WARM_HANDOFF = "HYPERSPACE_REFRESH_WARM_HANDOFF"


def _warm_handoff(index_data_path: str, schema_json: str) -> None:
    """Best-effort: decode a freshly committed version dir's files into the
    per-column scan cache (explicit index-schema columns — a bare read under
    `v__=N` would sprout the hive partition column). A failure here must
    never fail the action: the data and log commits are already correct."""
    import os

    if os.environ.get(ENV_REFRESH_WARM_HANDOFF, "1") == "0":
        return
    try:
        from ..engine import io as engine_io
        from ..engine.schema import Schema

        if not os.path.isdir(index_data_path):
            return
        files = sorted(
            os.path.join(index_data_path, n)
            for n in os.listdir(index_data_path)
            if n.endswith(".parquet")
        )
        if not files:
            return
        cols = list(Schema.from_json_string(schema_json).names)
        engine_io.warm_file_cache(files, "parquet", cols)
        # The pool no-ops for a single job (and entirely when the decode pool
        # is sized 1): sweep only the files still cache-COLD, so nothing the
        # pool just decoded is re-assembled.
        from ..engine.scan_cache import global_scan_cache

        cache = global_scan_cache()
        for f in files:
            if cache.missing_columns(f, cols) != []:
                engine_io.read_files([f], "parquet", cols)
        from ..telemetry import metrics

        metrics.counter("index.warm_handoff.files").inc(len(files))
    except Exception:
        pass


class RefreshAction(Action):
    def __init__(
        self,
        builder: IndexerBuilder,
        log_manager,
        index_path: str,
        index_data_path: str,
        event_logger=None,
    ):
        super().__init__(log_manager, event_logger)
        self._builder = builder
        self._index_path = index_path
        self._index_data_path = index_data_path
        self._prev: Optional[IndexLogEntry] = None
        self._df = None

    def _previous_entry(self) -> IndexLogEntry:
        if self._prev is None:
            prev = self._log_manager.get_log(self.base_id)
            if prev is None:
                raise HyperspaceException("Refresh is only supported on an existing index.")
            if prev.state in states.TRANSIENT_STATES:
                # Dead writer's orphan (killed mid-action): refresh judges the
                # latest STABLE entry; the log CAS arbitrates live races.
                prev = _recover_stable(self._log_manager, prev)
            self._prev = prev
        return self._prev

    def _source_df(self):
        if self._df is None:
            prev = self._previous_entry()
            relations = prev.relations
            if len(relations) != 1:
                raise HyperspaceException("Refresh supports indexes over a single relation.")
            self._df = self._builder.reconstruct_df(relations[0])
        return self._df

    @property
    def transient_state(self) -> str:
        return states.REFRESHING

    @property
    def final_state(self) -> str:
        return states.ACTIVE

    def validate(self) -> None:
        prev = self._previous_entry()
        if prev.state != states.ACTIVE:
            raise HyperspaceException(
                f"Refresh is only supported in {states.ACTIVE} state. "
                f"Current state: {prev.state}."
            )

    def op(self) -> None:
        config = self._builder.config_from_entry(self._previous_entry())
        self._builder.write(self._source_df(), config, self._index_data_path)
        _warm_handoff(self._index_data_path, self._previous_entry().schema_json)

    def log_entry(self) -> LogEntry:
        # Derived fresh per phase (see CreateAction.log_entry): the end() entry must
        # inventory the files op() wrote.
        config = self._builder.config_from_entry(self._previous_entry())
        return self._builder.derive_log_entry(
            self._source_df(), config, self._index_path, self._index_data_path
        )

    def event(self, message: str) -> HyperspaceEvent:
        name = self._prev.name if self._prev else ""
        return RefreshActionEvent(index_name=name, message=message)


class RefreshIncrementalAction(RefreshAction):
    """refreshIndex(mode="incremental"): index ONLY files appended since the recorded
    source inventory, into a new version dir; the new log entry's content spans all
    version dirs and its signature covers the current source state.

    North-star extension (BASELINE.md config 5) — absent from the v0 reference
    snapshot, whose refresh is full-rebuild only (`RefreshAction.scala:76-81`).

    Deleted source files FOLD through lineage when the index carries the
    per-row `_data_file_name` column: their paths land in the new entry's
    ``deletedSourceFiles`` set (merged with any set the previous entry already
    carried), and readers prune those rows at scan time via
    `rules.rule_utils.lineage_prune_condition` — no data rewrite at refresh
    time. The rows are physically removed (and the set cleared) by the next
    `optimize_index` compaction or full rewrite. Without lineage, deletes
    still reject (the rows are inseparable). Files modified IN PLACE always
    reject: their old rows are not addressable even by lineage (same path)."""

    def _diff_files(self):
        prev = self._previous_entry()
        recorded = {
            (f.name, f.size, f.modified_time)
            for f in prev.relations[0].data.file_infos()
        }
        current_files = self._source_df().plan.relation.files
        current_paths = {f.path for f in current_files}
        # A recorded path modified in place (same path, changed size/mtime)
        # invalidates the already-indexed rows — full rebuild required. A
        # vanished path is recoverable via lineage (delete folding); genuinely
        # NEW paths are incrementally indexable.
        recorded_paths = {p for (p, _, _) in recorded}
        deleted = sorted(recorded_paths - current_paths)
        modified = sorted(
            f.path
            for f in current_files
            if f.path in recorded_paths
            and (f.path, f.size, f.modified_time) not in recorded
        )
        appended = [f for f in current_files if f.path not in recorded_paths]
        return appended, deleted, modified

    def validate(self) -> None:
        super().validate()
        prev = self._previous_entry()
        if not prev.relations[0].data.file_infos():
            # Without the recorded per-file inventory there is nothing to diff
            # against — surfacing this beats silently treating every current
            # file as appended (which would duplicate already-indexed rows).
            raise HyperspaceException(
                "Incremental refresh requires per-file source signatures in "
                "the previous log entry, but it records no file inventory; "
                "use mode='full' to rebuild."
            )
        appended, deleted, modified = self._diff_files()
        # A previously-folded-deleted path that RE-APPEARED is modified-in-
        # place in disguise: the index still physically holds the OLD rows
        # under that path, and the path-keyed lineage prune cannot separate
        # them from the new file's rows — folding it out would resurrect the
        # old rows, folding it in would drop the new ones.
        reappeared = sorted(
            {f.path for f in appended} & set(prev.deleted_source_files())
        )
        if modified or reappeared:
            raise HyperspaceException(
                "Incremental refresh does not support source files modified "
                f"in place (modified: {(modified + reappeared)[:3]}); "
                "use mode='full'."
            )
        if deleted and not prev.has_lineage():
            raise HyperspaceException(
                "Incremental refresh found deleted source files "
                f"(deleted: {deleted[:3]}) but the index records no lineage "
                "column to fold them through; enable "
                "hyperspace.index.lineage.enabled at build time or use "
                "mode='full'."
            )
        if not appended and not deleted:
            raise NothingToRefreshError(
                "Refresh incremental aborted as no appended source data files found."
            )

    def op(self) -> None:
        appended, _, _ = self._diff_files()
        if appended:
            config = self._builder.config_from_entry(self._previous_entry())
            sub_df = self._builder.restrict_df_to_files(
                self._source_df(), [f.path for f in appended]
            )
            self._builder.write(sub_df, config, self._index_data_path)
            # Warm handoff: readers flip onto the merged generation at the
            # log commit below; the delta files are already decoded then.
            _warm_handoff(self._index_data_path, self._previous_entry().schema_json)
        # The merge window: delta data (if any) is committed, the merged log
        # entry has not landed. A transient fault here fails the refresh
        # cleanly (transient log entry + an unreferenced version dir the next
        # action recovers past); a `hang` is the SIGKILL window between data
        # commit and log commit the crash matrix aims at.
        from ..telemetry import faults as _faults

        _faults.check("refresh.merge")

    def log_entry(self) -> LogEntry:
        entry = super().log_entry()  # content = new version dir only; fresh signature
        prev = self._previous_entry()
        from ..index.log_entry import DELETED_SOURCE_FILES_KEY, Content

        entry.content = Content.merge([prev.content, entry.content])
        _, deleted, _ = self._diff_files()
        # Re-appeared previously-deleted paths cannot reach here: validate()
        # rejects them as modified-in-place (the path-keyed lineage prune
        # could not separate the old rows still in the data from the new
        # file's), so the carried set only ever grows until a rewrite.
        carried = sorted(set(prev.deleted_source_files()) | set(deleted))
        if carried:
            entry.extra[DELETED_SOURCE_FILES_KEY] = carried
        return entry
