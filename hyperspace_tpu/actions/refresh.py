"""RefreshAction: ACTIVE → REFRESHING → ACTIVE (full rebuild).

Parity: reference `actions/RefreshAction.scala:31-86` — reconstructs the source
dataframe from the previous log entry's `Relation` (root paths / schema / format /
options) and rewrites the index into the next version directory. The new log entry
carries a fresh signature over the current source files.

Extension (north-star, absent from the v0 reference): ``mode="incremental"`` indexes
only files appended since the recorded inventory and ``optimizeIndex`` compacts — see
`actions/optimize.py`.
"""

from __future__ import annotations

from typing import Optional

from ..exceptions import HyperspaceException
from ..index.log_entry import IndexLogEntry, LogEntry
from ..telemetry.events import HyperspaceEvent, RefreshActionEvent
from . import states
from .action import Action, _recover_stable
from .create import IndexerBuilder


class RefreshAction(Action):
    def __init__(
        self,
        builder: IndexerBuilder,
        log_manager,
        index_path: str,
        index_data_path: str,
        event_logger=None,
    ):
        super().__init__(log_manager, event_logger)
        self._builder = builder
        self._index_path = index_path
        self._index_data_path = index_data_path
        self._prev: Optional[IndexLogEntry] = None
        self._df = None

    def _previous_entry(self) -> IndexLogEntry:
        if self._prev is None:
            prev = self._log_manager.get_log(self.base_id)
            if prev is None:
                raise HyperspaceException("Refresh is only supported on an existing index.")
            if prev.state in states.TRANSIENT_STATES:
                # Dead writer's orphan (killed mid-action): refresh judges the
                # latest STABLE entry; the log CAS arbitrates live races.
                prev = _recover_stable(self._log_manager, prev)
            self._prev = prev
        return self._prev

    def _source_df(self):
        if self._df is None:
            prev = self._previous_entry()
            relations = prev.relations
            if len(relations) != 1:
                raise HyperspaceException("Refresh supports indexes over a single relation.")
            self._df = self._builder.reconstruct_df(relations[0])
        return self._df

    @property
    def transient_state(self) -> str:
        return states.REFRESHING

    @property
    def final_state(self) -> str:
        return states.ACTIVE

    def validate(self) -> None:
        prev = self._previous_entry()
        if prev.state != states.ACTIVE:
            raise HyperspaceException(
                f"Refresh is only supported in {states.ACTIVE} state. "
                f"Current state: {prev.state}."
            )

    def op(self) -> None:
        config = self._builder.config_from_entry(self._previous_entry())
        self._builder.write(self._source_df(), config, self._index_data_path)

    def log_entry(self) -> LogEntry:
        # Derived fresh per phase (see CreateAction.log_entry): the end() entry must
        # inventory the files op() wrote.
        config = self._builder.config_from_entry(self._previous_entry())
        return self._builder.derive_log_entry(
            self._source_df(), config, self._index_path, self._index_data_path
        )

    def event(self, message: str) -> HyperspaceEvent:
        name = self._prev.name if self._prev else ""
        return RefreshActionEvent(index_name=name, message=message)


class RefreshIncrementalAction(RefreshAction):
    """refreshIndex(mode="incremental"): index ONLY files appended since the recorded
    source inventory, into a new version dir; the new log entry's content spans all
    version dirs and its signature covers the current source state.

    North-star extension (BASELINE.md config 5) — absent from the v0 reference
    snapshot, whose refresh is full-rebuild only (`RefreshAction.scala:76-81`).
    Deleted source files require lineage-based repair and are rejected here."""

    def _diff_files(self):
        prev = self._previous_entry()
        recorded = {
            (f.name, f.size, f.modified_time)
            for f in prev.relations[0].data.file_infos()
        }
        current_files = self._source_df().plan.relation.files
        current_paths = {f.path for f in current_files}
        # A recorded path that vanished OR was modified in place (same path, changed
        # size/mtime) invalidates the already-indexed rows — both require full
        # rebuild. Only genuinely NEW paths are incrementally indexable.
        recorded_paths = {p for (p, _, _) in recorded}
        deleted = sorted(recorded_paths - current_paths)
        modified = sorted(
            f.path
            for f in current_files
            if f.path in recorded_paths
            and (f.path, f.size, f.modified_time) not in recorded
        )
        appended = [f for f in current_files if f.path not in recorded_paths]
        return appended, deleted, modified

    def validate(self) -> None:
        super().validate()
        appended, deleted, modified = self._diff_files()
        if deleted or modified:
            raise HyperspaceException(
                "Incremental refresh does not support deleted or modified source "
                f"files (deleted: {deleted[:3]}, modified: {modified[:3]}); "
                "use mode='full'."
            )
        if not appended:
            raise HyperspaceException(
                "Refresh incremental aborted as no appended source data files found."
            )

    def op(self) -> None:
        config = self._builder.config_from_entry(self._previous_entry())
        appended, _, _ = self._diff_files()
        sub_df = self._builder.restrict_df_to_files(
            self._source_df(), [f.path for f in appended]
        )
        self._builder.write(sub_df, config, self._index_data_path)

    def log_entry(self) -> LogEntry:
        entry = super().log_entry()  # content = new version dir only; fresh signature
        prev = self._previous_entry()
        from ..index.log_entry import Content

        entry.content = Content.merge([prev.content, entry.content])
        return entry
