"""CreateAction: (none/DOESNOTEXIST) → CREATING → ACTIVE.

Parity: reference `actions/CreateAction.scala:30-82` + `actions/CreateActionBase.scala`.
Validation: the source plan must be a single linear relation, columns must resolve
against the dataframe schema, and no live index of the same name may exist. The heavy
`op()` (bucketed build) and log-entry derivation (signature + file inventory) are
engine concerns, injected as an ``IndexerBuilder`` so the FSM is testable against fakes
— the same seam the reference tests exploit with mocked log managers.
"""

from __future__ import annotations

from typing import Optional

from ..exceptions import HyperspaceException
from ..index.index_config import IndexConfig
from ..index.log_entry import IndexLogEntry, LogEntry
from ..index.log_manager import IndexLogManager
from ..telemetry.events import AppInfo, CreateActionEvent, HyperspaceEvent
from . import states
from .action import Action


class IndexerBuilder:
    """Engine seam used by Create/Refresh: validates, writes index data, derives the
    metadata record (reference `CreateActionBase.scala:33-203`)."""

    def validate_source(self, df, index_config: IndexConfig) -> None:
        """Raise if the plan is not indexable or columns don't resolve."""
        raise NotImplementedError

    def write(self, df, index_config: IndexConfig, index_data_path: str) -> None:
        """The bucketed build: partition by indexed cols, sort, write index files."""
        raise NotImplementedError

    def derive_log_entry(
        self, df, index_config: IndexConfig, index_path: str, index_data_path: str
    ) -> IndexLogEntry:
        """Build the IndexLogEntry: signature over source files, relations inventory,
        index content tree (reference `getIndexLogEntry`, `CreateActionBase.scala:41-86`)."""
        raise NotImplementedError

    def reconstruct_df(self, relation):
        """Rebuild a dataframe from a logged Relation (reference `RefreshAction.scala:44-56`)."""
        raise NotImplementedError

    def config_from_entry(self, entry: IndexLogEntry):
        """Reconstruct the index spec from a log entry (used by refresh)."""
        from ..index.index_config import IndexConfig

        return IndexConfig(entry.name, entry.indexed_columns, entry.included_columns)


class CreateAction(Action):
    def __init__(
        self,
        df,
        index_config: IndexConfig,
        builder: IndexerBuilder,
        log_manager: IndexLogManager,
        index_path: str,
        index_data_path: str,
        event_logger=None,
    ):
        super().__init__(log_manager, event_logger)
        self._df = df
        self._config = index_config
        self._builder = builder
        self._index_path = index_path
        self._index_data_path = index_data_path

    @property
    def transient_state(self) -> str:
        return states.CREATING

    @property
    def final_state(self) -> str:
        return states.ACTIVE

    def validate(self) -> None:
        # Existing live index of the same name blocks creation
        # (reference `CreateAction.scala:44-64`). A latest entry stuck in a
        # TRANSIENT state is a dead writer's orphan (a SIGKILLed build):
        # creation judges the latest STABLE state instead — effectively the
        # cancel() rollback applied implicitly, with the log CAS arbitrating
        # should the "dead" writer still be alive (`actions/action._recover_stable`).
        latest = self._log_manager.get_latest_log()
        if latest is not None and latest.state in states.TRANSIENT_STATES:
            from .action import _recover_stable

            # None = nothing durable was ever committed: create proceeds.
            latest = _recover_stable(self._log_manager, latest, missing_ok=True)
        if latest is not None and latest.state != states.DOESNOTEXIST:
            raise HyperspaceException(
                f"Another Index with name {self._config.index_name} already exists."
            )
        self._builder.validate_source(self._df, self._config)

    def op(self) -> None:
        self._builder.write(self._df, self._config, self._index_data_path)

    def log_entry(self) -> LogEntry:
        # Derived fresh per phase — the end() entry must inventory the index files
        # that op() wrote, so it cannot be cached from begin().
        return self._builder.derive_log_entry(
            self._df, self._config, self._index_path, self._index_data_path
        )

    def event(self, message: str) -> HyperspaceEvent:
        return CreateActionEvent(index_name=self._config.index_name, message=message)
