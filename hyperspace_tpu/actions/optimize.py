"""OptimizeAction: compact an index's small per-bucket files.

North-star extension (BASELINE.md config 5) — absent from the v0 reference snapshot.
After incremental refreshes an index's buckets are spread over many small files (one
per version dir); optimize merges them: ACTIVE → OPTIMIZING → ACTIVE, new version dir
holds one merged, re-sorted file per optimized bucket.

Modes: "quick" merges only files below `hyperspace.index.optimize.fileSizeThreshold`
(default 256 MB); "full" merges everything.
"""

from __future__ import annotations

import os
import re
from collections import defaultdict
from typing import Dict, List, Optional

from ..exceptions import HyperspaceException
from ..index.log_entry import Content, FileInfo, IndexLogEntry, LogEntry
from ..telemetry.events import HyperspaceEvent, OptimizeActionEvent
from . import states
from .action import Action, _recover_stable

OPTIMIZE_FILE_SIZE_THRESHOLD = "hyperspace.index.optimize.fileSizeThreshold"
OPTIMIZE_FILE_SIZE_THRESHOLD_DEFAULT = 256 * 1024 * 1024
OPTIMIZE_MODES = ("quick", "full")

_BUCKET_RE = re.compile(r"part-(\d+)")


class OptimizeAction(Action):
    def __init__(
        self,
        builder,
        session,
        log_manager,
        index_path: str,
        index_data_path: str,
        mode: str = "quick",
        event_logger=None,
    ):
        super().__init__(log_manager, event_logger)
        if mode not in OPTIMIZE_MODES:
            raise HyperspaceException(
                f"Unsupported optimize mode '{mode}'; supported: {OPTIMIZE_MODES}."
            )
        self._builder = builder
        self._session = session
        self._index_data_path = index_data_path
        self._mode = mode
        self._prev: Optional[IndexLogEntry] = None

    @property
    def transient_state(self) -> str:
        return states.OPTIMIZING

    @property
    def final_state(self) -> str:
        return states.ACTIVE

    def _previous_entry(self) -> IndexLogEntry:
        if self._prev is None:
            prev = self._log_manager.get_log(self.base_id)
            if prev is None:
                raise HyperspaceException("Optimize is only supported on an existing index.")
            if prev.state in states.TRANSIENT_STATES:
                # A dead writer's orphan (killed mid-action): fall back to the
                # latest STABLE entry — the operation-log CAS arbitrates if
                # the "dead" writer turns out to be alive (`actions/action.py`).
                prev = _recover_stable(self._log_manager, prev)
            self._prev = prev
        return self._prev

    def _partition_files(self):
        """Split content files into (to_merge per bucket, untouched)."""
        prev = self._previous_entry()
        threshold = int(
            self._session.conf.get(
                OPTIMIZE_FILE_SIZE_THRESHOLD, str(OPTIMIZE_FILE_SIZE_THRESHOLD_DEFAULT)
            )
        )
        per_bucket: Dict[int, List[FileInfo]] = defaultdict(list)
        untouched: List[FileInfo] = []
        for f in prev.content.file_infos():
            m = _BUCKET_RE.search(os.path.basename(f.name))
            if m is None:
                untouched.append(f)
                continue
            if self._mode == "full" or f.size < threshold:
                per_bucket[int(m.group(1))].append(f)
            else:
                untouched.append(f)
        # A bucket with a single (small) file gains nothing from merging.
        for b in [b for b, fs in per_bucket.items() if len(fs) < 2]:
            untouched.extend(per_bucket.pop(b))
        return per_bucket, untouched

    def validate(self) -> None:
        prev = self._previous_entry()
        if prev.state != states.ACTIVE:
            raise HyperspaceException(
                f"Optimize is only supported in {states.ACTIVE} state."
            )
        if prev.kind != "CoveringIndex":
            # Sketch files are tiny; compacting a DataSkippingIndex is just a full
            # refresh. Rejecting here (pre-begin) leaves the index ACTIVE.
            raise HyperspaceException(
                f"Optimize is only supported for CoveringIndex (got {prev.kind}); "
                "use refresh_index(mode='full') instead."
            )
        per_bucket, _ = self._partition_files()
        if not per_bucket:
            raise HyperspaceException(
                "Optimize aborted as no optimizable index files found "
                f"(mode={self._mode})."
            )

    def op(self) -> None:
        from ..engine import io as engine_io
        from ..engine.table import Table
        from ..index.staging import stage_commit
        from ..ops.partition import bucketize_table
        import numpy as np

        prev = self._previous_entry()
        per_bucket, _ = self._partition_files()
        # Staged commit (crash-safe, same contract as create/refresh): the
        # compacted files land in `index_data_path` via one atomic rename.
        with stage_commit(self._index_data_path) as stage:
            os.makedirs(stage, exist_ok=True)
            for b, files in sorted(per_bucket.items()):
                merged = engine_io.read_files([f.name for f in files], "parquet")
                # Re-sort within the bucket by the indexed columns (same contract as the
                # original bucketed write).
                sorted_t, _ = bucketize_table(merged, prev.indexed_columns, prev.num_buckets)
                # Same bounded row-group layout as the original bucketed write, so
                # compacted files stay prunable by the scan pushdown's zone maps.
                engine_io.write_parquet(
                    sorted_t,
                    os.path.join(stage, f"part-{b:05d}.parquet"),
                    row_group_rows=engine_io.index_row_group_rows(),
                )

    def log_entry(self) -> LogEntry:
        import copy

        prev = self._previous_entry()
        entry = copy.deepcopy(prev)
        _, untouched = self._partition_files()
        merged_content = Content.from_directory(self._index_data_path, self._session.fs)
        entry.content = Content.merge(
            [Content.from_file_infos(untouched), merged_content]
        )
        return entry

    def event(self, message: str) -> HyperspaceEvent:
        name = self._prev.name if self._prev else ""
        return OptimizeActionEvent(index_name=name, message=message)
