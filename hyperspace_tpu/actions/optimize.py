"""OptimizeAction: compact an index's small per-bucket files.

North-star extension (BASELINE.md config 5) — absent from the v0 reference snapshot.
After incremental refreshes an index's buckets are spread over many small files (one
per version dir); optimize merges them: ACTIVE → OPTIMIZING → ACTIVE, new version dir
holds one merged, re-sorted file per optimized bucket.

Modes: "quick" merges only files below `hyperspace.index.optimize.fileSizeThreshold`
(default 256 MB); "full" merges everything.
"""

from __future__ import annotations

import os
import re
from collections import defaultdict
from typing import Dict, List, Optional

from ..exceptions import HyperspaceException
from ..index.log_entry import Content, FileInfo, IndexLogEntry, LogEntry
from ..telemetry.events import HyperspaceEvent, OptimizeActionEvent
from . import states
from .action import Action, _recover_stable

OPTIMIZE_FILE_SIZE_THRESHOLD = "hyperspace.index.optimize.fileSizeThreshold"
OPTIMIZE_FILE_SIZE_THRESHOLD_DEFAULT = 256 * 1024 * 1024
OPTIMIZE_MODES = ("quick", "full")

#: Background-compaction trigger: an index "needs compaction" once any bucket
#: is spread over this many delta files (or it carries a folded delete set).
ENV_COMPACT_TRIGGER_FILES = "HYPERSPACE_COMPACT_TRIGGER_FILES"
_DEFAULT_COMPACT_TRIGGER_FILES = 2

_BUCKET_RE = re.compile(r"part-(\d+)")
_VERSION_RE = re.compile(r"v__=(\d+)")


def _compact_trigger_files() -> int:
    try:
        return max(
            2,
            int(
                os.environ.get(ENV_COMPACT_TRIGGER_FILES, "")
                or _DEFAULT_COMPACT_TRIGGER_FILES
            ),
        )
    except ValueError:
        return _DEFAULT_COMPACT_TRIGGER_FILES


def _version_rank(path: str) -> int:
    """Numeric index-version rank of an index data file path (`v__=N` path
    component). STRING order would misplace v__=10 before v__=2, so delta
    files must merge in numeric version order for the compacted row order to
    reproduce a from-scratch rebuild's."""
    m = _VERSION_RE.search(path)
    return int(m.group(1)) if m else -1


def needs_compaction(entry: IndexLogEntry) -> bool:
    """Whether background compaction should run on `entry`: it carries a
    folded delete set (rows awaiting physical removal), or incremental
    refreshes have spread some bucket over ≥ ``HYPERSPACE_COMPACT_TRIGGER_FILES``
    delta files. The serving loop's batch lane polls this after refreshes
    (docs/reliability.md "Live tables")."""
    if entry.kind != "CoveringIndex":
        return False
    if entry.deleted_source_files():
        return True
    from collections import Counter as _Counter

    per_bucket = _Counter()
    for f in entry.content.file_infos():
        m = _BUCKET_RE.search(os.path.basename(f.name))
        if m is not None:
            per_bucket[int(m.group(1))] += 1
    return bool(per_bucket) and max(per_bucket.values()) >= _compact_trigger_files()


class OptimizeAction(Action):
    def __init__(
        self,
        builder,
        session,
        log_manager,
        index_path: str,
        index_data_path: str,
        mode: str = "quick",
        event_logger=None,
    ):
        super().__init__(log_manager, event_logger)
        if mode not in OPTIMIZE_MODES:
            raise HyperspaceException(
                f"Unsupported optimize mode '{mode}'; supported: {OPTIMIZE_MODES}."
            )
        self._builder = builder
        self._session = session
        self._index_data_path = index_data_path
        self._mode = mode
        self._prev: Optional[IndexLogEntry] = None

    @property
    def transient_state(self) -> str:
        return states.OPTIMIZING

    @property
    def final_state(self) -> str:
        return states.ACTIVE

    def _previous_entry(self) -> IndexLogEntry:
        if self._prev is None:
            prev = self._log_manager.get_log(self.base_id)
            if prev is None:
                raise HyperspaceException("Optimize is only supported on an existing index.")
            if prev.state in states.TRANSIENT_STATES:
                # A dead writer's orphan (killed mid-action): fall back to the
                # latest STABLE entry — the operation-log CAS arbitrates if
                # the "dead" writer turns out to be alive (`actions/action.py`).
                prev = _recover_stable(self._log_manager, prev)
            self._prev = prev
        return self._prev

    def _partition_files(self):
        """Split content files into (to_merge per bucket, untouched).

        With a folded delete set on the entry every `part-<bucket>` file is
        rewritten regardless of mode/threshold (singletons included): clearing
        ``deletedSourceFiles`` from the log is only sound once no data file
        can still hold a deleted file's rows."""
        prev = self._previous_entry()
        folding = bool(prev.deleted_source_files())
        threshold = int(
            self._session.conf.get(
                OPTIMIZE_FILE_SIZE_THRESHOLD, str(OPTIMIZE_FILE_SIZE_THRESHOLD_DEFAULT)
            )
        )
        per_bucket: Dict[int, List[FileInfo]] = defaultdict(list)
        untouched: List[FileInfo] = []
        for f in prev.content.file_infos():
            m = _BUCKET_RE.search(os.path.basename(f.name))
            if m is None:
                untouched.append(f)
                continue
            if folding or self._mode == "full" or f.size < threshold:
                per_bucket[int(m.group(1))].append(f)
            else:
                untouched.append(f)
        if not folding:
            # A bucket with a single (small) file gains nothing from merging.
            for b in [b for b, fs in per_bucket.items() if len(fs) < 2]:
                untouched.extend(per_bucket.pop(b))
        return per_bucket, untouched

    def validate(self) -> None:
        prev = self._previous_entry()
        if prev.state != states.ACTIVE:
            raise HyperspaceException(
                f"Optimize is only supported in {states.ACTIVE} state."
            )
        if prev.kind != "CoveringIndex":
            # Sketch files are tiny; compacting a DataSkippingIndex is just a full
            # refresh. Rejecting here (pre-begin) leaves the index ACTIVE.
            raise HyperspaceException(
                f"Optimize is only supported for CoveringIndex (got {prev.kind}); "
                "use refresh_index(mode='full') instead."
            )
        per_bucket, _ = self._partition_files()
        if not per_bucket:
            raise HyperspaceException(
                "Optimize aborted as no optimizable index files found "
                f"(mode={self._mode})."
            )

    def op(self) -> None:
        import numpy as np

        from ..config import IndexConstants
        from ..engine import io as engine_io
        from ..engine.table import Table
        from ..index.staging import stage_commit
        from ..ops.partition import host_sort_perm
        from ..telemetry import faults as _faults
        from .. import resilience

        from ..engine.schema import Schema

        prev = self._previous_entry()
        per_bucket, _ = self._partition_files()
        folded = set(prev.deleted_source_files())
        # Explicit column list = the index schema. A bare read of a file under
        # a `v__=N` version dir sprouts a hive-inferred `v__` partition column
        # that would be WRITTEN into the compacted file (diverging from a
        # from-scratch rebuild's bytes and breaking later dataset-API reads).
        index_cols = list(Schema.from_json_string(prev.schema_json).names)
        lineage_col = None
        if prev.has_lineage():
            target = IndexConstants.DATA_FILE_NAME_COLUMN.lower()
            lineage_col = next(n for n in index_cols if n.lower() == target)
        # Canonical tie order (the PR-10 stable (bucket, keys…, source row id)
        # contract): a from-scratch rebuild reads source files in path-sorted
        # order, so equal-key rows land in (source file rank, intra-file row)
        # order. Each version dir's rows already carry key-sorted,
        # source-order-tied rows for ITS file subset; with lineage the merged
        # rows re-rank by the CURRENT inventory's path order before the stable
        # key sort, reproducing the rebuild's byte order exactly. Without
        # lineage the merge falls back to numeric version order — identical
        # whenever appended files sort after earlier ones (the append-only
        # naming pattern).
        src_rank = {
            f.name: i for i, f in enumerate(prev.relations[0].data.file_infos())
        }

        def canonical_rows(files) -> Table:
            parts = [
                engine_io.read_files([f.name], "parquet", index_cols)
                for f in sorted(
                    files,
                    key=lambda f: (_version_rank(f.name), os.path.basename(f.name)),
                )
            ]
            merged = parts[0] if len(parts) == 1 else Table.concat(parts)
            if lineage_col is None:
                return merged
            col = merged.column(lineage_col)
            keep = np.arange(merged.num_rows)
            if folded:
                # Delete folding's physical half: rows of vanished source
                # files leave the data here, and `log_entry` clears the set.
                dropped_dict = np.isin(col.dictionary, sorted(folded))
                keep = keep[~dropped_dict[col.data]]
            dict_ranks = np.array(
                [src_rank.get(v, len(src_rank)) for v in col.dictionary],
                dtype=np.int64,
            )
            order = np.argsort(dict_ranks[col.data[keep]], kind="stable")
            return merged.take(keep[order])

        # Staged commit (crash-safe, same contract as create/refresh): the
        # compacted files land in `index_data_path` via one atomic rename.
        with stage_commit(self._index_data_path) as stage:
            os.makedirs(stage, exist_ok=True)
            for b, files in sorted(per_bucket.items()):
                # Batch-lane citizenship: a deadline/yield boundary per bucket
                # (the serving scheduler's cooperative gate pauses here while
                # interactive queries are pending).
                resilience.check_deadline("optimize.bucket")
                merged = canonical_rows(files)
                if merged.num_rows == 0:
                    continue  # every row deleted: no file, like the builder
                # Re-sort within the bucket by the indexed columns (same
                # contract as the original bucketed write; stable, so the
                # canonical tie order holds). Every row already belongs to
                # bucket `b`, so this is a pure key sort — `host_sort_perm`
                # with a constant bucket lane, the exact composite the build
                # paths share. Re-hashing through `bucketize_table` here would
                # dispatch one differently-shaped device program PER BUCKET
                # (a compile storm that made compaction ~100x slower).
                perm = host_sort_perm(
                    np.zeros(merged.num_rows, dtype=np.int64),
                    [merged.column(c) for c in prev.indexed_columns],
                    prev.num_buckets,
                )
                sorted_t = merged.take(perm)
                # Same bounded row-group layout as the original bucketed write, so
                # compacted files stay prunable by the scan pushdown's zone maps.
                engine_io.write_parquet(
                    sorted_t,
                    os.path.join(stage, f"part-{b:05d}.parquet"),
                    row_group_rows=engine_io.index_row_group_rows(),
                )
            # The compaction commit window: every compacted bucket is staged,
            # the atomic rename has not happened. A transient fault aborts
            # cleanly (staging dir deleted, log untouched); a `hang` is the
            # SIGKILL-mid-compaction window of the crash matrix.
            _faults.check("compact.commit")
        # Warm handoff (same contract as refresh): the compacted generation
        # is decoded into the scan cache before the log commit flips readers.
        from .refresh import _warm_handoff

        _warm_handoff(self._index_data_path, prev.schema_json)

    def log_entry(self) -> LogEntry:
        import copy

        from ..index.log_entry import DELETED_SOURCE_FILES_KEY

        prev = self._previous_entry()
        entry = copy.deepcopy(prev)
        _, untouched = self._partition_files()
        merged_content = Content.from_directory(self._index_data_path, self._session.fs)
        entry.content = Content.merge(
            [Content.from_file_infos(untouched), merged_content]
        )
        if prev.deleted_source_files():
            # Folding mode rewrote EVERY part file (`_partition_files`), so no
            # data file can still hold a deleted file's rows.
            entry.extra.pop(DELETED_SOURCE_FILES_KEY, None)
        return entry

    def event(self, message: str) -> HyperspaceEvent:
        name = self._prev.name if self._prev else ""
        return OptimizeActionEvent(index_name=name, message=message)
