"""The Hyperspace facade + session enable/disable implicits.

Parity: reference `Hyperspace.scala:24-133` (user-facing CRUD + explain; one manager
per session via a cached context) and `package.scala:34-74` (`enableHyperspace`
appends JoinIndexRule :: FilterIndexRule — join first, deliberately: join indexes
typically beat filter indexes — `disableHyperspace` removes them,
`isHyperspaceEnabled` checks).
"""

from __future__ import annotations

from typing import Optional

from .engine.session import DataFrame, HyperspaceSession
from .engine.table import Table
from .index.collection_manager import CachingIndexCollectionManager, IndexManager
from .index.index_config import IndexConfig
from .rules.data_skipping_rule import DataSkippingFilterRule
from .rules.filter_index_rule import FilterIndexRule
from .rules.join_index_rule import JoinIndexRule

_ALL_RULES = (JoinIndexRule, FilterIndexRule, DataSkippingFilterRule)

_MANAGER_ATTR = "_hyperspace_index_manager"


def _index_manager_for(session: HyperspaceSession) -> IndexManager:
    """Per-session cached manager (the reference's HyperspaceContext,
    `Hyperspace.scala:108-133`)."""
    mgr = getattr(session, _MANAGER_ATTR, None)
    if mgr is None:
        mgr = CachingIndexCollectionManager(session)
        setattr(session, _MANAGER_ATTR, mgr)
    return mgr


class Hyperspace:
    def __init__(self, session: Optional[HyperspaceSession] = None):
        self._session = session or HyperspaceSession.active()
        self._manager = _index_manager_for(self._session)

    # -- index CRUD (reference Hyperspace.scala:40-104) ---------------------

    def create_index(self, df: DataFrame, index_config: IndexConfig) -> None:
        from . import resilience
        from .telemetry import tracing

        with resilience.query_scope("build:create_index"):
            with tracing.query_span(
                "build:create_index", index_name=index_config.index_name
            ):
                self._manager.create(df, index_config)

    def delete_index(self, index_name: str) -> None:
        self._manager.delete(index_name)

    def restore_index(self, index_name: str) -> None:
        self._manager.restore(index_name)

    def vacuum_index(self, index_name: str) -> None:
        self._manager.vacuum(index_name)

    def refresh_index(self, index_name: str, mode: Optional[str] = None) -> None:
        """mode="full": rebuild from scratch (reference behavior).
        mode="incremental": index only appended source files, fold deletes
        through lineage (extension; docs/reliability.md "Live tables").
        mode="auto": incremental when its preconditions hold, full otherwise,
        no-op when already fresh.
        mode=None defers to ``HYPERSPACE_REFRESH_MODE`` (default "full").

        Runs as a BATCH-lane citizen: under a live `serve.QueryServer` the
        cooperative yield gate deprioritizes the refresh whenever interactive
        queries are pending, so refreshes never dent interactive p99."""
        import os

        from . import resilience
        from .telemetry import tracing

        mode = mode or os.environ.get("HYPERSPACE_REFRESH_MODE") or "full"
        with resilience.query_scope("build:refresh_index"):
            with resilience.lane_scope("batch"):
                with tracing.query_span(
                    "build:refresh_index", index_name=index_name, mode=mode
                ):
                    self._manager.refresh(index_name, mode)

    def optimize_index(self, index_name: str, mode: str = "quick") -> None:
        """Compact small per-bucket index files (extension; quick/full modes).
        Physically removes rows folded as deleted by incremental refreshes and
        clears the entry's delete set. Like refresh, runs as a BATCH-lane
        citizen under the serving scheduler's yield gate."""
        from . import resilience
        from .telemetry import tracing

        with resilience.query_scope("build:optimize_index"):
            with resilience.lane_scope("batch"):
                with tracing.query_span(
                    "build:optimize_index", index_name=index_name, mode=mode
                ):
                    self._manager.optimize(index_name, mode)

    def cancel(self, index_name: str) -> None:
        self._manager.cancel(index_name)

    def indexes(self) -> Table:
        return self._manager.indexes()

    def server(
        self,
        max_concurrent: Optional[int] = None,
        queue_depth: Optional[int] = None,
        tenant_budget: Optional[int] = None,
    ):
        """A multi-tenant `serve.QueryServer` front door over this session's
        engine process (docs/serving.md): bounded workers, priority lanes,
        per-tenant admission control, and single-flight shared caches.

            with hs.server() as srv:
                fut = srv.submit(lambda: df.collect(), tenant="alice",
                                 lane="interactive")

        ``HYPERSPACE_SERVING=0`` makes every submission execute inline and
        serially — the exact single-caller engine."""
        from .serve import QueryServer

        return QueryServer(
            max_concurrent=max_concurrent,
            queue_depth=queue_depth,
            tenant_budget=tenant_budget,
        )

    def explain(
        self,
        df: DataFrame,
        verbose: bool = False,
        redirect=None,
        analyze: bool = False,
    ) -> Optional[str]:
        """Plan diff with indexes on vs off (reference `Hyperspace.scala:101-104`).
        Prints unless `redirect` is given (a callable receiving the string).
        With ``analyze=True`` the query EXECUTES under a trace and the chosen
        plan renders annotated with measured wall times, row counts, cache
        hits, and the rule decisions that shaped it (`plananalysis.analyze`;
        same output as `df.explain(analyze=True)`)."""
        if analyze:
            from .plananalysis.analyze import explain_analyze_string

            s = explain_analyze_string(df)
        else:
            from .plananalysis.plan_analyzer import explain_string

            s = explain_string(df, self._session, self._manager.indexes(), verbose)
        if redirect is not None:
            redirect(s)
            return None
        print(s)
        return None


# ---------------------------------------------------------------------------
# Session implicits (reference package.scala:34-74)
# ---------------------------------------------------------------------------


def enable_hyperspace(session: HyperspaceSession) -> HyperspaceSession:
    """Plug the rewrite rules into the optimizer: JoinIndexRule first, then
    FilterIndexRule (ordering is deliberate, reference `package.scala:24-33`), then
    the data-skipping file-pruning rule (extension) for scans the covering rules
    left in place."""
    if not is_hyperspace_enabled(session):
        session.extra_optimizations = session.extra_optimizations + [
            JoinIndexRule(),
            FilterIndexRule(),
            DataSkippingFilterRule(),
        ]
    return session


def disable_hyperspace(session: HyperspaceSession) -> HyperspaceSession:
    session.extra_optimizations = [
        r for r in session.extra_optimizations if not isinstance(r, _ALL_RULES)
    ]
    return session


def is_hyperspace_enabled(session: HyperspaceSession) -> bool:
    return any(isinstance(r, _ALL_RULES) for r in session.extra_optimizations)
