"""Table-level distributed operations: the bridge between host `Table`s and the
mesh kernels in `distributed.py`.

These are the entry points the REAL build and query paths call (not just tests):

- `distributed_bucketize_table` — the index build's shuffle. The TPU-native
  analogue of the reference's cluster-wide `repartition(numBuckets, cols)` +
  bucketed write (`CreateActionBase.scala:119-140`): rows leave the host as
  row-sharded blocks, ride a two-pass `lax.all_to_all` to their bucket's device,
  and come back grouped by bucket and sorted within bucket. Same contract as the
  single-device `ops.partition.bucketize_table` (identical hash AND identical
  stable tie order → byte-identical index files).
- `distributed_exchange_table` — the general join's ShuffleExchange. Both sides
  exchanged with the same key hash are co-partitioned, so the merge join after it
  needs no further communication.
- `distributed_bucketed_join_pairs` — the co-bucketed sort-merge join probe,
  sharded over the mesh's bucket axis with ZERO collectives (the whole point of
  the covering-index layout, reference `JoinIndexRule.scala:137-162`). Same
  contract as `ops.bucket_join.bucketed_merge_join_pairs`.

Compile contract: every shape that reaches a device program here is quantized
on the `mesh.quantize_cap`/`mesh.quantized_rows` pow2 grid — the hash inputs
are padded BEFORE hashing (so `hashing.combined_hash`/`hashing.key64` trace
one shape per workload class, not one per table size — the exact failure mode
that hung the r05 TPU bench for 2400 s inside `ops/hashing.bucket_id`), and
the exchange/probe capacities are floored at the mesh row quantum. Each
`parallel.*` program compiles exactly once per process per class, asserted by
`tests/test_mesh_compile.py` and reported in `bench_detail.mesh`.
"""

from __future__ import annotations

from functools import lru_cache
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..engine.table import Table
from ..ops.hashing import _SEED1, combined_hash_u32, key64
from ..telemetry.compile_log import observed_jit as _observed_jit
from .distributed import distributed_bucketize, distributed_bucketize_coded
from .mesh import BUCKET_AXIS, quantize_cap, quantized_rows, row_sharding
from .shim import shard_map

_PAD = np.iinfo(np.int64).max


def _bucket_lane_dtype(num_buckets: int):
    """Smallest signed width carrying bucket ids [0, num_buckets)."""
    if num_buckets <= 127:
        return np.int8
    if num_buckets <= 32767:
        return np.int16
    return np.int32


def _coded_rowid_dtype(n_pad_total: int):
    """int32 row ids whenever the padded global row count fits (it always
    does at realistic per-host scales; the int64 fallback keeps the contract
    total)."""
    return np.int32 if n_pad_total <= np.iinfo(np.int32).max else np.int64


def _record_coded_stage(n_rows: int, flat_lanes, coded_lanes, packed_spec=None) -> None:
    """Encoded-vs-flat ledger entry for one exchange's wire lanes: what the
    flat itemsizes would have staged vs what the narrow lanes stage. Lanes
    marked in `packed_spec` (aligned with `coded_lanes`) cross bit-packed —
    their staged cost is true bits-on-the-wire, surfaced in the ledger's
    `packed` tier."""
    from ..telemetry import device_observatory as _devobs

    flat = sum(n_rows * int(np.dtype(d).itemsize) for d in flat_lanes)
    staged = 0
    packed = 0
    for i, a in enumerate(coded_lanes):
        bits = packed_spec[i][0] if packed_spec is not None else 0
        if bits:
            lane_bytes = -(-n_rows * bits // 8)  # ceil(n_rows*bits/8)
            packed += lane_bytes
        else:
            lane_bytes = n_rows * int(a.dtype.itemsize)
        staged += lane_bytes
    _devobs.record_encoded_stage(
        "mesh_exchange", flat, staged, packed_bytes=packed if packed else None
    )


def _packed_wire_spec(
    num_buckets: int, bucket_np, n_pad_total: int, rowid_p, key_pairs=()
):
    """Per-lane (bits, bias) mesh-wire spec, ordered (bucket, valid,
    rowid, *extra payload/keys) — empty when the packed layer is off. A lane
    packs only when its wire class genuinely beats its narrow itemsize (an
    int8 bucket lane needing 6 bits stays int8; an int32 row-id lane under
    65537 padded rows drops to 16 bits). `key_pairs` is (column, staged lane)
    per sort key; string keys within a sub-byte class pack biased by 1 so the
    null code -1 lands on the reserved field value 0."""
    from ..engine.packed_codes import (
        bits_for_cardinality,
        packed_codes_enabled,
        wire_bits_for_range,
    )

    if not packed_codes_enabled():
        return ()

    def lane(n_values, arr, bias=0):
        bits = wire_bits_for_range(n_values)
        if bits is None or bits >= 8 * int(arr.dtype.itemsize):
            return (0, 0)
        return (bits, bias)

    spec = [
        lane(num_buckets, bucket_np),
        (1, 0),  # validity: int8 {0, 1} -> 1 bit
        lane(n_pad_total, rowid_p),
    ]
    for col, staged in key_pairs:
        bits = None
        if getattr(col, "is_string", False) and col.dictionary is not None:
            if staged.dtype in (np.int8, np.int16):  # actually narrowed
                bits = bits_for_cardinality(len(col.dictionary))
        spec.append((bits, 1) if bits else (0, 0))
    return tuple(spec)


def _pad_rows(arr: np.ndarray, pad: int, fill=0) -> np.ndarray:
    if pad == 0:
        return arr
    return np.concatenate([arr, np.full(pad, fill, dtype=arr.dtype)])


def _sort_key_arrays(
    table: Table, columns: Sequence[str], pad: int, narrow: bool = False
) -> List[np.ndarray]:
    from ..engine.encoded_device import narrow_codes, narrowable

    out = []
    for c in columns:
        col = table.column(c)
        a = col.data
        if narrow and narrowable(col):
            # Code-space wire lane: narrowing preserves code VALUES, so the
            # receive-side (bucket, keys..., row) sort orders identically.
            a = narrow_codes(col)
        if a.dtype == np.bool_:
            a = a.astype(np.int32)
        out.append(_pad_rows(a, pad))
    return out


def _padded_hash_inputs(cols, pad: int):
    """Device inputs for the fused hash programs, padded to the quantized row
    count BEFORE hashing: the hash is elementwise, so padding changes nothing
    for the real rows, and the program traces ONE shape per pow2 class
    instead of one per exact table size. String columns ride their dictionary
    codes (pad code 0 = a valid in-range index; the pad rows are dropped by
    the exchange's validity lane anyway), narrowed to the dictionary's width
    when the encoded-device path is on — the hash gathers dh_table[codes],
    so the hash values are identical from narrow lanes."""
    from ..engine.encoded_device import narrow_codes, narrowable

    return [
        jnp.asarray(
            _pad_rows(narrow_codes(c) if narrowable(c) else c.data, pad)
        )
        for c in cols
    ]


def _gather_valid_perm(bucket, valid, rowid) -> Tuple[np.ndarray, np.ndarray]:
    """Host-gather an exchange result into (permutation, bucket ids of valid rows).

    Device d's block holds its bucket range with valid rows first, sorted by
    (bucket, keys...); blocks are in device order, so the concatenation is globally
    grouped by bucket."""
    valid_h = np.asarray(valid).reshape(-1).astype(bool)
    perm = np.asarray(rowid).reshape(-1)[valid_h]
    bucket_v = np.asarray(bucket).reshape(-1)[valid_h]
    return perm, bucket_v


def distributed_bucketize_table(
    mesh: Mesh, table: Table, bucket_columns: Sequence[str], num_buckets: int
) -> Tuple[Table, np.ndarray]:
    """Mesh-wide hash-partition + in-bucket sort; drop-in for `bucketize_table`.

    The exchange moves (hash, row id, sort keys) over ICI; the permutation comes
    back to the host, which materializes the reordered table for the bucketed
    parquet write (index files are host I/O regardless of where the shuffle ran).
    Bucket assignment is identical to the single-device path (h1 % num_buckets over
    the same column hash) AND the within-bucket order is the same canonical
    stable (bucket, keys..., original row) order, so the two paths produce
    BYTE-IDENTICAL index files — `HYPERSPACE_DISTRIBUTED=0` is an exact
    fallback, pinned by the on/off oracles in tests/test_mesh_compile.py."""
    n_dev = mesh.devices.size
    n = table.num_rows
    cols = [table.column(c) for c in bucket_columns]

    n_pad_total = quantized_rows(n, n_dev)
    pad = n_pad_total - n
    arrs_p = _padded_hash_inputs(cols, pad)
    h1_np = np.asarray(combined_hash_u32(cols, arrs_p, _SEED1))

    sh = row_sharding(mesh)

    def put(x):
        return jax.device_put(jnp.asarray(x), sh)

    from ..engine.encoded_device import encoded_device_enabled

    if encoded_device_enabled():
        # Code-space wire lanes: the narrow (h1 % num_buckets) lane replaces
        # the uint32 hash, validity rides int8, row ids int32 when the
        # padded count fits, and string sort keys travel as narrow codes.
        # Every lane carries the SAME VALUES as the flat path, so the
        # exchange permutation — and the index files — are byte-identical;
        # only `parallel.exchange.bytes_moved` shrinks.
        bucket_np = (h1_np % np.uint32(num_buckets)).astype(
            _bucket_lane_dtype(num_buckets)
        )
        valid_p = np.ones(n + pad, np.int8)
        valid_p[n:] = 0
        rowid_p = _pad_rows(np.arange(n, dtype=_coded_rowid_dtype(n_pad_total)), pad)
        keys_p = _sort_key_arrays(table, bucket_columns, pad, narrow=True)
        flat_keys = [
            np.int32 if c.data.dtype == np.bool_ else c.data.dtype for c in cols
        ]
        packed_spec = _packed_wire_spec(
            num_buckets, bucket_np, n_pad_total, rowid_p, list(zip(cols, keys_p))
        )
        _record_coded_stage(
            n_pad_total,
            [np.uint32, np.int32, np.int64, *flat_keys],
            [bucket_np, valid_p, rowid_p, *keys_p],
            packed_spec=packed_spec or None,
        )
        bucket, out_valid, (rowid_out,) = distributed_bucketize_coded(
            mesh,
            put(bucket_np),
            [put(rowid_p)],
            [put(k) for k in keys_p],
            num_buckets,
            in_valid=put(valid_p),
            n_valid=n,
            packed_spec=packed_spec,
        )
    else:
        valid_p = np.ones(n + pad, np.int32)
        valid_p[n:] = 0
        rowid_p = _pad_rows(np.arange(n, dtype=np.int64), pad)
        keys_p = _sort_key_arrays(table, bucket_columns, pad)

        bucket, out_valid, (rowid_out,) = distributed_bucketize(
            mesh,
            put(h1_np),
            [put(rowid_p)],
            [put(k) for k in keys_p],
            num_buckets,
            in_valid=put(valid_p),
            n_valid=n,
        )
    perm, bucket_v = _gather_valid_perm(bucket, out_valid, rowid_out)
    assert len(perm) == n, f"exchange dropped rows: {len(perm)} != {n}"
    starts = np.searchsorted(bucket_v, np.arange(num_buckets + 1))
    return table.take(perm), starts


def distributed_exchange_table(
    mesh: Mesh,
    table: Table,
    key_columns: Sequence[str],
    partitions_per_device: int = 8,
) -> Tuple[Table, np.ndarray, "DistBlocks"]:
    """Real hash exchange of a table over the mesh — what `ShuffleExchangeExec`
    executes in distributed mode. Returns (reordered table, partition starts,
    device-resident key blocks). Two tables exchanged on compatible keys with the
    same mesh are co-partitioned: partition p of both sides lands on the same
    device, so the downstream merge join runs with no further communication — and
    the exchanged keys STAY on device between exchange and probe (the r2 review
    flagged the old host round-trip of the full key column here).

    Hidden assumption made explicit: the probe consumes each device's exchange
    output block directly, so the block must hold that device's partitions with
    valid rows first, sorted by (partition, key64) — exactly what
    `distributed_bucketize`'s receive-side sort produces."""
    n_dev = mesh.devices.size
    num_partitions = n_dev * partitions_per_device
    n = table.num_rows
    cols = [table.column(c) for c in key_columns]

    n_pad_total = quantized_rows(n, n_dev)
    pad = n_pad_total - n
    arrs_p = _padded_hash_inputs(cols, pad)
    h1_np = np.asarray(combined_hash_u32(cols, arrs_p, _SEED1))
    k64_p = np.asarray(key64(cols, arrs_p))

    sh = row_sharding(mesh)

    def put(x):
        return jax.device_put(jnp.asarray(x), sh)

    from ..engine.encoded_device import encoded_device_enabled

    if encoded_device_enabled():
        # Code-space exchange: narrow partition lane instead of the uint32
        # hash, int8 validity, int32 row ids when they fit — and the k64
        # payload lane DOUBLES as the sort key (`sort_from_payload`), so it
        # crosses the interconnect once instead of twice.
        bucket_np = (h1_np % np.uint32(num_partitions)).astype(
            _bucket_lane_dtype(num_partitions)
        )
        valid_p = np.ones(n + pad, np.int8)
        valid_p[n:] = 0
        rowid_p = _pad_rows(np.arange(n, dtype=_coded_rowid_dtype(n_pad_total)), pad)
        # Spec covers (bucket, valid, rowid); the k64 payload lane appends
        # unpacked — 64-bit hashes have no narrower wire class.
        packed_spec = _packed_wire_spec(num_partitions, bucket_np, n_pad_total, rowid_p)
        if packed_spec:
            packed_spec = packed_spec + ((0, 0),)
        _record_coded_stage(
            n_pad_total,
            [np.uint32, np.int32, np.int64, np.int64, np.int64],
            [bucket_np, valid_p, rowid_p, k64_p],
            packed_spec=packed_spec or None,
        )
        bucket, out_valid, (rowid_out, k64_out) = distributed_bucketize_coded(
            mesh,
            put(bucket_np),
            [put(rowid_p), put(k64_p)],
            [],
            num_partitions,
            in_valid=put(valid_p),
            n_valid=n,
            sort_from_payload=(1,),
            packed_spec=packed_spec,
        )
    else:
        valid_p = np.ones(n + pad, np.int32)
        valid_p[n:] = 0
        rowid_p = _pad_rows(np.arange(n, dtype=np.int64), pad)

        bucket, out_valid, (rowid_out, k64_out) = distributed_bucketize(
            mesh,
            put(h1_np),
            [put(rowid_p), put(k64_p)],
            [put(k64_p)],
            num_partitions,
            in_valid=put(valid_p),
            n_valid=n,
        )
    valid_h = np.asarray(out_valid).reshape(-1).astype(bool)
    perm = np.asarray(rowid_out).reshape(-1)[valid_h]
    bucket_v = np.asarray(bucket).reshape(-1)[valid_h]
    assert len(perm) == n, f"exchange dropped rows: {len(perm)} != {n}"
    starts = np.searchsorted(bucket_v, np.arange(num_partitions + 1))

    # Device-resident key blocks for the co-partitioned probe: invalid slots
    # masked to the probe's pad value (sort-last), real keys clipped below it.
    masked = jnp.where(
        out_valid.astype(bool), jnp.minimum(k64_out, _PAD - 1), _PAD
    )
    buckets_local = num_partitions // n_dev
    lens = np.diff(starts)
    cap = quantize_cap(int(lens.max())) if lens.size and lens.max(initial=0) else 1
    _, lstarts = _local_starts(starts, n_dev, buckets_local)
    blocks = DistBlocks(
        masked,
        jax.device_put(jnp.asarray(lstarts), NamedSharding(mesh, P(BUCKET_AXIS))),
        starts,
        buckets_local,
        cap,
    )
    return table.take(perm), starts, blocks


# ---------------------------------------------------------------------------
# Sharded co-bucketed join probe (zero collectives)
# ---------------------------------------------------------------------------


@lru_cache(maxsize=64)
def _probe_program(mesh: Mesh, buckets_local: int, cap_l: int, cap_r: int):
    """Compiled sharded pad+sort+probe: each device scatters its bucket block into
    padded [B_local, cap] matrices, argsorts within bucket, and range-probes —
    entirely device-local (the jitted HLO contains no collectives)."""

    def fn(lk, lst, rk, rst):
        lk, lst, rk, rst = lk[0], lst[0], rk[0], rst[0]

        def pad_sort(keys, starts, cap):
            m = keys.shape[0]
            pos = jnp.arange(m)
            b_of = jnp.clip(jnp.searchsorted(starts, pos, side="right") - 1, 0, buckets_local - 1)
            slot = pos - starts[b_of]
            padded = jnp.full((buckets_local, cap), _PAD, dtype=jnp.int64)
            # Host-pad rows past the block's real size get slots beyond their
            # bucket's length (harmless: PAD sorts last, lengths mask them) or
            # beyond cap (dropped).
            padded = padded.at[b_of, slot].set(keys, mode="drop")
            order = jnp.argsort(padded, axis=1)
            sorted_keys = jnp.take_along_axis(padded, order, axis=1)
            lengths = starts[1:] - starts[:-1]
            return sorted_keys, order, lengths

        ls, l_order, l_len = pad_sort(lk, lst, cap_l)
        rs, r_order, r_len = pad_sort(rk, rst, cap_r)
        lo = jax.vmap(lambda r, l: jnp.searchsorted(r, l, side="left"))(rs, ls)
        hi = jax.vmap(lambda r, l: jnp.searchsorted(r, l, side="right"))(rs, ls)
        r_len_b = r_len[:, None]
        lo = jnp.minimum(lo, r_len_b)
        hi = jnp.minimum(hi, r_len_b)
        valid_left = jnp.arange(cap_l)[None, :] < l_len[:, None]
        counts = jnp.where(valid_left, hi - lo, 0)
        return lo, counts, l_order, r_order

    return _observed_jit(
        shard_map(
            fn,
            mesh=mesh,
            in_specs=(P(BUCKET_AXIS), P(BUCKET_AXIS), P(BUCKET_AXIS), P(BUCKET_AXIS)),
            out_specs=(P(BUCKET_AXIS), P(BUCKET_AXIS), P(BUCKET_AXIS), P(BUCKET_AXIS)),
        ),
        label="parallel.probe",
    )


def _local_starts(
    starts_np: np.ndarray, n_dev: int, buckets_local: int
) -> Tuple[np.ndarray, np.ndarray]:
    """(device bounds [n_dev+1], per-device local bucket offsets [n_dev, B_local+1])
    — the single source of the 'device d owns its contiguous bucket range' layout
    contract shared by the host block builder and the exchange output."""
    bounds = starts_np[0 :: buckets_local][: n_dev + 1]
    local = np.zeros((n_dev, buckets_local + 1), dtype=np.int64)
    for d in range(n_dev):
        local[d] = (
            starts_np[d * buckets_local : (d + 1) * buckets_local + 1] - bounds[d]
        )
    return bounds, local


def _block_layout(
    keys_np: np.ndarray, starts_np: np.ndarray, n_dev: int, buckets_local: int
) -> Tuple[np.ndarray, np.ndarray]:
    """Lay out per-row keys (bucket order) as [n_dev, max_block] device blocks plus
    per-device local bucket offsets [n_dev, B_local+1]; device d's block is its
    contiguous bucket range — host→device transfer is one sharded device_put."""
    bounds, local_starts = _local_starts(starts_np, n_dev, buckets_local)
    max_block = quantize_cap(int(np.diff(bounds).max()) if n_dev else 1)
    blocks = np.full((n_dev, max_block), _PAD, dtype=np.int64)
    for d in range(n_dev):
        lo, hi = int(bounds[d]), int(bounds[d + 1])
        blocks[d, : hi - lo] = keys_np[lo:hi]
    return blocks, local_starts


class DistBlocks:
    """Device-resident sharded block layout of one join side: `blocks`
    [n_dev, max_block] (device, sharded over the bucket axis), `lstarts`
    [n_dev, B_local+1] (device, sharded), plus the host metadata the expansion
    needs. Built ONCE per (table, mesh) — the steady-state sharded join re-probes
    these without any host round-trip of the key columns."""

    __slots__ = ("blocks", "lstarts", "starts_np", "buckets_local", "cap")

    def __init__(self, blocks, lstarts, starts_np, buckets_local, cap):
        self.blocks = blocks
        self.lstarts = lstarts
        self.starts_np = starts_np
        self.buckets_local = buckets_local
        self.cap = cap

    @property
    def nbytes(self) -> int:
        total = 0
        for a in (self.blocks, self.lstarts, self.starts_np):
            total += int(getattr(a, "nbytes", 0) or 0)
        return total


#: Steady-state instrumentation: how many block layouts were BUILT (host→device
#: upload) vs how many probes ran. A cached steady state probes >> builds.
DIST_JOIN_STATS = {"block_builds": 0, "probes": 0}


def pad_starts_to_mesh(starts_np: np.ndarray, n_dev: int) -> np.ndarray:
    """Append empty virtual buckets so the bucket count divides the mesh (the
    default 200-bucket index rides a 16-device mesh: 200 → 208 empty-padded)."""
    B = len(starts_np) - 1
    pad_b = (-B) % n_dev
    if not pad_b:
        return starts_np
    return np.concatenate(
        [starts_np, np.full(pad_b, starts_np[-1], dtype=starts_np.dtype)]
    )


def build_dist_blocks(mesh: Mesh, keys, starts_np: np.ndarray) -> Optional[DistBlocks]:
    """Lay one side's keys out as sharded device blocks (one-time host work; the
    result is cached by the caller per table identity)."""
    n_dev = mesh.devices.size
    starts_np = pad_starts_to_mesh(starts_np, n_dev)
    B = len(starts_np) - 1
    if B == 0:
        return None
    buckets_local = B // n_dev
    lens = np.diff(starts_np)
    if lens.max(initial=0) == 0:
        return None
    cap = quantize_cap(int(lens.max()))
    keys_np = np.minimum(np.asarray(keys), _PAD - 1)
    blocks, lstarts = _block_layout(keys_np, starts_np, n_dev, buckets_local)
    sh = NamedSharding(mesh, P(BUCKET_AXIS))
    DIST_JOIN_STATS["block_builds"] += 1
    return DistBlocks(
        jax.device_put(jnp.asarray(blocks), sh),
        jax.device_put(jnp.asarray(lstarts), sh),
        starts_np,
        buckets_local,
        cap,
    )


def probe_dist_blocks(
    mesh: Mesh, left: DistBlocks, right: DistBlocks
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Sharded zero-collective probe of two pre-built block layouts → global
    (left_row, right_row) pairs. The per-query device→host traffic is the probe
    OUTPUT (lo/counts/orders — bounded by bucket capacity), never the keys.

    Probes the SMALLER side into the larger (search count scales with the
    probing side's capacity), swapping the output pair order back."""
    if left.buckets_local != right.buckets_local:
        return None
    if left.cap > right.cap:
        out = probe_dist_blocks(mesh, right, left)
        if out is None:
            return None
        ri, li = out
        return li, ri
    DIST_JOIN_STATS["probes"] += 1
    lo, counts, l_order, r_order = _probe_program(
        mesh, left.buckets_local, left.cap, right.cap
    )(left.blocks, left.lstarts, right.blocks, right.lstarts)
    counts_h = np.asarray(counts)
    total = int(counts_h.sum())
    if total == 0:
        return np.empty(0, np.int64), np.empty(0, np.int64)

    from ..ops.bucket_join import _expand_np

    return _expand_np(
        np.asarray(lo),
        counts_h,
        left.starts_np,
        right.starts_np,
        np.asarray(l_order),
        np.asarray(r_order),
    )


def distributed_bucketed_join_pairs(
    mesh: Mesh,
    l_keys,
    l_starts_np: np.ndarray,
    r_keys,
    r_starts_np: np.ndarray,
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Mesh-sharded equivalent of `bucketed_merge_join_pairs`: all bucket pairs
    probed concurrently, each on the device owning that bucket range, with no data
    exchange. Bucket counts that don't divide the mesh are padded with virtual
    EMPTY buckets (zero length → zero probe work), so the default 200-bucket index
    still takes this path on any mesh size (200 % 16 != 0 included). Returns None
    only when the two sides' bucket counts disagree (caller falls back to the
    single-device kernel).

    Uncached convenience entry (block layouts rebuilt per call); the engine's
    steady-state path caches `build_dist_blocks` per table identity instead."""
    if len(l_starts_np) - 1 != len(r_starts_np) - 1:
        return None
    l_blocks = build_dist_blocks(mesh, l_keys, l_starts_np)
    r_blocks = build_dist_blocks(mesh, r_keys, r_starts_np)
    if l_blocks is None or r_blocks is None:
        return np.empty(0, np.int64), np.empty(0, np.int64)
    return probe_dist_blocks(mesh, l_blocks, r_blocks)
