"""Version-tolerant resolution of the modern `jax.sharding` program APIs.

The mesh subsystem is written against the CURRENT jax surface — `jax.shard_map`
+ `jax.jit` with `jax.sharding.NamedSharding` — but must run on every jaxlib
the deployment images carry. The two entry points that moved across jax's
0.4 → 0.5/0.6 reorganization are resolved here, once, at import time:

- ``shard_map``: `jax.shard_map` (0.4.34+ exposes it at top level on some
  builds, all 0.6+ builds) → `jax.experimental.shard_map.shard_map` (the
  0.4.x home) → `None` (a jax too old for the mesh path at all; callers see
  a clear error instead of an AttributeError mid-build).
- ``pjit``: `jax.jit` IS pjit on every jax this repo supports (the two were
  unified in 0.4); `jax.experimental.pjit.pjit` remains the fallback spelling
  for builds where `jax.jit` rejects `in_shardings`.

Everything else the subsystem needs (`Mesh`, `NamedSharding`,
`PartitionSpec`, `lax.all_to_all`) has been stable across these versions and
is imported directly where used.

This file is the ONE place version probing happens: `distributed.py` and
`table_ops.py` import `shard_map` from here and stay clean modern-API code.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax

__all__ = ["shard_map", "pjit", "require_shard_map"]


def _resolve_shard_map() -> Optional[Callable[..., Any]]:
    fn = getattr(jax, "shard_map", None)
    if fn is not None:
        return fn
    try:
        from jax.experimental.shard_map import shard_map as fn  # type: ignore

        return fn
    except Exception:
        return None


_shard_map_impl = _resolve_shard_map()


def shard_map(f, *, mesh, in_specs, out_specs):
    """`jax.shard_map(f, mesh=..., in_specs=..., out_specs=...)` on whichever
    module this jax spells it in. Raises a actionable error on a jax with no
    shard_map at all (the mesh path cannot exist there)."""
    impl = require_shard_map()
    return impl(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)


def require_shard_map() -> Callable[..., Any]:
    if _shard_map_impl is None:
        raise RuntimeError(
            "this jax build has neither jax.shard_map nor "
            "jax.experimental.shard_map — the distributed mesh path needs "
            "jax >= 0.4.30; set HYPERSPACE_DISTRIBUTED=0 to run single-device"
        )
    return _shard_map_impl


def pjit(fun, **kwargs):
    """Sharded jit: `jax.jit` (which IS pjit on modern jax) with
    `jax.experimental.pjit.pjit` as the fallback spelling."""
    try:
        return jax.jit(fun, **kwargs)
    except TypeError:
        from jax.experimental.pjit import pjit as _pjit  # type: ignore

        return _pjit(fun, **kwargs)
