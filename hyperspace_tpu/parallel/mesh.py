"""Device mesh helpers.

The framework's distribution axis is "buckets": index data is hash-partitioned into
`num_buckets` buckets, and on a mesh each device owns a contiguous bucket block. Both
the build's all-to-all exchange and the co-bucketed join's zero-communication
execution ride this one axis (ICI within a slice, DCN across slices — the axis order
in `jax.devices()` already reflects the platform's topology).
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

BUCKET_AXIS = "buckets"


def make_mesh(num_devices: Optional[int] = None) -> Mesh:
    devices = jax.devices()
    n = num_devices or len(devices)
    if n > len(devices):
        raise ValueError(f"Requested {n} devices; only {len(devices)} available.")
    return Mesh(np.asarray(devices[:n]), (BUCKET_AXIS,))


def row_sharding(mesh: Mesh) -> NamedSharding:
    """Rows sharded over the mesh (axis 0)."""
    return NamedSharding(mesh, PartitionSpec(BUCKET_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())
