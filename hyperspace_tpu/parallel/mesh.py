"""Device mesh helpers.

The framework's distribution axis is "buckets": index data is hash-partitioned into
`num_buckets` buckets, and on a mesh each device owns a contiguous bucket block. Both
the build's all-to-all exchange and the co-bucketed join's zero-communication
execution ride this one axis (ICI within a slice, DCN across slices — the axis order
in `jax.devices()` already reflects the platform's topology).
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

BUCKET_AXIS = "buckets"

#: Minimum rows a device program shape is quantized to (env-tunable). Every
#: mesh program's row dimension — exchange shard rows, send-matrix capacity,
#: probe block width, padded bucket capacity — is ceil'd to a power of two
#: AND floored at this quantum, so each device program compiles exactly once
#: per pow2 workload class and small workloads all share ONE class. 1024 rows
#: of int64 is 8 KiB/lane — noise on any device, and it keeps the warm
#: program set tiny for the persistent compilation cache.
ENV_ROW_QUANTUM = "HYPERSPACE_MESH_ROW_QUANTUM"
_DEFAULT_ROW_QUANTUM = 1024


def mesh_row_quantum() -> int:
    try:
        q = int(os.environ.get(ENV_ROW_QUANTUM, _DEFAULT_ROW_QUANTUM))
    except ValueError:
        return _DEFAULT_ROW_QUANTUM
    if q < 1:
        return _DEFAULT_ROW_QUANTUM
    # The quantum itself must be a power of two (it is a shape class bound).
    return 1 << (q - 1).bit_length()


def quantize_cap(n: int) -> int:
    """Pow2-quantize a per-device capacity, floored at the mesh row quantum."""
    return 1 << (max(int(n), mesh_row_quantum()) - 1).bit_length()


def quantized_rows(num_rows: int, n_dev: int) -> int:
    """The padded GLOBAL row count for `num_rows` rows on an `n_dev` mesh: each
    device's shard is the same pow2-quantized size, so the exchange programs
    (whose traced shapes are the shard sizes) compile once per workload class
    instead of once per exact row count — the fix for the r05 failure mode
    (a 2400 s compile inside an unquantized-shape device program)."""
    per = -(-max(int(num_rows), 1) // n_dev)  # ceil division
    return quantize_cap(per) * n_dev


def force_virtual_cpu(n_devices: int = 8) -> None:
    """Force jax onto a virtual n-device CPU platform BEFORE first backend init.

    The image preloads jax at interpreter start with JAX_PLATFORMS=axon (TPU
    tunnel), so env-var defaults alone are ignored — the already-created jax
    config must be overridden too. Used by both the test harness (conftest) and
    the driver's `dryrun_multichip` entry point.

    The process stays CPU-pinned afterwards (a jax backend cannot be re-selected
    once initialized); the env mutations are reverted after init so child
    processes are unaffected.
    """
    import os
    import re

    old_flags = os.environ.get("XLA_FLAGS")
    old_platforms = os.environ.get("JAX_PLATFORMS")
    stripped = re.sub(
        r"--xla_force_host_platform_device_count=\d+", "", old_flags or ""
    ).strip()
    os.environ["XLA_FLAGS"] = (
        stripped + f" --xla_force_host_platform_device_count={n_devices}"
    ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")
    avail = len(jax.devices())  # initializes the backend under our flags

    for key, old in (("XLA_FLAGS", old_flags), ("JAX_PLATFORMS", old_platforms)):
        if old is None:
            os.environ.pop(key, None)
        else:
            os.environ[key] = old

    if avail < n_devices:
        raise RuntimeError(
            f"virtual CPU platform has {avail} devices (need {n_devices}): the jax "
            "backend was already initialized before force_virtual_cpu ran"
        )


def make_mesh(num_devices: Optional[int] = None) -> Mesh:
    devices = jax.devices()
    n = num_devices or len(devices)
    if n > len(devices):
        raise ValueError(f"Requested {n} devices; only {len(devices)} available.")
    return Mesh(np.asarray(devices[:n]), (BUCKET_AXIS,))


def row_sharding(mesh: Mesh) -> NamedSharding:
    """Rows sharded over the mesh (axis 0)."""
    return NamedSharding(mesh, PartitionSpec(BUCKET_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())
