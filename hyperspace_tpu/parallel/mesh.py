"""Device mesh helpers.

The framework's distribution axis is "buckets": index data is hash-partitioned into
`num_buckets` buckets, and on a mesh each device owns a contiguous bucket block. Both
the build's all-to-all exchange and the co-bucketed join's zero-communication
execution ride this one axis (ICI within a slice, DCN across slices — the axis order
in `jax.devices()` already reflects the platform's topology).
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

BUCKET_AXIS = "buckets"


def force_virtual_cpu(n_devices: int = 8) -> None:
    """Force jax onto a virtual n-device CPU platform BEFORE first backend init.

    The image preloads jax at interpreter start with JAX_PLATFORMS=axon (TPU
    tunnel), so env-var defaults alone are ignored — the already-created jax
    config must be overridden too. Used by both the test harness (conftest) and
    the driver's `dryrun_multichip` entry point.

    The process stays CPU-pinned afterwards (a jax backend cannot be re-selected
    once initialized); the env mutations are reverted after init so child
    processes are unaffected.
    """
    import os
    import re

    old_flags = os.environ.get("XLA_FLAGS")
    old_platforms = os.environ.get("JAX_PLATFORMS")
    stripped = re.sub(
        r"--xla_force_host_platform_device_count=\d+", "", old_flags or ""
    ).strip()
    os.environ["XLA_FLAGS"] = (
        stripped + f" --xla_force_host_platform_device_count={n_devices}"
    ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")
    avail = len(jax.devices())  # initializes the backend under our flags

    for key, old in (("XLA_FLAGS", old_flags), ("JAX_PLATFORMS", old_platforms)):
        if old is None:
            os.environ.pop(key, None)
        else:
            os.environ[key] = old

    if avail < n_devices:
        raise RuntimeError(
            f"virtual CPU platform has {avail} devices (need {n_devices}): the jax "
            "backend was already initialized before force_virtual_cpu ran"
        )


def make_mesh(num_devices: Optional[int] = None) -> Mesh:
    devices = jax.devices()
    n = num_devices or len(devices)
    if n > len(devices):
        raise ValueError(f"Requested {n} devices; only {len(devices)} available.")
    return Mesh(np.asarray(devices[:n]), (BUCKET_AXIS,))


def row_sharding(mesh: Mesh) -> NamedSharding:
    """Rows sharded over the mesh (axis 0)."""
    return NamedSharding(mesh, PartitionSpec(BUCKET_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())
