from .mesh import (  # noqa: F401
    BUCKET_AXIS,
    force_virtual_cpu,
    make_mesh,
    mesh_row_quantum,
    quantize_cap,
    quantized_rows,
    replicated,
    row_sharding,
)
from .shim import pjit, require_shard_map, shard_map  # noqa: F401
from .distributed import (  # noqa: F401
    distributed_bucketed_join_counts,
    distributed_bucketize,
    exchange_counts,
    exchange_rows,
)
from .table_ops import (  # noqa: F401
    distributed_bucketed_join_pairs,
    distributed_bucketize_table,
    distributed_exchange_table,
)
