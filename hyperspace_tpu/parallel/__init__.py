from .mesh import BUCKET_AXIS, make_mesh, replicated, row_sharding  # noqa: F401
from .distributed import (  # noqa: F401
    distributed_bucketed_join_counts,
    distributed_bucketize,
    exchange_counts,
    exchange_rows,
)
