from .mesh import BUCKET_AXIS, force_virtual_cpu, make_mesh, replicated, row_sharding  # noqa: F401
from .distributed import (  # noqa: F401
    distributed_bucketed_join_counts,
    distributed_bucketize,
    exchange_counts,
    exchange_rows,
)
from .table_ops import (  # noqa: F401
    distributed_bucketed_join_pairs,
    distributed_bucketize_table,
    distributed_exchange_table,
)
