"""Distributed index build + join over a jax.sharding.Mesh.

This is the TPU-native replacement for Spark's cluster-wide shuffle machinery
(SURVEY §2.11): the build's `repartition(numBuckets, cols)` becomes an XLA
`all_to_all` over the mesh's ICI, and the co-bucketed join needs NO communication at
all because both sides' bucket blocks are co-located by construction.

Build exchange (two-pass, static shapes — the standard way around ragged all-to-all):
1. Count pass (shard_map): each device computes its per-destination row counts.
2. Host sync: capacity = global max count (one scalar per mesh; amortized, and
   stable across repeated builds of similar data).
3. Exchange pass (shard_map): rows sorted by destination, scattered into a padded
   [n_dev, cap] send matrix per column, `lax.all_to_all` over the bucket axis,
   then a local (bucket, keys...) sort of the received rows.

Device d ends up owning buckets [d*B/n, (d+1)*B/n) fully sorted — exactly the layout
the bucketed writer persists and the co-bucketed join consumes.

Compile contract (docs/distributed.md): every device program here is declared
through `observed_jit` with a `parallel.*` label, and every shape it traces is
pow2-quantized — callers pad row counts to `mesh.quantized_rows` and the
exchange capacity is floored at the mesh row quantum — so each program
compiles EXACTLY ONCE per process per workload class, verified by the compile
observatory (`tests/test_mesh_compile.py`, `bench_detail.mesh`). The ordering
contract of the receive-side sort is the engine's canonical build order:
stable (bucket, sort keys...) with ties broken by ORIGINAL global row id —
identical to `ops.partition.host_sort_perm`/`_sort_perm`, which is what makes
mesh-built index files byte-identical to single-device ones.
"""

from __future__ import annotations

from functools import lru_cache
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..telemetry import metrics as _metrics
from ..telemetry import stage_ledger as _stage_ledger
from ..telemetry.compile_log import observed_jit as _observed_jit
from .mesh import BUCKET_AXIS, quantize_cap
from .shim import shard_map

_PAD_SLOT = -1
#: Packed wire lanes ride uint32 words (`engine/packed_codes.py` layout).
_WORD_BITS = 32

#: All-to-all traffic accounting (ticked once per exchange on the host):
#: payload = real row bytes moved, moved = the padded send-matrix bytes the
#: interconnect actually carries. The gap between them is the static-shape
#: padding tax — `bench_detail.mesh` reports both.
_EXCHANGE_ROWS = _metrics.counter("parallel.exchange.rows")
_EXCHANGE_BYTES_PAYLOAD = _metrics.counter("parallel.exchange.bytes_payload")
_EXCHANGE_BYTES_MOVED = _metrics.counter("parallel.exchange.bytes_moved")
_EXCHANGES = _metrics.counter("parallel.exchange.count")


def _dest_of(h1, num_buckets: int, n_dev: int):
    bucket = (h1 % jnp.uint32(num_buckets)).astype(jnp.int32)
    return bucket * n_dev // num_buckets, bucket


# Program factories are lru_cached so repeated exchanges (every distributed build
# and every exchanged join in a process) hit jax's compiled-computation cache
# instead of re-tracing a fresh shard_map closure per call.


@lru_cache(maxsize=128)
def _counts_program(mesh: Mesh, num_buckets: int):
    n_dev = mesh.devices.size

    def count_fn(h1_local):
        dest, _ = _dest_of(h1_local, num_buckets, n_dev)
        one_hot = jax.nn.one_hot(dest, n_dev, dtype=jnp.int32)
        return jnp.sum(one_hot, axis=0, keepdims=True)  # [1, n_dev]

    return _observed_jit(
        shard_map(count_fn, mesh=mesh, in_specs=P(BUCKET_AXIS), out_specs=P(BUCKET_AXIS)),
        label="parallel.exchange_counts",
    )


def exchange_counts(mesh: Mesh, h1, num_buckets: int) -> np.ndarray:
    """Pass 1: [n_dev, n_dev] matrix of rows device i sends to device j."""
    return np.asarray(_counts_program(mesh, num_buckets)(h1))


@lru_cache(maxsize=128)
def _exchange_program(mesh: Mesh, num_buckets: int, cap: int):
    n_dev = mesh.devices.size

    def fn(h1_local, valid_local, payload_local, keys_local):
        n_local = h1_local.shape[0]
        dest, _ = _dest_of(h1_local, num_buckets, n_dev)
        order = jnp.argsort(dest)  # stable: ties keep original (= global) order
        dest_s = dest[order]
        starts = jnp.searchsorted(dest_s, jnp.arange(n_dev))
        slot = jnp.arange(n_local) - starts[dest_s]

        def scatter(col):
            send = jnp.zeros((n_dev, cap), dtype=col.dtype)
            send = send.at[dest_s, slot].set(col[order])
            return jax.lax.all_to_all(
                send, BUCKET_AXIS, split_axis=0, concat_axis=0, tiled=False
            )

        # Validity travels as its own lane.
        valid_recv = scatter(valid_local)

        h1_recv = scatter(h1_local)
        payload_recv = [scatter(c) for c in payload_local]
        keys_recv = [scatter(c) for c in keys_local]

        # Local sort: invalid rows last, then by (bucket, sort keys...). The
        # final iota operand breaks ties by receive position = (sender id,
        # sender-local order) = ORIGINAL GLOBAL ROW ORDER — the canonical
        # stable build order every other build path produces.
        flat_valid = valid_recv.reshape(-1)
        bucket = (h1_recv.reshape(-1) % jnp.uint32(num_buckets)).astype(jnp.int32)
        sort_operands = (
            1 - flat_valid,
            bucket,
            *[k.reshape(-1) for k in keys_recv],
            jnp.arange(flat_valid.shape[0], dtype=jnp.int32),
        )
        res = jax.lax.sort(sort_operands, num_keys=2 + len(keys_recv))
        perm = res[-1]
        out_bucket = bucket[perm][None]
        out_valid = flat_valid[perm][None]
        out_payload = [c.reshape(-1)[perm][None] for c in payload_recv]
        return out_bucket, out_valid, out_payload

    return _observed_jit(
        shard_map(
            fn,
            mesh=mesh,
            in_specs=(P(BUCKET_AXIS), P(BUCKET_AXIS), P(BUCKET_AXIS), P(BUCKET_AXIS)),
            out_specs=(P(BUCKET_AXIS), P(BUCKET_AXIS), P(BUCKET_AXIS)),
        ),
        label="parallel.exchange",
    )


def _record_exchange(n_valid: int, n_dev: int, cap: int, lanes, packed_spec=None) -> None:
    """Host-side traffic accounting for one exchange call (cheap: arithmetic
    over lane dtypes, no device sync). `packed_spec` (aligned with `lanes`)
    marks bit-packed wire lanes: a packed lane's interconnect cost is its
    uint32 WORD matrix — `cap // lanes_per_word` words per destination — and
    its payload is the true bits-on-the-wire of the valid rows."""
    _EXCHANGES.inc()
    _EXCHANGE_ROWS.inc(int(n_valid))
    payload = 0
    moved = 0
    for i, lane in enumerate(lanes):
        bits = packed_spec[i][0] if packed_spec is not None else 0
        if bits:
            payload += -(-int(n_valid) * bits // 8)  # ceil(n_valid*bits/8)
            moved += n_dev * n_dev * (cap * bits // _WORD_BITS) * 4
        else:
            item = int(jnp.asarray(lane).dtype.itemsize)
            payload += int(n_valid) * item
            moved += n_dev * n_dev * cap * item
    _EXCHANGE_BYTES_PAYLOAD.inc(payload)
    _EXCHANGE_BYTES_MOVED.inc(moved)
    # The mesh exchange was the ORIGINAL payload-vs-moved honesty split; it
    # now also feeds the generalized padding ledger so `pad_ratio` covers
    # every pow2 staging site with one definition (padding = moved − payload).
    from ..telemetry import device_observatory as _devobs

    _devobs.record_pad("mesh_exchange", payload, moved - payload)


def exchange_rows(
    mesh: Mesh,
    h1,
    payload: Sequence[jnp.ndarray],
    sort_keys: Sequence[jnp.ndarray],
    num_buckets: int,
    cap: int,
    in_valid=None,
    n_valid=None,
) -> Tuple[jnp.ndarray, jnp.ndarray, List[jnp.ndarray]]:
    """Pass 2: all-to-all exchange + local in-bucket sort.

    `in_valid` (optional, int32 0/1 per row, sharded like `h1`) marks padding rows
    added by the caller to make the global row count divisible by the mesh size;
    they travel through the exchange but come out with valid=0 (sorted last).
    `n_valid` is the real (un-padded) row count for the traffic counters; the
    table-level callers pass it, and a plain unpadded call infers it from the
    shape — never from a device sync, so tracing/lowering this function stays
    legal.

    Returns (bucket_ids [n_dev*cap], valid mask, payload arrays), each sharded over
    the mesh: device d's block holds its bucket range, valid rows sorted by
    (bucket, sort_keys...) and grouped before padding."""
    # The whole call is the ``exchange`` stage for attribution: the pad
    # ledger tick in _record_exchange and the exchange program's device time
    # bill the mesh lane, not whichever stage submitted the bucketize.
    with _stage_ledger.stage_scope("exchange"):
        n_dev = mesh.devices.size
        if in_valid is None:
            in_valid = jnp.ones(h1.shape, dtype=jnp.int32)
            if n_valid is None:
                n_valid = int(h1.shape[0])
        if n_valid is not None:
            _record_exchange(n_valid, n_dev, cap, [h1, in_valid, *payload, *sort_keys])
        return _exchange_program(mesh, num_buckets, cap)(
            h1, in_valid, list(payload), list(sort_keys)
        )


def distributed_bucketize(
    mesh: Mesh,
    h1,
    payload: Sequence[jnp.ndarray],
    sort_keys: Sequence[jnp.ndarray],
    num_buckets: int,
    in_valid=None,
    n_valid=None,
):
    """Full two-pass distributed bucketize. Rows arrive sharded over the mesh; the
    result is (bucket_ids, valid, payload) blocks, one bucket range per device."""
    counts = exchange_counts(mesh, h1, num_buckets)
    cap = int(counts.max()) if counts.size else 0
    # Quantize to the mesh row quantum's power-of-two grid so repeated builds
    # of growing data reuse ONE compiled exchange instead of recompiling per
    # exact capacity (the compile-boundedness contract).
    cap = quantize_cap(cap)
    return exchange_rows(
        mesh, h1, payload, sort_keys, num_buckets, cap, in_valid, n_valid=n_valid
    )


# ---------------------------------------------------------------------------
# Code-space exchange (HYPERSPACE_ENCODED_DEVICE): same two-pass shape, but
# the wire lanes are narrowed — the caller ships a pre-computed bucket lane in
# the smallest width num_buckets fits (instead of the uint32 hash), an int8
# validity lane, an int32 row id when the global row count allows, and
# dictionary codes narrowed to the dictionary's width. Every sort operand
# carries the SAME VALUES as the flat path (narrowing is value-preserving and
# bucket/dest are computed from the identical h1 % num_buckets), so the
# receive-side permutation — and therefore the index files and join outputs —
# are byte-identical in both flag states; only `parallel.exchange.bytes_moved`
# shrinks. Both programs keep their flat twins' observability labels: the
# compile-per-class contract is about the label's compile COUNT per workload
# class, and a process runs one staging mode per class.
# ---------------------------------------------------------------------------


@lru_cache(maxsize=128)
def _counts_coded_program(mesh: Mesh, num_buckets: int):
    n_dev = mesh.devices.size

    def count_fn(bucket_local):
        dest = bucket_local.astype(jnp.int32) * n_dev // num_buckets
        one_hot = jax.nn.one_hot(dest, n_dev, dtype=jnp.int32)
        return jnp.sum(one_hot, axis=0, keepdims=True)  # [1, n_dev]

    return _observed_jit(
        shard_map(count_fn, mesh=mesh, in_specs=P(BUCKET_AXIS), out_specs=P(BUCKET_AXIS)),
        label="parallel.exchange_counts",
    )


def exchange_counts_coded(mesh: Mesh, bucket, num_buckets: int) -> np.ndarray:
    """Pass 1 over a pre-computed (narrow) bucket-id lane."""
    return np.asarray(_counts_coded_program(mesh, num_buckets)(bucket))


def _pack_wire(send, bits: int):
    """Pack a scattered [n_dev, cap] send matrix (already biased into its
    unsigned field range) into [n_dev, cap/lanes_per_word] uint32 words —
    the shared big-endian layout primitive (`engine/packed_codes.py`)."""
    from ..engine.packed_codes import pack_rows_traced

    return pack_rows_traced(send, bits)


def _unpack_wire(words, bits: int, dtype):
    """Inverse of `_pack_wire`: [n_dev, words] uint32 → [n_dev, cap] biased
    field values in the lane's original dtype."""
    from ..engine.packed_codes import unpack_rows_traced

    return unpack_rows_traced(words, bits).astype(dtype)


@lru_cache(maxsize=128)
def _exchange_coded_program(
    mesh: Mesh,
    num_buckets: int,
    cap: int,
    sort_from_payload: tuple,
    packed_spec: tuple = (),
):
    """Coded twin of `_exchange_program`: input lanes arrive narrow, and sort
    keys may be REFERENCED from payload lanes (`sort_from_payload` indexes)
    instead of shipped twice — the k64 of the exchanged join travels once.

    `packed_spec` (static, folded into the program cache key like `cap`) is a
    per-lane (bits, bias) tuple aligned with (bucket, valid, *payload, *keys);
    a (0, 0) entry ships the lane as-is. A packed lane is biased by `bias`
    (so the null code -1 lands on the reserved field value 0), bit-packed
    AFTER the destination scatter, crosses the all_to_all as uint32 words,
    and unpacks back to the identical [n_dev, cap] matrix on the receive
    side — pad slots scatter as 0, pack as the bias value, and unpack back
    to 0, so every downstream operand is value-identical to the unpacked
    program and the receive-side sort permutation cannot move."""
    n_dev = mesh.devices.size
    for bits, _bias in packed_spec:
        if bits:
            assert _WORD_BITS % bits == 0 and cap % (_WORD_BITS // bits) == 0, (
                bits,
                cap,
            )

    def fn(bucket_local, valid_local, payload_local, keys_local):
        n_local = bucket_local.shape[0]
        dest = bucket_local.astype(jnp.int32) * n_dev // num_buckets
        order = jnp.argsort(dest)  # stable: ties keep original (= global) order
        dest_s = dest[order]
        starts = jnp.searchsorted(dest_s, jnp.arange(n_dev))
        slot = jnp.arange(n_local) - starts[dest_s]

        def _wire(send):
            return jax.lax.all_to_all(
                send, BUCKET_AXIS, split_axis=0, concat_axis=0, tiled=False
            )

        def _spec(i):
            return packed_spec[i] if i < len(packed_spec) else (0, 0)

        def scatter(col, spec=(0, 0)):
            bits, bias = spec
            send = jnp.zeros((n_dev, cap), dtype=col.dtype)
            send = send.at[dest_s, slot].set(col[order])
            if not bits:
                return _wire(send)
            biased = (send.astype(jnp.int32) + bias) if bias else send
            recv = _unpack_wire(_wire(_pack_wire(biased, bits)), bits, jnp.int32)
            if bias:
                recv = recv - bias
            return recv.astype(col.dtype)

        n_pay = len(payload_local)
        valid_recv = scatter(valid_local, _spec(1))
        bucket_recv = scatter(bucket_local, _spec(0))
        payload_recv = [scatter(c, _spec(2 + i)) for i, c in enumerate(payload_local)]
        keys_recv = [scatter(c, _spec(2 + n_pay + i)) for i, c in enumerate(keys_local)]

        # Receive-side widening is free (post-wire); the sort operand VALUES
        # match the flat program's exactly, so the permutation — and with it
        # the canonical stable build order — is identical.
        flat_valid = valid_recv.reshape(-1).astype(jnp.int32)
        bucket = bucket_recv.reshape(-1).astype(jnp.int32)
        sort_lanes = [payload_recv[i].reshape(-1) for i in sort_from_payload]
        sort_lanes += [k.reshape(-1) for k in keys_recv]
        sort_operands = (
            1 - flat_valid,
            bucket,
            *sort_lanes,
            jnp.arange(flat_valid.shape[0], dtype=jnp.int32),
        )
        res = jax.lax.sort(sort_operands, num_keys=2 + len(sort_lanes))
        perm = res[-1]
        out_bucket = bucket[perm][None]
        out_valid = flat_valid[perm][None]
        out_payload = [c.reshape(-1)[perm][None] for c in payload_recv]
        return out_bucket, out_valid, out_payload

    return _observed_jit(
        shard_map(
            fn,
            mesh=mesh,
            in_specs=(P(BUCKET_AXIS), P(BUCKET_AXIS), P(BUCKET_AXIS), P(BUCKET_AXIS)),
            out_specs=(P(BUCKET_AXIS), P(BUCKET_AXIS), P(BUCKET_AXIS)),
        ),
        label="parallel.exchange",
    )


def distributed_bucketize_coded(
    mesh: Mesh,
    bucket,
    payload: Sequence[jnp.ndarray],
    sort_keys: Sequence[jnp.ndarray],
    num_buckets: int,
    in_valid,
    n_valid: int,
    sort_from_payload: Sequence[int] = (),
    packed_spec: Sequence[Tuple[int, int]] = (),
):
    """Two-pass distributed bucketize over NARROW lanes: `bucket` is the
    pre-computed (h1 % num_buckets) lane in its smallest width, `in_valid` is
    int8, and `sort_from_payload` names payload lanes that double as sort
    keys (so they are not shipped twice). `packed_spec` (optional, aligned
    (bucket, valid, *payload, *sort_keys)) bit-packs the marked lanes across
    the all_to_all (`HYPERSPACE_PACKED_CODES`). Output contract (and bytes of
    the output) match `distributed_bucketize`: int32 bucket ids, int32
    validity, payload lanes in their input dtypes."""
    with _stage_ledger.stage_scope("exchange"):
        counts = exchange_counts_coded(mesh, bucket, num_buckets)
        cap = quantize_cap(int(counts.max()) if counts.size else 0)
        n_dev = mesh.devices.size
        spec = tuple(tuple(s) for s in packed_spec)
        _record_exchange(
            n_valid,
            n_dev,
            cap,
            [bucket, in_valid, *payload, *sort_keys],
            packed_spec=spec if spec else None,
        )
        return _exchange_coded_program(
            mesh, num_buckets, cap, tuple(sort_from_payload), spec
        )(bucket, in_valid, list(payload), list(sort_keys))


# ---------------------------------------------------------------------------
# Distributed co-bucketed join: zero-communication by construction
# ---------------------------------------------------------------------------


@lru_cache(maxsize=64)
def _join_counts_program(mesh: Mesh):
    def fn(ls, rs, ll, rl):
        lo = jax.vmap(lambda r, l: jnp.searchsorted(r, l, side="left"))(rs, ls)
        hi = jax.vmap(lambda r, l: jnp.searchsorted(r, l, side="right"))(rs, ls)
        rl_b = rl[:, None]
        lo = jnp.minimum(lo, rl_b)
        hi = jnp.minimum(hi, rl_b)
        valid = jnp.arange(ls.shape[1])[None, :] < ll[:, None]
        return jnp.sum(jnp.where(valid, hi - lo, 0), axis=1)

    return _observed_jit(
        shard_map(
            fn,
            mesh=mesh,
            in_specs=(P(BUCKET_AXIS), P(BUCKET_AXIS), P(BUCKET_AXIS), P(BUCKET_AXIS)),
            out_specs=P(BUCKET_AXIS),
        ),
        label="parallel.join_counts",
    )


def distributed_bucketed_join_counts(
    mesh: Mesh, l_sorted_keys, r_sorted_keys, l_len, r_len
):
    """Per-bucket match counts for co-located padded bucket matrices [B, cap] sharded
    over the mesh's bucket axis. Runs entirely device-local (the proof that the
    co-bucketed layout needs no collectives: the jitted HLO contains none)."""
    return _join_counts_program(mesh)(l_sorted_keys, r_sorted_keys, l_len, r_len)
