"""Filesystem abstraction (the L1 storage layer).

Parity: reference L1 = Hadoop `FileSystem` API reached through `util/FileUtils.scala:28-117`
and `index/factories.scala:43-50` (`FileSystemFactory.create(path)`). The design point kept
from the reference: *all* persistent state (metadata log + index data) lives on a
filesystem-like store with an atomic rename, so the optimistic-concurrency protocol of the
operation log works on any backend.

Backends here: a local-disk implementation and an in-memory one (used by unit tests the way
the reference injects mocked `FileSystem`s, `IndexCollectionManagerTest.scala:29-91`).
"""

from __future__ import annotations

import os
import shutil
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional


@dataclass(frozen=True)
class FileStatus:
    """Metadata of one file or directory (name is the full path)."""

    path: str
    size: int
    modified_time: int  # epoch millis
    is_dir: bool

    @property
    def name(self) -> str:
        return os.path.basename(self.path.rstrip("/"))


class FileSystem:
    """Minimal filesystem contract needed by the log/data managers and IO layer."""

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def is_dir(self, path: str) -> bool:
        raise NotImplementedError

    def mkdirs(self, path: str) -> None:
        raise NotImplementedError

    def list_status(self, path: str) -> List[FileStatus]:
        """Non-recursive listing of a directory."""
        raise NotImplementedError

    def get_status(self, path: str) -> FileStatus:
        raise NotImplementedError

    def delete(self, path: str, recursive: bool = False) -> None:
        raise NotImplementedError

    def rename(self, src: str, dst: str) -> bool:
        """Atomic rename; returns False if dst already exists (no overwrite)."""
        raise NotImplementedError

    def read_bytes(self, path: str) -> bytes:
        raise NotImplementedError

    def write_bytes(self, path: str, data: bytes) -> None:
        """Create-or-overwrite write (creates parent dirs)."""
        raise NotImplementedError

    # -- Conveniences shared by all backends (reference util/FileUtils.scala) --

    def read_text(self, path: str) -> str:
        return self.read_bytes(path).decode("utf-8")

    def write_text(self, path: str, text: str) -> None:
        self.write_bytes(path, text.encode("utf-8"))

    def atomic_write_text(self, path: str, text: str) -> bool:
        """OCC commit primitive: write to a unique temp then atomically link into place.

        Returns False (and cleans up the temp) if ``path`` already exists — this is the
        exact contract of the reference's `IndexLogManagerImpl.writeLog`
        (`IndexLogManager.scala:146-162`). The commit must be atomic no-overwrite even
        under concurrent writers (two processes racing on the same log id: exactly one
        wins).
        """
        if self.exists(path):
            return False
        tmp = f"{path}.{os.getpid()}.{threading.get_ident()}.temp"
        self.write_text(tmp, text)
        ok = self.rename(tmp, path)
        if self.exists(tmp):
            self.delete(tmp)
        return ok

    def list_leaf_files(self, path: str) -> List[FileStatus]:
        """Recursive listing of all plain files beneath ``path``."""
        out: List[FileStatus] = []
        stack = [path]
        while stack:
            p = stack.pop()
            for st in self.list_status(p):
                if st.is_dir:
                    stack.append(st.path)
                else:
                    out.append(st)
        return sorted(out, key=lambda s: s.path)

    def directory_size(self, path: str) -> int:
        return sum(f.size for f in self.list_leaf_files(path))


class LocalFileSystem(FileSystem):
    """Local-disk backend (the default)."""

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def is_dir(self, path: str) -> bool:
        return os.path.isdir(path)

    def mkdirs(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def _status(self, path: str) -> FileStatus:
        st = os.stat(path)
        return FileStatus(
            path=path,
            size=st.st_size if not os.path.isdir(path) else 0,
            modified_time=int(st.st_mtime * 1000),
            is_dir=os.path.isdir(path),
        )

    def list_status(self, path: str) -> List[FileStatus]:
        if not os.path.isdir(path):
            return []
        return sorted(
            (self._status(os.path.join(path, n)) for n in os.listdir(path)),
            key=lambda s: s.path,
        )

    def get_status(self, path: str) -> FileStatus:
        return self._status(path)

    def delete(self, path: str, recursive: bool = False) -> None:
        if os.path.isdir(path):
            if recursive:
                shutil.rmtree(path)
            else:
                os.rmdir(path)
        elif os.path.exists(path):
            os.remove(path)

    def rename(self, src: str, dst: str) -> bool:
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        if os.path.isdir(src):
            # Directory moves are not on the OCC path; a pre-check suffices.
            if os.path.exists(dst):
                return False
            try:
                os.rename(src, dst)
                return True
            except OSError:
                return False
        try:
            # os.link raises FileExistsError atomically if dst exists — unlike
            # os.rename, which silently replaces it. This is what makes the
            # operation log's optimistic concurrency sound under racing writers.
            os.link(src, dst)
            os.unlink(src)
            return True
        except FileExistsError:
            return False
        # Any other OSError (no hard-link support, EACCES, ENOSPC) is a real IO
        # failure, not an OCC conflict — let it propagate.

    def read_bytes(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()

    def write_bytes(self, path: str, data: bytes) -> None:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "wb") as f:
            f.write(data)


class InMemoryFileSystem(FileSystem):
    """Dict-backed filesystem for unit tests and fault injection."""

    def __init__(self):
        self._files: Dict[str, bytes] = {}
        self._mtimes: Dict[str, int] = {}
        self._dirs: set = set()
        # RLock: write_bytes holds the lock and calls mkdirs, which locks again.
        self._lock = threading.RLock()

    @staticmethod
    def _norm(path: str) -> str:
        return os.path.normpath(path)

    def exists(self, path: str) -> bool:
        p = self._norm(path)
        with self._lock:
            if p in self._files or p in self._dirs:
                return True
            prefix = p + os.sep
            return any(f.startswith(prefix) for f in self._files)

    def is_dir(self, path: str) -> bool:
        p = self._norm(path)
        with self._lock:
            if p in self._dirs:
                return True
            prefix = p + os.sep
            return any(f.startswith(prefix) for f in self._files)

    def mkdirs(self, path: str) -> None:
        p = self._norm(path)
        with self._lock:
            while p and p != os.sep:
                self._dirs.add(p)
                p = os.path.dirname(p)

    def list_status(self, path: str) -> List[FileStatus]:
        p = self._norm(path)
        prefix = p + os.sep
        with self._lock:
            children = set()
            for f in list(self._files) + list(self._dirs):
                if f.startswith(prefix):
                    rest = f[len(prefix):]
                    children.add(rest.split(os.sep)[0])
            return [self.get_status(os.path.join(p, c)) for c in sorted(children)]

    def get_status(self, path: str) -> FileStatus:
        p = self._norm(path)
        with self._lock:
            if p in self._files:
                return FileStatus(p, len(self._files[p]), self._mtimes.get(p, 0), False)
            return FileStatus(p, 0, 0, True)

    def delete(self, path: str, recursive: bool = False) -> None:
        p = self._norm(path)
        with self._lock:
            self._files.pop(p, None)
            self._mtimes.pop(p, None)
            self._dirs.discard(p)
            if recursive:
                prefix = p + os.sep
                for f in [f for f in self._files if f.startswith(prefix)]:
                    del self._files[f]
                    self._mtimes.pop(f, None)
                self._dirs = {d for d in self._dirs if not d.startswith(prefix)}

    def rename(self, src: str, dst: str) -> bool:
        s, d = self._norm(src), self._norm(dst)
        with self._lock:
            if d in self._files or d in self._dirs:
                return False
            if s in self._files:
                self._files[d] = self._files.pop(s)
                self._mtimes[d] = self._mtimes.pop(s, 0)
                return True
            if s in self._dirs or self.is_dir(s):
                prefix = s + os.sep
                for f in [f for f in self._files if f.startswith(prefix)]:
                    self._files[d + f[len(s):]] = self._files.pop(f)
                    self._mtimes[d + f[len(s):]] = self._mtimes.pop(f, 0)
                moved_dirs = {x for x in self._dirs if x == s or x.startswith(prefix)}
                self._dirs -= moved_dirs
                self._dirs |= {d + x[len(s):] for x in moved_dirs}
                self._dirs.add(d)
                return True
            return False

    def read_bytes(self, path: str) -> bytes:
        p = self._norm(path)
        if p not in self._files:
            raise FileNotFoundError(path)
        return self._files[p]

    def write_bytes(self, path: str, data: bytes) -> None:
        p = self._norm(path)
        with self._lock:
            self._files[p] = data
            self._mtimes[p] = int(time.time() * 1000)
            parent = os.path.dirname(p)
            if parent:
                self.mkdirs(parent)
