"""A Delta-style transactional table source ("delta" format).

North-star extension (BASELINE.md config 5: "Delta Lake source"). A minimal
transaction-log table: parquet data files plus `_delta_log/<version>.json` commits of
`add`/`remove` actions. The reader resolves the ACTIVE file set from the log, so a
DataFrame over a delta table sees a consistent snapshot, and index signatures
fingerprint exactly the active files (appends/removes change the signature, exactly
like plain-directory sources).

This is our own implementation of the table-format concept (no delta-rs in the
image); the log layout intentionally mirrors Delta's shape so the semantics carry:
JSON commits, monotonically numbered, last-writer-wins via atomic no-overwrite file
creation (same OCC primitive as the index operation log).
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional

from ..exceptions import HyperspaceException
from .filesystem import FileStatus, FileSystem, LocalFileSystem

DELTA_LOG_DIR = "_delta_log"


def _log_dir(path: str) -> str:
    return os.path.join(path, DELTA_LOG_DIR)


def _commit_path(path: str, version: int) -> str:
    return os.path.join(_log_dir(path), f"{version:020d}.json")


def is_delta_table(path: str, fs: Optional[FileSystem] = None) -> bool:
    fs = fs or LocalFileSystem()
    return fs.exists(_log_dir(path))


def latest_version(path: str, fs: FileSystem) -> Optional[int]:
    d = _log_dir(path)
    if not fs.exists(d):
        return None
    versions = [
        int(st.name.split(".")[0])
        for st in fs.list_status(d)
        if st.name.endswith(".json") and st.name.split(".")[0].isdigit()
    ]
    return max(versions) if versions else None


def commit(path: str, actions: List[Dict], fs: Optional[FileSystem] = None) -> int:
    """Append one commit; OCC on the version number (atomic no-overwrite create)."""
    fs = fs or LocalFileSystem()
    for _ in range(50):  # bounded retry under contention
        latest = latest_version(path, fs)
        version = 0 if latest is None else latest + 1
        text = "\n".join(json.dumps(a) for a in actions)
        if fs.atomic_write_text(_commit_path(path, version), text):
            return version
    raise HyperspaceException(f"Could not commit to delta table {path} (contention).")


def active_files(path: str, fs: Optional[FileSystem] = None) -> List[FileStatus]:
    """Replay the log: the current snapshot's data files."""
    fs = fs or LocalFileSystem()
    latest = latest_version(path, fs)
    if latest is None:
        raise HyperspaceException(f"Not a delta table (no {DELTA_LOG_DIR}): {path}")
    active: Dict[str, Dict] = {}
    for v in range(latest + 1):
        p = _commit_path(path, v)
        if not fs.exists(p):
            continue
        for line in fs.read_text(p).splitlines():
            if not line.strip():
                continue
            a = json.loads(line)
            if "add" in a:
                active[a["add"]["path"]] = a["add"]
            elif "remove" in a:
                active.pop(a["remove"]["path"], None)
    out = []
    for rel_path, add in sorted(active.items()):
        full = os.path.join(path, rel_path)
        if "size" in add:
            size = add["size"]
        else:
            size = fs.get_status(full).size if fs.exists(full) else 0
        out.append(
            FileStatus(
                path=full,
                size=size,
                modified_time=add.get("modificationTime", 0),
                is_dir=False,
            )
        )
    return out


def write_delta(
    table,
    path: str,
    mode: str = "append",
    fs: Optional[FileSystem] = None,
) -> int:
    """Write a Table as one parquet file + a commit (mode: append | overwrite)."""
    from ..engine import io as engine_io

    fs = fs or LocalFileSystem()
    if mode not in ("append", "overwrite"):
        raise HyperspaceException(f"Unsupported delta write mode: {mode}")
    latest = latest_version(path, fs)
    next_v = 0 if latest is None else latest + 1
    fname = f"part-{next_v:05d}-{int(time.time() * 1000)}.parquet"
    full = os.path.join(path, fname)
    engine_io.write_parquet(table, full)
    st = fs.get_status(full)
    actions: List[Dict] = []
    if mode == "overwrite" and latest is not None:
        for f in active_files(path, fs):
            actions.append({"remove": {"path": os.path.relpath(f.path, path)}})
    actions.append(
        {
            "add": {
                "path": fname,
                "size": st.size,
                "modificationTime": st.modified_time,
            }
        }
    )
    return commit(path, actions, fs)


def remove_file(path: str, rel_path: str, fs: Optional[FileSystem] = None) -> int:
    """Commit a remove action (the file itself is left for vacuum, like Delta)."""
    return commit(path, [{"remove": {"path": rel_path}}], fs)
