"""Remote-protocol storage backend: an fsspec adapter behind the FileSystem seam.

Parity: reference L1 is the Hadoop FileSystem abstraction reached through
`FileSystemFactory.create(path)` (`index/factories.scala:43-50`), which serves
HDFS/ABFS/local uniformly. The engine analogue adapts any fsspec backend
(memory://, s3://, gcs://, abfs://, hdfs:// — whatever protocol the deployment
has drivers for) to the same `FileSystem` contract the log/data managers and IO
layer are written against.

The load-bearing requirement is the operation log's optimistic concurrency: the
commit primitive must be atomic no-overwrite. Object stores have no atomic
rename, so this backend implements `atomic_write_text` with fsspec's exclusive
create (`open(path, "xb")`), mapping to the conditional-put primitive object
stores offer (S3 If-None-Match, GCS precondition, ABFS lease). CAVEAT, stated
plainly: fsspec documents mode "x" as implementation-dependent — some backends
implement it as a non-atomic exists+put or not at all. The OCC guarantee
therefore holds exactly on backends whose driver does a true server-side
conditional create (verified here: memory; s3fs>=2024 uses If-None-Match);
deployments must verify their driver before trusting racing writers. `rename`
remains check-then-move and is documented non-atomic; nothing on the OCC path
uses it.
"""

from __future__ import annotations

import posixpath
from typing import List, Optional

from .filesystem import FileStatus, FileSystem


_occ_fallback_warned = False


def _epoch_ms(v) -> int:
    if v is None:
        return 0
    if hasattr(v, "timestamp"):
        return int(v.timestamp() * 1000)
    try:
        return int(float(v) * 1000)
    except (TypeError, ValueError):
        return 0


class FsspecFileSystem(FileSystem):
    """`FileSystem` over an fsspec instance (default: the in-process `memory://`
    backend — the remote-protocol stand-in CI can run without cloud credentials)."""

    def __init__(self, fs=None, protocol: str = "memory"):
        if fs is None:
            import fsspec

            fs = fsspec.filesystem(protocol)
        self._fs = fs

    def exists(self, path: str) -> bool:
        return self._fs.exists(path)

    def is_dir(self, path: str) -> bool:
        return self._fs.isdir(path)

    def mkdirs(self, path: str) -> None:
        self._fs.makedirs(path, exist_ok=True)

    def _to_status(self, info: dict) -> FileStatus:
        mtime = info.get("mtime", info.get("LastModified", info.get("created")))
        return FileStatus(
            path=info["name"],
            size=int(info.get("size") or 0),
            modified_time=_epoch_ms(mtime),
            is_dir=info.get("type") == "directory",
        )

    def list_status(self, path: str) -> List[FileStatus]:
        if not self._fs.isdir(path):
            return []
        return sorted(
            (self._to_status(i) for i in self._fs.ls(path, detail=True)),
            key=lambda s: s.path,
        )

    def get_status(self, path: str) -> FileStatus:
        return self._to_status(self._fs.info(path))

    def delete(self, path: str, recursive: bool = False) -> None:
        if self._fs.exists(path):
            self._fs.rm(path, recursive=recursive)

    def rename(self, src: str, dst: str) -> bool:
        """Check-then-move — NOT atomic under racing writers (object stores have
        no atomic rename); the OCC commit uses `atomic_write_text` instead."""
        if self._fs.exists(dst):
            return False
        self._fs.mv(src, dst, recursive=self._fs.isdir(src))
        return True

    def read_bytes(self, path: str) -> bytes:
        with self._fs.open(path, "rb") as f:
            return f.read()

    def write_bytes(self, path: str, data: bytes) -> None:
        parent = posixpath.dirname(path)
        if parent:
            self._fs.makedirs(parent, exist_ok=True)
        with self._fs.open(path, "wb") as f:
            f.write(data)

    def atomic_write_text(self, path: str, text: str) -> bool:
        """OCC commit: exclusive create (`xb`). On backends with a true
        conditional put, exactly one of N racing writers of the same log id
        succeeds; the rest get FileExistsError → False
        (`IndexLogManager.scala:146-162` contract). See the module docstring for
        the per-backend atomicity caveat."""
        parent = posixpath.dirname(path)
        if parent:
            self._fs.makedirs(parent, exist_ok=True)
        try:
            with self._fs.open(path, "xb") as f:
                f.write(text.encode("utf-8"))
            return True
        except FileExistsError:
            return False
        except ValueError:
            # Driver doesn't implement mode "x" at all (fsspec documents it as
            # implementation-dependent; MemoryFileSystem raises ValueError).
            # Emulate exclusive create as check-then-put. For memory:// this
            # is exact (state is process-local, the GIL serializes the
            # check+put against other threads' opens); for a SHARED remote
            # store it is weaker than a true conditional put, so racing
            # writers could both "win" — warn loudly ONCE instead of silently
            # downgrading the OCC contract the crash-safe commits depend on.
            if not self._is_process_local():
                global _occ_fallback_warned
                if not _occ_fallback_warned:
                    _occ_fallback_warned = True
                    import warnings

                    warnings.warn(
                        f"fsspec driver {type(self._fs).__name__} does not "
                        "support exclusive create (mode 'x'); the operation "
                        "log's OCC commit falls back to NON-ATOMIC "
                        "check-then-put. Racing writers on a shared store can "
                        "both succeed — verify your driver before trusting "
                        "concurrent index mutations (see storage/remote.py).",
                        RuntimeWarning,
                        stacklevel=3,
                    )
            if self._fs.exists(path):
                return False
            with self._fs.open(path, "wb") as f:
                f.write(text.encode("utf-8"))
            return True

    def _is_process_local(self) -> bool:
        """Whether this fsspec backend's state lives in THIS process (the
        memory:// family), making check-then-put as good as exclusive create."""
        proto = getattr(self._fs, "protocol", "")
        protos = proto if isinstance(proto, (tuple, list)) else (proto,)
        return "memory" in protos


_SCHEMES = ("memory://", "s3://", "gcs://", "gs://", "abfs://", "az://", "hdfs://")


def filesystem_for_path(path: str) -> Optional[FileSystem]:
    """Scheme-based backend selection (the `FileSystemFactory.create(path)`
    analogue): returns an FsspecFileSystem for remote-protocol paths, None for
    plain local paths."""
    for scheme in _SCHEMES:
        if path.startswith(scheme):
            import fsspec

            protocol = scheme.split(":", 1)[0]
            return FsspecFileSystem(fsspec.filesystem(protocol))
    return None
