from .filesystem import FileStatus, FileSystem, InMemoryFileSystem, LocalFileSystem  # noqa: F401
