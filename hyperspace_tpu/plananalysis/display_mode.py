"""Explain display modes.

Parity: reference `index/plananalysis/DisplayMode.scala:26-89` — `PlainTextMode`
(`<---- ---->` highlight tags), `HTMLMode` (`<pre>` wrap, bold highlights, `<br/>`
newlines), `ConsoleMode` (ANSI green background); begin/end tags overridable via conf.
"""

from __future__ import annotations

from ..config import IndexConstants, SessionConf


class DisplayMode:
    new_line = "\n"
    begin_end_tag = ("", "")

    def __init__(self, conf: SessionConf):
        self.highlight_tag = (
            conf.get(IndexConstants.HIGHLIGHT_BEGIN_TAG, self.default_highlight[0]),
            conf.get(IndexConstants.HIGHLIGHT_END_TAG, self.default_highlight[1]),
        )

    @property
    def default_highlight(self):
        return ("", "")


class PlainTextMode(DisplayMode):
    @property
    def default_highlight(self):
        return ("<----", "---->")


class HTMLMode(DisplayMode):
    new_line = "<br/>"
    begin_end_tag = ("<pre>", "</pre>")

    @property
    def default_highlight(self):
        return ('<b style="background: #ff9900">', "</b>")


class ConsoleMode(DisplayMode):
    @property
    def default_highlight(self):
        return ("[42m", "[0m")


def create_display_mode(conf: SessionConf) -> DisplayMode:
    from ..exceptions import HyperspaceException

    name = (conf.get(IndexConstants.DISPLAY_MODE) or "plaintext").lower()
    if name == "html":
        return HTMLMode(conf)
    if name == "console":
        return ConsoleMode(conf)
    if name == "plaintext":
        return PlainTextMode(conf)
    raise HyperspaceException(f"Display mode: {name} not supported.")
