"""EXPLAIN ANALYZE: the plan tree re-rendered with runtime truth.

The static explain subsystem (`plan_analyzer`) compiles plans without running
them. This module EXECUTES the query once under a trace capture
(`telemetry.tracing.capture`) and renders the SAME physical tree annotated
per node with what actually happened: measured wall seconds, rows out,
cache/memo hit-or-miss, and the pipelined executors' stage spans
(probe/verify/gather/…) nested where they ran. Sections below the tree report
the optimizer-rule decisions (which index rule rewrote the plan, and why the
others sat out), Pallas kernel fallbacks, and the per-query cache/metric
counter deltas.

Nodes with no span did not execute — the engine served them another way (a
fused/streamed parent, a memoized pair cache, a footer-only count). That is
reported as such rather than as zero: the annotated tree never claims time
that was not spent.

Entry points: `DataFrame.explain(analyze=True)` and
`Hyperspace.explain(df, analyze=True)`.
"""

from __future__ import annotations

from typing import Dict, List, Optional


def _fmt_seconds(s: Optional[float]) -> str:
    if s is None:
        return "?"
    if s >= 1:
        return f"{s:.3f}s"
    return f"{s * 1000:.1f}ms"


# Per-node annotation attrs: cache verdicts plus the scan pushdown's
# row-group pruning outcome (set only when a scan actually pruned).
_VERDICT_KEYS = (
    "bucketed_cache",
    "pairs_memo",
    "row_groups_scanned",
    "row_groups_skipped",
    # Retries that happened under this node (resilience layer): a plan that
    # was correct-but-retried shows it inline, per operator.
    "io_retries",
)


def _subtree_verdict(span, children_of, key, rendered_ids, own_id, depth: int = 0):
    """First cache-verdict value for `key` in the span's subtree, WITHOUT
    crossing into another rendered node's spans (a join line must not show
    its child scan's verdict). The attr may land on an INNER span of the
    same node (`execute` delegating to the wrapped `execute_concat`) or on a
    delegate node's span that matches no rendered tree node (e.g. the
    temporary demoted bucketed scan a hybrid `ScanExec.execute` constructs)
    — the verdict still belongs on the line the user sees."""
    v = span.attrs.get(key)
    if v is not None or depth >= 4:
        return v
    for child in children_of.get(span.span_id, ()):
        if not child.name.startswith("op:"):
            continue
        nid = child.attrs.get("node_id")
        if nid is not None and nid != own_id and nid in rendered_ids:
            continue  # a different rendered node's span: its verdict is its own
        v = _subtree_verdict(child, children_of, key, rendered_ids, own_id, depth + 1)
        if v is not None:
            return v
    return None


def _node_annotation(span, children_of, rendered_ids) -> str:
    parts = [f"time={_fmt_seconds(span.duration_s)}"]
    rows = span.attrs.get("rows_out")
    if rows is not None:
        parts.append(f"rows={rows}")
    own_id = span.attrs.get("node_id")
    for key in _VERDICT_KEYS:
        v = _subtree_verdict(span, children_of, key, rendered_ids, own_id)
        if v is not None:
            parts.append(f"{key}={v}")
    compiles = span.attrs.get("xla_compiles")
    if compiles:
        # Compile-observatory delta: XLA compiles triggered while this span
        # was the ambient one (a cold operator shows its compile bill here).
        parts.append(
            f"compiles={compiles}"
            f"(+{_fmt_seconds(span.attrs.get('xla_compile_s') or 0.0)})"
        )
    if span.status != "ok":
        parts.append(f"status={span.status}")
    return "   [" + ", ".join(parts) + "]"


def _stage_lines(span, children_of, indent: int) -> List[str]:
    """The synthesized `<kind>:stages` children of one operator span, each
    rendered as one busy-time breakdown line (stages overlap; their busy sum
    over wall is the overlap ratio — see `StageTimings`)."""
    out: List[str] = []
    pad = "  " * indent + "     "
    for child in children_of.get(span.span_id, ()):
        if not child.name.endswith(":stages"):
            continue
        kind = child.name.split(":", 1)[0]
        stages = []
        for st in children_of.get(child.span_id, ()):
            stages.append(f"{st.name.split(':', 1)[1]}={_fmt_seconds(st.duration_s)}")
        wall = child.attrs.get("wall_s")
        overlap = child.attrs.get("overlap_ratio")
        extra = []
        if wall is not None:
            extra.append(f"wall={_fmt_seconds(wall)}")
        if overlap is not None:
            extra.append(f"overlap={overlap}")
        mode = child.attrs.get("mode")
        if mode:
            extra.append(f"mode={mode}")
        out.append(
            pad
            + f"{kind} stages: "
            + " ".join(stages + extra)
        )
        # Multiway star joins carry per-dimension stage walls in the summary
        # (`star_dims`): render one line per dimension so the explain tree
        # shows WHERE a star probe spent its time, not just a bare node.
        if child.attrs.get("join_mode") == "star":
            for dim in child.attrs.get("star_dims") or ():
                if not isinstance(dim, dict):
                    continue
                cells = [f"dim[{dim.get('index', '?')}]:"]
                for k in ("pad_s", "probe_s", "verify_s"):
                    v = dim.get(k)
                    if v is not None:
                        cells.append(f"{k[:-2]}={_fmt_seconds(v)}")
                for k in ("buckets", "pairs", "memo"):
                    v = dim.get(k)
                    if v is not None:
                        cells.append(f"{k}={v}")
                out.append(pad + "  " + " ".join(cells))
        fallbacks = child.attrs.get("pallas_fallbacks")
        if fallbacks:
            out.append(pad + f"pallas fallbacks: {fallbacks}")
    return out


def explain_analyze_string(df) -> str:
    """Execute `df` once under a trace and render the annotated plan tree."""
    import time as _time

    from .. import resilience
    from ..engine.physical import ExecContext
    from ..telemetry import accounting, metrics, tracing
    from . import attribution as _attribution
    from . import planner as _planner

    session = df.session
    snap0 = metrics.snapshot()
    with tracing.capture() as cap:
        # The resilience scope mirrors collect(): the planner's decisions
        # ride the QueryScope into pool workers here exactly as they do on
        # the production path, so the analyzed run IS the decided run.
        with resilience.query_scope("query:explain_analyze"):
            with tracing.query_span("query:explain_analyze") as root:
                with tracing.span("plan"):
                    phys = df.physical_plan()
                fp = df._attach_fingerprint(phys)
                pd = _planner.decide(phys, fp)
                with _planner.decisions_scope(pd):
                    t0 = _time.monotonic()
                    result = phys.execute(ExecContext(session))
                _planner.observe(
                    pd,
                    _time.monotonic() - t0,
                    stages=_attribution.query_stage_walls(),
                )
                root.set_attr("rows_out", int(result.num_rows))
                accounting.set_value("rows_produced", int(result.num_rows))
    snap1 = metrics.snapshot()
    trace = cap.trace
    if trace is None:  # defensive: capture always receives the root above
        return "EXPLAIN ANALYZE: no trace captured"

    # Operator spans join the rendered tree on node identity; the OUTERMOST
    # span of a node wins (a node may open several — e.g. execute over
    # execute_concat).
    by_node: Dict[int, object] = {}
    for s in trace.spans:
        nid = s.attrs.get("node_id")
        if nid is not None and nid not in by_node:
            by_node[nid] = s
    children_of = trace.spans_by_parent()

    lines: List[str] = []
    root_span = trace.root
    lines.append("=" * 61)
    lines.append("EXPLAIN ANALYZE")
    lines.append("=" * 61)
    lines.append(
        f"query_id={trace.query_id}  wall={_fmt_seconds(root_span.duration_s)}  "
        f"rows={result.num_rows}"
    )
    plan_spans = trace.find("plan")
    if plan_spans:
        lines.append(f"planning={_fmt_seconds(plan_spans[0].duration_s)}")
    lines.append("")

    rendered_ids = {id(n) for n in phys.collect_nodes()}

    def walk(node, indent: int) -> None:
        line = node.format_line(indent)
        span = by_node.get(id(node))
        if span is not None:
            line += _node_annotation(span, children_of, rendered_ids)
        else:
            line += "   [not executed: fused/streamed into parent or served from cache]"
        lines.append(line)
        if span is not None:
            lines.extend(_stage_lines(span, children_of, indent))
        for c in node.children():
            walk(c, indent + 1)

    walk(phys, 0)
    # Stage spans recorded under the ROOT (e.g. a streamed aggregate whose
    # operator span closed before the summary landed) still get shown.
    root_stages = _stage_lines(root_span, children_of, 0)
    if root_stages:
        lines.append("")
        lines.extend(root_stages)

    decisions: List[dict] = []
    for s in trace.spans:
        if s.name.startswith("rule:"):
            decisions.extend(s.attrs.get("decisions", ()))
    lines.append("")
    lines.append("Rule decisions:")
    if decisions:
        for d in decisions:
            verdict = "applied" if d.get("applied") else "skipped"
            detail = {
                k: v
                for k, v in d.items()
                if k not in ("rule", "applied") and v not in (None, [], {})
            }
            suffix = f"  {detail}" if detail else ""
            lines.append(f"  {d.get('rule')}: {verdict}{suffix}")
    else:
        lines.append("  (none recorded — no optimizer rules fired on this plan)")

    # Planner: the adaptive cost-based decisions this run executed under,
    # each knob's chosen arm with BOTH arms' predicted attributable cost and
    # the predicted-vs-actual drift ratio (`docs/planner.md`). Pinned knobs
    # (explicit env flags) are reported too — the planner never overrides
    # them, it only prices what the pin costs.
    lines.append("")
    lines.append("Planner:")
    if pd is None:
        lines.append(
            "  (off: HYPERSPACE_PLANNER=0 — env-flag defaults in force)"
        )
    else:
        from .costmodel import KNOBS as _knobs

        wall_actual = root_span.duration_s or 0.0
        lines.append(
            f"  calibration={pd.calibration_source}  "
            f"fingerprint={pd.fingerprint or '(not computed)'}"
        )
        for knob in _knobs:
            d = pd.decisions.get(knob)
            if d is None:
                continue
            drift = ""
            if d.predicted_s and d.predicted_s > 0:
                drift = f"  drift_x={wall_actual / d.predicted_s:.2f}"
            lines.append(
                f"  {knob}: {d.arm} [{d.source}]  "
                f"predicted={_fmt_seconds(d.predicted_s)}  "
                f"(alt {_planner.arm_label(d.alt)}: "
                f"{_fmt_seconds(d.predicted_alt_s)}){drift}"
            )
        lines.append(
            f"  actual wall={_fmt_seconds(wall_actual)}  "
            "(drift_x = wall / the arm's predicted attributable cost)"
        )

    # Resource ledger: what THIS query spent (exact per-query attribution —
    # the contextvar-scoped ledger, not the process-wide counters below).
    led = accounting.ledger_for(trace.query_id)
    lines.append("")
    lines.append("Resource ledger (this query):")
    if led is not None:
        d = led.to_dict()
        for key in sorted(k for k in d if k not in ("query_id", "name", "start_s")):
            lines.append(f"  {key}: {d[key]}")
    else:
        lines.append("  (no ledger recorded)")

    # Device cost section (device_observatory): the device/host wall split
    # from the sampled execute probes, the transfer bytes both ways, and the
    # pow2 padding tax. Probes ride HYPERSPACE_DEVICE_TIMING; without it the
    # split is unknown and says so rather than claiming host-only time.
    lines.append("")
    lines.append("Device cost (this query):")
    if led is not None:
        d = led.to_dict()
        dev_s = d.get("device_time_s")
        if dev_s is not None:
            wall = d.get("wall_s") or 0.0
            host_s = d.get("host_time_s", max(0.0, wall - dev_s))
            pct = f" ({dev_s / wall:.0%} of wall)" if wall else ""
            lines.append(
                f"  device={_fmt_seconds(dev_s)}{pct}  host={_fmt_seconds(host_s)}"
                "  (sampled execute probes; see docs/observability.md)"
            )
        else:
            lines.append(
                "  device/host split unknown (set HYPERSPACE_DEVICE_TIMING=1 "
                "to sample execute timing)"
            )
        h2d = d.get("device_upload_bytes")
        d2h = d.get("d2h_bytes")
        if h2d or d2h:
            lines.append(f"  transfers: h2d={h2d or 0}B d2h={d2h or 0}B")
        if d.get("pad_ratio") is not None:
            lines.append(
                f"  padding tax: payload={d.get('pad_bytes_payload', 0)}B "
                f"padded={d.get('pad_bytes_padded', 0)}B "
                f"pad_ratio={d['pad_ratio']}"
            )
    else:
        lines.append("  (no ledger recorded)")

    # Attribution: per-stage cost vectors (stage ledger) joined with knob
    # ownership and the planner's stage-grain predicted-vs-actual. Only
    # rendered when the query carried stage data (HYPERSPACE_STAGE_ATTRIBUTION
    # on and at least one labeled bracket ran).
    attr_lines = _attribution.explain_lines(led.to_dict() if led else None)
    if attr_lines:
        lines.append("")
        lines.extend(attr_lines)

    delta = metrics.counters_delta(snap0, snap1)
    lines.append("")
    # The registry is process-wide: under concurrent queries this section
    # includes their counters too (span-tree attributions above are exact).
    lines.append("Cache/metric deltas (process-wide during this query):")
    if delta:
        for name in sorted(delta):
            lines.append(f"  {name}: +{delta[name]}")
    else:
        lines.append("  (none)")
    return "\n".join(lines)
