"""String builder honoring a DisplayMode (reference
`index/plananalysis/BufferStream.scala:23-83`)."""

from __future__ import annotations

from .display_mode import DisplayMode


class BufferStream:
    def __init__(self, mode: DisplayMode):
        self._mode = mode
        self._parts = []

    def write(self, s: str) -> "BufferStream":
        self._parts.append(s)
        return self

    def write_line(self, s: str = "") -> "BufferStream":
        self._parts.append(s + self._mode.new_line)
        return self

    def highlight(self, s: str) -> "BufferStream":
        begin, end = self._mode.highlight_tag
        self._parts.append(begin + s + end)
        return self

    def highlight_line(self, s: str) -> "BufferStream":
        self.highlight(s)
        self._parts.append(self._mode.new_line)
        return self

    def __repr__(self):
        return f"BufferStream({len(self._parts)} parts)"

    def to_string(self) -> str:
        begin, end = self._mode.begin_end_tag
        return begin + "".join(self._parts) + end
