"""Adaptive cost-based planner: choose every governed knob per plan, learn
from predicted-vs-actual, self-correct per plan class.

ROADMAP item 2, closing the loop the observability PRs opened: PR 11's
per-fingerprint baselines and PR 14's transfer/pad ledgers measure exactly
what each knob choice costs, but the knobs themselves were static env flags
or crude auto-gates. This module decides them at plan time:

1. **Pin-vs-auto** — an env flag that is SET (non-empty) pins its knob
   exactly as before: the planner never overrides an explicit operator
   choice, and pinned values stay part of the fingerprint posture
   (`fingerprint.FLAG_KEYS`). An UNSET flag hands the knob to the planner.
2. **Model** — `costmodel.estimate` prices both arms of every governed knob
   from footer-cache column stats + device-observatory calibration. The
   model's priors reproduce today's defaults; it deviates only on decisive,
   warm-stats-backed margins.
3. **Self-correction** — each decided query feeds a per-(fingerprint, knob,
   arm) outcome store (the PR 12 pred-fuse mint-count gate generalized):
   when a class's measured wall drifts past `HYPERSPACE_PLANNER_DRIFT_X`
   times the chosen arm's prediction, the planner explores the alternative
   arm (one knob at a time — every arm is byte-identical by the standing
   flag contracts, so exploration can never change results); once both arms
   hold `HYPERSPACE_PLANNER_MIN_SAMPLES` measurements the better-measured
   arm wins deterministically — recomputed from folded stats on every
   decide, so a flip survives process restarts (the store re-folds its
   JSONL segments from disk, exactly like the history store's baselines).

**Threading**: `decide()` runs in the session right after physical planning;
the returned `PlanDecisions` is set as an ambient contextvar AND stamped on
the `resilience.QueryScope`, so every pool worker that adopts the scope
(`use_scope` — the established propagation channel) sees the same decisions.
Gates (`streaming.streaming_enabled`, `encoding.encoded_exec_enabled`,
`pushdown.pushdown_enabled`, `packed_codes.packed_codes_enabled`,
`ops.hashing._hash_quantize_enabled`, `ops.bucket_join.size_classes_enabled`)
consult `decided_value(knob)` only AFTER their env flag came back unset —
explicit flags always win, and with the planner off the whole surface is one
contextvar read returning None (the `HYPERSPACE_PLANNER=0` zero-cost-off
oracle, pinned by tests/test_planner.py).

**Ledger loop**: decisions + per-arm predictions land on the query span and
ledger at decide time (`accounting.set_value("planner", ...)`); at ledger
close `annotate_close` joins predicted-vs-actual and the per-fingerprint
history baseline onto the same dict, so history records, hsreport's planner
drift table, and `explain(analyze=True)`'s Planner section all read one
source of truth.

**Persistence**: outcome records are JSONL segments (one per writer process,
crash-tolerant appends) in ``HYPERSPACE_PLANNER_DIR``, defaulting to
``<history_dir>/planner`` when ``HYPERSPACE_HISTORY=1``. Without a
persistent home the planner stays pure-model: learning requires the same
operator opt-in as the baselines it corrects against. Appends are bounded:
an arm stops persisting after `_PERSIST_CAP` samples (stats saturate), so
the store is bounded by class cardinality, not query volume.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import socket
import threading
import time
import uuid
from typing import Dict, Iterator, Optional

from .costmodel import INT_KNOBS, KNOB_ENV, KNOBS

ENV_PLANNER = "HYPERSPACE_PLANNER"
ENV_PLANNER_DIR = "HYPERSPACE_PLANNER_DIR"
ENV_MIN_SAMPLES = "HYPERSPACE_PLANNER_MIN_SAMPLES"
ENV_DRIFT_X = "HYPERSPACE_PLANNER_DRIFT_X"

_DEFAULT_MIN_SAMPLES = 4
_DEFAULT_DRIFT_X = 1.5
#: An arm keeps persisting outcome records until it holds this many samples;
#: beyond it the folded stats are saturated and further appends only grow
#: the store (bounded-by-construction — no compaction machinery needed).
_PERSIST_CAP = 64
#: Measured must beat measured by this margin to FLIP away from the model
#: arm (hysteresis: a 2% timing wobble must not oscillate a class).
_FLIP_MARGIN = 0.9
#: Drift exploration needs a prediction of at least this much attributable
#: cost — a 50 us prediction drowning in constant overhead is not evidence.
_MIN_PRED_S = 0.005


def planner_enabled() -> bool:
    """Default ON; ``HYPERSPACE_PLANNER=0`` restores the pure env-flag
    defaults with zero planner work anywhere past this one read."""
    return os.environ.get(ENV_PLANNER, "") != "0"


def _min_samples() -> int:
    raw = os.environ.get(ENV_MIN_SAMPLES, "")
    try:
        return max(1, int(raw)) if raw else _DEFAULT_MIN_SAMPLES
    except ValueError:
        return _DEFAULT_MIN_SAMPLES


def _drift_x() -> float:
    raw = os.environ.get(ENV_DRIFT_X, "")
    try:
        return max(1.0, float(raw)) if raw else _DEFAULT_DRIFT_X
    except ValueError:
        return _DEFAULT_DRIFT_X


def arm_label(value) -> str:
    """Canonical arm name: bools -> on/off, ints -> their decimal value."""
    if isinstance(value, bool):
        return "on" if value else "off"
    return str(int(value))


class Decision:
    """One knob's resolution for one plan: the chosen value, the single
    alternative it is A/B'd against, both arms' predicted attributable
    seconds, and where the choice came from — "model" (cost model),
    "measured" (outcome-store flip), "explore" (gathering alternative-arm
    samples after drift), or "pinned" (explicit env flag; the planner only
    reports it, gates never consult it)."""

    __slots__ = ("knob", "value", "alt", "predicted_s", "predicted_alt_s", "source")

    def __init__(self, knob, value, alt, predicted_s, predicted_alt_s, source):
        self.knob = knob
        self.value = value
        self.alt = alt
        self.predicted_s = predicted_s
        self.predicted_alt_s = predicted_alt_s
        self.source = source

    @property
    def arm(self) -> str:
        return arm_label(self.value)


class PlanDecisions:
    """The one decisions object threaded through a query's execution. Gates
    read `value(knob)`; pinned knobs answer None there (the env flag already
    decided at the gate — the planner must never even appear to override)."""

    __slots__ = ("fingerprint", "decisions", "calibration_source")

    def __init__(self, fingerprint: Optional[str], decisions: Dict[str, Decision], calibration_source: str = "default"):
        self.fingerprint = fingerprint
        self.decisions = decisions
        self.calibration_source = calibration_source

    def value(self, knob: str):
        d = self.decisions.get(knob)
        if d is None or d.source == "pinned":
            return None
        return d.value

    def to_ledger(self) -> dict:
        """The compact dict that rides the query ledger/span (and from there
        history records and hsreport)."""
        out = {}
        for knob, d in self.decisions.items():
            out[knob] = {
                "arm": d.arm,
                "alt": arm_label(d.alt),
                "predicted_s": d.predicted_s,
                "predicted_alt_s": d.predicted_alt_s,
                "source": d.source,
            }
        return out


# ---------------------------------------------------------------------------
# Ambient threading: contextvar on the deciding thread + the QueryScope slot
# for pool workers (use_scope adoption — the use_ledger twin).
# ---------------------------------------------------------------------------

_ambient: "contextvars.ContextVar[Optional[PlanDecisions]]" = contextvars.ContextVar(
    "hyperspace_plan_decisions", default=None
)


def current_decisions() -> Optional[PlanDecisions]:
    """The ambient `PlanDecisions`, or None (planner off / nothing decided).
    Checks this thread's contextvar first, then the adopted QueryScope —
    zero env reads, zero stat reads either way (the hot-path contract)."""
    pd = _ambient.get()
    if pd is not None:
        return pd
    from .. import resilience as _resilience

    sc = _resilience.current_scope()
    if sc is not None:
        return sc.plan_decisions
    return None


def decided_value(knob: str):
    """What the planner decided for `knob`, or None (undecided / pinned /
    planner off). THE gate helper: call only after the knob's own env flag
    came back unset."""
    pd = current_decisions()
    if pd is None:
        return None
    return pd.value(knob)


@contextlib.contextmanager
def decisions_scope(pd: Optional[PlanDecisions]) -> Iterator[None]:
    """Install `pd` as the ambient decisions for the duration: contextvar on
    this thread plus the current QueryScope's slot (pool propagation)."""
    if pd is None:
        yield
        return
    from .. import resilience as _resilience

    token = _ambient.set(pd)
    sc = _resilience.current_scope()
    prev = None
    if sc is not None:
        prev = sc.plan_decisions
        sc.plan_decisions = pd
    try:
        yield
    finally:
        _ambient.reset(token)
        if sc is not None:
            sc.plan_decisions = prev


# ---------------------------------------------------------------------------
# Outcome store: per-(fingerprint, knob, arm) measured walls, JSONL-persisted
# ---------------------------------------------------------------------------


class _ArmStat:
    # stage_n/stage_sum fold the knob-relevant STAGE subtotal when the query
    # carried stage attribution (schema_version-2 outcome records); wall
    # stats keep folding regardless, so v1 records and attribution-off
    # queries contribute exactly as before.
    __slots__ = ("n", "wall_sum", "pred_sum", "stage_n", "stage_sum")

    def __init__(self):
        self.n = 0
        self.wall_sum = 0.0
        self.pred_sum = 0.0
        self.stage_n = 0
        self.stage_sum = 0.0

    def fold(self, wall_s: float, predicted_s: float, stage_s=None) -> None:
        self.n += 1
        self.wall_sum += float(wall_s)
        self.pred_sum += float(predicted_s)
        if isinstance(stage_s, (int, float)) and stage_s > 0:
            self.stage_n += 1
            self.stage_sum += float(stage_s)

    def mean_wall(self) -> float:
        return self.wall_sum / self.n if self.n else 0.0

    def mean_pred(self) -> float:
        return self.pred_sum / self.n if self.n else 0.0

    def mean_stage(self) -> float:
        return self.stage_sum / self.stage_n if self.stage_n else 0.0


class OutcomeStore:
    """Folded per-(fingerprint, knob, arm) outcome stats + the JSONL append
    log they fold from. One segment per writer process (crash-safe append +
    flush, torn final lines skipped on read — the history store's landing
    idiom); re-folding every ``planner-*.jsonl`` at open is what makes a
    learned flip survive a restart.

    Besides arm walls, the store folds each class's observed ``io.pruning``
    row-group counters (`[scanned, skipped]` per fingerprint) so the cost
    model's pushdown arm prices this class's MEASURED prune selectivity
    instead of the static half-prune prior."""

    def __init__(self, dir_path: str):
        self.dir = dir_path
        self._lock = threading.Lock()
        self._stats: Dict[tuple, _ArmStat] = {}
        self._prune: Dict[str, list] = {}  # fp -> [scanned, skipped]
        self._fh = None
        os.makedirs(dir_path, exist_ok=True)
        self._load()

    def _load(self) -> None:
        from ..telemetry import history as _history

        try:
            names = sorted(os.listdir(self.dir))
        except OSError:
            names = []
        for n in names:
            if not (n.startswith("planner-") and n.endswith(".jsonl")):
                continue
            for rec in _history.iter_file_records(os.path.join(self.dir, n)):
                if rec.get("kind") != "planner_outcome":
                    continue  # forward compat: unknown kinds skip
                fp = rec.get("fingerprint")
                outcomes = rec.get("outcomes")
                if not fp or not isinstance(outcomes, dict):
                    continue
                pr = rec.get("pruning")
                if isinstance(pr, (list, tuple)) and len(pr) == 2:
                    try:
                        self._fold_prune(fp, int(pr[0]), int(pr[1]))
                    except (TypeError, ValueError):
                        pass
                for knob, o in outcomes.items():
                    if not isinstance(o, dict):
                        continue
                    try:
                        self._fold(
                            fp,
                            knob,
                            str(o["arm"]),
                            float(o["wall_s"]),
                            float(o.get("predicted_s", 0.0)),
                            # v1 records carry no stage_s — they fold as
                            # wall-only, exactly the old semantics.
                            stage_s=o.get("stage_s"),
                        )
                    except (KeyError, TypeError, ValueError):
                        continue

    def _fold(self, fp, knob, arm, wall_s, predicted_s, stage_s=None) -> _ArmStat:
        st = self._stats.get((fp, knob, arm))
        if st is None:
            st = self._stats[(fp, knob, arm)] = _ArmStat()
        st.fold(wall_s, predicted_s, stage_s=stage_s)
        return st

    def _fold_prune(self, fp: str, scanned: int, skipped: int) -> None:
        p = self._prune.get(fp)
        if p is None:
            p = self._prune[fp] = [0, 0]
        p[0] += max(0, scanned)
        p[1] += max(0, skipped)

    def stat(self, fp: str, knob: str, arm: str) -> _ArmStat:
        with self._lock:
            return self._stats.get((fp, knob, arm)) or _ArmStat()

    def prune_selectivity(self, fp: str) -> Optional[float]:
        """This class's measured scanned fraction, or None before any query
        of the class has pushed a zone map through the pruning counters."""
        with self._lock:
            p = self._prune.get(fp)
        if not p:
            return None
        total = p[0] + p[1]
        if total <= 0:
            return None
        return p[0] / total

    def observe(self, fp: str, outcomes: Dict[str, dict], pruning=None) -> None:
        """Fold one query's measured outcomes and append the record —
        skipping persistence for arms already holding `_PERSIST_CAP` samples
        (the boundedness rule). `pruning` is the query's `(scanned, skipped)`
        row-group counter delta; it folds into the per-class selectivity even
        when every arm has saturated persistence (selectivity keeps tracking
        the live workload either way — only the APPEND is capped)."""
        persist = {}
        with self._lock:
            if isinstance(pruning, (list, tuple)) and len(pruning) == 2:
                try:
                    self._fold_prune(fp, int(pruning[0]), int(pruning[1]))
                except (TypeError, ValueError):
                    pruning = None
            else:
                pruning = None
            for knob, o in outcomes.items():
                st = self._fold(
                    fp,
                    knob,
                    o["arm"],
                    o["wall_s"],
                    o.get("predicted_s", 0.0),
                    stage_s=o.get("stage_s"),
                )
                if st.n <= _PERSIST_CAP:
                    persist[knob] = o
            if not persist:
                return
            # Records carrying a stage-local subtotal are the versioned
            # per-stage record kind (v2); readers of either version tolerate
            # the other — v1 folds wall-only, and an old reader of a v2
            # record simply ignores the extra ``stage_s`` key.
            versioned = any(
                isinstance(o.get("stage_s"), (int, float))
                for o in persist.values()
            )
            rec = {
                "schema_version": 2 if versioned else 1,
                "kind": "planner_outcome",
                "ts": round(time.time(), 6),
                "fingerprint": fp,
                "outcomes": persist,
            }
            if pruning is not None:
                rec["pruning"] = [int(pruning[0]), int(pruning[1])]
            try:
                if self._fh is None:
                    self._fh = open(
                        os.path.join(
                            self.dir,
                            f"planner-{socket.gethostname()}-{os.getpid()}"
                            f"-{uuid.uuid4().hex[:8]}.jsonl",
                        ),
                        "a",
                    )
                self._fh.write(json.dumps(rec, default=str) + "\n")
                self._fh.flush()
            except OSError:
                pass  # learning must never fail the query it observed

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None

    def summary(self) -> Dict[tuple, dict]:
        with self._lock:
            out = {}
            for key, st in self._stats.items():
                row = {
                    "n": st.n,
                    "mean_wall_s": round(st.mean_wall(), 6),
                    "mean_predicted_s": round(st.mean_pred(), 6),
                }
                if st.stage_n:
                    row["stage_n"] = st.stage_n
                    row["mean_stage_s"] = round(st.mean_stage(), 6)
                out[key] = row
            return out


_stores: Dict[str, OutcomeStore] = {}
_stores_lock = threading.Lock()


def outcome_dir() -> Optional[str]:
    """Where outcome records persist: ``HYPERSPACE_PLANNER_DIR``, else the
    history store's ``planner/`` sidecar when history is on, else None —
    no persistent home means no learning (pure-model planner)."""
    env = os.environ.get(ENV_PLANNER_DIR, "")
    if env:
        return env
    from ..telemetry import history as _history

    if _history.enabled():
        return os.path.join(_history.history_dir(), "planner")
    return None


def _outcome_store() -> Optional[OutcomeStore]:
    d = outcome_dir()
    if d is None:
        return None
    d = os.path.abspath(d)
    with _stores_lock:
        st = _stores.get(d)
        if st is None:
            try:
                st = _stores[d] = OutcomeStore(d)
            except OSError:
                return None
        return st


def reset() -> None:
    """Close and forget every outcome store (tests; the history
    `reset_stores` twin). On-disk segments survive — the next store open
    re-folds them, which is exactly the restart contract under test."""
    with _stores_lock:
        for st in _stores.values():
            st.close()
        _stores.clear()
    with _activity_lock:
        _activity.clear()


# ---------------------------------------------------------------------------
# decide / observe / annotate_close — the planner loop
# ---------------------------------------------------------------------------


def _pinned_value(knob: str, raw: str):
    if knob in INT_KNOBS:
        try:
            return int(raw)
        except ValueError:
            return raw
    return raw != "0"


def decide(phys, fingerprint: Optional[str]) -> Optional[PlanDecisions]:
    """Resolve every governed knob for one physical plan. None when the
    planner is off. Never raises — a broken model must never break a query
    (the quarantine-fallback posture)."""
    if not planner_enabled():
        return None
    try:
        return _decide(phys, fingerprint)
    except Exception:
        return None


def _decide(phys, fingerprint: Optional[str]) -> PlanDecisions:
    from . import costmodel

    stats = costmodel.collect_stats(phys)
    cal = costmodel.current_calibration()
    store = _outcome_store()
    sel = None
    if store is not None and fingerprint:
        sel = store.prune_selectivity(fingerprint)
    if sel is None:
        est = costmodel.estimate(stats, cal)
    else:
        try:
            est = costmodel.estimate(stats, cal, prune_selectivity=sel)
        except TypeError:  # tests substitute two-arg estimators
            est = costmodel.estimate(stats, cal)
    min_n = _min_samples()
    drift_x = _drift_x()

    decisions: Dict[str, Decision] = {}
    explore_claimed = False
    for knob in KNOBS:
        raw = os.environ.get(KNOB_ENV[knob], "")
        model_v, alt_v, pred_m, pred_a = est.get(knob, (True, False, 0.0, 0.0))
        if raw != "":
            decisions[knob] = Decision(knob, _pinned_value(knob, raw), alt_v, pred_m, pred_a, "pinned")
            continue
        value, source = model_v, "model"
        if store is not None and fingerprint:
            sm = store.stat(fingerprint, knob, arm_label(model_v))
            sa = store.stat(fingerprint, knob, arm_label(alt_v))
            if sm.n >= min_n and sa.n >= min_n:
                # Both arms measured: the better-measured arm wins, with
                # hysteresis — flipping away from the model needs a margin.
                # STAGE grain when both arms hold enough stage-attributed
                # samples: the knob-relevant stage subtotal can't be masked
                # by (or blamed for) cost in stages the knob doesn't govern.
                if sm.stage_n >= min_n and sa.stage_n >= min_n:
                    flip = sa.mean_stage() < sm.mean_stage() * _FLIP_MARGIN
                else:
                    flip = sa.mean_wall() < sm.mean_wall() * _FLIP_MARGIN
                if flip:
                    value, source = alt_v, "measured"
            elif (
                not explore_claimed
                and sm.n >= min_n
                and sa.n < min_n
                and sm.mean_pred() >= _MIN_PRED_S
                and (
                    # Drift at stage grain when the chosen arm's stage
                    # subtotal is measured (predictions are per-knob
                    # ATTRIBUTABLE seconds, so stage-local actual is the
                    # matching denominator); whole wall otherwise.
                    sm.mean_stage() if sm.stage_n >= min_n else sm.mean_wall()
                )
                > drift_x * sm.mean_pred()
            ):
                # Predicted-vs-actual drift on the chosen arm: the model is
                # provably mispricing this class. Gather alternative-arm
                # samples (one exploring knob per query keeps the whole-wall
                # A/B attribution sound).
                value, source, explore_claimed = alt_v, "explore", True
        decisions[knob] = Decision(knob, value, alt_v if value == model_v else model_v, pred_m if value == model_v else pred_a, pred_a if value == model_v else pred_m, source)

    pd = PlanDecisions(fingerprint, decisions, cal.source)
    _note_activity(decisions)
    _record(pd)
    return pd


# Per-knob planner activity since process start (or reset()): how many
# queries the planner DECIDED the knob for (non-pinned), how many times it
# explored the alternative arm, and how many times a measured flip won.
# Monotonic like the registry counters; the exporter's `planner` frame key
# and dashboards read `activity_summary()`.
_activity: Dict[str, dict] = {}
_activity_lock = threading.Lock()


def _note_activity(decisions: Dict[str, Decision]) -> None:
    with _activity_lock:
        for knob, d in decisions.items():
            if d.source == "pinned":
                continue
            a = _activity.get(knob)
            if a is None:
                a = _activity[knob] = {
                    "decisions": 0,
                    "explorations": 0,
                    "measured_flips": 0,
                }
            a["decisions"] += 1
            if d.source == "explore":
                a["explorations"] += 1
            elif d.source == "measured":
                a["measured_flips"] += 1


def activity_summary() -> Dict[str, dict]:
    """Per-knob decision/exploration/measured-flip counts since start —
    ``{knob: {decisions, explorations, measured_flips}}``, empty when the
    planner never decided (off, or no queries yet)."""
    with _activity_lock:
        return {k: dict(v) for k, v in sorted(_activity.items())}


def _record(pd: PlanDecisions) -> None:
    """Land decisions + predictions on the ambient ledger and root span at
    decide time (satellite: the chosen hash-quantize arm must be visible on
    the span next to its measured wall)."""
    try:
        from ..telemetry import accounting, tracing

        d = pd.to_ledger()
        accounting.set_value("planner", d)
        sp = tracing.current_span()
        if sp is not None:
            sp.set_attr("planner", d)
    except Exception:
        pass


def prune_counters(base=None):
    """The ``io.pruning`` row-group counters as ``(scanned, skipped)`` —
    absolute when `base` is None, else the clamped delta since `base` (the
    per-query attribution the session captures around a run). None when the
    metrics registry is unavailable; never raises."""
    try:
        from ..telemetry import metrics as _metrics

        s = int(_metrics.counter("io.pruning.row_groups_scanned").value)
        k = int(_metrics.counter("io.pruning.row_groups_skipped").value)
    except Exception:
        return None
    if base is not None:
        try:
            s = max(0, s - int(base[0]))
            k = max(0, k - int(base[1]))
        except (TypeError, ValueError, IndexError):
            return None
    return (s, k)


def observe(
    pd: Optional[PlanDecisions], wall_s: float, pruning=None, stages=None
) -> None:
    """Feed one executed query's measured wall into the outcome store: the
    whole wall lands on every non-pinned knob's chosen arm (sound per class
    because the class — the fingerprint — holds everything else fixed, and
    only one knob explores at a time). Called by the session with its own
    monotonic measurement, so learning works with every telemetry sink off.
    `pruning` is the query's `(scanned, skipped)` row-group counter delta
    (from `prune_counters`), folded into the class's pushdown selectivity
    prior. `stages` is the query's per-stage busy-wall snapshot
    (`attribution.query_stage_walls()`): when present, each knob's outcome
    additionally records the STAGE subtotal that knob governs
    (`attribution.KNOB_STAGES`), so flips and drift trigger on stage-local
    cost instead of whole wall — an unrelated stage's slowdown can no
    longer mask (or fake) a knob's effect."""
    if pd is None or pd.fingerprint is None:
        return
    try:
        store = _outcome_store()
        if store is None:
            return
        from . import attribution as _attribution

        outcomes = {}
        for knob, d in pd.decisions.items():
            if d.source == "pinned":
                continue
            o = {
                "arm": d.arm,
                "wall_s": round(float(wall_s), 6),
                "predicted_s": d.predicted_s,
            }
            stage_s = _attribution.knob_stage_seconds(knob, stages)
            if stage_s is not None:
                o["stage_s"] = round(float(stage_s), 6)
            outcomes[knob] = o
        if outcomes:
            store.observe(pd.fingerprint, outcomes, pruning=pruning)
    except Exception:
        pass


def annotate_close(led, wall_s: float) -> None:
    """Ledger-close join: stamp the measured wall next to the recorded
    decisions and, when the history store holds a baseline for this class,
    the predicted-vs-actual and vs-baseline ratios — the fields hsreport's
    planner drift table and `explain(analyze=True)` render. Mutates the
    ledger's "planner" dict in place (before `to_dict` snapshots it)."""
    p = led.get("planner")
    if not isinstance(p, dict):
        return
    p["actual_wall_s"] = round(float(wall_s), 6)
    # Stage-grain join: when the ledger carries per-stage vectors, each
    # knob's entry also gets its stage-local actual and drift — the numbers
    # hsreport's stage-drift table and the Attribution section render.
    stage_walls = None
    stages = led.get("stages")
    if isinstance(stages, dict) and stages:
        stage_walls = {
            st: vec.get("wall_s", 0.0)
            for st, vec in stages.items()
            if isinstance(vec, dict) and vec.get("wall_s")
        }
    from . import attribution as _attribution

    for knob, d in p.items():
        if isinstance(d, dict) and isinstance(d.get("predicted_s"), (int, float)):
            pred = d["predicted_s"]
            d["drift_x"] = round(wall_s / pred, 3) if pred and pred > 0 else None
            if stage_walls:
                stage_s = _attribution.knob_stage_seconds(knob, stage_walls)
                if stage_s is not None:
                    d["stage_actual_s"] = round(stage_s, 6)
                    d["stage_drift_x"] = (
                        round(stage_s / pred, 3) if pred and pred > 0 else None
                    )
    try:
        from ..telemetry import history as _history

        fp = led.get("plan_fingerprint")
        if fp and _history.enabled():
            bl = _history.get_store().baseline_for(fp)
            if bl is not None and bl.count:
                mean, _ = bl.mean_std()
                p["baseline_mean_s"] = round(mean, 6)
                p["vs_baseline_x"] = round(wall_s / mean, 3) if mean else None
    except Exception:
        pass


def outcome_summary() -> Dict[tuple, dict]:
    """Folded (fingerprint, knob, arm) stats of the active store (empty when
    learning has no persistent home) — what tests and hsreport read."""
    store = _outcome_store()
    return store.summary() if store is not None else {}
