"""Physical operator frequency analysis (reference
`index/plananalysis/PhysicalOperatorAnalyzer.scala:30-57`): counts operator
occurrences per plan and pairs the two plans' counts for the explain verbose table."""

from __future__ import annotations

from collections import Counter
from dataclasses import dataclass
from typing import Dict, List

from ..engine.physical import PhysicalNode


@dataclass
class PhysicalOperatorComparison:
    name: str
    num_occurrences_before: int
    num_occurrences_after: int


def count_operators(plan: PhysicalNode) -> Dict[str, int]:
    return Counter(n.name for n in plan.collect_nodes())


def compare_operators(
    before: PhysicalNode, after: PhysicalNode
) -> List[PhysicalOperatorComparison]:
    b = count_operators(before)
    a = count_operators(after)
    names = sorted(set(b) | set(a))
    return [
        PhysicalOperatorComparison(n, b.get(n, 0), a.get(n, 0))
        for n in names
    ]
