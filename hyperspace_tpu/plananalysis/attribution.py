"""Knob → stage ownership: which pipeline stages a governed knob can
actually move, and the stage-local cost the planner should learn from.

PR 17's outcome store credits or blames a knob for the ENTIRE query wall,
so a pushdown win hides behind a cold decode and a packed-codes regression
behind a warm cache (ROADMAP item 2's "per-knob cost attribution finer than
whole-wall A/B"). With the stage ledger (`telemetry/stage_ledger.py`)
labeling every stage bracket, this module closes the loop:

- `KNOB_STAGES` declares, per cost-model knob, the stage set whose seconds
  that knob governs — pushdown can only change decode, packed_codes only
  the h2d/exchange lanes, join_size_classes only the pad/probe/verify
  brackets it shapes, and so on.
- `knob_stage_seconds(knob, stage_walls)` reduces one query's per-stage
  walls to the knob-relevant subtotal (None when nothing relevant was
  labeled — the planner then falls back to whole wall, exactly the old
  behavior, so sparse labeling degrades gracefully instead of lying).
- `explain_lines(ledger)` renders the Attribution section of
  `explain(analyze=True)`: per-stage cost vectors joined with knob
  ownership and the planner's predicted-vs-actual at stage grain.

Stage walls are BUSY time (concurrent workers sum — the `StageTimings`
convention), so a knob subtotal can exceed the query wall on an overlapped
pipeline; the planner only ever compares subtotals of the SAME stage set
across arms, so the comparison stays apples-to-apples.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..telemetry import stage_ledger as _stage_ledger

#: Governed knob -> the stages whose cost it can move. Keys mirror
#: `costmodel.KNOBS`; stage names are the `StageTimings.timed` bracket
#: names plus the dedicated ``h2d`` / ``exchange`` attribution lanes.
#: A knob that genuinely shapes the whole pipeline (streaming, chunk_rows)
#: lists every stage it touches — the subtotal is then close to whole wall,
#: which is the honest answer for a whole-pipeline knob.
KNOB_STAGES: Dict[str, tuple] = {
    # Zone-map pushdown only changes what the decode stage reads.
    "pushdown": ("decode",),
    # Streaming re-shapes the whole read-side pipeline.
    "streaming": ("decode", "filter", "partial", "merge"),
    # Chunk sizing moves decode granularity and the join staging it feeds.
    "chunk_rows": ("decode", "pad", "probe"),
    # Encoded execution trades decode (stay in codes) against staging.
    "encoded_exec": ("decode", "h2d"),
    # Bit-packed lanes only change transfer/exchange bytes.
    "packed_codes": ("h2d", "exchange"),
    # Size-class bucketing shapes pad/probe/verify of the streamed join.
    "join_size_classes": ("pad", "probe", "verify"),
    # Multiway star probing replaces per-dim probe/expand cascades.
    "multiway": ("probe", "expand", "verify"),
    # Hash quantization changes probe and the aggregate partial/merge work.
    "hash_quantize": ("probe", "partial", "merge"),
}


def knob_stage_seconds(
    knob: str, stage_walls: Optional[Dict[str, float]]
) -> Optional[float]:
    """The stage-local seconds `knob` governs in one query's per-stage wall
    snapshot. None (→ caller falls back to whole wall) when the knob has no
    declared stage set, no snapshot exists, or none of its stages were
    labeled in this query — a zero subtotal is indistinguishable from "the
    relevant stages never ran", and learning from it would credit the knob
    for free queries."""
    if not stage_walls:
        return None
    stages = KNOB_STAGES.get(knob)
    if not stages:
        return None
    total = 0.0
    seen = False
    for st in stages:
        v = stage_walls.get(st)
        if v:
            total += float(v)
            seen = True
    return total if seen and total > 0.0 else None


def query_stage_walls() -> Optional[Dict[str, float]]:
    """The running query's per-stage busy walls (None when attribution is
    off or nothing was labeled) — what `session` / `explain(analyze=True)`
    pass to `planner.observe(stages=...)`."""
    return _stage_ledger.query_stage_walls()


def stages_of(ledger: Optional[dict]) -> Dict[str, dict]:
    """The per-stage cost vectors of one closed ledger dict ({} when the
    query ran without attribution)."""
    if not isinstance(ledger, dict):
        return {}
    stages = ledger.get("stages")
    return stages if isinstance(stages, dict) else {}


def _owners(stage: str) -> List[str]:
    return sorted(k for k, sts in KNOB_STAGES.items() if stage in sts)


def _fmt_cell(field: str, v) -> str:
    if field.endswith("_s"):
        return f"{v * 1e3:.2f}ms"
    if field.startswith("bytes_"):
        return f"{_fmt_bytes(v)}"
    return str(v)


def _fmt_bytes(n) -> str:
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024
    return f"{n:.1f}GiB"


def explain_lines(ledger: Optional[dict]) -> List[str]:
    """Render the Attribution section: one line per stage (canonical vector
    field order, zero fields dropped) with the knobs owning that stage, then
    the planner's stage-grain predicted-vs-actual when decisions rode the
    ledger. Empty list when the query carried no stage data."""
    stages = stages_of(ledger)
    if not stages:
        return []
    lines: List[str] = ["Attribution (per-stage cost vectors):"]
    for st in sorted(stages):
        vec = stages[st]
        parts = []
        for f in _stage_ledger.VECTOR_FIELDS:
            v = vec.get(f)
            if v:
                parts.append(f"{f}={_fmt_cell(f, v)}")
        owners = _owners(st)
        own = f"  [knobs: {', '.join(owners)}]" if owners else ""
        lines.append(f"  {st:<12} {' '.join(parts)}{own}")
    # The planner ledger dict keys knob entries at top level (next to the
    # close-time annotations like actual_wall_s) — render predicted vs
    # stage-local actual for every decided knob whose stages were labeled.
    planner = ledger.get("planner") if isinstance(ledger, dict) else None
    if isinstance(planner, dict):
        walls = {
            st: vec.get("wall_s", 0.0)
            for st, vec in stages.items()
            if isinstance(vec, dict) and vec.get("wall_s")
        }
        grain: List[str] = []
        for knob in sorted(planner):
            d = planner[knob]
            if not isinstance(d, dict) or "arm" not in d:
                continue
            sub = d.get("stage_actual_s")
            if not isinstance(sub, (int, float)):
                sub = knob_stage_seconds(knob, walls)
            if sub is None:
                continue
            cell = f"{knob}[{d['arm']}]: stage_actual={sub * 1e3:.2f}ms"
            pred = d.get("predicted_s")
            if isinstance(pred, (int, float)) and pred > 0:
                cell += f" predicted={pred * 1e3:.2f}ms"
                sd = d.get("stage_drift_x")
                if not isinstance(sd, (int, float)):
                    sd = round(sub / pred, 3)
                cell += f" drift={sd:g}x"
            grain.append(cell)
        if grain:
            lines.append(
                "  knob-relevant subtotals (busy time, stages overlap):"
            )
            for g in grain:
                lines.append(f"    {g}")
    return lines
