"""Plan-time cost model: bytes-moved estimates per candidate knob setting.

The engine's behavior knobs (streaming, encoded exec, packed codes, hash
quantize, pushdown, chunk rows, join size classes) each trade one kind of
byte movement for another: a materialized aggregate pays an extra full-table
concat, flat string staging pays decoded value bytes where codes would do,
pow2 hash quantization pays padded copies to save per-shape recompiles. In
the "cost = bytes moved" framing (JSPIM's external-memory join accounting;
FractalSortCPU's bandwidth-first model where compressed width x rows is the
dominant term), every one of those trades prices in seconds as

    predicted_s = bytes_moved / measured_bandwidth  (+ per-event constants)

This module is the pure pricing half of the adaptive planner
(`plananalysis.planner`): given one physical plan it gathers `PlanStats`
from footer-cache column stats the scan layer already holds (row counts,
row-group byte sizes, per-column dictionary-encoding facts —
`engine.io.FileFooterMeta`, WARM cache peeks only: the model never opens a
file), reads `Calibration` constants from the device observatory's measured
ledgers (transfer GB/s when probes have run; honest defaults otherwise), and
prices BOTH arms of every governed knob. Choosing, pinning, learning, and
self-correction live in `planner.py`.

Model posture: the chosen arm equals today's default unless the stats give a
decisive, warm-footer-backed margin — the priors reproduce the existing
heuristics (hash quantize keys off `use_device_path`, exactly the gate it
replaces, but now with BOTH arms priced so the planner's outcome store can
overturn a wrong guess from measurements). Predictions are attributable
marginal costs, not wall-clock forecasts: the planner compares a knob's
predicted_s against measured walls only for drift *ratios*, never absolutes.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, Optional, Set, Tuple

#: Every knob the planner governs, in exploration-priority order (the
#: outcome store explores at most one knob per query; the HASH_QUANTIZE
#: auto-gate is the motivating mis-guess, so it goes first).
KNOBS = (
    "hash_quantize",
    "streaming",
    "chunk_rows",
    "encoded_exec",
    "packed_codes",
    "pushdown",
    "join_size_classes",
    "multiway",
)

#: knob -> the env flag that PINS it (set flag = pinned, unset = planner).
KNOB_ENV = {
    "hash_quantize": "HYPERSPACE_HASH_QUANTIZE",
    "streaming": "HYPERSPACE_QUERY_STREAMING",
    "chunk_rows": "HYPERSPACE_QUERY_CHUNK_ROWS",
    "encoded_exec": "HYPERSPACE_ENCODED_EXEC",
    "packed_codes": "HYPERSPACE_PACKED_CODES",
    "pushdown": "HYPERSPACE_SCAN_PUSHDOWN",
    "join_size_classes": "HYPERSPACE_JOIN_SIZE_CLASSES",
    "multiway": "HYPERSPACE_MULTIWAY",
}

INT_KNOBS = ("chunk_rows",)

#: Calibration override/injection: bench measures the host memcpy peak
#: (`bench.py` memcpy_peak_gbps) and can hand it to the planner; tests pin it.
ENV_MEMCPY_GBPS = "HYPERSPACE_PLANNER_MEMCPY_GBPS"

_DEFAULT_MEMCPY_GBPS = 8.0  # conservative host copy ceiling
_DEFAULT_DECODE_GBPS = 1.5  # parquet decode throughput (uncompressed bytes)
_HOST_COMPILE_S = 0.03  # one XLA-CPU program build
_DEVICE_COMPILE_S = 0.5  # one accelerator program build (lowering + compile)
_CHUNK_OVERHEAD_S = 0.0008  # per-chunk dispatch/carry overhead
_TARGET_CHUNK_BYTES = 64 << 20  # streamed working set the chunk shaper aims at
_DEFAULT_CHUNK_ROWS = 4_000_000  # streaming._DEFAULT_QUERY_CHUNK_ROWS
_MIN_CHUNK_ROWS = 65_536
#: Below this many (warm-footer-counted) rows the chunk shaper keeps the
#: default: re-shaping tiny tables moves nothing and would churn chunk-count
#: expectations for no win.
_CHUNK_SHAPE_MIN_ROWS = 1_000_000
_EST_ROW_BYTES = 50  # cold fallback: rows from file bytes
_EST_DECODE_RATIO = 3.0  # cold fallback: uncompressed from compressed bytes
_CODE_BYTES_PER_ROW = 1.25  # narrow codes + validity, per dictionary column


@dataclass
class Calibration:
    """Measured (or default) constants the pricing uses. `source` says where
    the bandwidth came from ("env" / "measured" / "default") so explain and
    hsreport can show whether a decision was calibrated or guessed."""

    memcpy_gbps: float = _DEFAULT_MEMCPY_GBPS
    decode_gbps: float = _DEFAULT_DECODE_GBPS
    compile_s: float = _HOST_COMPILE_S
    chunk_overhead_s: float = _CHUNK_OVERHEAD_S
    device: bool = False
    source: str = "default"


def current_calibration() -> Calibration:
    """Calibration from the device observatory's measured ledgers: the h2d
    transfer GB/s when timing probes have recorded any (the honest measured
    number for 'what does moving a byte cost here'), env-pinned when
    `HYPERSPACE_PLANNER_MEMCPY_GBPS` is set, defaults otherwise. Never
    raises and never touches a device."""
    cal = Calibration()
    env = os.environ.get(ENV_MEMCPY_GBPS, "")
    if env:
        try:
            cal.memcpy_gbps = max(0.01, float(env))
            cal.source = "env"
        except ValueError:
            pass
    else:
        try:
            from ..telemetry import device_observatory as _devobs

            h2d = _devobs.transfer_summary().get("h2d", {})
            gbps = h2d.get("gb_per_s")
            if gbps:
                cal.memcpy_gbps = max(0.01, float(gbps))
                cal.source = "measured"
        except Exception:
            pass
    try:
        from ..ops.backend import use_device_path

        cal.device = bool(use_device_path())
    except Exception:
        cal.device = False
    cal.compile_s = _DEVICE_COMPILE_S if cal.device else _HOST_COMPILE_S
    return cal


@dataclass
class PlanStats:
    """What the plan walk gathered: plan shape plus per-scan footer facts
    from WARM cache entries only (`scan_cache.get_meta` peeks — a cold file
    contributes its `FileStatus.size` and nothing else; the model never
    performs I/O or parses a footer)."""

    n_scans: int = 0
    n_files: int = 0
    warm_files: int = 0
    file_bytes: int = 0  # compressed on-disk bytes (every file)
    rows: int = 0  # footer row counts (warm files only)
    decoded_bytes: int = 0  # uncompressed row-group bytes (warm files only)
    dict_cols: int = 0  # distinct dictionary-encoded columns seen
    dict_col_bytes: int = 0  # uncompressed bytes of those columns
    row_shapes: Set[int] = field(default_factory=set)  # per-file row counts
    has_agg: bool = False
    has_join: bool = False
    has_filter: bool = False
    star_dims: int = 0  # dimension count of the plan's MultiwayJoinExec (0 = none)

    def est_rows(self) -> int:
        if self.rows:
            return self.rows
        return max(1, self.file_bytes // _EST_ROW_BYTES)

    def est_decoded_bytes(self) -> int:
        if self.decoded_bytes:
            return self.decoded_bytes
        return int(self.file_bytes * _EST_DECODE_RATIO)

    def fully_warm(self) -> bool:
        return self.n_files > 0 and self.warm_files == self.n_files


def collect_stats(phys) -> PlanStats:
    """Walk one physical plan; peek the footer cache for every scanned file.
    Cheap by construction: dict lookups against the already-resident scan
    cache, no env reads, no file opens."""
    from ..engine.scan_cache import global_scan_cache

    cache = global_scan_cache()
    st = PlanStats()
    dict_col_names: Set[str] = set()
    seen_rels: Set[int] = set()
    for node in phys.collect_nodes():
        kind = type(node).__name__
        if kind == "HashAggregateExec":
            st.has_agg = True
        elif kind == "SortMergeJoinExec":
            st.has_join = True
        elif kind == "MultiwayJoinExec":
            st.has_join = True
            st.star_dims = max(st.star_dims, len(node.dims))
        elif kind == "FilterExec":
            st.has_filter = True
        rel = getattr(node, "relation", None)
        if rel is None:
            continue
        # A MultiwayJoinExec carries its fallback cascade as a child, so the
        # same relation object reaches this walk twice (star scan + cascade
        # scan). Counting it once keeps byte totals honest.
        if id(rel) in seen_rels:
            continue
        seen_rels.add(id(rel))
        st.n_scans += 1
        for f in getattr(rel, "files", None) or ():
            st.n_files += 1
            st.file_bytes += int(getattr(f, "size", 0) or 0)
            try:
                meta = cache.get_meta(f.path)
            except Exception:
                meta = None
            if meta is None:
                continue
            st.warm_files += 1
            st.rows += int(meta.num_rows or 0)
            st.row_shapes.add(int(meta.num_rows or 0))
            for rg in meta.row_groups:
                st.decoded_bytes += int(rg.total_bytes or 0)
                for c, b in rg.col_bytes.items():
                    if meta.dict_cols.get(c):
                        st.dict_col_bytes += int(b or 0)
                        dict_col_names.add(c)
    st.dict_cols = len(dict_col_names)
    return st


def _pow2(n: int) -> int:
    return 1 << max(1, int(n) - 1).bit_length()


def _copy_s(nbytes: float, cal: Calibration) -> float:
    return float(nbytes) / (cal.memcpy_gbps * 1e9)


def _decode_s(nbytes: float, cal: Calibration) -> float:
    return float(nbytes) / (cal.decode_gbps * 1e9)


def estimate(
    stats: PlanStats,
    cal: Calibration,
    prune_selectivity: Optional[float] = None,
) -> Dict[str, Tuple[object, object, float, float]]:
    """Price both arms of every governed knob for this plan:
    ``{knob: (model_value, alt_value, predicted_s_model, predicted_s_alt)}``.
    model_value is the arm the model picks (bool for on/off knobs, int for
    chunk_rows); alt_value is the single alternative the planner A/Bs it
    against. Predictions are marginal attributable seconds — two arms with
    equal predictions mean "this plan doesn't exercise the knob".

    `prune_selectivity` is the per-class learned fraction of row groups the
    pushdown gate actually scanned (scanned / (scanned + skipped), from the
    outcome store's recorded ``io.pruning`` counters); None keeps the static
    half-prune prior."""
    out: Dict[str, Tuple[object, object, float, float]] = {}
    rows = stats.est_rows()
    decoded = stats.est_decoded_bytes()

    # streamed-vs-materialized: both arms decode the same bytes; the
    # materialized arm additionally assembles the full concat on the host
    # before any reduction starts (bench r05's 500 MB-materialized-then-
    # reduced case). No aggregate in the plan -> the gate is not exercised.
    stream_s = _decode_s(decoded, cal)
    if stats.has_agg:
        out["streaming"] = (True, False, round(stream_s, 9), round(stream_s + _copy_s(decoded, cal), 9))
    else:
        out["streaming"] = (True, False, round(stream_s, 9), round(stream_s, 9))

    # chunk rows: per-chunk dispatch overhead vs working-set spill. Only
    # re-shaped on large, fully-warm scans (cold or small stats keep the
    # default — the conservative posture tier-1 pins).
    chunk = _DEFAULT_CHUNK_ROWS
    if (
        stats.has_agg
        and stats.fully_warm()
        and rows >= _CHUNK_SHAPE_MIN_ROWS
        and decoded > 0
    ):
        row_bytes = max(1.0, decoded / max(1, rows))
        chunk = int(_TARGET_CHUNK_BYTES / row_bytes)
        chunk = max(_MIN_CHUNK_ROWS, min(_DEFAULT_CHUNK_ROWS, _pow2(chunk)))

    def _chunk_cost(v: int) -> float:
        n_chunks = max(1, -(-rows // v))
        row_bytes = max(1.0, decoded / max(1, rows))
        spill = max(0.0, v * row_bytes - _TARGET_CHUNK_BYTES) * n_chunks
        return n_chunks * cal.chunk_overhead_s + _copy_s(spill, cal)

    out["chunk_rows"] = (
        chunk,
        _DEFAULT_CHUNK_ROWS,
        round(_chunk_cost(chunk), 9),
        round(_chunk_cost(_DEFAULT_CHUNK_ROWS), 9),
    )

    # encoded-vs-flat: codes + dictionary vs decoded value bytes for every
    # dictionary-encoded column the footers report. No dictionary columns ->
    # neutral (the gate costs nothing either way).
    code_bytes = rows * stats.dict_cols * _CODE_BYTES_PER_ROW
    out["encoded_exec"] = (
        True,
        False,
        round(_copy_s(code_bytes, cal), 9),
        round(_copy_s(max(stats.dict_col_bytes, code_bytes), cal), 9),
    )

    # packed-vs-narrow: sub-byte lanes below the int8 narrow floor. The
    # dictionary cardinality is not in the footer, so the prior prices the
    # 4-bit midpoint of the packed class set against one byte per code; the
    # outcome store corrects classes where packing does not apply or the
    # pack/unpack overhead loses.
    narrow_bytes = rows * stats.dict_cols  # one byte per code (int8 floor)
    out["packed_codes"] = (
        True,
        False,
        round(_copy_s(narrow_bytes / 2.0, cal), 9),
        round(_copy_s(narrow_bytes, cal), 9),
    )

    # pushdown row-group selection: zone evaluation is ~free; the win is
    # every pruned row group's decode. Selectivity starts as a half-prune
    # prior when a filter exists over warm zone maps; once this class has
    # recorded ``io.pruning`` counters, the measured scanned fraction
    # replaces the guess (satellite of the multiway PR: learned priors over
    # static ones wherever the engine already counts the truth).
    if stats.has_filter and stats.warm_files:
        sel = 0.5
        if prune_selectivity is not None:
            try:
                sel = min(1.0, max(0.0, float(prune_selectivity)))
            except (TypeError, ValueError):
                sel = 0.5
        out["pushdown"] = (
            True,
            False,
            round(_decode_s(decoded * sel, cal), 9),
            round(_decode_s(decoded, cal), 9),
        )
    else:
        out["pushdown"] = (True, False, round(_decode_s(decoded, cal), 9), round(_decode_s(decoded, cal), 9))

    # hash-quantize on/off: ON pays pow2 padded copies of every hashed batch
    # (the pad-tax ledger's "hash_quantize" site); OFF pays one program
    # build per distinct row shape. On the host path there are no device
    # hash programs to recompile, so OFF is free — which is exactly the
    # measured 45% CPU regression the device-only heuristic hid. Both arms
    # priced; the planner's per-class outcome store settles the rest.
    if stats.has_agg or stats.has_join:
        hashed_rows = min(rows, chunk)
        n_batches = max(1, -(-rows // max(1, hashed_rows)))
        pad_excess = (_pow2(hashed_rows) - hashed_rows) * 8.0 * n_batches
        quant_on = _copy_s(pad_excess, cal)
        shapes = max(1, len(stats.row_shapes) or 1)
        quant_off = (shapes * cal.compile_s) if cal.device else 0.0
        out["hash_quantize"] = (cal.device, not cal.device, *(
            (round(quant_on, 9), round(quant_off, 9))
            if cal.device
            else (round(quant_off, 9), round(quant_on, 9))
        ))
    else:
        out["hash_quantize"] = (cal.device, not cal.device, 0.0, 0.0)

    # join size-class count: the classed layout (<=8 pow2 capacity classes)
    # pays per-class dispatch to avoid the global-max pad blowup under skew;
    # skew is unknown at plan time, so the prior keeps the classed default
    # and charges the dense arm a representative pad-risk term only when
    # the plan actually joins.
    if stats.has_join:
        classed_s = 8 * cal.chunk_overhead_s
        dense_s = _copy_s(decoded * 0.25, cal)
        out["join_size_classes"] = (True, False, round(classed_s, 9), round(classed_s + dense_s, 9))
    else:
        out["join_size_classes"] = (True, False, 0.0, 0.0)

    # multiway star execution vs cascaded binary joins: the cascade
    # materializes an intermediate fact table per extra dimension (rows x
    # row-width copied once per non-final join — the exact bytes the star
    # pass never assembles); the star arm instead pays one 8-byte key64
    # probe per row per dimension. Plans without a recognized star shape
    # leave the knob neutral (nothing to trade).
    if stats.star_dims >= 2:
        row_bytes = max(1.0, decoded / max(1, rows))
        inter_bytes = rows * row_bytes * (stats.star_dims - 1)
        probe_bytes = rows * 8.0 * stats.star_dims
        out["multiway"] = (
            True,
            False,
            round(_copy_s(probe_bytes, cal), 9),
            round(_copy_s(inter_bytes, cal), 9),
        )
    else:
        out["multiway"] = (True, False, 0.0, 0.0)

    return out
