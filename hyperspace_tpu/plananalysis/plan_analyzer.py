"""PlanAnalyzer: the explain subsystem.

Parity: reference `index/plananalysis/PlanAnalyzer.scala:34-410` — compiles the
physical plan twice (Hyperspace enabled vs disabled; no job executed), prints both
trees highlighting the subtrees that differ, lists "Indexes used" (matched by index
location against the chosen plan's scans), and in verbose mode a physical-operator
frequency diff table.
"""

from __future__ import annotations

from typing import List, Set

from ..engine.physical import PhysicalNode
from ..engine.session import DataFrame, HyperspaceSession
from .buffer_stream import BufferStream
from .display_mode import create_display_mode
from .op_analyzer import compare_operators


def _subtree_strings(plan: PhysicalNode) -> Set[str]:
    return {n.tree_string() for n in plan.collect_nodes()}


def _write_plan(buffer: BufferStream, plan: PhysicalNode, other: PhysicalNode) -> None:
    """Write a plan tree, highlighting every node whose subtree does not appear in
    the other plan (the whole differing subtree ends up highlighted)."""
    other_subtrees = _subtree_strings(other)

    def walk(node: PhysicalNode, indent: int):
        line = node.format_line(indent)
        if node.tree_string() in other_subtrees:
            buffer.write_line(line)
        else:
            buffer.highlight_line(line)
        for c in node.children():
            walk(c, indent + 1)

    walk(plan, 0)


def _with_hyperspace_state(df: DataFrame, session: HyperspaceSession, enabled: bool) -> PhysicalNode:
    """Compile the physical plan with hyperspace forced on/off, restoring the session
    state afterwards (reference `withHyperspaceState`, :341-360)."""
    from ..hyperspace import disable_hyperspace, enable_hyperspace, is_hyperspace_enabled

    was_enabled = is_hyperspace_enabled(session)
    try:
        (enable_hyperspace if enabled else disable_hyperspace)(session)
        return df.physical_plan()
    finally:
        (enable_hyperspace if was_enabled else disable_hyperspace)(session)


def explain_string(
    df: DataFrame,
    session: HyperspaceSession,
    indexes_table,
    verbose: bool = False,
) -> str:
    mode = create_display_mode(session.conf)
    buffer = BufferStream(mode)

    plan_with = _with_hyperspace_state(df, session, enabled=True)
    plan_without = _with_hyperspace_state(df, session, enabled=False)

    buffer.write_line("=============================================================")
    buffer.write_line("Plan with indexes:")
    buffer.write_line("=============================================================")
    _write_plan(buffer, plan_with, plan_without)
    buffer.write_line()

    buffer.write_line("=============================================================")
    buffer.write_line("Plan without indexes:")
    buffer.write_line("=============================================================")
    _write_plan(buffer, plan_without, plan_with)
    buffer.write_line()

    buffer.write_line("=============================================================")
    buffer.write_line("Indexes used:")
    buffer.write_line("=============================================================")
    used = {}
    for n in plan_with.collect_nodes():
        rel = getattr(n, "relation", None)
        if rel is not None and rel.index_name:
            used[rel.index_name] = rel.root_paths[0]
    idx = indexes_table.to_pydict()
    for name, location in sorted(used.items()):
        # Cross-check against the registry like the reference (:209-221).
        if name in idx.get("name", []):
            i = idx["name"].index(name)
            location = idx["indexLocation"][i]
        buffer.write_line(f"{name}:{location}")
    buffer.write_line()

    if verbose:
        buffer.write_line("=============================================================")
        buffer.write_line("Physical operator stats:")
        buffer.write_line("=============================================================")
        comparisons = compare_operators(plan_without, plan_with)
        name_w = max([len("Physical Operator")] + [len(c.name) for c in comparisons]) + 2
        header = (
            f"{'Physical Operator':<{name_w}}|{'Hyperspace Disabled':>21}|"
            f"{'Hyperspace Enabled':>20}|{'Difference':>12}"
        )
        buffer.write_line(header)
        buffer.write_line("-" * len(header))
        for c in comparisons:
            diff = c.num_occurrences_after - c.num_occurrences_before
            buffer.write_line(
                f"{c.name:<{name_w}}|{c.num_occurrences_before:>21}|"
                f"{c.num_occurrences_after:>20}|{diff:>12}"
            )
    return buffer.to_string()
