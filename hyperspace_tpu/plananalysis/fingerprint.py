"""Stable plan fingerprints: the identity of a query's EXECUTION CLASS.

The workload history store (`telemetry.history`) keys every observed ledger
by "what kind of plan ran", so a cost model (ROADMAP item 4) can learn
per-class baselines from durable history instead of env-flag folklore. That
key must be:

- **Stable**: the same query against the same index generation under the
  same flag posture hashes identically — across processes and across
  re-created lakes (root paths are normalized to their basenames; two runs
  of the same bench script in different temp dirs share fingerprints).
- **Class-shaped, not instance-shaped**: literal VALUES are abstracted to
  their types (``orderkey == 7`` and ``orderkey == 12`` are the same point-
  lookup class — a cost model wants one baseline for both), while the
  predicate STRUCTURE (columns, operators, conjunct shape) is kept.
- **Version-sensitive**: an index refresh/optimize advances the relation's
  ``log_entry_id`` and therefore the fingerprint — observed history from a
  superseded index generation never pollutes the new generation's baseline.
- **Posture-sensitive**: the ambient behavior flags (streaming, size
  classes, pushdown, encoded exec, …) are part of the hash — the SAME plan
  under a different engine posture is a different cost class (the exact
  distinction `tools/bench_compare.py --history` gates on).

`plan_fingerprint(node)` walks the PHYSICAL tree (what actually executes:
rule rewrites, index substitutions, and pushdown attachment have already
happened) and hashes a canonical JSON of the node signatures plus the flag
posture. One sha256, 16 hex chars — collision-safe at any realistic
workload-class cardinality.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Optional

from ..telemetry import accounting as _accounting
from ..telemetry import history as _history
from ..telemetry import tracing as _tracing

#: Behavior knobs folded into every fingerprint ("ambient flag posture").
#: Telemetry sinks (trace/metrics files, accounting) are deliberately NOT
#: here — observing a query must not change its class. Order is irrelevant
#: (the posture dict is serialized with sorted keys); unset flags are
#: omitted, so setting a flag to its non-default value is what changes the
#: fingerprint.
FLAG_KEYS = (
    "HYPERSPACE_BUILD_DECODE_THREADS",
    "HYPERSPACE_DISTRIBUTED",
    "HYPERSPACE_ENCODED_DICT_MAX",
    "HYPERSPACE_ENCODED_EXEC",
    "HYPERSPACE_FORCE_DEVICE_OPS",
    "HYPERSPACE_HASH_QUANTIZE",
    "HYPERSPACE_INDEX_ROW_GROUP_ROWS",
    "HYPERSPACE_JOIN_CHUNK_ROWS",
    "HYPERSPACE_JOIN_OUTLIER_FACTOR",
    "HYPERSPACE_JOIN_SIZE_CLASSES",
    "HYPERSPACE_MESH_ROW_QUANTUM",
    "HYPERSPACE_MULTIWAY",
    "HYPERSPACE_PALLAS_PROBE",
    "HYPERSPACE_PALLAS_SORT",
    "HYPERSPACE_PLANNER",
    "HYPERSPACE_PRED_FUSE_MAX_CLASSES",
    "HYPERSPACE_PRED_FUSE_MIN_ROWS",
    "HYPERSPACE_QUERY_CHUNK_ROWS",
    "HYPERSPACE_QUERY_PREFETCH_FILES",
    "HYPERSPACE_QUERY_STREAMING",
    "HYPERSPACE_SCAN_PUSHDOWN",
    "HYPERSPACE_SERVING",
)


def flag_posture() -> dict:
    """The ambient behavior-flag values that shape execution, set keys only."""
    out = {}
    for k in FLAG_KEYS:
        v = os.environ.get(k)
        if v is not None:
            out[k] = v
    return out


def expr_signature(e) -> list:
    """Canonical structure of an expression: columns and operators verbatim,
    literal VALUES abstracted to their type names (class identity, not
    instance identity). Unknown expression types degrade to their class name
    plus child signatures — never an error."""
    from ..engine import expr as _expr

    if e is None:
        return ["none"]
    if isinstance(e, _expr.Col):
        return ["col", e.name]
    if isinstance(e, _expr.Lit):
        return ["lit", type(e.value).__name__]
    if isinstance(e, _expr.BinaryOp):
        return [e.op, expr_signature(e.left), expr_signature(e.right)]
    if isinstance(e, _expr.IsNull):
        return ["isnotnull" if e.negated else "isnull", expr_signature(e.child)]
    if isinstance(e, _expr.Not):
        return ["not", expr_signature(e.child)]
    if isinstance(e, _expr.IsIn):
        kinds = sorted({type(v).__name__ for v in e.values})
        return ["in", expr_signature(e.child), kinds]
    out = [type(e).__name__.lower()]
    try:
        out.extend(expr_signature(c) for c in e.children())
    except Exception:
        pass
    return out


def _relation_signature(rel) -> list:
    """A relation's class identity: normalized roots (basenames — stable
    across re-created temp lakes), format, the substituting index's name AND
    log-entry id (advances on refresh/vacuum/optimize: a new index
    generation is a new cost class), bucket layout, and hybrid-append
    presence (an index merging appended source files scans differently)."""
    roots = sorted(
        os.path.basename(p.rstrip("/\\")) or p for p in (rel.root_paths or [])
    )
    out = ["rel", roots, rel.file_format, rel.index_name,
           getattr(rel, "log_entry_id", None)]
    if rel.bucket_spec is not None:
        out.append(["buckets", rel.bucket_spec.num_buckets,
                    list(rel.bucket_spec.bucket_columns),
                    list(rel.bucket_spec.sort_columns)])
    if rel.hybrid_append is not None:
        out.append(["hybrid", len(rel.hybrid_append.files)])
    if rel.pruned_by:
        out.append(["pruned_by", sorted(rel.pruned_by)])
    return out


def node_signature(node) -> list:
    """Canonical recursive signature of one physical operator."""
    from ..engine import physical as _phys

    sig: list = [type(node).__name__]
    if isinstance(node, (_phys.ScanExec, _phys.BucketedIndexScanExec)):
        sig.append(_relation_signature(node.relation))
        cols = getattr(node, "columns", None)
        if cols:
            sig.append(["cols", sorted(cols)])
        pd = getattr(node, "pushdown", None)
        if pd is not None:
            sig.append(["pushdown", expr_signature(pd)])
    elif isinstance(node, _phys.FilterExec):
        sig.append(expr_signature(node.condition))
    elif isinstance(node, _phys.ProjectExec):
        sig.append(list(node.column_names))
    elif isinstance(node, _phys.WithColumnExec):
        sig.append([node.col_name, expr_signature(node.expr)])
    elif isinstance(node, _phys.HashAggregateExec):
        sig.append(["keys", list(node.group_keys)])
        sig.append(["aggs", [list(a) for a in node.aggs]])
    elif isinstance(node, _phys.SortMergeJoinExec):
        sig.append([node.how, bool(node.bucketed),
                    list(node.left_keys), list(node.right_keys)])
    elif isinstance(node, _phys.MultiwayJoinExec):
        # Each star SHAPE is its own class: the dimension count, key
        # mappings, and covering indexes (name + bucket count) all change
        # what executes. The cascade child recurses below, so the fallback
        # structure is part of the class too.
        sig.append(["star", [
            [list(fk), list(dk), name, int(nb)]
            for _exec, fk, dk, name, nb in node.dims
        ]])
    elif isinstance(node, (_phys.SortExec, _phys.ShuffleExchangeExec)):
        sig.append(list(getattr(node, "keys", ())))
    elif isinstance(node, _phys.OrderByExec):
        sig.append([[k, bool(asc)] for k, asc in node.keys])
    elif isinstance(node, _phys.LimitExec):
        sig.append(node.n)
    elif isinstance(node, _phys.SetOpExec):
        sig.append(node.op)
    children = node.children()
    if children:
        sig.append([node_signature(c) for c in children])
    return sig


def plan_fingerprint(node, posture: Optional[dict] = None) -> str:
    """16-hex-char fingerprint of `node`'s execution class (tree signature +
    ambient flag posture). Deterministic across processes: the signature is
    serialized as canonical JSON (sorted keys, no whitespace) before
    hashing. Never raises — an unexpected plan shape degrades to hashing
    whatever signature the walk produced."""
    if posture is None:
        posture = flag_posture()
    try:
        payload = json.dumps(
            {"plan": node_signature(node), "flags": posture},
            sort_keys=True,
            separators=(",", ":"),
            default=str,
        )
    except Exception:
        payload = f"{type(node).__name__}:{posture}"
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


def fingerprint_wanted() -> bool:
    """Whether computing a fingerprint at plan time can reach any consumer:
    the history store is enabled, a ledger is open (exporter / accounting /
    serving tenant), or a span sink is recording. With everything off this
    is one env read + one contextvar read — the zero-cost-off contract."""
    if _history.enabled():
        return True
    if _accounting.current_ledger() is not None:
        return True
    return _tracing.active()
